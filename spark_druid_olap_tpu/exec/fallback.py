"""Host (pandas) fallback execution of logical plans the planner cannot
rewrite.

Reference parity: in the reference, a failed rewrite is not an error — the
query simply runs as a vanilla Spark plan over the base data (SURVEY.md
§3.2 "fallback: vanilla Spark plan", §2 DruidStrategy row `[U]`).  This
module is that safety net for the standalone framework: when
`Planner.plan` raises RewriteError (an unconforming join, an expression no
transform covers), the SAME logical plan is interpreted over decoded host
frames.  Slow but correct and complete — a user never hits a wall, they
hit a warning.

Gated by `SessionConfig.fallback_execution` (True by default, like the
reference's always-on Spark fallback; set False to surface RewriteError —
useful in tests that assert pushdown coverage).

Semantics notes:
* COUNT(DISTINCT)/approx_count_distinct evaluate EXACTLY here (pandas
  nunique) — the fallback has no reason to approximate.
* SUM/MIN/MAX/AVG over zero rows are SQL NULL; COUNT is 0 (the engine's
  convention, pinned by the differential fuzz suite).
* Grouping sets expand exactly like the device path (one pass per set,
  absent dims as nulls, __grouping_id bitmask).
"""

from __future__ import annotations

from typing import Dict

import numpy as np
import pandas as pd

from ..catalog.segment import DataSource
from ..models import aggregations as A
from ..plan import logical as L
from ..plan.expr import Expr, compile_expr
from ..plan import expr as E
from ..utils.log import get_logger

log = get_logger("exec.fallback")

# Wire-aggregator parity registry (graftlint wire-parity/GL1002): every
# aggregation class `models/wire.py` can decode from a Druid request
# maps to the host aggregate function `_agg_one` interprets it with —
# so a degraded answer can never silently lose a feature the device
# path serves.  Distinct-count sketches evaluate EXACTLY on the host
# (pandas nunique; the fallback has no reason to approximate), which is
# the documented semantics divergence, not a missing feature.  Adding a
# wire aggregator without extending this table (and `_agg_one` when the
# function is new) fails the lint gate.
WIRE_AGG_FALLBACK = {
    A.Count: "count",
    A.LongSum: "sum",
    A.DoubleSum: "sum",
    A.LongMin: "min",
    A.DoubleMin: "min",
    A.LongMax: "max",
    A.DoubleMax: "max",
    # FD-pruning carrier: max over dictionary codes, decoded at the API
    # layer — plain max on the host
    A.DimCodeMax: "max",
    # base-routed at lowering time; every base ("doubleSum"/"longSum"/
    # "doubleMin"/"doubleMax") is one of the host functions above
    A.ExpressionAgg: "sum",
    # wrapper: interpreted as the inner aggregator under AggExpr.filter
    A.FilteredAgg: "count",
    A.HyperUnique: "approx_count_distinct",
    A.CardinalityAgg: "approx_count_distinct",
    A.ThetaSketch: "approx_count_distinct_ds_theta",
    A.QuantilesSketch: "approx_quantile",
}


def fallback_agg_fn(agg: A.Aggregation) -> str:
    """The `_agg_one` function name that interprets `agg` on the host.
    Raises for classes outside the wire-parity registry — a loud signal
    the degraded path is about to lose a feature."""
    if isinstance(agg, A.FilteredAgg):
        return fallback_agg_fn(agg.aggregator)
    for cls, fn in WIRE_AGG_FALLBACK.items():
        if type(agg) is cls:
            return fn
    raise NotImplementedError(
        f"no host fallback interpretation for {type(agg).__name__}"
    )


# Per-(segment, column) decoded-array cache: the streamed-ingest tier made
# the fallback's input INCREMENTAL (a delta append adds one small segment
# to an otherwise unchanged set), so re-decoding every historical segment
# per fallback query — the pre-ingest behavior — re-pays exactly the work
# that did not change.  Keys carry the segment uid and the dictionary's
# content_key: an append hits for historical segments and decodes only the
# fresh deltas; a dictionary extension (new content_key) or compaction
# (new uids) misses cleanly.  Byte-budgeted LRU; object arrays meter at
# pointer width, which undercounts string storage — the decoded VALUES are
# shared with the dictionary tuples, so pointers are the marginal cost.
_DECODE_CACHE_BYTES = 1 << 30
_decode_cache = None


def _decoded_segment_cache():
    global _decode_cache
    if _decode_cache is None:
        from ..utils.lru import ByteBudgetCache

        _decode_cache = ByteBudgetCache(_DECODE_CACHE_BYTES)
    return _decode_cache


def evict_decoded_segments(uids) -> None:
    """Drop decoded-frame entries for retired segment uids — called from
    the same segment-drop hook that evicts device residency (compaction
    and dictionary-extension remaps retire uids; their dead decode
    entries would otherwise squat in the LRU displacing live ones)."""
    if _decode_cache is None:
        return
    uids = set(uids)
    for k in [k for k in _decode_cache if k[0] in uids]:
        _decode_cache.pop(k)


def decoded_frame(ds: DataSource, columns=None) -> pd.DataFrame:
    """Real rows of a datasource as a pandas frame: dimensions decoded to
    values, metrics as float64, time as int64 ms.  `columns` restricts the
    decode to the names a plan actually references (decoding a wide
    table's every column would dominate fallback latency).

    The decode iterates SEGMENT-outer so a deadline expiring mid-decode
    can truncate to whole segments: every column then carries exactly the
    same row prefix, and the interpreter's answer over the truncated
    frame is a sound "rows seen so far" partial (the collector accounts
    the seen/total split, including delta vs historical rows)."""
    from ..obs import SPAN_FALLBACK_DECODE, span
    from ..resilience import checkpoint_partial, current_partial, fire, injector
    from .engine import _row_counts

    fire("fallback_decode")  # fault-injection site: host decode
    # `partial` fault mode truncates every segment's decode to a fraction —
    # the deterministic torn-result shape watchdog/flush tests need
    frac = injector().partial_fraction("fallback_decode")
    cache = _decoded_segment_cache() if frac is None else None
    names = [
        c.name
        for c in ds.columns
        if columns is None or c.name in columns
    ]
    dict_keys = {
        n: (ds.dicts[n].content_key if n in ds.dicts else None)
        for n in names
    }
    segs = list(ds.segments)
    pc = current_partial()
    if pc is not None:
        # the fallback accumulates scope ACROSS the plan's tables (a join
        # decodes several) — never begin_pass here; _run_fallback owns it
        pc.add_scope(len(segs), *_row_counts(segs))
    parts: Dict[str, list] = {n: [] for n in names}
    draining = False
    with span(SPAN_FALLBACK_DECODE, datasource=ds.name):
        for seg in segs:
            # per-segment decode is the fallback's unit of work; on
            # expiry the segments decoded so far (whole rows, every
            # column aligned) become the partial input
            if draining or checkpoint_partial("fallback.decode"):
                draining = True
                # drain mode (the collector already triggered — e.g.
                # _run_fallback's interpreter-expiry rerun): a segment
                # whose needed columns are ALL warm in the decoded-
                # segment cache is free to serve and counts as seen;
                # the first segment needing fresh decode work ends the
                # pass.  Whole segments stay row-aligned either way.
                if cache is None or any(
                    cache.get((seg.uid, "decoded", n, dict_keys[n]))
                    is None
                    for n in names
                ):
                    break
            for n in names:
                ckey = (seg.uid, "decoded", n, dict_keys[n])
                arr = cache.get(ckey) if cache is not None else None
                if arr is None:
                    arr = np.asarray(seg.column(n))[seg.valid]
                    if n in ds.dicts:
                        arr = ds.dicts[n].decode(arr)
                    elif arr.dtype.kind == "f":
                        arr = arr.astype(np.float64)
                    if frac is not None:
                        arr = arr[: int(len(arr) * frac)]
                    if cache is not None:
                        cache[ckey] = arr
                parts[n].append(arr)
            if pc is not None:
                pc.add_seen(1, *_row_counts((seg,)))
    out: Dict[str, np.ndarray] = {
        n: (
            np.concatenate(p) if p else np.array([], dtype=object)
        )
        for n, p in parts.items()
    }
    return pd.DataFrame(out)


def _plan_columns(lp: L.LogicalPlan) -> set:
    """Every column name any expression in the plan references (a superset
    per table — good enough to bound the decode)."""
    cols: set = set()

    def from_expr(e):
        if isinstance(e, Expr):
            cols.update(e.columns())

    if isinstance(lp, L.Filter):
        from_expr(lp.condition)
    elif isinstance(lp, L.Project):
        for _, e in lp.exprs:
            from_expr(e)
    elif isinstance(lp, L.Join):
        cols.update(lp.left_keys)
        cols.update(lp.right_keys)
    elif isinstance(lp, L.Aggregate):
        for _, e in lp.group_exprs:
            from_expr(e)
        for ae in lp.agg_exprs:
            if ae.arg is not None:
                from_expr(ae.arg)
            if ae.filter is not None:
                from_expr(ae.filter)
        for _, e in lp.post_exprs:
            from_expr(e)
    elif isinstance(lp, L.Having):
        from_expr(lp.condition)
    elif isinstance(lp, L.Window):
        for w in lp.wins:
            for e in (w.arg, w.filter, *w.partition, *w.order_exprs):
                if e is not None:
                    from_expr(e)
        for _, e in lp.out_exprs:
            from_expr(e)
    elif isinstance(lp, L.Sort):
        for k in lp.keys:
            from_expr(k.expr)
    for child in lp.children():
        cols |= _plan_columns(child)
    return cols


def _apply_mask(df: pd.DataFrame, mask) -> pd.DataFrame:
    """Row-select with scalar-safety: a constant predicate (e.g. resolved
    EXISTS) keeps or drops everything."""
    m = np.asarray(mask)
    if m.ndim == 0:
        return df if bool(m) else df.iloc[0:0]
    return df[m.astype(bool)]


def _eval(e: Expr, df: pd.DataFrame) -> np.ndarray:
    fn = compile_expr(e, raw_strings=True)
    cols = {c: np.asarray(df[c]) for c in df.columns}
    return np.asarray(fn(cols))


class _SubqNull(E.Literal):
    """A NULL that arrived as a VALUE (empty or NULL-valued scalar
    subquery), as opposed to the parser's `== Literal(None)` IS-NULL
    encoding (sql/parser.py): comparing anything against it is UNKNOWN,
    not an IS NULL test."""


def _is_null_lit(s) -> bool:
    return isinstance(s, E.Literal) and (
        s.value is None
        or (isinstance(s.value, float) and np.isnan(s.value))
    )


def _eval_memo(e: Expr, df: pd.DataFrame, memo) -> np.ndarray:
    """_eval with per-filter memoization: the Kleene evaluator reads each
    comparison operand once for its value and once for its null mask, and
    Expr nodes are frozen/hashable — share the evaluation."""
    if memo is None:
        return _eval(e, df)
    try:
        v = memo.get(e)
    except TypeError:  # unhashable literal payload
        return _eval(e, df)
    if v is None:
        v = memo[e] = _eval(e, df)
    return v


def _null_rows(e: Expr, df: pd.DataFrame, memo=None) -> np.ndarray:
    """Per-row SQL-NULL mask of a VALUE expression over a decoded frame
    (decoded dims hold None, metrics hold NaN — pd.isna covers both)."""
    n = len(df)
    if isinstance(e, E.Literal):
        return np.full(n, _is_null_lit(e), dtype=bool)
    v = np.asarray(_eval_memo(e, df, memo))
    if v.ndim == 0:
        return np.full(n, bool(pd.isna(v[()])), dtype=bool)
    return np.asarray(pd.isna(v))


def _coerce_bool(v, n: int) -> np.ndarray:
    v = np.asarray(v)
    if v.ndim == 0:
        return np.full(n, bool(v), dtype=bool)
    return v.astype(bool)


def _eval3(e: Expr, df: pd.DataFrame, memo=None):
    """Kleene three-valued evaluation of a boolean expression: returns
    (true_mask, unknown_mask).  A filter keeps only TRUE rows; the
    two-valued NULL->False coalescing `_eval` does is indistinguishable
    from that in positive positions but wrong under negation (SQL:
    NOT UNKNOWN = UNKNOWN, while NOT False = True) — the round-2 advisor
    demonstrated wrong answers on exactly those shapes."""
    n = len(df)
    F = np.zeros(n, dtype=bool)

    if isinstance(e, E.BoolOp):
        parts = [_eval3(x, df, memo) for x in e.operands]
        if e.op == "not":
            t, u = parts[0]
            return ~t & ~u, u
        ts = [p[0] for p in parts]
        fs = [~p[0] & ~p[1] for p in parts]
        if e.op == "and":
            t = np.logical_and.reduce(ts)
            f = np.logical_or.reduce(fs)
        else:
            t = np.logical_or.reduce(ts)
            f = np.logical_and.reduce(fs)
        return t, ~t & ~f
    if isinstance(e, E.Comparison):
        lnull, rnull = _is_null_lit(e.left), _is_null_lit(e.right)
        if lnull or rnull:
            value_null = isinstance(e.left, _SubqNull) or isinstance(
                e.right, _SubqNull
            )
            if e.op in ("==", "!=") and not value_null:
                # the parser's IS [NOT] NULL encoding — two-valued
                other = e.right if lnull else e.left
                isn = _null_rows(other, df, memo)
                return (isn if e.op == "==" else ~isn), F
            # a genuine NULL comparison value: UNKNOWN for every row
            # (even rows whose operand is itself NULL)
            return F, ~F
        u = _null_rows(e.left, df, memo) | _null_rows(e.right, df, memo)
        return _coerce_bool(_eval(e, df), n) & ~u, u
    if isinstance(e, E.InExpr):
        if not e.values:
            return F, F  # x IN () is FALSE for every x, even NULL x
        vals = tuple(v for v in e.values if v is not None)
        u_op = _null_rows(e.operand, df, memo)
        if len(vals) != len(e.values):
            # a literal NULL in the list: `x IN (..., NULL)` is TRUE for
            # members and UNKNOWN for EVERYTHING else (same shape as the
            # NULL-producing subquery rewrite) — even when the stripped
            # list is empty (`x IN (NULL)` matches nothing, unknowably)
            t = (
                _coerce_bool(_eval(E.InExpr(e.operand, vals), df), n)
                & ~u_op
                if vals
                else F
            )
            return t, ~t
        return _coerce_bool(_eval(e, df), n) & ~u_op, u_op
    if isinstance(e, E.LikeExpr):
        # covers NOT LIKE too: a NULL operand is UNKNOWN either way
        u = _null_rows(e.operand, df, memo)
        return _coerce_bool(_eval(e, df), n) & ~u, u
    if isinstance(e, E.Literal):
        if _is_null_lit(e):
            return F, ~F
        return np.full(n, bool(e.value), dtype=bool), F
    # generic boolean-valued expression (CASE, cast, ...): a NULL result
    # is UNKNOWN, everything else coerces
    v = np.asarray(_eval(e, df))
    if v.ndim == 0:
        return np.full(n, bool(v), dtype=bool), F
    u = np.asarray(pd.isna(v))
    return np.where(u, False, v).astype(bool), u


def _filter_mask(cond: Expr, df: pd.DataFrame) -> np.ndarray:
    t, _ = _eval3(cond, df, memo={})
    return t


def _agg_one(ae: L.AggExpr, df: pd.DataFrame):
    """One aggregate over (a filtered view of) one group's rows."""
    fn = ae.fn.lower()
    if ae.filter is not None:
        pre_n = len(df)
        df = df[_filter_mask(ae.filter, df)]
        if pre_n and not len(df):
            # Druid's filtered aggregator over a NON-empty group whose
            # filter matches nothing: additive aggregates are 0, AVG's
            # 0/0 division is 0, extrema/quantiles are NULL — the device
            # engine's convention, pinned by the fuzz oracle
            if fn in ("sum", "avg"):
                return 0.0
            if fn.startswith("count") or fn.startswith(
                "approx_count_distinct"
            ):
                return 0
            return np.nan
    if fn == "count" and ae.arg is None and not ae.distinct:
        return len(df)
    arg = (
        np.asarray(_eval(ae.arg, df))
        if ae.arg is not None
        else np.ones(len(df))
    )
    if fn in (
        "count_distinct",
        "approx_count_distinct",
        "approx_count_distinct_ds_theta",
        "approx_count_distinct_ds_hll",
    ) or (fn == "count" and ae.distinct):
        # all distinct variants evaluate EXACTLY here (host pandas)
        return pd.Series(arg).nunique(dropna=True)
    if fn == "count":
        return int(pd.Series(arg).notna().sum())
    if fn == "approx_quantile":
        vals = pd.Series(arg).dropna().astype(np.float64)
        if not len(vals):
            return np.nan
        return float(np.quantile(vals, float(ae.args[0])))
    vals = pd.Series(arg, dtype=np.float64)
    if ae.distinct:
        # the device engine refuses SUM/AVG(DISTINCT) (partial aggregation
        # cannot deduplicate); host execution does it exactly
        vals = vals.drop_duplicates()
    if not len(vals):
        return np.nan  # SQL: aggregate over zero rows is NULL
    if fn == "sum":
        # min_count=1: SUM over all-NULL rows is NULL, not pandas' 0
        return vals.sum(min_count=1)
    return {
        "min": vals.min,
        "max": vals.max,
        "avg": vals.mean,
    }[fn]()


def _aggregate(node: L.Aggregate, df: pd.DataFrame) -> pd.DataFrame:
    def _vectorized_set(keys) -> Optional[pd.DataFrame]:
        """Vectorized grouped aggregation for the plain shapes (sum / min /
        max / avg / count, unfiltered, non-distinct): one pandas C groupby
        instead of a per-group Python loop — the loop cost ~25s of a
        q18-class 21K-group interpretation (measured).  Returns None when
        any aggregate needs the exact per-group path."""
        for ae in node.agg_exprs:
            if (
                ae.fn.lower() not in ("sum", "min", "max", "avg", "count")
                or ae.filter is not None
                or ae.distinct
            ):
                return None
        kf = pd.DataFrame(
            {name: _eval(e, df) for name, e in keys}, index=df.index
        )
        if not node.agg_exprs:
            # pure DISTINCT-keys shape (the EXISTS decorrelator emits it)
            return kf.drop_duplicates().reset_index(drop=True)
        if any(ae.name in kf.columns for ae in node.agg_exprs):
            return None  # aggregate shadowing a group key: exact path
        tmp = kf.copy()
        specs = {}
        fixups = []  # (agg name, count helper) for SUM's min_count=1 rule
        for i, ae in enumerate(node.agg_exprs):
            fn = ae.fn.lower()
            cn = f"__a{i}"
            if fn == "count" and ae.arg is None:
                tmp[cn] = np.ones(len(df))
                specs[ae.name] = (cn, "count")
                continue
            arg = np.asarray(_eval(ae.arg, df)) if ae.arg is not None else (
                np.ones(len(df))
            )
            if fn == "count":
                tmp[cn] = pd.Series(arg, index=df.index)
                specs[ae.name] = (cn, "count")
                continue
            tmp[cn] = pd.Series(arg, index=df.index, dtype=np.float64)
            specs[ae.name] = (cn, "mean" if fn == "avg" else fn)
            if fn == "sum":
                # SQL: SUM over all-NULL rows is NULL, not pandas' 0
                helper = f"__n{i}"
                specs[helper] = (cn, "count")
                fixups.append((ae.name, helper))
        out = (
            tmp.groupby(list(kf.columns), dropna=False, sort=False)
            .agg(**specs)
            .reset_index()
        )
        for name, helper in fixups:
            out.loc[out[helper] == 0, name] = np.nan
            out = out.drop(columns=[helper])
        return out[
            [n for n, _ in keys] + [ae.name for ae in node.agg_exprs]
        ]

    def one_set(indices) -> pd.DataFrame:
        keys = [node.group_exprs[i] for i in indices]
        if keys:
            fast = _vectorized_set(keys)
            if fast is not None:
                return fast
            kf = pd.DataFrame(
                {name: _eval(e, df) for name, e in keys},
                index=df.index,
            )
            grouped = df.groupby(
                [kf[n] for n, _ in keys], dropna=False, sort=False
            )
            from ..resilience import checkpoint

            rows = []
            for i, (gv, gdf) in enumerate(grouped):
                if i % 256 == 0:  # the q18-class per-group Python loop
                    checkpoint("fallback.group_loop")
                gv = gv if isinstance(gv, tuple) else (gv,)
                row = dict(zip((n for n, _ in keys), gv))
                for ae in node.agg_exprs:
                    row[ae.name] = _agg_one(ae, gdf)
                rows.append(row)
            out = pd.DataFrame(
                rows,
                columns=[n for n, _ in keys]
                + [ae.name for ae in node.agg_exprs],
            )
        else:
            out = pd.DataFrame(
                [{ae.name: _agg_one(ae, df) for ae in node.agg_exprs}]
            )
        return out

    if node.grouping_sets:
        k = len(node.group_exprs)
        frames = []
        for s in node.grouping_sets:
            f = one_set(s)
            gid = 0
            present = set(s)
            for i in range(k):
                if i not in present:
                    gid |= 1 << (k - 1 - i)
                    f[node.group_exprs[i][0]] = None
            f["__grouping_id"] = gid
            frames.append(f)
        out = pd.concat(frames, ignore_index=True)
        order = [n for n, _ in node.group_exprs]
        out = out[order + [c for c in out.columns if c not in order]]
    else:
        out = one_set(range(len(node.group_exprs)))
    # post-aggregate projections (exprs over agg outputs) — applied to the
    # plain and grouping-set shapes alike.  NO projection here: enclosing
    # Sort/Having nodes may reference group columns or hidden helpers; the
    # user-facing SELECT projection happens once at the fallback root.
    for name, pe in node.post_exprs:
        if isinstance(pe, E.Col) and pe.name in out.columns:
            if name != pe.name:
                out[name] = out[pe.name]  # SELECT alias of a group column
            continue
        out[name] = _eval(_refs_to_cols(pe), out)
    return out


def _refs_to_cols(e: Expr) -> Expr:
    """AggRef -> Col so result-frame expressions compile generically."""
    import dataclasses

    if isinstance(e, E.AggRef):
        return E.Col(e.name)
    kw = {}
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, Expr):
            kw[f.name] = _refs_to_cols(v)
        elif isinstance(v, tuple) and v and isinstance(v[0], Expr):
            kw[f.name] = tuple(_refs_to_cols(x) for x in v)
    return dataclasses.replace(e, **kw) if kw else e


def _needs_all_columns(lp: L.LogicalPlan, under_project: bool = False) -> bool:
    """True when some Scan reaches the root without a Project/Aggregate
    above it (SELECT *): its table's every column is part of the result, so
    decode pruning must not apply."""
    if isinstance(lp, L.Scan):
        return not under_project
    if isinstance(lp, L.SubqueryScan):
        # the inner plan decides its own needs (its _exec call passes
        # _needed=None anyway)
        return _needs_all_columns(lp.child, under_project)
    up = under_project or isinstance(
        lp, (L.Project, L.Aggregate, L.Window)
    )
    return any(_needs_all_columns(c, up) for c in lp.children())


def _select_list(lp: L.LogicalPlan):
    """The user-facing output column list: the outermost Project's names,
    or the outermost Aggregate's SELECT items (post_exprs) when present.
    None = no explicit list (SELECT *): return everything non-internal."""
    if isinstance(lp, (L.Limit, L.Sort, L.Having)):
        return _select_list(lp.children()[0])
    if isinstance(lp, L.Window):
        return [n for n, _ in lp.out_exprs]
    if isinstance(lp, L.Union):
        # branch frames are already projected/aligned to the first
        # branch's names
        return _select_list(lp.branches[0])
    if isinstance(lp, L.Project):
        return [n for n, _ in lp.exprs]
    if isinstance(lp, L.Aggregate):
        if lp.post_exprs:
            return [n for n, _ in lp.post_exprs]
        return [n for n, _ in lp.group_exprs] + [
            ae.name
            for ae in lp.agg_exprs
            if not ae.name.startswith("__agg")
        ]
    return None


def _run_in_subquery(sub, catalog):
    """Execute an InSubquery's inner statement: (values, has_null)."""
    from ..sql.parser import Analyzer

    inner_lp = Analyzer(sub.stmt, dict(sub.aliases or ())).to_logical()
    inner = execute_fallback(inner_lp, catalog)
    if inner.shape[1] != 1:
        raise ValueError("IN subquery must produce exactly one column")
    col = inner.iloc[:, 0]
    return tuple(pd.unique(col.dropna())), bool(col.isna().any())


def _resolve_subqueries(e, catalog, bool_ctx: bool = False):
    """Replace subquery nodes with concrete values.

    Three-valued semantics are preserved STRUCTURALLY and left to the
    Kleene evaluator (`_eval3`): an IN-subquery whose result set contained
    NULL becomes `(x IN S) OR NULL` — TRUE for members, UNKNOWN for
    everything else, which is exactly SQL's `x IN (S + {NULL})` in every
    context including arbitrary negation nesting (the round-2 rejection
    special-cases are gone).  NULL-valued scalar subqueries become
    `_SubqNull` so comparisons against them stay UNKNOWN instead of
    colliding with the parser's `== Literal(None)` IS-NULL encoding.

    `bool_ctx` is True only along the BOOLEAN SKELETON of a Filter/Having
    condition (BoolOp chains down to boolean leaves) — the positions the
    Kleene evaluator owns.  In VALUE positions (SELECT list, aggregate
    arguments, comparison operands) the `OR NULL` form would reach the
    two-valued compiler and crash, so those keep the plain InExpr
    approximation (UNKNOWN coalesces to FALSE) the round-2 resolver had."""
    import dataclasses as _dc

    from ..plan.expr import (
        BoolOp,
        Expr,
        InExpr,
        InSubquery,
        Literal,
    )

    if isinstance(
        e, (InSubquery, E.ExistsSubquery, E.ScalarSubquery)
    ) and getattr(e, "outer_refs", None):
        # CORRELATED: cannot resolve to a constant — left in place for
        # _materialize_correlated to evaluate per outer binding
        return e
    if isinstance(e, InSubquery):
        vals, has_null = _run_in_subquery(e, catalog)
        operand = _resolve_subqueries(e.operand, catalog)
        base = InExpr(operand, vals)
        if has_null and bool_ctx:
            return BoolOp("or", (base, _SubqNull(None)))
        return base
    if isinstance(e, E.ExistsSubquery):
        from ..sql.parser import Analyzer

        inner_lp = Analyzer(e.stmt, dict(e.aliases or ())).to_logical()
        inner = execute_fallback(inner_lp, catalog)
        # constant truth value; Filter/Having handle scalar masks
        return Literal(bool(len(inner)))
    if isinstance(e, E.ScalarSubquery):
        from ..sql.parser import Analyzer

        inner_lp = Analyzer(e.stmt, dict(e.aliases or ())).to_logical()
        inner = execute_fallback(inner_lp, catalog)
        if inner.shape[1] != 1:
            raise ValueError(
                "scalar subquery must produce exactly one column"
            )
        if len(inner) > 1:
            raise ValueError(
                f"scalar subquery produced {len(inner)} rows"
            )
        if not len(inner):
            return _SubqNull(None)  # zero rows -> SQL NULL value
        v = inner.iloc[0, 0]
        if pd.isna(v):
            return _SubqNull(None)
        if isinstance(v, (np.integer,)):
            v = int(v)
        elif isinstance(v, (np.floating,)):
            v = float(v)
        return Literal(v)
    if not isinstance(e, Expr):
        return e
    # bool_ctx survives only through BoolOp (the skeleton _eval3 walks);
    # any other node's operands are value positions
    child_ctx = bool_ctx and isinstance(e, BoolOp)
    kw = {}
    for f in _dc.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, Expr):
            kw[f.name] = _resolve_subqueries(v, catalog, child_ctx)
        elif isinstance(v, tuple) and v and isinstance(v[0], Expr):
            kw[f.name] = tuple(
                _resolve_subqueries(x, catalog, child_ctx) for x in v
            )
    return _dc.replace(e, **kw) if kw else e


def _substitute_outer(stmt, binding):
    """A correlated subquery's statement with its outer references bound:
    every `Col(alias.col)` in `binding` becomes a Literal — or `_SubqNull`
    for a NULL binding, so comparisons against it stay UNKNOWN (an inner
    `WHERE s.k = <null binding>` matches nothing, per SQL)."""
    import dataclasses as _dc

    from ..plan.expr import Col, Expr, Literal

    def conv(v):
        if v is None or (isinstance(v, float) and np.isnan(v)):
            return _SubqNull(None)
        if isinstance(v, np.integer):
            v = int(v)
        elif isinstance(v, np.floating):
            v = float(v)
        elif isinstance(v, np.str_):
            v = str(v)
        return Literal(v)

    from ..plan.expr import map_expr

    def sub_e(e):
        return map_expr(
            e,
            lambda x: conv(binding[x.name])
            if isinstance(x, Col) and x.name in binding
            else x,
        )

    return _dc.replace(
        stmt,
        items=[(n, sub_e(e)) for n, e in stmt.items],
        where=sub_e(stmt.where) if stmt.where is not None else None,
        having=sub_e(stmt.having) if stmt.having is not None else None,
        group_by=[sub_e(e) for e in stmt.group_by],
        order_by=[(sub_e(e), a) for e, a in stmt.order_by],
    )


def _broadcast_rows(vals, n: int) -> np.ndarray:
    """Per-row value array for an expression result: a CONSTANT operand
    (e.g. `10 IN (SELECT ...)`) evaluates 0-d and must broadcast."""
    a = np.asarray(vals)
    if a.ndim == 0:
        return np.full(n, a[()], dtype=object)
    return a


def _expr_has_outer(e, refs: set) -> bool:
    return E.any_node(
        e, lambda x: isinstance(x, E.Col) and x.name in refs
    )


def _expr_has_subquery(e) -> bool:
    return E.any_node(
        e,
        lambda x: isinstance(
            x, (E.InSubquery, E.ScalarSubquery, E.ExistsSubquery)
        ),
    )


def _try_decorrelate_fill(sub, df, catalog, refs, out) -> bool:
    """TRUE single-pass decorrelation for the common shape — every outer
    reference appears only as a top-level equality conjunct
    `inner_col = o.outer_col` in the subquery's WHERE: rewrite to ONE
    grouped/projected execution over the inner table and join the result
    back by key.  Turns O(distinct bindings x inner scan) into O(inner
    scan).  Returns False when the shape doesn't qualify (the caller's
    per-binding loop remains the complete path)."""
    import dataclasses as _dc

    from ..sql.parser import Analyzer, _contains_agg

    stmt = sub.stmt
    refset = set(refs)
    if stmt.limit is not None or stmt.offset or stmt.distinct:
        return False
    if stmt.group_by or stmt.grouping_sets or stmt.having is not None:
        return False
    if any(
        isinstance(e, E.Col) and e.name == "*" for _, e in stmt.items
    ):
        return False  # SELECT *: the Analyzer would discard synthetic items
    # nested subqueries inside the correlated statement: too opaque
    exprs = [e for _, e in stmt.items]
    if stmt.where is not None:
        exprs.append(stmt.where)
    if any(_expr_has_subquery(e) for e in exprs):
        return False
    for _, e in stmt.items:
        if _expr_has_outer(e, refset):
            return False
    for e, _ in stmt.order_by:
        if _expr_has_outer(e, refset) or _expr_has_subquery(e):
            return False

    # split WHERE: equality-correlation conjuncts vs residual
    def conjuncts(e):
        if isinstance(e, E.BoolOp) and e.op == "and":
            outl = []
            for o in e.operands:
                outl.extend(conjuncts(o))
            return outl
        return [e]

    eq_pairs = []  # (inner bare col, outer qualified ref)
    residual = []
    used = set()
    for c in conjuncts(stmt.where) if stmt.where is not None else []:
        pair = None
        if isinstance(c, E.Comparison) and c.op == "==":
            for a, b2 in ((c.left, c.right), (c.right, c.left)):
                if (
                    isinstance(a, E.Col)
                    and a.name in refset
                    and isinstance(b2, E.Col)
                    and b2.name not in refset
                    and "." not in b2.name
                ):
                    pair = (b2.name, a.name)
                    break
        if pair is not None:
            if pair not in eq_pairs:  # duplicate conjunct: one key column
                eq_pairs.append(pair)
            used.add(pair[1])
            continue
        if _expr_has_outer(c, refset):
            return False  # outer ref outside the equality form
        residual.append(c)
    if not eq_pairs or used != refset:
        return False

    has_agg_item = any(_contains_agg(e) for _, e in stmt.items)
    if isinstance(sub, (E.ExistsSubquery, E.InSubquery)) and has_agg_item:
        # an aggregate subquery yields exactly one row regardless of
        # matches (EXISTS is then always true) — keep the exact loop
        return False
    if isinstance(sub, E.ScalarSubquery) and not has_agg_item:
        return False  # per-binding >1-row detection must stay exact

    res_where = None
    for c in residual:
        res_where = c if res_where is None else E.BoolOp("and", (res_where, c))

    key_cols = [ic for ic, _ in eq_pairs]
    key_names = [f"__dk{i}" for i in range(len(eq_pairs))]

    # outer-side key per row (None anywhere -> can never match)
    outer_cols = [q.split(".", 1)[1] for _, q in eq_pairs]
    ocols = [np.asarray(df[c]) for c in outer_cols]
    onull = np.zeros(len(df), dtype=bool)
    for c in ocols:
        onull |= np.asarray(pd.isna(c))

    # outer key frame in df row order: every branch below resolves outer
    # rows against inner results with an order-preserving left merge (a
    # pandas hash join) instead of a per-row Python loop — the loops cost
    # O(outer rows) interpreter time on TPC-H q2/q17-class queries
    okf = pd.DataFrame(
        {n: c for n, c in zip(key_names, ocols)}, copy=False
    )

    if isinstance(sub, E.ExistsSubquery):
        stmt2 = _dc.replace(
            stmt,
            items=[(n, E.Col(ic)) for n, ic in zip(key_names, key_cols)],
            where=res_where,
            group_by=[E.Col(ic) for ic in key_cols],  # distinct keys
            order_by=[],
        )
        inner = execute_fallback(
            Analyzer(stmt2, dict(sub.aliases or ())).to_logical(), catalog
        )
        kf = inner[key_names]
        ok = ~kf.isna().any(axis=1)
        m = okf.merge(
            kf[ok].drop_duplicates(), on=key_names, how="left",
            indicator=True,
        )
        hit = (m["_merge"].to_numpy() == "both") & ~onull
        out[:] = hit
        return True

    if isinstance(sub, E.InSubquery):
        if len(stmt.items) != 1:
            return False
        stmt2 = _dc.replace(
            stmt,
            items=[(n, E.Col(ic)) for n, ic in zip(key_names, key_cols)]
            + [("__dv", stmt.items[0][1])],
            where=res_where,
            order_by=[],
        )
        inner = execute_fallback(
            Analyzer(stmt2, dict(sub.aliases or ())).to_logical(), catalog
        )
        kf = inner[key_names]
        ok = ~kf.isna().any(axis=1)
        op_vals = _broadcast_rows(_eval(sub.operand, df), len(df))
        op_null = np.asarray(pd.isna(op_vals))

        inner_ok = inner[ok]
        dv_null = inner_ok["__dv"].isna()
        # per-key stats: does the key's value set contain NULL / anything
        per_key = (
            pd.DataFrame(
                {
                    **{n: inner_ok[n] for n in key_names},
                    "__hasnull": dv_null.to_numpy(),
                    "__nvals": (~dv_null).to_numpy().astype(np.int64),
                }
            )
            .groupby(key_names, as_index=False, dropna=False)
            .agg(__hasnull=("__hasnull", "any"), __nvals=("__nvals", "sum"))
        )
        m = okf.merge(per_key, on=key_names, how="left")
        key_has_null = m["__hasnull"].fillna(False).to_numpy(dtype=bool)
        key_has_vals = m["__nvals"].fillna(0).to_numpy() > 0
        # null outer key: matches nothing, set treated as empty
        key_has_null &= ~onull
        key_has_vals &= ~onull

        # direct (key, value) hit: merge on keys + operand value; pandas
        # merge treats NaN as equal so null values/operands are excluded
        okv = okf.assign(__op=op_vals)
        iv = pd.DataFrame(
            {
                **{n: inner_ok[n][~dv_null.to_numpy()] for n in key_names},
                "__op": inner_ok["__dv"][~dv_null.to_numpy()],
            }
        ).drop_duplicates()
        mh = okv.merge(iv, on=key_names + ["__op"], how="left",
                       indicator=True)
        direct_hit = (
            (mh["_merge"].to_numpy() == "both") & ~op_null & ~onull
        )

        res = np.empty(len(df), dtype=object)
        res[:] = False
        empty_set = ~key_has_vals & ~key_has_null
        unknown = (key_has_null | op_null) & ~empty_set & ~direct_hit
        res[unknown] = None
        res[direct_hit] = True
        out[:] = res
        return True

    # ScalarSubquery with an aggregate item: group the aggregate by the
    # correlation keys; absent keys take the aggregate-over-empty value
    # (COUNT -> 0, SUM/AVG/MIN/MAX -> NULL), measured by executing the
    # original ungrouped aggregate over zero rows
    if len(stmt.items) != 1:
        return False
    stmt2 = _dc.replace(
        stmt,
        items=[(n, E.Col(ic)) for n, ic in zip(key_names, key_cols)]
        + [("__dv", stmt.items[0][1])],
        where=res_where,
        group_by=[E.Col(ic) for ic in key_cols],
        order_by=[],
    )
    inner = execute_fallback(
        Analyzer(stmt2, dict(sub.aliases or ())).to_logical(), catalog
    )
    false_where = E.Literal(False)
    if res_where is not None:
        false_where = E.BoolOp("and", (res_where, false_where))
    stmt_empty = _dc.replace(stmt, where=false_where, order_by=[])
    empty = execute_fallback(
        Analyzer(stmt_empty, dict(sub.aliases or ())).to_logical(), catalog
    )
    neutral = empty.iloc[0, 0] if len(empty) else None
    if neutral is not None and pd.isna(neutral):
        neutral = None
    kf = inner[key_names]
    ok = ~kf.isna().any(axis=1)
    m = okf.merge(
        inner[ok][key_names + ["__dv"]].drop_duplicates(key_names),
        on=key_names, how="left", indicator=True,
    )
    matched = (m["_merge"].to_numpy() == "both") & ~onull
    vals = np.array(m["__dv"], dtype=object)  # copy: owned, writable
    vals[pd.isna(vals)] = None  # aggregated NULL stays None, not NaN
    res = np.full(len(df), neutral, dtype=object)
    res[matched] = vals[matched]
    out[:] = res
    return True


def _correlated_column(sub, df: pd.DataFrame, catalog) -> pd.Series:
    """Evaluate a correlated subquery for every row of the outer frame —
    once per DISTINCT binding of its outer references (decorrelation by
    grouping), joined back positionally.  The common equality-correlated
    shape takes a TRUE single-pass decorrelation first
    (_try_decorrelate_fill): one grouped execution of the inner table,
    joined back by key.

    Column contents by node type:
    * InSubquery     -> object True / False / None (None = UNKNOWN — the
      Kleene evaluator owns three-valued logic from there)
    * ExistsSubquery -> bool
    * ScalarSubquery -> the scalar per row (None -> NULL); all-numeric
      results downcast to float64 so comparisons run vectorized
    """
    from ..sql.parser import Analyzer

    refs = list(sub.outer_refs)
    bare = [q.split(".", 1)[1] for q in refs]
    missing = [b for b in bare if b not in df.columns]
    if missing:
        raise KeyError(
            f"correlated subquery references outer columns {missing} "
            "not present in the outer frame"
        )
    out = np.empty(len(df), dtype=object)
    if _try_decorrelate_fill(sub, df, catalog, refs, out):
        return _correlated_series(sub, out, df)
    if isinstance(sub, E.InSubquery):
        op_vals = _broadcast_rows(_eval(sub.operand, df), len(df))
        op_null = np.asarray(pd.isna(op_vals))
    # .indices maps each distinct binding to POSITIONAL row indices
    grouped = df.groupby(bare, dropna=False).indices
    for key, ilocs in grouped.items():
        tup = key if isinstance(key, tuple) else (key,)
        binding = {
            q: (None if pd.isna(v) else v) for q, v in zip(refs, tup)
        }
        stmt2 = _substitute_outer(sub.stmt, binding)
        if (
            isinstance(sub, E.ExistsSubquery)
            and stmt2.limit is None
            and not stmt2.offset
        ):
            # existence only needs the first row — but a USER-written
            # LIMIT/OFFSET changes which rows exist and must be honored
            import dataclasses as _dc

            stmt2 = _dc.replace(stmt2, limit=1, offset=0)
        inner_lp = Analyzer(stmt2, dict(sub.aliases or ())).to_logical()
        inner = execute_fallback(inner_lp, catalog)
        if isinstance(sub, E.ExistsSubquery):
            out[ilocs] = bool(len(inner))
        elif isinstance(sub, E.ScalarSubquery):
            if inner.shape[1] != 1:
                raise ValueError(
                    "scalar subquery must produce exactly one column"
                )
            if len(inner) > 1:
                raise ValueError(
                    f"scalar subquery produced {len(inner)} rows"
                )
            v = inner.iloc[0, 0] if len(inner) else None
            if v is not None and pd.isna(v):
                v = None
            out[ilocs] = v
        else:  # InSubquery
            if inner.shape[1] != 1:
                raise ValueError(
                    "IN subquery must produce exactly one column"
                )
            col = inner.iloc[:, 0]
            vals = set(pd.unique(col.dropna()))
            has_null = bool(col.isna().any())
            for i in ilocs:
                if not op_null[i] and op_vals[i] in vals:
                    out[i] = True
                elif not vals and not has_null:
                    out[i] = False  # IN over an EMPTY set: FALSE, even NULL
                elif has_null or op_null[i]:
                    out[i] = None  # UNKNOWN
                else:
                    out[i] = False
    return _correlated_series(sub, out, df)


def _correlated_series(sub, out, df) -> pd.Series:
    ser = pd.Series(out, index=df.index)
    if isinstance(sub, E.ScalarSubquery):
        nn = [v for v in out if v is not None]
        if not nn:
            # every binding yielded NULL: float64 NaN keeps ordering
            # comparisons well-defined (object None would raise)
            return ser.astype(np.float64)
        if all(isinstance(v, (int, float, np.number)) for v in nn):
            # float64 vectorizes comparisons and None -> NaN carries NULL
            # semantics — but only when it is EXACT: int64 values at or
            # above 2^53 would round and silently match wrong rows
            if all(
                isinstance(v, (float, np.floating)) or abs(int(v)) < (1 << 53)
                for v in nn
            ):
                return ser.astype(np.float64)
    return ser


import itertools

_CSQ_IDS = itertools.count()  # temp-column namespace for correlated values


def _materialize_correlated(e, df: pd.DataFrame, catalog):
    """Replace every CORRELATED subquery node in an expression with a
    `Col` over a temp per-row column (see _correlated_column); returns
    (expression, frame-with-temp-columns).  After this, the ordinary
    two- and three-valued evaluators need no subquery knowledge."""
    from ..plan.expr import map_expr

    if not isinstance(e, Expr):
        return e, df
    added = {}

    def repl(x):
        if isinstance(
            x, (E.InSubquery, E.ExistsSubquery, E.ScalarSubquery)
        ) and getattr(x, "outer_refs", None):
            # PROCESS-UNIQUE temp name: callers (the Aggregate branch)
            # materialize several expressions into ONE accumulated frame,
            # and a per-call counter would collide and silently alias two
            # different subqueries (review-confirmed wrong answer)
            name = f"__csq{next(_CSQ_IDS)}"
            added[name] = _correlated_column(x, df, catalog)
            return E.Col(name)
        return x

    e2 = map_expr(e, repl)
    if not added:
        return e, df
    df2 = df.copy(deep=False)
    for k, v in added.items():
        df2[k] = v
    return e2, df2


def _resolve_plan_subqueries(lp: L.LogicalPlan, catalog) -> L.LogicalPlan:
    """Resolve IN-subqueries in every expression the plan holds."""
    import dataclasses as _dc

    def rx(e):
        return _resolve_subqueries(e, catalog) if e is not None else None

    def rx_bool(e):
        # Filter/Having conditions and aggregate FILTER clauses are owned
        # by the Kleene evaluator — subqueries there may resolve to the
        # three-valued `(x IN S) OR NULL` form
        return (
            _resolve_subqueries(e, catalog, bool_ctx=True)
            if e is not None
            else None
        )

    if isinstance(lp, L.Filter):
        return L.Filter(
            rx_bool(lp.condition),
            _resolve_plan_subqueries(lp.child, catalog),
        )
    if isinstance(lp, L.Having):
        return L.Having(
            rx_bool(lp.condition),
            _resolve_plan_subqueries(lp.child, catalog),
        )
    if isinstance(lp, L.Project):
        return L.Project(
            tuple((n, rx(e)) for n, e in lp.exprs),
            _resolve_plan_subqueries(lp.child, catalog),
        )
    if isinstance(lp, L.Aggregate):
        return _dc.replace(
            lp,
            group_exprs=tuple((n, rx(e)) for n, e in lp.group_exprs),
            agg_exprs=tuple(
                _dc.replace(ae, arg=rx(ae.arg), filter=rx_bool(ae.filter))
                for ae in lp.agg_exprs
            ),
            post_exprs=tuple((n, rx(e)) for n, e in lp.post_exprs),
            child=_resolve_plan_subqueries(lp.child, catalog),
        )
    if isinstance(lp, L.Sort):
        return L.Sort(
            tuple(
                _dc.replace(k, expr=rx(k.expr)) for k in lp.keys
            ),
            _resolve_plan_subqueries(lp.child, catalog),
        )
    if isinstance(lp, L.Window):
        return _dc.replace(
            lp,
            wins=tuple(
                _dc.replace(
                    w,
                    arg=rx(w.arg),
                    filter=rx_bool(w.filter),
                    partition=tuple(rx(p) for p in w.partition),
                    order_exprs=tuple(rx(o) for o in w.order_exprs),
                )
                for w in lp.wins
            ),
            out_exprs=tuple((n, rx(e)) for n, e in lp.out_exprs),
            child=_resolve_plan_subqueries(lp.child, catalog),
        )
    if isinstance(lp, (L.Limit, L.SubqueryScan)):
        return _dc.replace(
            lp, child=_resolve_plan_subqueries(lp.child, catalog)
        )
    if isinstance(lp, L.Union):
        return _dc.replace(
            lp,
            branches=tuple(
                _resolve_plan_subqueries(b, catalog) for b in lp.branches
            ),
        )
    if isinstance(lp, L.Join):
        return _dc.replace(
            lp,
            left=_resolve_plan_subqueries(lp.left, catalog),
            right=_resolve_plan_subqueries(lp.right, catalog),
        )
    return lp


def _project_root(df: pd.DataFrame, lp: L.LogicalPlan) -> pd.DataFrame:
    """Project an interpreted frame to the plan's SELECT list (enclosing
    Sort/Having saw every intermediate column; the consumer does not)."""
    sel = _select_list(lp)
    if sel is not None:
        missing = [c for c in sel if c not in df.columns]
        if missing:
            # a SELECT column no node materialized is a planner/interpreter
            # bug — fail loudly rather than return a narrower result
            raise KeyError(
                f"fallback result is missing SELECT columns {missing}"
            )
        df = df[list(sel)]
    else:
        internal = [
            c
            for c in df.columns
            if c.startswith("__agg") or c == "__grouping_id"
        ]
        df = df.drop(columns=internal)
    return df


def plan_tables(lp: L.LogicalPlan) -> set:
    """Every base table a plan scans (recursively, incl. derived tables
    and union branches; subqueries inside expressions are resolved later
    and guarded by their own execute_fallback pass)."""
    import dataclasses as _dc

    out: set = set()
    if isinstance(lp, L.Scan):
        out.add(lp.table)
        return out
    for f in _dc.fields(lp):
        v = getattr(lp, f.name)
        if isinstance(v, L.LogicalPlan):
            out |= plan_tables(v)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, L.LogicalPlan):
                    out |= plan_tables(x)
    return out


def plan_input_rows(lp: L.LogicalPlan, catalog) -> int:
    """Summed base-table row count a fallback execution would decode —
    the input-size guard's measure (single-threaded pandas; the ceiling
    keeps a mis-routed petabyte query from silently grinding)."""
    total = 0
    for t in plan_tables(lp):
        ds = catalog.get(t)
        if ds is not None:
            total += ds.num_rows
    return total


class FallbackSizeError(ValueError):
    """Fallback input exceeds SessionConfig.fallback_max_rows."""


# The active size ceiling, inherited by NESTED execute_fallback calls (IN /
# EXISTS / scalar subqueries execute their inner statements through the same
# entry point): a guard that only covered the outer plan's tables would be
# bypassed by `... WHERE k IN (SELECT x FROM huge)`.
import contextvars

_guard_max_rows = contextvars.ContextVar("fallback_guard_max_rows", default=0)
# device-assist hook (execute_fallback's device_exec): read wherever the
# interpreter meets an Aggregate subtree, including nested subquery plans
_device_exec = contextvars.ContextVar("fallback_device_exec", default=None)


def execute_fallback(
    lp: L.LogicalPlan, catalog, max_rows: int = 0, device_exec=None
) -> pd.DataFrame:
    """Interpret a logical plan over decoded host frames, projecting the
    result to the plan's SELECT list at the end.

    `max_rows` > 0 guards the input size: the fallback is single-threaded
    host pandas, and a clear refusal beats an unbounded grind.  Nested
    subquery executions inherit the caller's ceiling.

    `device_exec(subplan) -> DataFrame | None` is the device-assist hook
    (the reference's "push what you can" projection fixup, SURVEY.md §3.2
    fallback row): the interpreter offers every Aggregate subtree to it
    before interpreting host-side, so a window/subquery/set-op query whose
    GROUP BY base is device-eligible scans on the accelerator and only the
    (small) aggregated frame is windowed here.  A None return means "not
    plannable" and interpretation proceeds unchanged."""
    limit = max_rows or _guard_max_rows.get()
    if limit:
        rows_in = plan_input_rows(lp, catalog)
        if rows_in > limit:
            raise FallbackSizeError(
                f"host-fallback input is {rows_in:,} rows across "
                f"{sorted(plan_tables(lp))}, above the "
                f"fallback_max_rows ceiling of {limit:,}.  This query "
                "could not be rewritten to the accelerated engine; either "
                "restructure it (conforming star join / supported "
                "predicates), or raise the ceiling with "
                "SET fallback_max_rows."
            )
    token = _guard_max_rows.set(limit)
    dev_token = (
        _device_exec.set(device_exec) if device_exec is not None else None
    )
    try:
        lp = _resolve_plan_subqueries(lp, catalog)
        needed = (
            None if _needs_all_columns(lp) else (_plan_columns(lp) or None)
        )
        df = _exec(lp, catalog, needed)
        return _project_root(df, lp).reset_index(drop=True)
    finally:
        _guard_max_rows.reset(token)
        if dev_token is not None:
            _device_exec.reset(dev_token)


class _Null:
    """Sentinel standing in for SQL NULL inside set-operation row keys: set
    operations (unlike = comparison) treat NULLs as equal, and the decoded
    frames mix None / NaN / NaT representations that would not hash equal."""

    __slots__ = ()

    def __repr__(self):
        return "<null>"


_NULL = _Null()


def _row_keys(df: pd.DataFrame) -> list:
    """Hashable per-row keys with every null representation collapsed to
    one sentinel.  O(rows) Python — acceptable on the size-guarded
    fallback path."""
    arr = df.to_numpy(dtype=object)
    na = pd.isna(arr)
    return [
        tuple(_NULL if na[i, j] else arr[i, j] for j in range(arr.shape[1]))
        for i in range(arr.shape[0])
    ]


def _setop(op: str, frames: list) -> pd.DataFrame:
    """SQL set-operation semantics over positionally-aligned frames.
    Distinct variants keep the first occurrence of each row (from the
    leftmost frame that has it); ALL variants follow bag algebra
    (INTERSECT ALL: min multiplicity; EXCEPT ALL: left minus right)."""
    from collections import Counter

    if op == "union_all":
        return pd.concat(frames, ignore_index=True)
    if op == "union":
        cat = pd.concat(frames, ignore_index=True)
        keys = _row_keys(cat)
        seen, keep = set(), []
        for i, k in enumerate(keys):
            if k not in seen:
                seen.add(k)
                keep.append(i)
        return cat.iloc[keep].reset_index(drop=True)

    left = frames[0]
    lkeys = _row_keys(left)
    rkey_counts = [Counter(_row_keys(f)) for f in frames[1:]]
    if op in ("intersect", "intersect_all"):
        # multiplicity budget per key: min across ALL branches (n-ary:
        # intersect is associative); distinct variant caps it at 1
        budget: dict = {}
        for k in set(lkeys):
            m = min(c[k] for c in rkey_counts)
            if m:
                budget[k] = 1 if op == "intersect" else m
    elif op in ("except", "except_all"):
        rc = rkey_counts[0]
        if op == "except":
            budget = {k: 1 for k in set(lkeys) if rc[k] == 0}
        else:
            budget = {}
            for k, n in Counter(lkeys).items():
                if n - rc[k] > 0:
                    budget[k] = n - rc[k]
    else:
        raise NotImplementedError(f"set operation {op!r}")
    # the distinct variants' budget of 1 also dedups left-side duplicates
    keep = []
    for i, k in enumerate(lkeys):
        b = budget.get(k, 0)
        if b:
            budget[k] = b - 1
            keep.append(i)
    return left.iloc[keep].reset_index(drop=True)


def _sort_codes(v: np.ndarray, ascending: bool) -> np.ndarray:
    """Order-encoding of one sort key as int64 codes with NULLs always
    LAST (matching the L.Sort node's na_position='last' convention),
    honoring the ascending flag — lexsort-ready for any value dtype."""
    codes, uniques = pd.factorize(pd.Series(v), sort=True)
    k = len(uniques)
    if not ascending:
        codes = np.where(codes >= 0, k - 1 - codes, codes)
    return np.where(codes < 0, k, codes).astype(np.int64)


def _window_order(w: L.WindowExpr, df: pd.DataFrame, pid: np.ndarray):
    """Global evaluation order for a window: partition-major, then the
    OVER(ORDER BY ...) keys; returns (order, peer_codes) where `order`
    holds original row positions sorted for evaluation and `peer_codes`
    is an [n, m] int matrix whose row equality defines peer rows."""
    n = len(df)
    if not w.order_exprs:
        return np.argsort(pid, kind="stable"), None
    key_arrays = []
    for oe, asc in zip(w.order_exprs, w.order_asc):
        v = np.asarray(_eval(_refs_to_cols(oe), df))
        key_arrays.append(_sort_codes(v, asc))
    # np.lexsort: LAST key is primary -> (tiebreak, k_m..k_0, pid)
    order = np.lexsort(
        tuple([np.arange(n)] + key_arrays[::-1] + [pid])
    )
    return order, np.stack(key_arrays, axis=1)


def _window_col(w: L.WindowExpr, df: pd.DataFrame) -> np.ndarray:
    """Evaluate one window function over the frame.  Per-partition
    Python/numpy — the fallback path is size-guarded, and explicit ROWS
    frames are O(rows x frame) worst case."""
    n = len(df)
    res = np.empty(n, dtype=object)
    if n == 0:
        return res

    if w.partition:
        pcols = [
            np.asarray(_eval(_refs_to_cols(p), df)) for p in w.partition
        ]
        ids: dict = {}
        pid = np.empty(n, dtype=np.int64)
        for i in range(n):
            key = tuple(
                _NULL if pd.isna(c[i]) else c[i] for c in pcols
            )
            pid[i] = ids.setdefault(key, len(ids))
    else:
        pid = np.zeros(n, dtype=np.int64)

    order, peer_codes = _window_order(w, df, pid)

    va = (
        np.asarray(_eval(_refs_to_cols(w.arg), df))
        if w.arg is not None
        else None
    )
    fm = (
        np.asarray(_filter_mask(w.filter, df)).astype(bool)
        if w.filter is not None
        else None
    )

    pid_sorted = pid[order]
    starts = [0] + [
        i for i in range(1, n) if pid_sorted[i] != pid_sorted[i - 1]
    ] + [n]
    for a, b in zip(starts[:-1], starts[1:]):
        idxs = order[a:b]  # original row positions, evaluation order
        _window_partition(w, idxs, peer_codes, va, fm, res)
    return res


def _window_partition(w, idxs, peer_codes, va, fm, res):
    """Fill `res` for one partition (idxs = original row positions in
    window order)."""
    m = len(idxs)
    fn = w.fn

    # peer-group segmentation (rows equal on every ORDER BY key)
    if peer_codes is not None:
        pk = peer_codes[idxs]
        new_peer = np.empty(m, dtype=bool)
        new_peer[0] = True
        if m > 1:
            new_peer[1:] = (pk[1:] != pk[:-1]).any(axis=1)
        peer_id = np.cumsum(new_peer) - 1  # 0-based dense peer index
        peer_start = np.maximum.accumulate(
            np.where(new_peer, np.arange(m), 0)
        )
        # end position (inclusive) of each row's peer group
        peer_end = np.empty(m, dtype=np.int64)
        last = m - 1
        for i in range(m - 1, -1, -1):
            peer_end[i] = last
            if new_peer[i]:
                last = i - 1
    else:
        peer_id = np.zeros(m, dtype=np.int64)
        peer_start = np.zeros(m, dtype=np.int64)
        peer_end = np.full(m, m - 1, dtype=np.int64)

    if fn == "row_number":
        for i in range(m):
            res[idxs[i]] = i + 1
        return
    if fn == "rank":
        for i in range(m):
            res[idxs[i]] = int(peer_start[i]) + 1
        return
    if fn == "dense_rank":
        for i in range(m):
            res[idxs[i]] = int(peer_id[i]) + 1
        return
    if fn == "percent_rank":
        # (rank - 1) / (partition rows - 1); 0 for a single-row partition
        for i in range(m):
            res[idxs[i]] = (
                0.0 if m == 1 else float(peer_start[i]) / (m - 1)
            )
        return
    if fn == "cume_dist":
        for i in range(m):
            res[idxs[i]] = float(peer_end[i] + 1) / m
        return
    if fn == "ntile":
        k = int(w.args[0])
        base, rem = divmod(m, k)
        bucket_of = []
        for bi in range(k):
            bucket_of += [bi + 1] * (base + (1 if bi < rem else 0))
        for i in range(m):
            res[idxs[i]] = bucket_of[i] if i < len(bucket_of) else k
        return
    if fn in ("lag", "lead"):
        off = int(w.args[0]) if w.args else 1
        default = w.args[1] if len(w.args) > 1 else None
        vp = va[idxs]
        for i in range(m):
            j = i - off if fn == "lag" else i + off
            if 0 <= j < m:
                v = vp[j]
                res[idxs[i]] = None if pd.isna(v) else v
            else:
                res[idxs[i]] = default
        return

    # frame-based functions: first_value / last_value / sum / count /
    # avg / min / max
    def frame_bounds(i):
        if w.frame is not None:
            lo, hi = w.frame
            lo_i = 0 if lo is None else max(0, i + lo)
            hi_i = m - 1 if hi is None else min(m - 1, i + hi)
            return lo_i, hi_i
        if peer_codes is not None:
            # default frame with ORDER BY: RANGE UNBOUNDED PRECEDING ..
            # CURRENT ROW — includes the current row's peers
            return 0, int(peer_end[i])
        return 0, m - 1

    vp = va[idxs] if va is not None else None
    fmp = fm[idxs] if fm is not None else None

    if fn in ("first_value", "last_value", "nth_value"):
        nth = int(w.args[0]) if fn == "nth_value" else 1
        for i in range(m):
            lo_i, hi_i = frame_bounds(i)
            if lo_i > hi_i:
                res[idxs[i]] = None
                continue
            if fn == "last_value":
                j = hi_i
            else:  # first_value == nth_value(.., 1)
                j = lo_i + nth - 1
                if j > hi_i:
                    res[idxs[i]] = None  # frame shorter than N rows
                    continue
            v = vp[j]
            res[idxs[i]] = None if pd.isna(v) else v
        return

    # aggregate over the frame (filter-aware, NULL-skipping).  Default
    # frames (whole partition / cumulative-with-peers) take ONE running
    # pass — the common running-total idiom must be O(rows), not
    # O(rows^2); only explicit ROWS frames pay per-row slicing.
    if fn in ("sum", "count", "avg", "min", "max") and w.frame is None:
        run_sum, run_cnt, n_rows = 0.0, 0, 0
        run_min = run_max = None
        pref = [None] * m
        for i in range(m):
            if fmp is None or fmp[i]:
                n_rows += 1
                if vp is not None:
                    v = vp[i]
                    if not pd.isna(v):
                        run_cnt += 1
                        if fn in ("sum", "avg"):
                            run_sum += float(v)
                        elif fn == "min":
                            if run_min is None or v < run_min:
                                run_min = v
                        elif fn == "max":
                            if run_max is None or v > run_max:
                                run_max = v
            if fn == "count":
                pref[i] = n_rows if vp is None else run_cnt
            elif fn == "sum":
                pref[i] = run_sum if run_cnt else None
            elif fn == "avg":
                pref[i] = run_sum / run_cnt if run_cnt else None
            elif fn == "min":
                pref[i] = run_min
            else:
                pref[i] = run_max
        # peer_end is m-1 everywhere when there is no ORDER BY, which
        # makes this the whole-partition aggregate in the same stroke
        for i in range(m):
            res[idxs[i]] = pref[int(peer_end[i])]
        return

    for i in range(m):
        lo_i, hi_i = frame_bounds(i)
        if lo_i > hi_i:
            res[idxs[i]] = 0 if fn == "count" else None
            continue
        sl = slice(lo_i, hi_i + 1)
        rows = np.ones(hi_i - lo_i + 1, dtype=bool)
        if fmp is not None:
            rows &= fmp[sl]
        if fn == "count" and vp is None:  # count(*)
            res[idxs[i]] = int(rows.sum())
            continue
        vals = vp[sl][rows]
        ok = ~pd.isna(vals)
        vals = vals[ok]
        if fn == "count":
            res[idxs[i]] = int(len(vals))
            continue
        if len(vals) == 0:
            res[idxs[i]] = None
            continue
        if fn == "min":
            res[idxs[i]] = min(vals)  # min/max work on strings too
        elif fn == "max":
            res[idxs[i]] = max(vals)
        else:
            fvals = vals.astype(np.float64)
            if fn == "sum":
                res[idxs[i]] = float(fvals.sum())
            elif fn == "avg":
                res[idxs[i]] = float(fvals.mean())
            else:
                raise NotImplementedError(f"window function {fn!r}")


#: decoded scan frames above this row count are not cached (a dashboard
#: re-issuing window/set-op queries repays the decode; a one-off huge scan
#: must not pin gigabytes)
_FRAME_CACHE_MAX_ROWS = 5_000_000


def _cached_scan_frame(catalog, table: str, needed) -> pd.DataFrame:
    """Decoded frame of a table with a small per-catalog LRU: repeated
    fallback queries (BI dashboards full of window/set-op SQL) would
    otherwise pay the full dimension decode on every statement.  Keyed on
    the catalog version, so any re-registration invalidates.  Consumers
    only ever ADD columns to scan frames (assign/copy), never write rows
    in place, so a shallow copy shares the column arrays safely."""
    ds = catalog.get(table)
    if ds is None:
        raise KeyError(f"unknown table {table!r}")
    from ..resilience import injector

    if injector().armed("fallback_decode"):
        # injected decode faults (error/partial) must neither be masked by
        # a cached frame nor poison the cache for later healthy queries
        return decoded_frame(ds, columns=needed)
    from ..resilience import current_partial
    from .engine import _row_counts

    cache = getattr(catalog, "_fallback_frames", None)
    if cache is None:
        from ..utils.lru import CountBudgetCache

        cache = catalog._fallback_frames = CountBudgetCache(4)
    key = (
        table,
        getattr(catalog, "version", 0),
        frozenset(needed) if needed is not None else None,
    )
    pc = current_partial()
    df = cache.get(key)
    if df is None:
        df = decoded_frame(ds, columns=needed)
        # a deadline-TRUNCATED frame must never enter the cache: it
        # would be served back as the complete table to every later
        # fallback query at this catalog version
        if len(df) <= _FRAME_CACHE_MAX_ROWS and (
            pc is None or not pc.triggered
        ):
            cache[key] = df
    elif pc is not None:
        # cache hit: the table was fully seen without a decode —
        # account the full scope so the coverage fraction stays honest
        segs = list(ds.segments)
        rows, delta = _row_counts(segs)
        pc.add_scope(len(segs), rows, delta)
        pc.add_seen(len(segs), rows, delta)
    return df.copy(deep=False)


def _exec(
    lp: L.LogicalPlan, catalog, _needed=None
) -> pd.DataFrame:
    """Interpret a logical plan over decoded host frames."""
    from ..resilience import checkpoint

    # cooperative deadline checkpoint per plan node: a budgeted query
    # cancels between interpreter stages instead of grinding to the end
    checkpoint("fallback.interp")
    if isinstance(lp, L.Scan):
        return _cached_scan_frame(catalog, lp.table, _needed)
    if isinstance(lp, L.Filter):
        df = _exec(lp.child, catalog, _needed)
        if not len(df):
            return df
        cond, dfx = _materialize_correlated(lp.condition, df, catalog)
        return _apply_mask(df, _filter_mask(cond, dfx))
    if isinstance(lp, L.Project):
        df = _exec(lp.child, catalog, _needed)

        def proj(e):
            e2, dfx = _materialize_correlated(e, df, catalog)
            return _eval(e2, dfx)

        return pd.DataFrame(
            {name: proj(e) for name, e in lp.exprs},
            index=df.index,
        )
    if isinstance(lp, L.Join):
        # star-conforming joins collapse to the denormalized fact exactly
        # like the planner's JoinTransform: the flat fact may not even
        # carry the FK columns (lo_partkey et al. are dropped at
        # registration), so interpreting the textual join would KeyError —
        # and the collapse is also what keeps circuit-degraded SSB queries
        # at fact-scan cost instead of a 4-way host merge
        try:
            from ..catalog.star import try_collapse_join

            collapsed = try_collapse_join(lp, catalog)
        except Exception:  # fault-ok: collapse is an optimization; merge path below
            collapsed = None
        if collapsed is not None:
            return _exec(collapsed, catalog, _needed)
        left = _exec(lp.left, catalog, _needed)
        right = _exec(lp.right, catalog, _needed)
        out = left.merge(
            right,
            left_on=list(lp.left_keys),
            right_on=list(lp.right_keys),
            how=lp.how,
            # overlapping non-key names (a denormalized flat fact joined
            # back to its dimension table — the circuit-degraded SSB
            # shape): keep the LEFT column under its bare name so plan
            # expressions still resolve; the suffixed right duplicate is
            # unreachable by any expression and is dropped below
            suffixes=("", "__joindup"),
        )
        dup = [c for c in out.columns if c.endswith("__joindup")]
        return out.drop(columns=dup) if dup else out
    if isinstance(lp, L.Union):
        # each branch projects to ITS select list first (an aggregate
        # branch's frame carries group/helper columns that would wreck
        # positional alignment), then aligns to the first branch's names;
        # decode pruning is computed per branch
        frames = [
            _project_root(
                _exec(
                    b,
                    catalog,
                    None
                    if _needs_all_columns(b)
                    else (_plan_columns(b) or None),
                ),
                b,
            )
            for b in lp.branches
        ]
        first = frames[0].columns
        aligned = [frames[0]]
        for f in frames[1:]:
            if len(f.columns) != len(first):
                raise ValueError(
                    f"{lp.op} branch produced "
                    f"{len(f.columns)} columns, expected {len(first)}"
                )
            aligned.append(f.set_axis(list(first), axis=1))
        return _setop(lp.op, aligned)
    if isinstance(lp, L.SubqueryScan):
        # scope boundary: the derived table exports exactly its SELECT
        # list; outer references to anything else must fail, not fall
        # through to base-table columns.  The inner plan's decode pruning
        # is computed from the inner plan alone
        df = _exec(
            lp.child,
            catalog,
            None
            if _needs_all_columns(lp.child)
            else (_plan_columns(lp.child) or None),
        )
        if lp.columns is not None:
            missing = [c for c in lp.columns if c not in df.columns]
            if missing:
                raise KeyError(
                    f"derived table {lp.alias or '(subquery)'} does not "
                    f"produce columns {missing}"
                )
            df = df[list(lp.columns)]
        return df
    if isinstance(lp, L.Aggregate):
        dev = _device_exec.get()
        if dev is not None:
            # device-assist: a plannable GROUP BY base (no correlated
            # subqueries survive _resolve_plan_subqueries in it) scans on
            # the accelerated engine; only the aggregated frame continues
            # through the host interpreter.  Defensive contract check: the
            # frame must carry every column the node declares (the
            # decorrelator builds quirk-shaped internal Aggregates — mixed
            # ungrouped selects — that the planner lowers differently);
            # anything short declines to host interpretation.
            out = dev(lp)
            if out is not None:
                want = (
                    [n for n, _ in lp.group_exprs]
                    + [ae.name for ae in lp.agg_exprs]
                    + [n for n, _ in lp.post_exprs]
                )
                if all(c in out.columns for c in want):
                    return out
        df = _exec(lp.child, catalog, _needed)
        # correlated subqueries inside aggregate args / FILTER clauses /
        # group expressions bind per PRE-AGGREGATION row: materialize them
        # against the child frame before grouping
        import dataclasses as _dc

        def mat(e):
            nonlocal df
            if e is None:
                return None
            e2, df = _materialize_correlated(e, df, catalog)
            return e2

        new_groups = tuple((n, mat(e)) for n, e in lp.group_exprs)
        new_aggs = tuple(
            _dc.replace(ae, arg=mat(ae.arg), filter=mat(ae.filter))
            for ae in lp.agg_exprs
        )
        if (new_groups, new_aggs) != (lp.group_exprs, lp.agg_exprs):
            lp = _dc.replace(lp, group_exprs=new_groups, agg_exprs=new_aggs)
        return _aggregate(lp, df)
    if isinstance(lp, L.Window):
        df = _exec(lp.child, catalog, _needed).copy()
        for w in lp.wins:
            df[w.name] = _window_col(w, df)
        # evaluate every output against the UNMUTATED frame first, then
        # assign: a SELECT alias shadowing a source column (v+1 AS v) must
        # not corrupt later items that read the original column
        new_cols = {}
        for name, e in lp.out_exprs:
            if isinstance(e, E.Col) and e.name in df.columns:
                new_cols[name] = df[e.name]
                continue
            e2, dfx = _materialize_correlated(
                _refs_to_cols(e), df, catalog
            )
            new_cols[name] = _eval(e2, dfx)
        for name, v in new_cols.items():
            df[name] = v
        return df
    if isinstance(lp, L.Having):
        df = _exec(lp.child, catalog, _needed)
        if not len(df):
            return df
        cond, dfx = _materialize_correlated(
            _refs_to_cols(lp.condition), df, catalog
        )
        return _apply_mask(df, _filter_mask(cond, dfx))
    if isinstance(lp, L.Sort):
        df = _exec(lp.child, catalog, _needed)
        if not len(df):
            return df
        tmp = []
        for i, k in enumerate(lp.keys):
            c = f"__sort{i}"
            ke, dfx = _materialize_correlated(
                _refs_to_cols(k.expr), df, catalog
            )
            df = df.assign(**{c: _eval(ke, dfx)})
            tmp.append(c)
        df = df.sort_values(
            tmp,
            ascending=[k.ascending for k in lp.keys],
            kind="stable",
            na_position="last",
        )
        return df.drop(columns=tmp)
    if isinstance(lp, L.Limit):
        df = _exec(lp.child, catalog, _needed)
        return df.iloc[lp.offset : lp.offset + lp.n]
    raise NotImplementedError(
        f"fallback execution for {type(lp).__name__}"
    )
