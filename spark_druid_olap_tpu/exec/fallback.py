"""Host (pandas) fallback execution of logical plans the planner cannot
rewrite.

Reference parity: in the reference, a failed rewrite is not an error — the
query simply runs as a vanilla Spark plan over the base data (SURVEY.md
§3.2 "fallback: vanilla Spark plan", §2 DruidStrategy row `[U]`).  This
module is that safety net for the standalone framework: when
`Planner.plan` raises RewriteError (an unconforming join, an expression no
transform covers), the SAME logical plan is interpreted over decoded host
frames.  Slow but correct and complete — a user never hits a wall, they
hit a warning.

Gated by `SessionConfig.fallback_execution` (True by default, like the
reference's always-on Spark fallback; set False to surface RewriteError —
useful in tests that assert pushdown coverage).

Semantics notes:
* COUNT(DISTINCT)/approx_count_distinct evaluate EXACTLY here (pandas
  nunique) — the fallback has no reason to approximate.
* SUM/MIN/MAX/AVG over zero rows are SQL NULL; COUNT is 0 (the engine's
  convention, pinned by the differential fuzz suite).
* Grouping sets expand exactly like the device path (one pass per set,
  absent dims as nulls, __grouping_id bitmask).
"""

from __future__ import annotations

from typing import Dict

import numpy as np
import pandas as pd

from ..catalog.segment import DataSource
from ..plan import logical as L
from ..plan.expr import Expr, compile_expr
from ..plan import expr as E
from ..utils.log import get_logger

log = get_logger("exec.fallback")


def decoded_frame(ds: DataSource) -> pd.DataFrame:
    """All real rows of a datasource as a pandas frame: dimensions decoded
    to values, metrics as float64, time as int64 ms."""
    out: Dict[str, np.ndarray] = {}
    for c in ds.columns:
        parts = []
        for seg in ds.segments:
            arr = np.asarray(seg.column(c.name))[seg.valid]
            if c.name in ds.dicts:
                arr = ds.dicts[c.name].decode(arr)
            elif arr.dtype.kind == "f":
                arr = arr.astype(np.float64)
            parts.append(arr)
        out[c.name] = (
            np.concatenate(parts) if parts else np.array([], dtype=object)
        )
    return pd.DataFrame(out)


def _eval(e: Expr, df: pd.DataFrame) -> np.ndarray:
    fn = compile_expr(e, raw_strings=True)
    cols = {c: np.asarray(df[c]) for c in df.columns}
    return np.asarray(fn(cols))


def _agg_one(ae: L.AggExpr, df: pd.DataFrame):
    """One aggregate over (a filtered view of) one group's rows."""
    if ae.filter is not None:
        df = df[np.asarray(_eval(ae.filter, df), dtype=bool)]
    fn = ae.fn.lower()
    if fn == "count" and ae.arg is None and not ae.distinct:
        return len(df)
    arg = (
        np.asarray(_eval(ae.arg, df))
        if ae.arg is not None
        else np.ones(len(df))
    )
    if fn in ("count_distinct", "approx_count_distinct") or (
        fn == "count" and ae.distinct
    ):
        return pd.Series(arg).nunique(dropna=True)
    if fn == "count":
        return int(pd.Series(arg).notna().sum())
    if fn == "approx_quantile":
        vals = pd.Series(arg).dropna().astype(np.float64)
        if not len(vals):
            return np.nan
        return float(np.quantile(vals, float(ae.args[0])))
    vals = pd.Series(arg, dtype=np.float64)
    if ae.distinct:
        # the device engine refuses SUM/AVG(DISTINCT) (partial aggregation
        # cannot deduplicate); host execution does it exactly
        vals = vals.drop_duplicates()
    if not len(vals):
        return np.nan  # SQL: aggregate over zero rows is NULL
    return {
        "sum": vals.sum,
        "min": vals.min,
        "max": vals.max,
        "avg": vals.mean,
    }[fn]()


def _aggregate(node: L.Aggregate, df: pd.DataFrame) -> pd.DataFrame:
    def one_set(indices) -> pd.DataFrame:
        keys = [node.group_exprs[i] for i in indices]
        if keys:
            kf = pd.DataFrame(
                {name: _eval(e, df) for name, e in keys},
                index=df.index,
            )
            grouped = df.groupby(
                [kf[n] for n, _ in keys], dropna=False, sort=False
            )
            rows = []
            for gv, gdf in grouped:
                gv = gv if isinstance(gv, tuple) else (gv,)
                row = dict(zip((n for n, _ in keys), gv))
                for ae in node.agg_exprs:
                    row[ae.name] = _agg_one(ae, gdf)
                rows.append(row)
            out = pd.DataFrame(
                rows,
                columns=[n for n, _ in keys]
                + [ae.name for ae in node.agg_exprs],
            )
        else:
            out = pd.DataFrame(
                [{ae.name: _agg_one(ae, df) for ae in node.agg_exprs}]
            )
        return out

    if node.grouping_sets:
        k = len(node.group_exprs)
        frames = []
        for s in node.grouping_sets:
            f = one_set(s)
            gid = 0
            present = set(s)
            for i in range(k):
                if i not in present:
                    gid |= 1 << (k - 1 - i)
                    f[node.group_exprs[i][0]] = None
            f["__grouping_id"] = gid
            frames.append(f)
        out = pd.concat(frames, ignore_index=True)
        order = [n for n, _ in node.group_exprs]
        return out[order + [c for c in out.columns if c not in order]]
    out = one_set(range(len(node.group_exprs)))
    # post-aggregate projections (exprs over agg outputs)
    for name, pe in node.post_exprs:
        if isinstance(pe, E.Col) and pe.name in out.columns:
            continue
        out[name] = _eval(_refs_to_cols(pe), out)
    return out


def _refs_to_cols(e: Expr) -> Expr:
    """AggRef -> Col so result-frame expressions compile generically."""
    import dataclasses

    if isinstance(e, E.AggRef):
        return E.Col(e.name)
    kw = {}
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, Expr):
            kw[f.name] = _refs_to_cols(v)
        elif isinstance(v, tuple) and v and isinstance(v[0], Expr):
            kw[f.name] = tuple(_refs_to_cols(x) for x in v)
    return dataclasses.replace(e, **kw) if kw else e


def execute_fallback(lp: L.LogicalPlan, catalog) -> pd.DataFrame:
    """Interpret a logical plan over decoded host frames."""
    if isinstance(lp, L.Scan):
        ds = catalog.get(lp.table)
        if ds is None:
            raise KeyError(f"unknown table {lp.table!r}")
        return decoded_frame(ds)
    if isinstance(lp, L.Filter):
        df = execute_fallback(lp.child, catalog)
        if not len(df):
            return df
        return df[np.asarray(_eval(lp.condition, df), dtype=bool)]
    if isinstance(lp, L.Project):
        df = execute_fallback(lp.child, catalog)
        return pd.DataFrame(
            {name: _eval(e, df) for name, e in lp.exprs},
            index=df.index,
        )
    if isinstance(lp, L.Join):
        left = execute_fallback(lp.left, catalog)
        right = execute_fallback(lp.right, catalog)
        return left.merge(
            right,
            left_on=list(lp.left_keys),
            right_on=list(lp.right_keys),
            how=lp.how,
        )
    if isinstance(lp, L.Aggregate):
        return _aggregate(lp, execute_fallback(lp.child, catalog))
    if isinstance(lp, L.Having):
        df = execute_fallback(lp.child, catalog)
        if not len(df):
            return df
        return df[np.asarray(_eval(_refs_to_cols(lp.condition), df), bool)]
    if isinstance(lp, L.Sort):
        df = execute_fallback(lp.child, catalog)
        if not len(df):
            return df
        tmp = []
        for i, k in enumerate(lp.keys):
            c = f"__sort{i}"
            df = df.assign(**{c: _eval(_refs_to_cols(k.expr), df)})
            tmp.append(c)
        df = df.sort_values(
            tmp,
            ascending=[k.ascending for k in lp.keys],
            kind="stable",
            na_position="last",
        )
        return df.drop(columns=tmp)
    if isinstance(lp, L.Limit):
        df = execute_fallback(lp.child, catalog)
        return df.iloc[lp.offset : lp.offset + lp.n]
    raise NotImplementedError(
        f"fallback execution for {type(lp).__name__}"
    )
