"""Overlapped host->device transfer pipeline (ISSUE 10 tentpole).

The re-anchor numbers name the problem: the 25M-row streaming rollup is
LINK-bound (0.24x vs pandas at 45 MB/s h2d) and every single-device
executor still moved cold segment columns synchronously inside its
dispatch loop — transfer time serialized IN FRONT of compute instead of
streaming behind it.  This module is the one sanctioned home of segment
h2d issue (graftlint transfer-discipline/GL19xx):

* **Prefetch plan** — `TransferPipeline.start` takes the dispatch
  batches the planner's interval/zone-map pruning produced (in-scope
  segments, dispatch order, head of the queue) plus optional SPECULATIVE
  next-interval segments that trail it under a separate byte cap
  (`SessionConfig.prefetch_speculative_mb`).  After each batch's columns
  are consumed, `PlanRun.advance` issues **async `jnp.asarray` puts**
  for the next `prefetch_depth` batches' missing columns — JAX's async
  dispatch queue runs those transfers while the current batch's
  partial-aggregation program occupies the device.
* **Residency-aware dispatch order** — `PlanRun.order` runs
  already-resident batches first so cold batches stream BEHIND live
  compute instead of in front of it.  Reordering happens within bounded
  windows (`2*depth`, floor 4) so executors that fold partial states in
  canonical batch order (float32 sums are not reassociation-safe; the
  fold order is pinned for byte-identical results) hold at most a
  window's worth of un-folded states.
* **Lifecycle discipline** — the pipeline respects the existing
  machinery end to end: prefetched entries land in the engine's
  byte-budgeted residency cache (evictable, never exceeding the cap;
  residency meta registers before the insert so a racing budget
  eviction of a just-landed prefetch cannot leak phantom resident
  bytes), `resilience.prefetch_pressed()` stops issue cleanly on
  deadline expiry / partial drain (the owning loop's checkpoint stays
  where the expiry surfaces), segments retired by append/compaction
  (`TransferPipeline.note_retired`) are skipped, and the existing `h2d`
  fault-injection site fires per prefetched put — an injected (or real)
  prefetch failure is POISONED per key and re-raised when the query
  consumes that column, so it reaches the retry/breaker machinery in
  query context exactly like a foreground transfer failure.
* **Attribution** — prefetched puts record into the `prefetch` span and
  `ProfScope.prefetch_ms/bytes` (obs/prof.py), never into transfer
  stall: the cost receipt's `overlap_efficiency` = device-busy /
  (device-busy + transfer-stall) counts only foreground h2d waits,
  which is the metric ROADMAP direction 4 defines as success.
"""

from __future__ import annotations

import contextvars
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..obs import SPAN_PREFETCH, prof, span
from ..resilience import fire, prefetch_pressed
from ..utils.log import get_logger

log = get_logger("exec.pipeline")

# default prefetch lookahead, in dispatch batches
DEFAULT_DEPTH = 2

# the PlanRun whose prefetches THIS context is consuming.  Poisoned
# prefetch failures are scoped to their owning run: the run that issued
# a failed put is the one whose consume re-raises it (same thread, same
# executor loop), and a run abandoned mid-flight (deadline truncation,
# scan LIMIT) takes its poisons with it — a later query's cache miss
# attempts a FRESH transfer instead of inheriting a stale failure.
_active_run: contextvars.ContextVar[Optional["PlanRun"]] = (
    contextvars.ContextVar("sdol_active_plan_run", default=None)
)


class CanonicalFold:
    """Drain per-batch results into `fold` in CANONICAL batch order
    while the pipeline dispatches them residency-first.  Partial-state
    merges are not reassociation-safe (f32 sums, scatter-based
    sparse/sketch merges), so the fold order is pinned — this is what
    keeps pipeline-on results byte-identical to pipeline-off.  Shared
    by the dense, fused, and sparse loops."""

    __slots__ = ("_fold", "_pending", "_next")

    def __init__(self, fold):
        self._fold = fold
        self._pending: Dict[int, Any] = {}
        self._next = 0

    def add(self, bi: int, value) -> None:
        self._pending[bi] = value
        while self._next in self._pending:
            self._fold(self._pending.pop(self._next))
            self._next += 1

    def drain(self) -> None:
        """Fold whatever was dispatched AHEAD of canonical order before
        a truncation (or the loop end) — still in canonical order."""
        for bi in sorted(self._pending):
            self._fold(self._pending.pop(bi))


def pipelined_put(host, sharding=None, prefetched: bool = True):
    """One sanctioned async host->device placement OUTSIDE the engine's
    residency cache (the streaming executor's chunk path): fires the
    `h2d` fault site, issues the (async) `jax.device_put`, and records
    link accounting.  Returns the device array immediately — callers
    that need the honest link time on a sampled query wrap the result
    in `prof.transfer_sync` themselves."""
    import time as _time

    import jax
    import numpy as np

    fire("h2d")
    t0 = _time.perf_counter()
    arr = jax.device_put(host, sharding)
    if not prefetched:
        # sampled query: block so a FOREGROUND put's measured window is
        # the real link time, not the async enqueue — without this the
        # pipeline-off counterfactual under-reports its own stall
        # (obs/prof.py; a strict no-op at the default sample rate)
        arr = prof.transfer_sync(arr)
    dt = _time.perf_counter() - t0
    nbytes = int(np.asarray(host).nbytes) if hasattr(host, "nbytes") else 0
    prof.record_h2d(nbytes, dt, prefetched=prefetched)
    return arr, dt, nbytes


def placement_order(names, is_resident, size_of) -> List[str]:
    """Residency-aware placement order for a sharded column set — the
    prefetch plan's scheduling rule applied per-device on the mesh
    (parallel/distributed._place_arena): resident columns first (cache
    hits cost nothing and unblock program-argument assembly), then cold
    columns largest-first so the longest transfer issues earliest and
    overlaps the remaining host-side stacking work.  Pure and
    deterministic: ties keep the caller's order."""
    names = list(dict.fromkeys(names))
    resident = [n for n in names if is_resident(n)]
    cold = [n for n in names if not is_resident(n)]
    cold.sort(key=lambda n: -int(size_of(n)))
    return resident + cold


def _batch_keys(batch, names) -> List[Tuple]:
    """Residency-cache keys one dispatch batch needs: per-segment column
    entries plus the validity buffer — the SAME tagged scheme
    `Engine._device_cols` owns (jit-collision/GL1301)."""
    keys = []
    for seg in batch:
        for n in names:
            keys.append((seg.uid, "col", n))
        keys.append((seg.uid, "valid"))
    return keys


class PlanRun:
    """One execution's prefetch plan: dispatch order + issue cursor.

    Confined to the executing thread (one PlanRun per executor loop);
    only the pipeline-level retire/poison state is shared."""

    __slots__ = (
        "pipeline", "engine", "ds", "batches", "names", "order",
        "speculative", "poison", "_issued", "_cancelled", "_spec_budget",
    )

    def __init__(
        self, pipeline, ds, batches, names, speculative=(), reorder=True
    ):
        self.pipeline = pipeline
        self.engine = pipeline.engine
        self.ds = ds
        self.batches = list(batches)
        self.names = list(names)
        self.speculative = list(speculative)
        # key -> exception of a FAILED prefetch put, re-raised when THIS
        # run consumes the key (run-scoped: an abandoned run's poisons
        # die with it — see _active_run)
        self.poison: Dict[Tuple, BaseException] = {}
        self._issued = 0  # dispatch positions whose prefetch was issued
        self._cancelled = False
        self._spec_budget = int(pipeline.speculative_bytes)
        self.order = (
            self._order() if reorder else list(range(len(self.batches)))
        )
        _active_run.set(self)

    # -- residency-aware dispatch order --------------------------------------

    def _resident_fraction(self, bi: int) -> float:
        cache = self.engine._device_cache
        keys = _batch_keys(self.batches[bi], self.names)
        if not keys:
            return 1.0
        return sum(1 for k in keys if k in cache) / len(keys)

    def _order(self) -> List[int]:
        n = len(self.batches)
        if not self.pipeline.enabled or n <= 1:
            return list(range(n))
        # windowed resident-first partition: within each window of
        # 2*depth batches (floor 4), batches sort by resident fraction
        # (stable: ties keep canonical order).  Bounded windows cap how
        # many out-of-canonical-order partial states the caller's
        # pinned-order fold has to hold live.
        w = max(2 * self.pipeline.depth, 4)
        order: List[int] = []
        for w0 in range(0, n, w):
            idx = list(range(w0, min(w0 + w, n)))
            idx.sort(key=lambda i: -self._resident_fraction(i))
            order.extend(idx)
        return order

    # -- prefetch issue -------------------------------------------------------

    def _missing_entries(self, batch):
        cache = self.engine._device_cache
        retired = self.pipeline._retired
        out = []
        for seg in batch:
            if seg.uid in retired:
                # append/compaction retired this uid between plan build
                # and issue: prefetching it would re-resident a dead
                # segment the evict hook just dropped
                self.pipeline.skipped_retired += 1
                continue
            for n in self.names:
                key = (seg.uid, "col", n)
                if key not in cache:
                    out.append((key, seg, n))
            key = (seg.uid, "valid")
            if key not in cache:
                out.append((key, seg, None))
        return out

    def _issue(self, entries, speculative: bool = False) -> int:
        """Issue async puts for missing (key, seg, col) entries.  Returns
        bytes issued.  A failing put (injected h2d fault, real backend
        error) poisons its key: the failure re-raises in query context
        when `_device_cols` consumes that column."""
        eng = self.engine
        issued_bytes = 0
        for key, seg, n in entries:
            if self._cancelled or prefetch_pressed():
                self._cancelled = True
                self.pipeline.cancelled += 1
                break
            host = seg.valid if n is None else seg.column(n)
            if speculative and (
                issued_bytes + int(host.nbytes) > self._spec_budget
            ):
                # per-entry PRE-check: one oversized column must not
                # blow past the configured speculation cap
                break
            try:
                eng._put_device_col(
                    key, host, self.ds.name, prefetched=True
                )
            except BaseException as exc:  # fault-ok: re-raised at consume
                self.poison[key] = exc
                continue
            issued_bytes += int(host.nbytes)
            self.pipeline.issued += 1
            if speculative:
                self.pipeline.speculative_issued += 1
        return issued_bytes

    def advance(self, pos: int) -> None:
        """Called by the executor right after batch at dispatch position
        `pos` had its columns consumed (and before its program
        dispatches): issue prefetch for the next `depth` batches in
        dispatch order, then — once the whole plan is issued — the
        speculative tail under its byte cap."""
        if not self.pipeline.enabled or self._cancelled:
            return
        hi = min(len(self.order), pos + 1 + self.pipeline.depth)
        work = []
        while self._issued < hi:
            # positions <= pos were consumed by the foreground loop
            # already; issuing them would be a wasted pass over the cache
            if self._issued > pos:
                work.extend(
                    self._missing_entries(
                        self.batches[self.order[self._issued]]
                    )
                )
            self._issued += 1
        spec = []
        if (
            self._issued >= len(self.order)
            and self.speculative
            and self._spec_budget > 0
        ):
            spec = self._missing_entries(self.speculative)
            self.speculative = []
        if not work and not spec:
            return
        with span(
            SPAN_PREFETCH, entries=len(work) + len(spec),
            speculative=len(spec),
        ):
            self._issue(work)
            if spec and not self._cancelled:
                self._spec_budget -= self._issue(spec, speculative=True)

    def cancel(self) -> None:
        """Stop issuing (deadline expiry, partial drain, executor error).
        Already-issued async puts are just residency-cache entries — the
        byte budget evicts them like any other cold column."""
        if not self._cancelled:
            self._cancelled = True
            self.pipeline.cancelled += 1

    @property
    def cancelled(self) -> bool:
        return self._cancelled


class _NullRun:
    """Plan for a disabled pipeline: canonical order, no-op advance —
    the executor loops stay shape-identical either way."""

    __slots__ = ("order",)

    def __init__(self, n: int):
        self.order = list(range(n))

    def advance(self, pos: int) -> None:
        pass

    def cancel(self) -> None:
        pass

    @property
    def cancelled(self) -> bool:
        return False


class TransferPipeline:
    """Per-engine prefetch state shared across executions: the retired
    uid set (append/compaction), poisoned prefetch keys, and counters.
    PlanRuns are per-execution and thread-confined."""

    # retired-uid memory bound: uids are process-unique (never
    # re-published), so entries only matter while a PlanRun built before
    # the retirement is still running — far less than this.  Without a
    # bound, weeks of compaction churn grow the set forever.
    RETIRED_CAP = 16384

    def __init__(
        self,
        engine,
        enabled: bool = True,
        depth: int = DEFAULT_DEPTH,
        speculative_bytes: int = 0,
    ):
        from collections import OrderedDict

        self.engine = engine
        self.enabled = bool(enabled)
        self.depth = max(1, int(depth))
        self.speculative_bytes = max(0, int(speculative_bytes))
        self._lock = threading.Lock()
        # insertion-ordered so the bound evicts the OLDEST retirements
        self._retired: "OrderedDict[Any, None]" = OrderedDict()
        # counters (tests + /status introspection)
        self.issued = 0
        self.speculative_issued = 0
        self.skipped_retired = 0
        self.cancelled = 0

    def configure(self, config) -> None:
        """Apply SessionConfig knobs (api.TPUOlapContext wires this)."""
        self.enabled = bool(getattr(config, "transfer_pipeline", True))
        self.depth = max(1, int(getattr(config, "prefetch_depth", DEFAULT_DEPTH)))
        self.speculative_bytes = max(
            0, int(getattr(config, "prefetch_speculative_mb", 0)) << 20
        )

    # -- lifecycle hooks ------------------------------------------------------

    def note_retired(self, uids) -> None:
        """Append/compaction retired these segment uids: queued (not yet
        issued) prefetches for them must never land — a prefetch issued
        after the evict would re-resident a dead segment."""
        with self._lock:
            for u in uids:
                self._retired[u] = None
            while len(self._retired) > self.RETIRED_CAP:
                self._retired.popitem(last=False)

    def clear_poison(self, key) -> None:
        """A put for `key` landed successfully: any failure the ACTIVE
        run recorded for it is superseded.  Engine._put_device_col calls
        this on every landing."""
        run = _active_run.get()
        if run is not None and run.poison:
            run.poison.pop(key, None)

    def take_poison(self, key) -> Optional[BaseException]:
        """Pop the failure the ACTIVE run's prefetch recorded for `key`,
        if any — `Engine._device_cols` re-raises it in query context so
        injected h2d faults keep reaching the retry/breaker machinery
        with the pipeline on.  Run-scoped on purpose: a run abandoned
        before consuming its failed prefetch (deadline truncation, scan
        LIMIT) must not leak that failure into a LATER query's cache
        miss — the later query just attempts a fresh transfer.  Runs are
        context-confined (one executor loop each), so no lock."""
        run = _active_run.get()
        if run is None or run.cancelled or not run.poison:
            # a CANCELLED run is draining/abandoned: its unconsumed
            # prefetch failures are moot (nothing depends on those
            # columns anymore) and must not fail a later consumer
            return None
        return run.poison.pop(key, None)

    # -- plan construction ----------------------------------------------------

    def start(
        self,
        ds,
        batches: Sequence,
        names: Sequence[str],
        speculative: Sequence = (),
        reorder: bool = True,
    ):
        """Build one execution's PlanRun over already-batched segments.
        `speculative` are out-of-scope (next-interval) segments that
        trail the plan under the speculative byte cap.  `reorder=False`
        pins the dispatch order to canonical (scan row order and
        progressive refinement sequence are user-visible; only prefetch
        applies there)."""
        if not self.enabled:
            # clear the active-run slot too: a PREVIOUS execution's
            # abandoned run (and its poisons) must not be consultable
            # from this pipeline-off execution's cache misses
            _active_run.set(None)
            return _NullRun(len(batches))
        return PlanRun(
            self, ds, batches, names,
            speculative=speculative if self.speculative_bytes else (),
            reorder=reorder,
        )

    def speculative_candidates(self, q, ds, segs_in_scope) -> List:
        """Out-of-scope segments worth speculating on, next-interval
        first: a dashboard that just scanned [t0, t1) most often asks
        for the adjacent interval next, so segments starting at or after
        the scope's end head the tail (then the rest in catalog order).
        Empty when speculation is disabled or the scope is unpruned."""
        if not self.enabled or not self.speculative_bytes:
            return []
        in_scope = {s.uid for s in segs_in_scope}
        rest = [s for s in ds.segments if s.uid not in in_scope]
        if not rest:
            return []
        scope_end = max(
            (s.interval[1] for s in segs_in_scope if s.interval is not None),
            default=None,
        )
        if scope_end is None:
            return rest
        return sorted(
            rest,
            key=lambda s: (
                0 if s.interval is not None and s.interval[0] >= scope_end
                else 1,
                s.interval[0] if s.interval is not None else 0,
            ),
        )

    def to_dict(self) -> dict:
        return {
            "enabled": self.enabled,
            "depth": self.depth,
            "speculative_bytes": self.speculative_bytes,
            "issued": self.issued,
            "speculative_issued": self.speculative_issued,
            "skipped_retired": self.skipped_retired,
            "cancelled": self.cancelled,
        }
