"""Single-device query engine: QuerySpec × DataSource -> result table.

Reference parity: this layer replaces the external Druid cluster.  In
spark-druid-olap, `DruidRDD.compute` POSTs the query JSON to a broker /
historical and streams result rows back (SURVEY.md §3.3 `[U]`); the actual
aggregation happens inside Druid.  Here `Engine.execute` runs the same query
spec locally: segment columns (dictionary codes + metrics) are moved to TPU
HBM once and cached (the analog of Druid's segment residency / page cache),
the filter+aggregate runs as fused XLA (ops/groupby.py), and only the tiny
[G, M] aggregate state returns to host for finalization (decode group ids,
post-aggregations, having, sort/limit — the work Druid's broker does after
its scatter-gather merge).

Distributed execution (the broker scatter-gather analog over ICI) lives in
parallel/distributed.py and reuses this module's lowering.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..catalog.segment import DataSource, Segment
from ..models import aggregations as A
from ..models import query as Q
from ..models.dimensions import DimensionSpec
from ..models.filters import Filter
from ..ops.filters import DecodedView, compile_filter
from ..ops.groupby import (
    DENSE_MAX_GROUPS,
    combine_group_ids,
    partial_aggregate,
)
from ..plan.expr import compile_expr
from ..utils.granularity import bucket_starts, granularity_period_ms

# ---------------------------------------------------------------------------
# Dimension resolution
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ResolvedDim:
    """A dimension lowered to: device code producer + cardinality + decoder."""

    spec: DimensionSpec
    cardinality: int  # including the null slot when present
    codes_fn: Callable[[Mapping[str, jnp.ndarray]], jnp.ndarray]
    decode: Callable[[np.ndarray], np.ndarray]  # codes -> python values


def _resolve_dims(
    dims: Sequence[DimensionSpec],
    ds: DataSource,
    intervals: Tuple[Tuple[int, int], ...],
) -> List[ResolvedDim]:
    out: List[ResolvedDim] = []
    for spec in dims:
        if spec.dimension == "__time" or spec.granularity is not None:
            out.append(_resolve_time_dim(spec, ds, intervals))
            continue
        d = ds.dicts[spec.dimension]
        if spec.extraction is not None:
            # Host-side dictionary rewrite: apply fn to each dict value once,
            # build remap table code -> new code (SURVEY.md dimension-spec row).
            # Extraction fns are string fns; numeric dictionaries stringify.
            extracted = spec.extraction.apply_to_dict(
                [v if isinstance(v, str) else str(v) for v in d.values]
            )
            new_vals = sorted(set(extracted))
            index = {v: i for i, v in enumerate(new_vals)}
            remap = np.array([index[v] for v in extracted], dtype=np.int32)
            card = len(new_vals) + 1  # + null slot
            remap_dev = jnp.asarray(remap)
            name = spec.dimension

            def codes_fn(cols, remap_dev=remap_dev, name=name, card=card):
                c = cols[name]
                return jnp.where(c >= 0, remap_dev[jnp.maximum(c, 0)],
                                 jnp.int32(card - 1))

            vals_arr = np.asarray(new_vals, dtype=object)

            def decode(codes, vals_arr=vals_arr, card=card):
                o = np.empty(len(codes), dtype=object)
                isnull = codes == card - 1
                o[~isnull] = vals_arr[codes[~isnull]]
                o[isnull] = None
                return o

            out.append(ResolvedDim(spec, card, codes_fn, decode))
        else:
            card = d.cardinality + 1  # last slot = null
            name = spec.dimension

            def codes_fn(cols, name=name, card=card):
                c = cols[name]
                return jnp.where(c >= 0, c, jnp.int32(card - 1))

            vals_arr = np.asarray(d.values, dtype=object)

            def decode(codes, vals_arr=vals_arr, card=card):
                o = np.empty(len(codes), dtype=object)
                isnull = codes == card - 1
                o[~isnull] = vals_arr[codes[~isnull]]
                o[isnull] = None
                return o

            out.append(ResolvedDim(spec, card, codes_fn, decode))
    return out


def _resolve_time_dim(
    spec: DimensionSpec, ds: DataSource, intervals
) -> ResolvedDim:
    gran = spec.granularity or "all"
    iv = intervals[0] if intervals else ds.interval()
    if iv is None:
        raise ValueError("time-bucketed dimension requires a time column")
    lo, hi = iv
    if intervals:
        lo = min(a for a, _ in intervals)
        hi = max(b for _, b in intervals)
        # open-ended predicate intervals (t >= x -> hi = 2^62) would expand
        # the bucket table unboundedly; the data's own range bounds it
        dsiv = ds.interval()
        if dsiv is not None:
            lo = max(lo, dsiv[0])
            hi = max(lo, min(hi, dsiv[1]))
    starts = bucket_starts(lo, hi, gran)  # host-computed bucket boundaries
    card = len(starts)
    starts_dev = jnp.asarray(starts)

    if spec.extraction is not None:
        # EXTRACT-style dims: many buckets fold to one extracted value
        # (e.g. MONTH over 3 years: 36 buckets -> 12 groups).  Host-side
        # remap over bucket starts; the kernel adds one tiny gather.
        extracted = spec.extraction.apply_to_dict([int(s) for s in starts])
        new_vals = sorted(set(extracted))
        index = {v: i for i, v in enumerate(new_vals)}
        remap_dev = jnp.asarray(
            np.array([index[v] for v in extracted], dtype=np.int32)
        )

        def codes_fn(cols, starts_dev=starts_dev, remap_dev=remap_dev):
            t = cols["__time"]
            b = jnp.searchsorted(starts_dev, t, side="right").astype(jnp.int32) - 1
            return remap_dev[jnp.clip(b, 0, remap_dev.shape[0] - 1)]

        vals_arr = np.asarray(new_vals, dtype=object)

        def decode(codes, vals_arr=vals_arr):
            return vals_arr[np.clip(codes, 0, len(vals_arr) - 1)]

        return ResolvedDim(spec, len(new_vals), codes_fn, decode)

    def codes_fn(cols, starts_dev=starts_dev):
        t = cols["__time"]
        # bucket index via searchsorted over boundaries (log #buckets passes;
        # handles calendar granularities month/quarter/year exactly)
        return (
            jnp.searchsorted(starts_dev, t, side="right").astype(jnp.int32) - 1
        )

    starts_np = np.asarray(starts)

    def decode(codes, starts_np=starts_np):
        ms = starts_np[np.clip(codes, 0, len(starts_np) - 1)]
        return ms.astype("datetime64[ms]")

    return ResolvedDim(spec, card, codes_fn, decode)


# ---------------------------------------------------------------------------
# Aggregation lowering
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LoweredAggs:
    """Aggregations split by merge class for the kernel ABI.

    Layout contract with ops/groupby.py: sum-class aggs (psum merges) are the
    columns of `sum_values`; min-class then max-class are the columns of
    `minmax_values`.  Column 0 of sum_values is always the hidden `__rows`
    presence counter."""

    sum_names: List[str]
    min_names: List[str]
    max_names: List[str]
    sketch_aggs: List[A.Aggregation]
    long_valued: Dict[str, bool]
    value_fns: Dict[str, Callable]  # name -> fn(cols) -> f32[R]
    mask_fns: Dict[str, Optional[Callable]]  # name -> extra-mask fn or None
    count_like: set = dataclasses.field(default_factory=set)  # COUNT aggs


def _lower_aggs(
    aggs: Sequence[A.Aggregation], ds: DataSource
) -> LoweredAggs:
    la = LoweredAggs(["__rows"], [], [], [], {"__rows": True}, {}, {})
    la.value_fns["__rows"] = lambda cols: None  # ones; handled specially
    la.mask_fns["__rows"] = None

    def add(agg: A.Aggregation, extra_filter: Optional[Filter]):
        mask_fn = (
            compile_filter(extra_filter, ds) if extra_filter is not None else None
        )
        if isinstance(agg, A.FilteredAgg):
            inner_mask = compile_filter(agg.filter, ds)
            if mask_fn is None:
                combined = inner_mask
            else:
                outer = mask_fn
                combined = lambda cols: outer(cols) & inner_mask(cols)
            _add_base(agg.aggregator, combined)
            return
        _add_base(agg, mask_fn)

    def _add_base(agg: A.Aggregation, mask_fn):
        name = agg.name
        la.mask_fns[name] = mask_fn
        if isinstance(agg, A.Count):
            la.sum_names.append(name)
            la.long_valued[name] = True
            la.count_like.add(name)
            la.value_fns[name] = lambda cols: None  # ones
        elif isinstance(agg, (A.LongSum, A.DoubleSum)):
            field = agg.field_name
            la.sum_names.append(name)
            la.long_valued[name] = isinstance(agg, A.LongSum)
            la.value_fns[name] = _field_value_fn(field, ds)
            _add_null_skip(la, name, field, ds)
        elif isinstance(agg, (A.LongMin, A.DoubleMin)):
            field = agg.field_name
            la.min_names.append(name)
            la.long_valued[name] = isinstance(agg, A.LongMin)
            la.value_fns[name] = _field_value_fn(field, ds)
            _add_null_skip(la, name, field, ds)
        elif isinstance(agg, (A.LongMax, A.DoubleMax)):
            field = agg.field_name
            la.max_names.append(name)
            la.long_valued[name] = isinstance(agg, A.LongMax)
            la.value_fns[name] = _field_value_fn(field, ds)
            _add_null_skip(la, name, field, ds)
        elif isinstance(agg, A.ExpressionAgg):
            fn = compile_expr(agg.expression, ds.dicts)
            target = {
                "doubleSum": la.sum_names,
                "longSum": la.sum_names,
                "doubleMin": la.min_names,
                "doubleMax": la.max_names,
            }[agg.base]
            target.append(name)
            la.long_valued[name] = agg.base == "longSum"
            dicts = ds.dicts
            la.value_fns[name] = lambda cols, fn=fn, dicts=dicts: jnp.asarray(
                fn(DecodedView(cols, dicts))
            ).astype(jnp.float32)
        elif isinstance(agg, (A.HyperUnique, A.CardinalityAgg, A.ThetaSketch)):
            la.sketch_aggs.append(agg)
            la.long_valued[name] = True
        else:
            raise NotImplementedError(f"aggregation {type(agg).__name__}")

    for agg in aggs:
        add(agg, None)
    return la


def _field_value_fn(field: str, ds: DataSource):
    """Value reader for sum/min/max: metric columns pass through; numeric-
    dictionary dimension columns decode rank codes back to values (so
    sum(d_year)-style aggregates see years, not ranks)."""
    d = ds.dicts.get(field) if hasattr(ds.dicts, "get") else None
    if d is not None and d.numeric_values is not None:
        dicts = ds.dicts
        return lambda cols, field=field, dicts=dicts: DecodedView(cols, dicts)[
            field
        ].astype(jnp.float32)
    return lambda cols, field=field: cols[field].astype(jnp.float32)


def _add_null_skip(la: LoweredAggs, name: str, field: str, ds: DataSource):
    """SQL aggregates skip NULLs: for a dictionary-dimension field, rows with
    a null code (-1) must not contribute (they'd otherwise decode to -1 and
    poison SUM/MIN/MAX).  Metrics have no null representation — no-op."""
    d = ds.dicts.get(field) if hasattr(ds.dicts, "get") else None
    if d is None:
        return
    nm = lambda cols, field=field: cols[field] >= 0
    prev = la.mask_fns.get(name)
    la.mask_fns[name] = (
        nm if prev is None else lambda cols, p=prev, nm=nm: p(cols) & nm(cols)
    )


# ---------------------------------------------------------------------------
# Query lowering (shared by the local engine and parallel/distributed.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GroupByLowering:
    """A GroupByQuery lowered to device-executable pieces:

    * `columns` — physical columns to fetch per segment
    * `row_arrays(cols)` — pure, jit/shard_map-traceable row-wise kernel
      producing (gid, mask, sum_values, minmax_values, minmax_masks)
    * `dims` / `la` / `num_groups` — the finalization contract
    """

    query: Q.GroupByQuery
    dims: List[ResolvedDim]
    la: LoweredAggs
    num_groups: int
    columns: List[str]
    filter_fn: Optional[Callable]
    vcol_fns: Dict[str, Callable]

    def add_virtual(self, cols: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        for name, fn in self.vcol_fns.items():
            if name not in cols:
                cols[name] = jnp.asarray(fn(cols))
        return cols

    def row_mask(self, cols) -> jnp.ndarray:
        mask = cols["__valid"]
        q = self.query
        if q.intervals:
            t = cols["__time"]
            im = jnp.zeros(t.shape, jnp.bool_)
            for a, b in q.intervals:
                im = im | ((t >= a) & (t < b))
            mask = mask & im
        if self.filter_fn is not None:
            mask = mask & self.filter_fn(cols)
        return mask

    def row_arrays(self, cols: Dict[str, jnp.ndarray]):
        """cols: name -> row-aligned device array (must include "__valid",
        and "__time" when the query touches time).  Returns the kernel ABI
        tuple for ops/groupby.py."""
        cols = dict(cols)
        self.add_virtual(cols)
        mask = self.row_mask(cols)
        la = self.la
        gid, _ = combine_group_ids(
            [d.codes_fn(cols) for d in self.dims],
            [d.cardinality for d in self.dims],
        )
        if not self.dims:
            gid = jnp.zeros(mask.shape, jnp.int32)
        R = mask.shape[0]
        maskf = mask.astype(jnp.float32)
        sum_cols = []
        for n in la.sum_names:
            base = la.value_fns[n](cols) if la.value_fns[n] is not None else None
            v = maskf if base is None else base * maskf
            mfn = la.mask_fns.get(n)
            if mfn is not None:
                v = v * mfn(cols).astype(jnp.float32)
            sum_cols.append(v)
        sum_values = jnp.stack(sum_cols, axis=1)
        mm_names = la.min_names + la.max_names
        if mm_names:
            mm_vals, mm_masks = [], []
            for n in mm_names:
                mm_vals.append(la.value_fns[n](cols))
                mfn = la.mask_fns.get(n)
                mm_masks.append(
                    mfn(cols) if mfn is not None else jnp.ones((R,), jnp.bool_)
                )
            minmax_values = jnp.stack(mm_vals, axis=1)
            minmax_masks = jnp.stack(mm_masks, axis=1)
        else:
            minmax_values = jnp.zeros((R, 0), jnp.float32)
            minmax_masks = jnp.zeros((R, 0), jnp.bool_)
        return gid, mask, sum_values, minmax_values, minmax_masks


def _query_key(q: Q.QuerySpec, ds: DataSource) -> Tuple:
    """Identity of (query, datasource-schema) for program/state caches —
    single definition so every cache keys the same way."""
    import json as _json

    return (
        _json.dumps(q.to_druid(), sort_keys=True, default=str),
        schema_signature(ds),
    )


def schema_signature(ds: DataSource) -> Tuple:
    """Identity of a datasource's schema for program caches: name + per-column
    kind/cardinality + dictionary content + segment ids.  Dictionary content
    matters because rank codes are data-dependent: re-ingesting a same-name
    datasource with an equal-cardinality but different value domain must MISS
    the cache (compiled filters bake in literal->code translations)."""
    return (
        ds.name,
        tuple(
            (
                c.name,
                c.kind,
                c.cardinality,
                ds.dicts[c.name].content_key if c.name in ds.dicts else None,
            )
            for c in ds.columns
        ),
        tuple(s.uid for s in ds.segments),
    )


def timeseries_to_groupby(q: Q.TimeseriesQuery) -> Q.GroupByQuery:
    """Shared Timeseries->GroupBy rewrite (a Timeseries is a GroupBy whose
    only dimension is the time bucket) — used by both engines so semantics
    cannot drift."""
    return Q.GroupByQuery(
        datasource=q.datasource,
        dimensions=(
            DimensionSpec("__time", "timestamp", granularity=q.granularity),
        ),
        aggregations=q.aggregations,
        post_aggregations=q.post_aggregations,
        filter=q.filter,
        intervals=q.intervals,
        virtual_columns=q.virtual_columns,
    )


def finalize_timeseries(df, q: Q.TimeseriesQuery, ds: DataSource):
    """Shared Timeseries finalization: empty-bucket zero-fill + ordering."""
    import pandas as pd

    if not q.skip_empty_buckets:
        iv = q.intervals[0] if q.intervals else ds.interval()
        if iv is not None:
            lo = min(a for a, _ in q.intervals) if q.intervals else iv[0]
            hi = max(b for _, b in q.intervals) if q.intervals else iv[1]
            all_buckets = bucket_starts(lo, hi, q.granularity).astype(
                "datetime64[ms]"
            )
            df = (
                df.set_index("timestamp")
                .reindex(pd.Index(all_buckets, name="timestamp"))
                .reset_index()
            )
            for a in q.aggregations:
                if a.merge_op == "psum" and a.name in df:
                    filled = df[a.name].fillna(0)
                    if df[a.name].dtype.kind in ("i", "u"):
                        filled = filled.astype(np.int64)
                    df[a.name] = filled
    df = df.sort_values("timestamp", ascending=not q.descending)
    return df.reset_index(drop=True)


def topn_to_groupby(q: Q.TopNQuery) -> Q.GroupByQuery:
    """Shared TopN->GroupBy rewrite (exact TopN: full groupby then rank;
    Druid's native TopN is approximate — ours is exact and still one kernel)."""
    return Q.GroupByQuery(
        datasource=q.datasource,
        dimensions=(q.dimension,),
        aggregations=q.aggregations,
        post_aggregations=q.post_aggregations,
        filter=q.filter,
        intervals=q.intervals,
        granularity=q.granularity,
        virtual_columns=q.virtual_columns,
    )


def finalize_topn(df, q: Q.TopNQuery):
    """Shared TopN ranking, including per-bucket ranking under a non-'all'
    granularity."""
    df = df.sort_values(q.metric, ascending=not q.descending, kind="stable")
    if q.granularity not in ("all", None):
        df = (
            df.groupby("timestamp", sort=True, group_keys=False)
            .head(q.threshold)
            .sort_values(
                ["timestamp", q.metric],
                ascending=[True, not q.descending],
                kind="stable",
            )
        )
        return df.reset_index(drop=True)
    return df.head(q.threshold).reset_index(drop=True)


def lower_groupby(q: Q.GroupByQuery, ds: DataSource) -> GroupByLowering:
    dims = _resolve_dims(q.dimensions, ds, q.intervals)
    la = _lower_aggs(q.aggregations, ds)
    G = 1
    for d in dims:
        G *= d.cardinality
    if G > (1 << 26):
        raise ValueError(
            f"combined group cardinality {G} too large for dense domain; "
            "sort-based path not yet wired for this size"
        )
    filter_fn = compile_filter(q.filter, ds) if q.filter is not None else None
    vcol_fns = {
        v.name: _decoded_expr_fn(v.expression, ds) for v in q.virtual_columns
    }
    return GroupByLowering(
        q, dims, la, G, _needed_columns(q, ds, dims), filter_fn, vcol_fns
    )


def _decoded_expr_fn(expression, ds: DataSource):
    """Compile an expression so dimension references read decoded values."""
    fn = compile_expr(expression, ds.dicts)
    dicts = ds.dicts
    return lambda cols, fn=fn, dicts=dicts: fn(DecodedView(cols, dicts))


def _needed_columns(q, ds: DataSource, dims) -> List[str]:
    names: List[str] = []
    for d in dims:
        if d.spec.dimension != "__time" and d.spec.granularity is None:
            names.append(d.spec.dimension)
    for a in q.aggregations:
        names.extend(_agg_columns(a))
    if q.filter is not None:
        names.extend(_filter_columns(q.filter))
    for v in q.virtual_columns:
        names.extend(v.expression.columns())
    virt = {v.name for v in q.virtual_columns}
    need = [n for n in dict.fromkeys(names) if n not in virt and n != "__time"]
    if ds.time_column and (
        any(d.spec.dimension == "__time" or d.spec.granularity for d in dims)
        or q.intervals
        or "__time" in names
    ):
        need.append(ds.time_column)
    return need


# ---------------------------------------------------------------------------
# Post-aggregation / having / limit finalization (host-side, tiny)
# ---------------------------------------------------------------------------


def eval_post_agg(
    p: A.PostAggregation,
    table: Mapping[str, np.ndarray],
    states: Optional[Mapping[str, np.ndarray]] = None,
) -> np.ndarray:
    """`states` maps sketch-agg name -> raw per-group sketch state (HLL
    registers / theta hash sets); sketch post-aggs must finalize from the raw
    state, not from the already-finalized estimate column in `table`."""
    if isinstance(p, A.FieldAccess):
        return np.asarray(table[p.field_name])
    if isinstance(p, A.ConstantPost):
        return np.asarray(p.value)
    if isinstance(p, A.Arithmetic):
        vals = [eval_post_agg(f, table, states) for f in p.fields]
        acc = vals[0].astype(np.float64)
        for v in vals[1:]:
            if p.fn == "+":
                acc = acc + v
            elif p.fn == "-":
                acc = acc - v
            elif p.fn == "*":
                acc = acc * v
            elif p.fn in ("/", "quotient"):
                with np.errstate(divide="ignore", invalid="ignore"):
                    acc = np.where(v != 0, acc / np.where(v == 0, 1, v), 0.0)
            else:
                raise ValueError(f"arithmetic fn {p.fn!r}")
        return acc
    if isinstance(p, A.HyperUniqueCardinality):
        from ..ops.hll import estimate as hll_estimate

        if states is None or p.field_name not in states:
            raise KeyError(
                f"hyperUniqueCardinality over {p.field_name!r}: no raw HLL "
                "state available (field must name a hyperUnique/cardinality "
                "aggregation in the same query)"
            )
        return hll_estimate(states[p.field_name])
    if isinstance(p, A.ThetaSketchEstimate):
        from ..ops.theta import estimate as theta_estimate

        if states is None or p.field_name not in states:
            raise KeyError(
                f"thetaSketchEstimate over {p.field_name!r}: no raw theta "
                "state available (field must name a thetaSketch aggregation "
                "in the same query)"
            )
        return theta_estimate(states[p.field_name])
    raise NotImplementedError(f"post-aggregation {type(p).__name__}")


def _eval_having(h: Q.Having, table: Mapping[str, np.ndarray]) -> np.ndarray:
    if isinstance(h, Q.HavingCompare):
        v = np.asarray(table[h.aggregation], dtype=np.float64)
        return {
            ">": v > h.value,
            "<": v < h.value,
            ">=": v >= h.value,
            "<=": v <= h.value,
            "==": v == h.value,
            "!=": v != h.value,
        }[h.op]
    if isinstance(h, Q.HavingAnd):
        m = _eval_having(h.specs[0], table)
        for s in h.specs[1:]:
            m &= _eval_having(s, table)
        return m
    if isinstance(h, Q.HavingOr):
        m = _eval_having(h.specs[0], table)
        for s in h.specs[1:]:
            m |= _eval_having(s, table)
        return m
    raise NotImplementedError(type(h).__name__)


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class Engine:
    """Executes query specs on the local device set.

    `strategy` mirrors the reference's cost-model execution choice
    (SURVEY.md §2 DruidQueryCostModel `[U]`): "auto" lets plan/cost.py pick
    dense-one-hot vs scatter from the group cardinality."""

    def __init__(
        self,
        strategy: str = "auto",
        device_cache_bytes: int = 4 << 30,
        program_cache_entries: int = 256,
    ):
        from ..utils.lru import ByteBudgetCache, CountBudgetCache

        self.strategy = strategy
        # observability (SURVEY.md §5): populated on every execution
        self.last_metrics = None
        self._m = None  # metrics object being filled during one execution
        self._pallas_broken = False  # set on first Mosaic-compile failure
        # queries pinned off the sparse accelerator: compaction overflowed
        # SPARSE_SLOTS distinct groups, or the sparse program failed even
        # after the Pallas-inner retry (sparse is best-effort; pinning stops
        # us re-paying a doomed trace+compile on every execution)
        self._sparse_disabled: set = set()
        # LRU residency cache under a byte budget (VERDICT r1 weak #7: the
        # unbounded caches OOMed HBM over long sessions).  4 GiB default
        # leaves headroom on a 16 GiB v5e chip for kernel workspace.
        self._device_cache = ByteBudgetCache(device_cache_bytes)
        # (query-json, datasource, strategy) -> jitted per-segment program.
        # One fused XLA program per query shape: without this, every eager op
        # in the row pipeline is a separate device dispatch — ruinous when the
        # TPU sits behind a network tunnel (hundreds of ms of pure latency).
        self._query_fn_cache = CountBudgetCache(program_cache_entries)

    # -- segment residency ---------------------------------------------------

    def _device_cols(self, seg: Segment, names) -> Dict[str, jnp.ndarray]:
        import time as _time

        cols: Dict[str, jnp.ndarray] = {}

        def put(key, host):
            t0 = _time.perf_counter()
            arr = jnp.asarray(host)
            self._device_cache[key] = arr
            if self._m is not None:  # streamed-bytes metric (cache misses only)
                self._m.h2d_bytes += int(np.asarray(host).nbytes)
                self._m.h2d_ms += (_time.perf_counter() - t0) * 1e3
            return arr

        for n in names:
            key = (seg.uid, n)
            if key not in self._device_cache:
                put(key, seg.column(n))
            cols[n] = self._device_cache[key]
        key = (seg.uid, "__valid")
        if key not in self._device_cache:
            put(key, seg.valid)
        cols["__valid"] = self._device_cache[key]
        return cols

    def bytes_resident(self) -> int:
        """HBM bytes held by the segment residency cache."""
        return self._device_cache.bytes_used

    def clear_cache(self):
        """Analog of the reference's metadata/cache clear command."""
        self._device_cache.clear()

    # -- entry points --------------------------------------------------------

    def execute(self, q: Q.QuerySpec, ds: DataSource):
        import pandas as pd

        if isinstance(q, Q.GroupByQuery):
            return self._execute_groupby(q, ds)
        if isinstance(q, Q.TimeseriesQuery):
            return self._execute_timeseries(q, ds)
        if isinstance(q, Q.TopNQuery):
            return self._execute_topn(q, ds)
        if isinstance(q, Q.ScanQuery):
            return self._execute_scan(q, ds)
        if isinstance(q, Q.SearchQuery):
            return self._execute_search(q, ds)
        raise NotImplementedError(type(q).__name__)

    # -- groupby -------------------------------------------------------------

    def _segments_in_scope(self, q, ds: DataSource) -> List[Segment]:
        """Segment pruning by interval — the analog of the reference narrowing
        the Druid query interval from time predicates (§3.2)."""
        if not q.intervals:
            return list(ds.segments)
        out = []
        for s in ds.segments:
            if s.interval is None:
                out.append(s)
                continue
            lo, hi = s.interval
            if any(a <= hi and lo < b for a, b in q.intervals):
                out.append(s)
        return out

    def _partials_for_query(
        self, q: Q.GroupByQuery, ds: DataSource, lowering=None
    ):
        """Compute merged partial state across local segments.

        Returns (dims, la, G, sums[G, Ms], mins, maxs, sketch_states)."""
        if lowering is None:
            lowering = lower_groupby(q, ds)
        dims, la, G = lowering.dims, lowering.la, lowering.num_groups
        need = lowering.columns

        sums = mins = maxs = None
        sketch_states: Dict[str, Any] = {}
        segs = self._segments_in_scope(q, ds)
        if not segs:
            # empty time range is a valid query: zero-row result, not an error
            sums, mins, maxs, sketch_states = empty_partials(la, G)
            return dims, la, G, sums, mins, maxs, sketch_states
        seg_fn = self._segment_program(q, ds, lowering)
        for seg in segs:
            cols = self._device_cols(seg, need)
            if ds.time_column and ds.time_column in cols:
                cols["__time"] = cols[ds.time_column]
            (s, mn, mx, sk), seg_fn = self._call_segment_program(
                q, ds, lowering, seg_fn, cols
            )
            sums = s if sums is None else sums + s
            mins = mn if mins is None else jnp.minimum(mins, mn)
            maxs = mx if maxs is None else jnp.maximum(maxs, mx)
            _merge_sketch_states(la, sketch_states, sk)
        return dims, la, G, sums, mins, maxs, sketch_states

    def _call_segment_program(self, q, ds, lowering, seg_fn, cols):
        """Run one segment program with the Pallas compile-failure fallback.
        Returns (result, seg_fn) — seg_fn may be a rebuilt XLA-dense program
        after a Mosaic failure."""
        import time as _time

        try:
            # first call of a newly-built program = trace+compile (+async
            # dispatch); attribute it to compile_ms (see metrics.py)
            t0 = (
                _time.perf_counter()
                if self._m is not None
                and not self._m.program_cache_hit
                and self._m.compile_ms == 0
                else None
            )
            result = seg_fn(cols)
            if t0 is not None:
                self._m.compile_ms = (_time.perf_counter() - t0) * 1e3
            return result, seg_fn
        except Exception:
            # Auto-selected Pallas may fail to Mosaic-compile on exotic
            # backends: retry once on the XLA dense path.  Only 'auto'
            # and 'dense' (a kernel *class* the cost model picks, which
            # _resolve_strategy upgrades to Pallas) fall back — explicit
            # strategy='pallas' should surface the error.  Only
            # pallas-keyed programs are evicted, and if the dense retry
            # fails too the failure wasn't Pallas — unflag.
            if (
                self.strategy not in ("auto", "dense")
                or self._pallas_broken
                or self._resolve_strategy(lowering.num_groups) != "pallas"
            ):
                raise
            self._pallas_broken = True
            for k in [k for k in self._query_fn_cache if k[2] == "pallas"]:
                del self._query_fn_cache[k]
            seg_fn = self._segment_program(q, ds, lowering)
            try:
                return seg_fn(cols), seg_fn
            except Exception:
                self._pallas_broken = False
                raise

    def _resolve_strategy(self, num_groups: int) -> str:
        """Resolve 'auto' to a concrete kernel strategy (ops.groupby's shared
        resolver + this engine's compile-failure fallback flag).

        "dense" from the cost model is a kernel *class* (one-hot vs scatter);
        the Pallas kernel is its hand-scheduled implementation and is
        preferred whenever a TPU backend is present."""
        from ..ops.groupby import resolve_strategy
        from ..ops.pallas_groupby import pallas_available

        if self.strategy == "dense":
            from ..ops.groupby import SCATTER_CUTOVER

            if (
                num_groups <= SCATTER_CUTOVER
                and not self._pallas_broken
                and pallas_available()
            ):
                return "pallas"
            return "dense"
        if self.strategy == "sparse":
            # "sparse" is an execution-layer accelerator, not a kernel
            # strategy: when the sparse path declines a query (low G, sketch
            # aggs, overflow) the standard path resolves as if "auto"
            return resolve_strategy(
                "auto", num_groups, pallas_ok=not self._pallas_broken
            )
        return resolve_strategy(
            self.strategy, num_groups, pallas_ok=not self._pallas_broken
        )

    def _segment_program(
        self, q: Q.GroupByQuery, ds: DataSource, lowering: "GroupByLowering"
    ) -> Callable:
        """One fused, cached XLA program per query: row pipeline (virtual
        columns, filter mask, group ids) + partial aggregation + sketch
        partials in a single dispatch.  The analog of Druid compiling a query
        into one engine pass per segment."""
        la, G = lowering.la, lowering.num_groups
        strategy = self._resolve_strategy(G)
        # _query_key includes schema_signature: a re-ingested datasource
        # (new dict cardinalities => new G) must not reuse a stale program
        key = _query_key(q, ds) + (strategy,)
        if key in self._query_fn_cache:
            if self._m is not None:
                self._m.program_cache_hit = True
            return self._query_fn_cache[key]

        from ..ops import hll as hll_ops
        from ..ops import theta as theta_ops

        @jax.jit
        def seg_fn(cols):
            cols = lowering.add_virtual(dict(cols))  # sketches read virtuals
            gid, mask, sv, mmv, mmm = lowering.row_arrays(cols)
            s, mn, mx = partial_aggregate(
                gid, mask, sv, mmv, mmm,
                num_groups=G,
                num_min=len(la.min_names),
                num_max=len(la.max_names),
                strategy=strategy,
            )
            sk = {}
            for agg in la.sketch_aggs:
                if isinstance(agg, (A.HyperUnique, A.CardinalityAgg)):
                    sk[agg.name] = hll_ops.partial_hll(agg, cols, gid, mask, G)
                else:
                    sk[agg.name] = theta_ops.partial_theta(
                        agg, cols, gid, mask, G
                    )
            return s, mn, mx, sk

        self._query_fn_cache[key] = seg_fn
        return seg_fn

    # -- sparse (sort-compaction) path for high-cardinality domains ----------

    def _sparse_eligible(self, lowering: "GroupByLowering") -> bool:
        """Sparse applies when the scatter path would otherwise run: huge
        combined domain, plain (non-sketch) aggregates, and real dimensions.
        Sketch states are [G, registers] dense — compaction would have to
        re-key them too; at high G those queries stay on scatter."""
        from ..ops.groupby import SCATTER_CUTOVER

        # explicit strategy='segment' is the raw-scatter escape hatch and is
        # honored as such (ADVICE r1: the sparse accelerator must not hijack
        # an explicitly requested kernel); the cost model emits 'sparse' when
        # compaction should run
        return (
            lowering.num_groups > SCATTER_CUTOVER
            and not lowering.la.sketch_aggs
            and bool(lowering.dims)
            and self.strategy in ("auto", "dense", "sparse")
        )

    def _sparse_program(
        self, q: Q.GroupByQuery, ds: DataSource, lowering: "GroupByLowering"
    ) -> Callable:
        from ..ops.pallas_groupby import pallas_available
        from ..ops.sparse_groupby import sparse_partial_aggregate

        la = lowering.la
        # inner kernel over the compacted slots: the Pallas one-hot on TPU;
        # scatter on CPU backends (4096-slot one-hot matmuls starve a CPU,
        # and at `slots` segments CPU scatter is cheap)
        inner = (
            "pallas"
            if not self._pallas_broken and pallas_available()
            else "segment"
        )
        key = _query_key(q, ds) + (f"sparse:{inner}",)
        if key in self._query_fn_cache:
            if self._m is not None:
                self._m.program_cache_hit = True
            return self._query_fn_cache[key]

        @jax.jit
        def seg_fn(cols):
            gid, mask, sv, mmv, mmm = lowering.row_arrays(dict(cols))
            return sparse_partial_aggregate(
                gid, mask, sv, mmv, mmm,
                num_groups=lowering.num_groups,
                num_min=len(la.min_names),
                num_max=len(la.max_names),
                inner_strategy=inner,
            )

        self._query_fn_cache[key] = seg_fn
        return seg_fn

    def _execute_groupby_sparse(
        self, q: Q.GroupByQuery, ds: DataSource, lowering: "GroupByLowering"
    ):
        """Sparse execution attempt over the (non-empty) segment scope.
        Returns the result DataFrame, or None to fall back (overflow; any
        sparse-path compile/runtime failure even after the Pallas-inner
        retry — correctness never depends on this path)."""
        from ..ops.sparse_groupby import merge_sparse_states

        segs = self._segments_in_scope(q, ds)
        G = lowering.num_groups

        def run():
            seg_fn = self._sparse_program(q, ds, lowering)
            state = None
            for seg in segs:
                cols = self._device_cols(seg, lowering.columns)
                if ds.time_column and ds.time_column in cols:
                    cols["__time"] = cols[ds.time_column]
                st = seg_fn(cols)
                state = (
                    st
                    if state is None
                    else merge_sparse_states(state, st, num_groups=G)
                )
            return jax.device_get(state)

        def evict():
            # only THIS query's sparse programs — other queries' compiled
            # sparse programs are fine and expensive to rebuild
            base = _query_key(q, ds)
            for k in [
                k
                for k in self._query_fn_cache
                if k[:2] == base and str(k[2]).startswith("sparse")
            ]:
                del self._query_fn_cache[k]

        from ..ops.pallas_groupby import pallas_available

        try:
            host = run()
        except Exception:
            evict()
            # mirror _call_segment_program: a Mosaic failure of the Pallas
            # inner kernel downgrades to the scatter inner, not to the
            # whole-query scatter path
            if self._pallas_broken or not pallas_available():
                return None
            self._pallas_broken = True
            try:
                host = run()
            except Exception:
                self._pallas_broken = False
                evict()
                return None
        if bool(host["overflow"]):
            return None
        return finalize_groupby(
            q,
            lowering.dims,
            lowering.la,
            np.asarray(host["sums"]),
            np.asarray(host["mins"]),
            np.asarray(host["maxs"]),
            {},
            slot_gids=np.asarray(host["gids"]),
        )

    def _execute_groupby(self, q: Q.GroupByQuery, ds: DataSource):
        import time as _time

        from .metrics import QueryMetrics

        t_total = _time.perf_counter()
        q = groupby_with_time_granularity(q)
        lowering = lower_groupby(q, ds)
        segs = self._segments_in_scope(q, ds)
        m = self._m = QueryMetrics(
            query_type="groupBy",
            strategy=self._resolve_strategy(lowering.num_groups),
            rows_scanned=sum(s.num_rows for s in segs),
            segments=len(segs),
            num_groups=lowering.num_groups,
        )
        try:
            if self._sparse_eligible(lowering) and segs:
                qkey = _query_key(q, ds)
                if qkey not in self._sparse_disabled:
                    m.strategy = "sparse"
                    t0 = _time.perf_counter()
                    out = self._execute_groupby_sparse(q, ds, lowering)
                    if out is not None:
                        m.device_ms = (_time.perf_counter() - t0) * 1e3
                        return out
                    self._sparse_disabled.add(qkey)
                    m.strategy = self._resolve_strategy(lowering.num_groups)
            t0 = _time.perf_counter()
            dims, la, G, sums, mins, maxs, sketch_states = (
                self._partials_for_query(q, ds, lowering=lowering)
            )
            # ONE device_get for everything: each separate host fetch of a
            # device buffer pays a full round trip (dozens of ms when the TPU
            # sits behind a network tunnel); a single pytree fetch pays one.
            sums, mins, maxs, sketch_states = jax.device_get(
                (sums, mins, maxs, sketch_states)
            )
            m.device_ms = (
                (_time.perf_counter() - t0) * 1e3
                - m.h2d_ms
                - m.compile_ms
            )
            t0 = _time.perf_counter()
            out = finalize_groupby(
                q, dims, la,
                np.asarray(sums), np.asarray(mins), np.asarray(maxs),
                {k: np.asarray(v) for k, v in sketch_states.items()},
            )
            m.finalize_ms = (_time.perf_counter() - t0) * 1e3
            return out
        finally:
            m.total_ms = (_time.perf_counter() - t_total) * 1e3
            m.bytes_resident = self.bytes_resident()
            self.last_metrics = m
            self._m = None

    # -- timeseries: a groupby whose only dimension is the time bucket -------

    def _execute_timeseries(self, q: Q.TimeseriesQuery, ds: DataSource):
        df = self._execute_groupby(timeseries_to_groupby(q), ds)
        return finalize_timeseries(df, q, ds)

    # -- topn: single-dim groupby + rank (exact; Druid's is approximate) -----

    def _execute_topn(self, q: Q.TopNQuery, ds: DataSource):
        df = self._execute_groupby(topn_to_groupby(q), ds)
        return finalize_topn(df, q)

    # -- scan / search -------------------------------------------------------

    def _execute_scan(self, q: Q.ScanQuery, ds: DataSource):
        import pandas as pd

        filter_fn = compile_filter(q.filter, ds) if q.filter is not None else None
        vcol_fns = {
            v.name: _decoded_expr_fn(v.expression, ds)
            for v in q.virtual_columns
        }
        need = [c for c in q.columns if c not in vcol_fns and c != "__time"]
        if q.filter is not None:
            need += [c for c in _filter_columns(q.filter) if c != "__time"]
        for v in q.virtual_columns:
            need += [c for c in v.expression.columns() if c != "__time"]
        if ds.time_column:
            need.append(ds.time_column)
        need = dict.fromkeys(need)
        frames = []
        remaining = q.limit
        for seg in self._segments_in_scope(q, ds):
            cols = self._device_cols(seg, need)
            if ds.time_column and ds.time_column in cols:
                cols["__time"] = cols[ds.time_column]
            for name, fn in vcol_fns.items():
                cols[name] = jnp.asarray(fn(cols))
            mask = cols["__valid"]
            if q.intervals:
                t = cols["__time"]
                im = jnp.zeros(t.shape, jnp.bool_)
                for a, b in q.intervals:
                    im = im | ((t >= a) & (t < b))
                mask = mask & im
            if filter_fn is not None:
                mask = mask & filter_fn(cols)
            # one round trip for the mask + all projected columns
            fetched = jax.device_get(
                {"__mask": mask, **{c: cols[c] for c in q.columns}}
            )
            keep = fetched.pop("__mask")
            data = {}
            for c in q.columns:
                arr = fetched[c][keep]
                if c in ds.dicts:
                    arr = ds.dicts[c].decode(arr)
                data[c] = arr
            f = pd.DataFrame(data)
            if remaining is not None:
                f = f.head(remaining)
                remaining -= len(f)
            frames.append(f)
            if remaining is not None and remaining <= 0:
                break
        return (
            pd.concat(frames, ignore_index=True)
            if frames
            else pd.DataFrame(columns=list(q.columns))
        )

    def _execute_search(self, q: Q.SearchQuery, ds: DataSource):
        import pandas as pd

        rows = []
        needle = q.query.lower()
        for dim in q.dimensions:
            if len(rows) >= q.limit:
                break
            for v in ds.dicts[dim].values:
                if needle in str(v).lower():
                    rows.append({"dimension": dim, "value": v})
                    if len(rows) >= q.limit:
                        break
        return pd.DataFrame(rows, columns=["dimension", "value"])


def empty_partials(la: LoweredAggs, G: int):
    """Zero-row partial state (identity of every merge class) — shared by
    the segment-pruned-to-nothing path and the empty-stream path."""
    sums = jnp.zeros((G, len(la.sum_names)), jnp.float32)
    mins = jnp.full((G, len(la.min_names)), jnp.inf, jnp.float32)
    maxs = jnp.full((G, len(la.max_names)), -jnp.inf, jnp.float32)
    sketch_states: Dict[str, jnp.ndarray] = {}
    for agg in la.sketch_aggs:
        if isinstance(agg, (A.HyperUnique, A.CardinalityAgg)):
            sketch_states[agg.name] = jnp.zeros(
                (G, 1 << agg.precision), jnp.int32
            )
        else:
            from ..ops.theta import SENTINEL

            sketch_states[agg.name] = jnp.full(
                (G, agg.size), SENTINEL, jnp.uint32
            )
    return sums, mins, maxs, sketch_states


def groupby_with_time_granularity(q: Q.GroupByQuery) -> Q.GroupByQuery:
    """Druid semantics shared by all executors: a non-'all' granularity on
    GroupBy adds an implicit leading time-bucket dimension (one result row
    per bucket per group)."""
    if q.granularity in ("all", None) or any(
        d.dimension == "__time" or d.granularity for d in q.dimensions
    ):
        return q
    return dataclasses.replace(
        q,
        dimensions=(
            DimensionSpec("__time", "timestamp", granularity=q.granularity),
        )
        + tuple(q.dimensions),
        granularity="all",
    )


def _merge_sketch_states(
    la: LoweredAggs, acc: Dict[str, Any], new: Dict[str, Any]
) -> None:
    """Merge one segment's sketch partials into the accumulator in place:
    HLL registers max-merge; theta states union (shared with streaming)."""
    from ..ops import theta as theta_ops

    for agg in la.sketch_aggs:
        st = new[agg.name]
        prev = acc.get(agg.name)
        if prev is None:
            acc[agg.name] = st
        elif isinstance(agg, (A.HyperUnique, A.CardinalityAgg)):
            acc[agg.name] = jnp.maximum(prev, st)
        else:
            acc[agg.name] = theta_ops.merge_states(prev, st, agg.size)


# ---------------------------------------------------------------------------
# Shared finalization (also used by the distributed path)
# ---------------------------------------------------------------------------


def finalize_groupby(
    q: Q.GroupByQuery,
    dims: List[ResolvedDim],
    la: LoweredAggs,
    sums: np.ndarray,
    mins: np.ndarray,
    maxs: np.ndarray,
    sketch_states: Dict[str, np.ndarray],
    slot_gids: Optional[np.ndarray] = None,
):
    """Merged partial state -> result DataFrame (decode, post-aggs, having,
    order/limit) — the broker-side finalization of SURVEY.md §3.3.

    `slot_gids` switches to sparse-state layout (ops/sparse_groupby.py):
    arrays are slot-indexed and slot_gids maps slot -> combined gid (-1 =
    empty slot)."""
    import pandas as pd

    rows_per_group = sums[:, 0]
    if slot_gids is not None:
        present = (slot_gids >= 0) & (rows_per_group > 0)
        sel = np.nonzero(present)[0]
        idx = slot_gids[sel].astype(np.int64)  # combined gid per kept row
        empty_group = np.zeros(len(sel), dtype=bool)
    else:
        present = rows_per_group > 0
        if not dims:
            # SQL: a global aggregate always yields one row (COUNT=0, SUM/
            # MIN/MAX=NULL when nothing matched) — never an empty result
            present = np.ones_like(present, dtype=bool)
        sel = np.nonzero(present)[0]
        idx = sel.astype(np.int64)
        empty_group = rows_per_group[sel] == 0

    table: Dict[str, np.ndarray] = {}
    # decode combined gid -> per-dimension codes (row-major order)
    rem = idx
    codes_list = []
    for d in reversed(dims):
        codes_list.append((rem % d.cardinality).astype(np.int64))
        rem = rem // d.cardinality
    codes_list.reverse()
    for d, codes in zip(dims, codes_list):
        table[d.spec.name] = d.decode(codes)

    for j, n in enumerate(la.sum_names):
        if n == "__rows":
            continue
        v = sums[sel, j].astype(np.float64)
        if n in la.count_like or not empty_group.any():
            table[n] = np.rint(v).astype(np.int64) if la.long_valued[n] else v
        else:
            # SQL: SUM over zero rows is NULL; COUNT stays 0
            table[n] = np.where(empty_group, np.nan, v)
    def _finalize_extremum(v: np.ndarray, long_valued: bool) -> np.ndarray:
        v = v.astype(np.float64)
        v = np.where(np.isinf(v), np.nan, v)
        if long_valued and not np.isnan(v).any():
            return np.rint(v).astype(np.int64)
        return v

    for j, n in enumerate(la.min_names):
        table[n] = _finalize_extremum(mins[sel, j], la.long_valued[n])
    for j, n in enumerate(la.max_names):
        table[n] = _finalize_extremum(maxs[sel, j], la.long_valued[n])

    raw_states: Dict[str, np.ndarray] = {}
    for agg in la.sketch_aggs:
        from ..ops import hll as hll_ops
        from ..ops import theta as theta_ops

        st = sketch_states[agg.name][sel]
        raw_states[agg.name] = st
        if isinstance(agg, (A.HyperUnique, A.CardinalityAgg)):
            table[agg.name] = np.rint(hll_ops.estimate(st)).astype(np.int64)
        else:
            table[agg.name] = np.rint(theta_ops.estimate(st)).astype(np.int64)

    for p in q.post_aggregations:
        table[p.name] = np.broadcast_to(
            eval_post_agg(p, table, raw_states), sel.shape
        ).copy()

    if q.having is not None:
        m = _eval_having(q.having, table)
        table = {k: np.asarray(v)[m] for k, v in table.items()}

    df = pd.DataFrame(table)

    # grouping-set subtotals (CUBE/ROLLUP) are handled by the planner issuing
    # one query per set and concatenating — see plan/transforms.py.

    if q.limit_spec is not None:
        ls = q.limit_spec
        if ls.columns:
            df = df.sort_values(
                [c.dimension for c in ls.columns],
                ascending=[c.direction == "ascending" for c in ls.columns],
                kind="stable",
            )
        if ls.offset:
            df = df.iloc[ls.offset :]
        if ls.limit is not None:
            df = df.head(ls.limit)
    return df.reset_index(drop=True)


# ---------------------------------------------------------------------------
# Column discovery helpers
# ---------------------------------------------------------------------------


def _agg_columns(a: A.Aggregation) -> List[str]:
    if isinstance(a, A.FilteredAgg):
        return _filter_columns(a.filter) + _agg_columns(a.aggregator)
    if isinstance(a, A.ExpressionAgg):
        return list(a.expression.columns())
    if isinstance(a, A.Count):
        return []
    if isinstance(a, A.CardinalityAgg):
        return list(a.field_names)
    return [a.field_name]  # type: ignore[attr-defined]


def _filter_columns(f: Filter) -> List[str]:
    from ..models import filters as F

    if isinstance(f, (F.Selector, F.InFilter, F.Bound, F.Regex, F.LikeFilter)):
        return [f.dimension]
    if isinstance(f, (F.And, F.Or)):
        out: List[str] = []
        for x in f.fields:
            out.extend(_filter_columns(x))
        return out
    if isinstance(f, F.Not):
        return _filter_columns(f.field)
    if isinstance(f, F.IntervalFilter):
        return ["__time"] if f.dimension == "__time" else [f.dimension]
    if isinstance(f, F.ExpressionFilter):
        return list(f.expression.columns())
    return []
