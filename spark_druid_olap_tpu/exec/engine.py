"""Single-device query engine: QuerySpec × DataSource -> result table.

Reference parity: this layer replaces the external Druid cluster.  In
spark-druid-olap, `DruidRDD.compute` POSTs the query JSON to a broker /
historical and streams result rows back (SURVEY.md §3.3 `[U]`); the actual
aggregation happens inside Druid.  Here `Engine.execute` runs the same query
spec locally: segment columns (dictionary codes + metrics) are moved to TPU
HBM once and cached (the analog of Druid's segment residency / page cache),
the filter+aggregate runs as fused XLA (ops/groupby.py), and only the tiny
[G, M] aggregate state returns to host for finalization (decode group ids,
post-aggregations, having, sort/limit — the work Druid's broker does after
its scatter-gather merge).

Distributed execution (the broker scatter-gather analog over ICI) lives in
parallel/distributed.py.  Query lowering is exec/lowering.py and host-side
result finalization is exec/finalize.py — both shared by the local,
distributed, and streaming executors and re-exported here.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..catalog.segment import DataSource, Segment
from ..models import aggregations as A
from ..models import query as Q
from ..ops import hll as hll_ops
from ..ops import quantiles as quantiles_ops
from ..ops import theta as theta_ops
from ..ops.filters import compile_filter
from ..ops.groupby import partial_aggregate

# Lowering + finalization were split out of this module (VERDICT r1 weak #8);
# re-exported here because the distributed/streaming executors and external
# users import them from exec.engine.
from .lowering import (  # noqa: F401
    GroupByLowering,
    LoweredAggs,
    ResolvedDim,
    _agg_columns,
    _decoded_expr_fn,
    _filter_columns,
    _query_key,
    empty_partials,
    groupby_with_time_granularity,
    lower_groupby,
    memo_key,
    schema_signature,
    timeseries_to_groupby,
    topn_to_groupby,
)
from .finalize import (  # noqa: F401
    _eval_having,
    _merge_sketch_states,
    apply_limit_spec,
    eval_post_agg,
    finalize_groupby,
    finalize_timeseries,
    finalize_topn,
)
from ..obs import (
    SPAN_DEVICE_FETCH,
    SPAN_FINALIZE,
    SPAN_H2D,
    SPAN_LOWER,
    SPAN_SEGMENT_DISPATCH,
    current_query_id,
    prof,
    record_query_metrics,
    span,
)
from ..resilience import checkpoint, checkpoint_partial, current_partial, fire
from ..utils.log import get_logger
from .adaptive_exec import AdaptiveDomainMixin
from .sparse_exec import SparseExecMixin

log = get_logger("exec.engine")


def _bytes_scanned(segs, columns) -> int:
    """Bytes of segment data a query's kernel reads: needed columns plus
    the validity mask (and time when fetched) over REAL rows — the
    roofline numerator (QueryMetrics.bytes_scanned)."""
    total = 0
    # graftlint: disable=checkpoint-coverage -- O(segments) host metadata sum, no dispatch/decode per iteration
    for s in segs:
        row_bytes = 1  # valid mask
        for n in columns:
            try:
                row_bytes += s.column(n).dtype.itemsize
            except KeyError:
                pass  # virtual columns are computed, not read
        total += row_bytes * s.num_rows
    return total


def _row_counts(segs) -> Tuple[int, int]:
    """(total real rows, delta-segment rows) of a segment list — the
    partial-result coverage accounting unit.  Delta rows are reported
    separately so a best-effort answer can say how much of it came from
    freshly-appended data vs historicals (and the ingest hammer can
    assert deltas are never double-counted)."""
    from ..catalog.segment import DeltaSegment

    rows = delta = 0
    # graftlint: disable=checkpoint-coverage -- O(segments) host metadata sum, no dispatch/decode per iteration
    for s in segs:
        rows += s.num_rows
        if isinstance(s, DeltaSegment):
            delta += s.num_rows
    return rows, delta


def _pack_host_state(sums, mins, maxs, sketches=None) -> dict:
    """Canonical HOST partial-state dict — the interchange currency of
    the unified executor core: the result cache stores it,
    merge_groupby_states ⊕'s it, finalize_groupby_state renders it, and
    the mesh executor (parallel/distributed) returns the identical shape
    from its collective merge, so every consumer stays backend-agnostic."""
    return {
        "sums": np.asarray(sums),
        "mins": np.asarray(mins),
        "maxs": np.asarray(maxs),
        "sketches": {
            k: np.asarray(v) for k, v in (sketches or {}).items()
        },
    }


def _prune_by_stats(segs, filt, ds: DataSource, vcol_names=frozenset()):
    """Zone-map pruning on a CONSERVATIVE filter subset: top-level AND
    conjuncts that are Selector/In over dictionary columns (matched in code
    space — dictionaries are datasource-global, so codes compare across
    segments) or numeric Bounds over metric columns.  Everything else
    (OR, NOT, expressions, string bounds) is left to the row kernel —
    pruning may only ever REMOVE provably-empty segments.

    `vcol_names`: virtual-column names defined by the query.  A filter on
    a virtual column that SHADOWS a physical column evaluates against the
    virtual values at execution, so pruning it against the physical
    column's stats would silently drop live segments — skip those."""
    from ..models import filters as F

    def _conjuncts(f):
        # the planner builds Ands pairwise (And(And(a, b), c)): flatten
        # recursively or buried conjuncts never get a pruning look
        if isinstance(f, F.And):
            out = []
            for x in f.fields:
                out.extend(_conjuncts(x))
            return out
        return [f]

    conjuncts = _conjuncts(filt)

    def excluded(seg, c) -> bool:
        if getattr(c, "dimension", None) in vcol_names:
            return False
        if isinstance(c, F.Or):
            # a disjunction can only match if SOME disjunct can
            return bool(c.fields) and all(
                excluded(seg, x) for x in c.fields
            )
        if isinstance(c, F.And):
            return any(excluded(seg, x) for x in c.fields)
        st = seg.stats or {}
        if isinstance(c, F.Selector):
            if c.value is None or c.dimension not in ds.dicts:
                return False  # null stats aren't tracked
            code = ds.dicts[c.dimension].code_of(c.value)
            if code is None:
                return True  # value absent from the whole datasource
            b = st.get(c.dimension)
            return b is not None and not (b[0] <= code <= b[1])
        if isinstance(c, F.InFilter):
            if c.dimension not in ds.dicts:
                return False
            if any(v is None for v in c.values):
                return False  # null membership isn't in the stats
            codes = [
                x
                for x in (
                    ds.dicts[c.dimension].code_of(v) for v in c.values
                )
                if x is not None
            ]
            if not codes:
                return True  # none of the values exist in the datasource
            b = st.get(c.dimension)
            return b is not None and not any(
                b[0] <= x <= b[1] for x in codes
            )
        if isinstance(c, F.Bound) and c.ordering == "numeric":
            b = st.get(c.dimension)
            if b is None:
                return False
            if c.dimension in ds.dicts:
                # numeric dictionary: translate to code space with the SAME
                # helper the kernel compile uses (ops/filters.py), then
                # compare against the code-space zone map
                nv = ds.dicts[c.dimension].numeric_values
                if nv is None:
                    return False
                from ..ops.filters import numeric_dict_code_bounds

                cb = numeric_dict_code_bounds(c, np.asarray(nv))
                if cb is None:
                    return False
                lo_code, hi_code = cb
                if lo_code is not None and b[1] < lo_code:
                    return True
                if hi_code is not None and b[0] > hi_code:
                    return True
                return False
            try:
                if c.lower is not None:
                    lo = float(c.lower)
                    if b[1] < lo or (c.lower_strict and b[1] <= lo):
                        return True
                if c.upper is not None:
                    hi = float(c.upper)
                    if b[0] > hi or (c.upper_strict and b[0] >= hi):
                        return True
            except ValueError:
                return False
            return False
        return False

    out = [
        s for s in segs if not any(excluded(s, c) for c in conjuncts)
    ]
    if len(out) < len(segs):
        log.info(
            "zone maps pruned %d of %d segments", len(segs) - len(out),
            len(segs),
        )
    return out

def segments_in_scope(q, ds: DataSource) -> List[Segment]:
    """Segment pruning: by time interval (the analog of the reference
    narrowing the Druid query interval from time predicates, §3.2) and
    by per-segment zone maps (SURVEY.md §2 metadata "stats" row) —
    a top-level filter conjunct whose values provably fall outside a
    segment's [min, max] excludes that segment without a dispatch.
    Module-level: the distributed engine shares this exact policy for its
    metrics scope (its shards span the full set; the row mask excludes)."""
    segs = list(ds.segments)
    if q.intervals:
        out = []
        # graftlint: disable=checkpoint-coverage -- interval pruning is O(segments) metadata arithmetic, no per-iteration work
        for s in segs:
            if s.interval is None:
                out.append(s)
                continue
            lo, hi = s.interval
            if any(a <= hi and lo < b for a, b in q.intervals):
                out.append(s)
        segs = out
    filt = getattr(q, "filter", None)
    if filt is not None and segs:
        vcols = frozenset(
            v.name for v in getattr(q, "virtual_columns", ()) or ()
        )
        segs = _prune_by_stats(segs, filt, ds, vcols)
    return segs


# Above this many in-scope segments a query stops unrolling them into one
# fused program (compile time grows linearly with the unroll) and falls back
# to the per-segment dispatch loop.  Below it, the whole query is ONE device
# dispatch + ONE host fetch — the difference between ~4 and ~N+2 round trips,
# which dominates warm latency when the TPU sits behind a network tunnel.
MULTI_SEGMENT_UNROLL_MAX = 32

# On the CPU backend the fused mega-program is a double loss: XLA schedules
# the long unrolled scatter/one-hot chain ~2x slower than the same work as
# small programs (measured at SF2: 57 -> 111 Mrows/s on a G=504k scatter by
# capping the unroll at 2), and compile time is brutal (~60 s for a
# 12-segment unroll of an 8k-group scatter vs ~2 s for the pair program).
# Local dispatch costs microseconds, so small batches only forgo RPC
# amortization that CPU never needed; the cross-batch partial merge in
# _partials_for_query is unchanged.
CPU_SEGMENT_UNROLL_MAX = 2


def _platform_unroll_max() -> int:
    from ..config import _current_platform

    if _current_platform() == "cpu":
        return CPU_SEGMENT_UNROLL_MAX
    return MULTI_SEGMENT_UNROLL_MAX

# Consecutive sparse-path exception fallbacks before a query is pinned off
# the accelerator (transient blips recover; deterministic failures stop
# re-paying doomed trace+compiles).
_SPARSE_ERROR_PIN_AFTER = 2


def _segment_partials(
    lowering: "GroupByLowering", strategy: str, cols, memo=None, share=None
):
    """Partial-aggregate one segment's columns under one query lowering —
    the traced body shared by the single-query fused program and the
    multi-query fused-batch program (serve/ micro-batch fusion): virtual
    columns, row pipeline, dense partial aggregation, sketch partials.

    `memo`/`share` power the fused-batch common-subexpression dedup
    (serve/fusion.shared_row_plan, ROADMAP 1(a)): `share` is
    `(mask_group, gid_group, segment_index)` and `memo` a per-trace dict
    — members whose filter/dimension sub-lowerings are identical reuse
    ONE traced mask / gid per segment instead of re-tracing them.

    This function runs DURING jit tracing: the sketch-op modules it
    needs are imported at engine module scope (below), never here — a
    first import inside a trace would create their module-level jnp
    constants (theta.SENTINEL) as tracers that leak into later traces."""
    la, G = lowering.la, lowering.num_groups
    cols = lowering.add_virtual(dict(cols))  # sketches read virtuals
    mask0 = gid0 = None
    if memo is not None and share is not None:
        mg, gg, j = share
        mask0 = memo.get(("mask", mg, j))
        gid0 = memo.get(("gid", gg, j))
    gid, mask, sv, mmv, mmm = lowering.row_arrays(cols, mask=mask0, gid=gid0)
    if memo is not None and share is not None:
        memo.setdefault(("mask", mg, j), mask)
        memo.setdefault(("gid", gg, j), gid)
    s, mn, mx = partial_aggregate(
        gid, mask, sv, mmv, mmm,
        num_groups=G,
        num_min=len(la.min_names),
        num_max=len(la.max_names),
        strategy=strategy,
    )
    sk = {}
    for agg in la.sketch_aggs:
        # per-agg FILTER mask (SQL `agg(...) FILTER (WHERE ...)`)
        # composes with the row mask — sketches must honor it the
        # same way sum/min/max columns do
        mfn = la.mask_fns.get(agg.name)
        amask = mask & mfn(cols) if mfn is not None else mask
        if isinstance(agg, (A.HyperUnique, A.CardinalityAgg)):
            sk[agg.name] = hll_ops.partial_hll(agg, cols, gid, amask, G)
        elif isinstance(agg, A.QuantilesSketch):
            sk[agg.name] = quantiles_ops.partial_quantiles(
                agg, cols, gid, amask, G
            )
        else:
            sk[agg.name] = theta_ops.partial_theta(
                agg, cols, gid, amask, G
            )
    return s, mn, mx, sk


def _default_device_budget() -> int:
    """Residency byte budget when the caller does not pin one.

    TPU/GPU: 3/4 of the device's HBM (12 GiB on a 16 GiB v5e when the
    runtime does not report memory stats), leaving the rest for kernel
    workspace and merge states.  The round-4 default of 4 GiB looked
    safe but was a trap at SF100: the ~9 GB working set thrashed through
    the eviction window, and over the tunneled link (45 MB/s measured)
    each re-upload of an evicted column set cost minutes per query.
    CPU backend: "device" buffers ARE host RAM, so evicting to re-copy is
    pure waste — budget half the machine's memory instead (SF100's 51 GB
    of encoded segments stays resident across queries on a 125 GB host
    rather than re-streaming ~15 GB per query through a 4 GiB window)."""
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform != "cpu":
            try:
                hbm = int(dev.memory_stats()["bytes_limit"])
                return hbm * 3 // 4
            except Exception:  # fault-ok: capacity probe; sized fallback below
                # no memory stats: size by known device kinds, else stay
                # at the conservative floor (a 12 GiB budget on an 8 GiB
                # accelerator would turn graceful eviction into hard OOM)
                kind = str(getattr(dev, "device_kind", "")).lower()
                if "v5 lite" in kind or "v5e" in kind:
                    return 12 << 30
                return 4 << 30
        import os

        pages = os.sysconf("SC_PHYS_PAGES")
        page = os.sysconf("SC_PAGE_SIZE")
        return max(4 << 30, int(pages * page) // 2)
    except Exception:  # fault-ok: capacity probe; conservative floor below
        return 4 << 30


class Engine(AdaptiveDomainMixin, SparseExecMixin):
    """Executes query specs on the local device set.

    `strategy` mirrors the reference's cost-model execution choice
    (SURVEY.md §2 DruidQueryCostModel `[U]`): "auto" lets plan/cost.py pick
    dense-one-hot vs scatter from the group cardinality."""

    def __init__(
        self,
        strategy: str = "auto",
        device_cache_bytes: Optional[int] = None,
        program_cache_entries: int = 256,
    ):
        from ..utils.lru import ByteBudgetCache, CountBudgetCache

        if device_cache_bytes is None:
            device_cache_bytes = _default_device_budget()
        self.strategy = strategy
        # observability (SURVEY.md §5): populated on every execution
        self.last_metrics = None
        # metrics object being filled during one execution — THREAD-LOCAL
        # (the `_m` property below): the serving layer runs concurrent
        # queries through ONE engine, and a shared field let query A's
        # finish() null the object query B was mid-way through stamping
        # (crash) while both garbled each other's h2d/compile attribution
        import threading as _threading

        self._m_local = _threading.local()
        self._pallas_broken = False  # set on first Mosaic-compile failure
        # resilience wiring (resilience.py): transient device failures and
        # recoveries are reported to the breaker; TPUOlapContext replaces
        # this default with its shared per-context breaker and syncs the
        # retry budget from SessionConfig.  The breaker never gates THIS
        # layer — routing around an open circuit is the api's job.
        from ..resilience import CircuitBreaker

        self.breaker = CircuitBreaker()
        self._retry_attempts = 2  # total attempts (2 = one retry)
        self._retry_backoff_ms = 25.0
        # queries pinned off the sparse accelerator because compaction
        # deterministically overflowed SPARSE_SLOTS distinct groups.
        # Exception fallbacks do NOT pin immediately (a transient device
        # blip must not demote a query for the engine's lifetime) but are
        # counted per query: repeated failures pin after
        # _SPARSE_ERROR_PIN_AFTER so a deterministically-broken sparse
        # lowering stops re-paying doomed trace+compiles every execution.
        self._sparse_disabled: set = set()
        self._sparse_error_counts: Dict = {}
        # queries whose survivors overflowed the base row-compaction
        # capacity: the kernel reports the exact survivor count, the engine
        # picks the smallest adequate ROW_CAPACITY_LADDER rung (None = full
        # sort) — deterministic for a given (query, data), so repeats go
        # straight to the remembered rung
        self._sparse_row_capacity: Dict = {}
        # adaptive dictionary-domain compaction (exec/adaptive_exec.py):
        # per-query kept code sets and the decline memo
        self._adaptive_kept: Dict = {}
        self._adaptive_declined: set = set()
        # queries whose distinct-present count overflowed the one-hot slot
        # tier: remembered SLOTS_LADDER rung for the segmented-reduce tier
        # (sparse_exec.fetch_slot_laddered)
        self._sparse_slots: Dict = {}
        # LRU residency cache under a byte budget (VERDICT r1 weak #7: the
        # unbounded caches OOMed HBM over long sessions).  4 GiB default
        # leaves headroom on a 16 GiB v5e chip for kernel workspace.
        self._device_cache = ByteBudgetCache(device_cache_bytes)
        # residency attribution (obs/prof.py, ISSUE 9): per-datasource
        # resident-bytes gauges + budget-pressure eviction counters need
        # a key -> (datasource, bytes) side table (cache keys carry only
        # segment uids)
        self._resident_lock = _threading.Lock()
        self._resident_meta: Dict = {}
        self._resident_by_ds: Dict[str, int] = {}
        self._device_cache.on_evict = (
            lambda key, arr: self._note_resident_drop(key, evicted=True)
        )
        # (query-json, datasource, strategy) -> jitted per-segment program.
        # One fused XLA program per query shape: without this, every eager op
        # in the row pipeline is a separate device dispatch — ruinous when the
        # TPU sits behind a network tunnel (hundreds of ms of pure latency).
        self._query_fn_cache = CountBudgetCache(program_cache_entries)
        # (query-json, datasource) -> GroupByLowering.  Lowering is host work
        # that also stages device constants (dictionary remaps, bucket tables,
        # filter literal sets); rebuilding it per execution pays one blocking
        # H2D transfer per constant — the warm-path killer over a tunnel.
        self._lowering_cache = CountBudgetCache(program_cache_entries)
        # overlapped h2d transfer pipeline (exec/pipeline.py, ISSUE 10):
        # prefetches the next dispatch batches' cold columns behind the
        # current batch's compute and orders dispatch resident-first.
        # TPUOlapContext re-configures it from SessionConfig
        # (configure_pipeline); a bare Engine() gets the defaults.
        from .pipeline import TransferPipeline

        self._pipeline = TransferPipeline(self)
        # one-dispatch arena execution (exec/arena.py, ISSUE 14): stack
        # the uniform-shape prefix of a scope and fold it inside ONE
        # scanned program instead of one dispatch per segment batch.
        # TPUOlapContext syncs this from SessionConfig.arena_execution
        # (configure_pipeline); per-query opt-out via
        # arena.arena_disabled().
        self.arena_execution = True

    @property
    def _m(self):
        """The execution THIS THREAD is currently stamping metrics into
        (None outside an execution).  `last_metrics` stays shared —
        "most recent" is a cross-thread statement by design."""
        return getattr(self._m_local, "m", None)

    @_m.setter
    def _m(self, value):
        self._m_local.m = value

    # -- segment residency ---------------------------------------------------

    def _note_resident_add(self, key, ds_name: str, nbytes: int) -> None:
        with self._resident_lock:
            prev = self._resident_meta.get(key)
            if prev is not None:  # re-put of a live key: replace, not add
                self._resident_by_ds[prev[0]] = max(
                    0, self._resident_by_ds.get(prev[0], 0) - prev[1]
                )
            self._resident_meta[key] = (ds_name, nbytes)
            self._resident_by_ds[ds_name] = (
                self._resident_by_ds.get(ds_name, 0) + nbytes
            )
            now = self._resident_by_ds[ds_name]
        prof.record_resident(ds_name, now)

    def _note_resident_drop(self, key, evicted: bool = False) -> None:
        with self._resident_lock:
            meta = self._resident_meta.pop(key, None)
            if meta is None:
                return
            ds_name, nbytes = meta
            now = max(0, self._resident_by_ds.get(ds_name, 0) - nbytes)
            self._resident_by_ds[ds_name] = now
        prof.record_resident(ds_name, now)
        if evicted:
            prof.record_eviction(ds_name)

    def _put_device_col(
        self, key, host, ds_name: str, prefetched: bool = False
    ) -> jnp.ndarray:
        """The ONE sanctioned host->device placement of a segment column
        into the residency cache (graftlint transfer-discipline/GL19xx):
        fires the `h2d` fault site, issues the (async) placement,
        registers residency meta, and records link accounting.  Both the
        foreground miss path (`_device_cols`) and the transfer
        pipeline's prefetch issue (exec/pipeline.py) ride it —
        `prefetched` puts never sync (blocking a prefetch would destroy
        the overlap it exists to create) and account into the prefetch
        bucket instead of transfer stall."""
        import time as _time

        # DISK rung of the residency ladder (ISSUE 13): snapshot-restored
        # columns arrive as np.memmap views over the persisted .npy files
        # (catalog/persist.LazyColumnMap).  Materialize to host RAM HERE —
        # the one chokepoint both the foreground miss path and the
        # prefetch pipeline ride — so page-fault time lands inside the
        # measured transfer window (prefetched puts thus overlap the DISK
        # read behind compute too, not just the link), and the device
        # never holds a buffer aliasing a file that compaction may retire.
        from ..catalog.persist import is_disk_backed, materialize

        if is_disk_backed(host):
            host = materialize(host)
        fire("h2d")  # fault-injection site: host->device transfer
        t0 = _time.perf_counter()
        arr = jnp.asarray(host)
        if not prefetched:
            # sampled query: block so the measured window is the real
            # link time, not the enqueue (obs/prof.py; no-op otherwise)
            arr = prof.transfer_sync(arr)
        dt = _time.perf_counter() - t0
        nbytes = int(np.asarray(host).nbytes)
        # residency meta registers BEFORE the cache insert: a
        # concurrent put can budget-evict this key the instant it
        # lands, and on_evict must find the meta to drop — the
        # reverse order leaked phantom resident bytes
        self._note_resident_add(key, ds_name or "unknown", nbytes)
        self._device_cache[key] = arr
        # a successful landing supersedes any STALE poison for this key
        # (a failed prefetch whose owning query was truncated before
        # consuming it must not resurface on a future cache miss)
        self._pipeline.clear_poison(key)
        # link-utilization accounting: bytes + effective MB/s into
        # the scrapeable histogram (the 45 MB/s h2d floor claim)
        prof.record_h2d(nbytes, dt, prefetched=prefetched)
        if self._m is not None:  # streamed-bytes metric (cache misses only)
            self._m.h2d_bytes += nbytes
            self._m.h2d_ms += dt * 1e3
        return arr

    def configure_pipeline(self, config) -> None:
        """Apply SessionConfig's execution knobs (api context): the
        transfer-pipeline tunables plus the arena-execution gate."""
        self._pipeline.configure(config)
        self.arena_execution = bool(
            getattr(config, "arena_execution", True)
        )

    def _device_cols(
        self, seg: Segment, names, ds_name: str = ""
    ) -> Dict[str, jnp.ndarray]:
        cols: Dict[str, jnp.ndarray] = {}

        def lookup(key, host_fn):
            arr = self._device_cache.get(key)
            if arr is not None:
                prof.note_residency(hit=True)
                return arr
            # a prefetched put that FAILED (injected h2d fault, real
            # backend error) poisoned this key: re-raise in query
            # context so the retry/breaker machinery sees the failure
            # exactly as if the foreground transfer had raised
            exc = self._pipeline.take_poison(key)
            if exc is not None:
                raise exc
            prof.note_residency(hit=False)
            return self._put_device_col(key, host_fn(), ds_name)

        # "col"/"valid" tags: a user column literally named "__valid"
        # must not alias the validity-mask entry (jit-collision/GL1301)
        for n in names:
            cols[n] = lookup(
                (seg.uid, "col", n), lambda n=n: seg.column(n)
            )
        cols["__valid"] = lookup((seg.uid, "valid"), lambda: seg.valid)
        return cols

    def bytes_resident(self) -> int:
        """HBM bytes held by the segment residency cache."""
        return self._device_cache.bytes_used

    def missing_resident_bytes(self, ds, cols) -> int:
        """Estimated bytes a query over `cols` would have to move
        host->device before executing — 0 when everything is already
        resident.  Owns the cache-key scheme AND the buffer set
        (_device_cols: per-segment columns plus the validity buffer) so
        planner-side h2d costing (api device-assist) never re-encodes
        either.  4 bytes/row/buffer: codes are <=4 B, metric values f32."""
        need = [("col", c) for c in cols] + [("valid",)]
        return sum(
            4 * seg.num_rows
            for seg in ds.segments
            for tail in need
            if (seg.uid,) + tail not in self._device_cache
        )

    def clear_cache(self):
        """Analog of the reference's metadata/cache clear command.  Drops the
        program cache too: compiled programs close over their lowering's
        staged device constants, so leaving them would pin the HBM this is
        documented to release."""
        self._device_cache.clear()
        self._lowering_cache.clear()
        self._query_fn_cache.clear()
        with self._resident_lock:
            self._resident_meta.clear()
            dropped = list(self._resident_by_ds)
            self._resident_by_ds.clear()
        for name in dropped:
            prof.record_resident(name, 0)

    def drop_residency(self) -> None:
        """Evict EVERY device-resident segment column while keeping the
        compiled programs and lowerings (unlike `clear_cache`) and
        WITHOUT retiring uids (unlike `evict_segments` — the segments
        stay live and prefetchable).  The overlap bench uses it to
        re-cold the link between pipeline-on/off counterfactual runs."""
        for k in list(self._device_cache):
            self._device_cache.pop(k)
            self._note_resident_drop(k)

    def evict_segments(self, uids) -> None:
        """Drop device residency of specific segments — the ingestion
        tier's hook: compaction (and dictionary-extension remaps) retire
        segment uids from the published set, and their HBM should come
        back immediately rather than waiting for LRU pressure."""
        uids = set(uids)
        # queued-but-unissued prefetches for these uids must never land:
        # a put issued after this evict would re-resident a dead segment
        self._pipeline.note_retired(uids)
        from . import arena as _arena

        # arena slices stack MANY uids under one ("arena", *uids) key:
        # any intersection with the retired set invalidates the whole
        # stack (a later query re-plans and re-stacks the live segments)
        for k in [
            k
            for k in self._device_cache
            if k[0] in uids
            or (_arena.is_arena_key(k) and uids.intersection(k[0][1:]))
        ]:
            self._device_cache.pop(k)
            self._note_resident_drop(k)

    def _segment_batches(self, segs, names):
        """Split in-scope segments into dispatch batches: each batch becomes
        ONE fused program call.  Bounded by MULTI_SEGMENT_UNROLL_MAX (compile
        time grows with the unroll) and by the device-cache byte budget (a
        batch pins every member's columns on device simultaneously, so an
        unbounded batch would defeat the residency budget)."""
        budget = self._device_cache.budget_bytes
        unroll_max = _platform_unroll_max()
        batch: List[Segment] = []
        batch_bytes = 0
        # graftlint: disable=checkpoint-coverage -- batching is nbytes arithmetic; every CONSUMER of these batches checkpoints per batch
        for seg in segs:
            est = int(seg.valid.nbytes) + sum(
                int(seg.column(n).nbytes) for n in names
            )
            if batch and (
                len(batch) >= unroll_max
                or batch_bytes + est > budget
            ):
                yield batch
                batch, batch_bytes = [], 0
            batch.append(seg)
            batch_bytes += est
        if batch:
            yield batch

    def _lowering_for(self, q: Q.GroupByQuery, ds: DataSource):
        from .lowering import cached_lowering

        return cached_lowering(self._lowering_cache, q, ds)

    def _cols_for_segment(self, seg: Segment, ds: DataSource, names):
        cols = self._device_cols(seg, names, ds_name=ds.name)
        if ds.time_column and ds.time_column in cols:
            cols["__time"] = cols[ds.time_column]
        return cols

    # -- entry points --------------------------------------------------------

    def execute(self, q: Q.QuerySpec, ds: DataSource):
        import pandas as pd

        if isinstance(q, Q.GroupByQuery):
            return self._execute_groupby(q, ds)
        if isinstance(q, Q.TimeseriesQuery):
            return self._execute_timeseries(q, ds)
        if isinstance(q, Q.TopNQuery):
            return self._execute_topn(q, ds)
        if isinstance(q, Q.ScanQuery):
            return self._execute_scan(q, ds)
        if isinstance(q, Q.SearchQuery):
            return self._execute_search(q, ds)
        if isinstance(q, Q.TimeBoundaryQuery):
            return self._execute_time_boundary(q, ds)
        if isinstance(q, Q.DataSourceMetadataQuery):
            return self._execute_datasource_metadata(q, ds)
        if isinstance(q, Q.SegmentMetadataQuery):
            return self._execute_segment_metadata(q, ds)
        raise NotImplementedError(type(q).__name__)

    # -- groupby -------------------------------------------------------------

    def _segments_in_scope(self, q, ds: DataSource) -> List[Segment]:
        return segments_in_scope(q, ds)

    def _partials_for_query(
        self,
        q: Q.GroupByQuery,
        ds: DataSource,
        lowering=None,
        key_extra=(),
        strategy_override=None,
        segs=None,
    ):
        """Compute merged partial state across local segments.

        `key_extra` disambiguates the program cache when the SAME query runs
        over a rewritten lowering (adaptive domain compaction passes the
        compacted cardinalities).  `segs` overrides the scanned segment
        list (already scope-pruned) — the delta-aware result cache passes
        just the freshly-appended segments.

        Returns (dims, la, G, sums[G, Ms], mins, maxs, sketch_states)."""
        if lowering is None:
            lowering = self._lowering_for(q, ds)
        dims, la, G = lowering.dims, lowering.la, lowering.num_groups
        need = lowering.columns

        sums = mins = maxs = None
        sketch_states: Dict[str, Any] = {}
        if segs is None:
            segs = self._segments_in_scope(q, ds)
        pc = current_partial()
        if not segs:
            # empty time range is a valid query: zero-row result, not an
            # error — and a COMPLETE one.  Declare the empty scope so a
            # deadline trigger later in the lifecycle cannot flag the
            # exact empty answer partial with an unknown denominator.
            if pc is not None:
                pc.begin_pass()
                pc.add_scope(0, 0)
            sums, mins, maxs, sketch_states = empty_partials(la, G)
            return dims, la, G, sums, mins, maxs, sketch_states
        # deadline-bounded partial answers: declare the pass's scope so a
        # mid-scan expiry can stamp an honest coverage fraction onto the
        # merged partials (resilience.PartialCollector)
        if pc is not None:
            pc.begin_pass()
            pc.add_scope(len(segs), *_row_counts(segs))
        # remainder segments fuse into batched programs (partial agg +
        # cross-segment merge inside; built lazily below — the arena may
        # cover the whole scope, needing no per-batch program at all)

        def fold(st):
            nonlocal sums, mins, maxs
            s, mn, mx, sk = st
            sums = s if sums is None else sums + s
            mins = mn if mins is None else jnp.minimum(mins, mn)
            maxs = mx if maxs is None else jnp.maximum(maxs, mx)
            _merge_sketch_states(la, sketch_states, sk)

        batches = list(self._segment_batches(segs, need))
        # transfer pipeline (exec/pipeline.py): residency-aware dispatch
        # order + async prefetch of the next batches' cold columns behind
        # this batch's compute; speculative next-interval segments trail
        # the plan under their own byte cap.  CanonicalFold pins the
        # merge order to canonical batch order (f32 partial sums and the
        # sketch scatter merges are not reassociation-safe) so
        # pipeline-on results stay byte-identical to pipeline-off.
        from .pipeline import CanonicalFold

        # one-dispatch arena (exec/arena.py, ISSUE 14): the uniform-shape
        # whole-batch PREFIX of the scope stacks into resident [B, R]
        # columns and folds inside ONE scanned program — one dispatch
        # where this loop pays one per batch.  Remainder batches (shape
        # -change tail, deltas, budget overflow) fall through to the loop
        # below with the fold continuing in canonical order, so results
        # stay byte-identical arena-on vs arena-off.  Sketch aggs decline
        # (their merge states carry no exact in-scan fold identity).
        plan = run = None
        if self.arena_execution and not la.sketch_aggs:
            from . import arena as _arena

            if not _arena.query_disabled():
                plan = _arena.plan_for(self, batches, need)
        if plan is not None:
            strategy = strategy_override or self._resolve_strategy(G)
            run = self._pipeline.start(
                ds, plan.remainder, need,
                speculative=self._pipeline.speculative_candidates(
                    q, ds, segs
                ),
            )
            # remainder prefetch issues BEFORE the arena dispatch: the
            # async puts land behind the scanned program's compute
            run.advance(-1)
            try:
                program = self._arena_program(
                    q, ds, lowering, strategy, key_extra=key_extra
                )
                # the arena IS the segment loop, scanned: it checkpoints
                # under the same site name, so deadline tests and armed
                # injections drive its chunked truncation exactly like
                # the dispatch loop's
                carries, _done = _arena.run_plan(
                    self, ds, plan, need, program, [lowering], pc=pc,
                    checkpoint_site="engine.segment_loop",
                )
            except Exception:
                if (
                    plan.folded
                    or self.strategy not in ("auto", "dense")
                    or self._pallas_broken
                    or strategy != "pallas"
                ):
                    raise
                # Mosaic declined the scanned kernel before anything
                # folded: pin the XLA path (same contract as
                # _call_segment_program) and rerun the whole scope
                # through the dispatch loop below
                self._pallas_broken = True
                for k in [
                    k
                    for k in self._query_fn_cache
                    if any("pallas" in str(p) for p in k[2:])
                ]:
                    self._query_fn_cache.pop(k)
                run.cancel()
                run = None
            else:
                batches = plan.remainder
                if plan.folded:
                    s, mn, mx, _live = _arena.finish_member(carries[0])
                    sums, mins, maxs = s, mn, mx
                if plan.folded < len(plan.batches):
                    # truncated mid-arena: the remainder must not run
                    # (and its pending prefetch cancels with it)
                    run.cancel()
        if run is None:
            run = self._pipeline.start(
                ds, batches, need,
                speculative=self._pipeline.speculative_candidates(
                    q, ds, segs
                ),
            )
        seg_fn = None
        if batches and not run.cancelled:
            seg_fn = self._segment_program(
                q, ds, lowering, key_extra=key_extra,
                strategy_override=strategy_override,
            )
        folder = CanonicalFold(fold)
        for pos, bi in enumerate(run.order if seg_fn is not None else ()):
            # cooperative deadline checkpoint: a query with a wall-clock
            # budget cancels between batch dispatches, not at the very
            # end — and with a partial collector armed, expiry STOPS the
            # dispatch loop instead of erroring (the partials accumulated
            # so far merge into a best-effort answer).  Any pending
            # prefetch cancels with it.
            if checkpoint_partial("engine.segment_loop"):
                run.cancel()
                break
            batch = batches[bi]
            with span(SPAN_H2D, batch=bi, segments=len(batch)):
                cols_list = [
                    self._cols_for_segment(seg, ds, need) for seg in batch
                ]
            run.advance(pos)
            with span(SPAN_SEGMENT_DISPATCH, batch=bi, segments=len(batch)):
                (s, mn, mx, sk), seg_fn = self._call_segment_program(
                    q, ds, lowering, seg_fn, cols_list, key_extra=key_extra
                )
            folder.add(bi, (s, mn, mx, sk))
            if pc is not None:
                pc.add_seen(len(batch), *_row_counts(batch))
        # a truncation can leave batches dispatched AHEAD of canonical
        # order un-folded: drain them (still canonical) so every batch
        # pc accounted merges
        folder.drain()
        if sums is None:
            # the deadline expired before the FIRST batch dispatched: the
            # well-formed zero-coverage answer is the empty partial state
            sums, mins, maxs, sketch_states = empty_partials(la, G)
        return dims, la, G, sums, mins, maxs, sketch_states

    def _call_segment_program(
        self, q, ds, lowering, seg_fn, cols_list, key_extra=()
    ):
        """Run one segment program (over a list of per-segment column dicts)
        with the Pallas compile-failure fallback.  Returns (result, seg_fn) —
        seg_fn may be a rebuilt XLA-dense program after a Mosaic failure."""
        import time as _time

        # fault-injection site OUTSIDE the try below: an injected (or real
        # pre-dispatch) transient fault must reach the retry/breaker
        # machinery, not be misread as a Mosaic compile failure that pins
        # _pallas_broken for the engine's lifetime
        fire("device_dispatch")
        m = self._m  # one read: this thread's in-flight metrics object
        try:
            # first call of a newly-built program = trace+compile (+async
            # dispatch); attribute it to compile_ms (see metrics.py)
            t0 = (
                _time.perf_counter()
                if m is not None
                and not m.program_cache_hit
                and m.compile_ms == 0
                else None
            )
            t_call = _time.perf_counter()
            result = seg_fn(cols_list)
            # sampled query: block here so the enclosing dispatch span
            # splits into enqueue vs device-complete time (obs/prof.py);
            # a literal no-op at the default sample rate of 0
            result = prof.dispatch_sync(result, t_call)
            if t0 is not None:
                m.compile_ms = (_time.perf_counter() - t0) * 1e3
                # first-trace/compile attributed to the tagged program
                # family whose cache miss built this program
                prof.note_compile(m.compile_ms)
            return result, seg_fn
        except Exception:
            # Auto-selected Pallas may fail to Mosaic-compile on exotic
            # backends: retry once on the XLA dense path.  Only 'auto'
            # and 'dense' (a kernel *class* the cost model picks, which
            # _resolve_strategy upgrades to Pallas) fall back — explicit
            # strategy='pallas' should surface the error.  Only
            # pallas-keyed programs are evicted, and if the dense retry
            # fails too the failure wasn't Pallas — unflag.
            if (
                self.strategy not in ("auto", "dense")
                or self._pallas_broken
                or self._resolve_strategy(lowering.num_groups) != "pallas"
            ):
                raise
            self._pallas_broken = True
            for k in [
                k
                for k in self._query_fn_cache
                if any("pallas" in str(p) for p in k[2:])
            ]:
                self._query_fn_cache.pop(k)
            seg_fn = self._segment_program(q, ds, lowering, key_extra=key_extra)
            try:
                return seg_fn(cols_list), seg_fn
            except Exception:
                self._pallas_broken = False
                raise

    def _resolve_strategy(self, num_groups: int) -> str:
        """Resolve 'auto' to a concrete kernel strategy (ops.groupby's shared
        resolver + this engine's compile-failure fallback flag).

        "dense" from the cost model is a kernel *class* (one-hot vs scatter);
        the Pallas kernel is its hand-scheduled implementation and is
        preferred whenever a TPU backend is present."""
        from ..ops.groupby import resolve_strategy
        from ..ops.pallas_groupby import pallas_available

        if self.strategy == "dense":
            from ..ops.groupby import SCATTER_CUTOVER

            if (
                num_groups <= SCATTER_CUTOVER
                and not self._pallas_broken
                and pallas_available()
            ):
                return "pallas"
            return "dense"
        if self.strategy in ("sparse", "adaptive"):
            # execution-layer accelerators, not kernel strategies: when the
            # sparse/adaptive path declines a query (low G, sketch aggs,
            # overflow, no shrink) the standard path resolves as if "auto"
            return resolve_strategy(
                "auto", num_groups, pallas_ok=not self._pallas_broken
            )
        return resolve_strategy(
            self.strategy, num_groups, pallas_ok=not self._pallas_broken
        )

    def _segment_program(
        self,
        q: Q.GroupByQuery,
        ds: DataSource,
        lowering: "GroupByLowering",
        key_extra=(),
        strategy_override=None,
    ) -> Callable:
        """One fused, cached XLA program per query: row pipeline (virtual
        columns, filter mask, group ids) + partial aggregation + sketch
        partials for EVERY in-scope segment, merged in-program — a single
        dispatch.  The analog of Druid compiling a query into one engine pass,
        with the broker's cross-segment merge folded in."""
        la, G = lowering.la, lowering.num_groups
        strategy = strategy_override or self._resolve_strategy(G)
        # _query_key includes schema_signature: a re-ingested datasource
        # (new dict cardinalities => new G) must not reuse a stale program.
        # The "fused" tag pins this key family apart from the tagged
        # sparse/adaptive/stream families sharing this cache: without it
        # nothing stops `strategy` + key_extra from ever spelling another
        # family's tuple (graftlint jit-collision/GL1301)
        key = _query_key(q, ds) + ("fused", strategy) + tuple(key_extra)
        family = (
            "fused" if not key_extra else f"fused/{key_extra[0]}"
        )
        cached = self._query_fn_cache.get(key)
        if cached is not None:
            if self._m is not None:
                self._m.program_cache_hit = True
            prof.note_program_cache(family, hit=True)
            return cached
        prof.note_program_cache(family, hit=False)
        fire("compile")  # fault-injection site: new program build

        @jax.jit
        def seg_fn(cols_list):
            sums = mins = maxs = None
            sketch_states: Dict[str, Any] = {}
            for cols in cols_list:
                s, mn, mx, sk = _segment_partials(lowering, strategy, cols)
                sums = s if sums is None else sums + s
                mins = mn if mins is None else jnp.minimum(mins, mn)
                maxs = mx if maxs is None else jnp.maximum(maxs, mx)
                _merge_sketch_states(la, sketch_states, sk)
            return sums, mins, maxs, sketch_states

        self._query_fn_cache[key] = seg_fn
        return seg_fn

    def _arena_program(
        self, q, ds, lowering, strategy: str, key_extra=()
    ) -> Callable:
        """The one-dispatch arena program for one query (exec/arena.py):
        a single traced `lax.scan` over the stacked segment blocks with
        the cross-batch fold inside the trace.  Cached under its own
        "arena"-tagged key family: the tag keeps it disjoint from the
        per-batch "fused" programs sharing this cache, and the strategy
        component lets the Pallas-fallback eviction sweep find it
        (jit-collision/GL1301)."""
        from . import arena as _arena

        key = _query_key(q, ds) + ("arena", strategy) + tuple(key_extra)
        family = "arena" if not key_extra else f"arena/{key_extra[0]}"
        cached = self._query_fn_cache.get(key)
        if cached is not None:
            if self._m is not None:
                self._m.program_cache_hit = True
            prof.note_program_cache(family, hit=True)
            return cached
        prof.note_program_cache(family, hit=False)
        fire("compile")  # fault-injection site: new program build
        fn = _arena.build_arena_program([lowering], [strategy])
        self._query_fn_cache[key] = fn
        return fn

    # -- micro-batch fusion (serve/, ISSUE 8) --------------------------------

    def _groupby_family(self, q: Q.QuerySpec, ds: DataSource):
        """Normalize a GroupBy-family query to its inner GroupBy plus the
        per-type result shaper — the one mapping execute_progressive,
        execute_fused, and the state-capture paths all share."""
        if isinstance(q, Q.TimeseriesQuery):
            return (
                timeseries_to_groupby(q),
                lambda df: finalize_timeseries(df, q, ds),
            )
        if isinstance(q, Q.TopNQuery):
            return topn_to_groupby(q), lambda df: finalize_topn(df, q)
        if isinstance(q, Q.GroupByQuery):
            return q, lambda df: df
        return None, None

    def fusable(self, q: Q.QuerySpec, ds: DataSource) -> bool:
        """May this query join a fused micro-batch / the state-capturing
        dense path?  GroupBy-family only (mergeable partial state), no
        wire subtotals, and neither the sparse nor the adaptive
        accelerator would engage (those tiers have their own dispatch
        protocols a fused program cannot host)."""
        inner, _ = self._groupby_family(q, ds)
        if inner is None or inner.subtotals:
            return False
        try:
            lowering = self._lowering_for(
                groupby_with_time_granularity(inner), ds
            )
        except Exception:  # fault-ok: an unlowerable query declines fusion
            return False
        return not (
            self._sparse_eligible(lowering)
            or self._adaptive_eligible(lowering)
        )

    def execute_fused(self, queries, ds: DataSource, query_ids=None):
        """Execute N compatible GroupBy-family queries as ONE fused device
        program per segment batch: the union of the members' in-scope
        segments moves host->device once (shared residency), every
        member's partial aggregation runs inside the same dispatch, and
        ONE host fetch returns all members' states — the 66 ms dispatch
        round trip is paid once for the batch instead of once per query.

        Returns a list of (df, state, metrics) per member, in order:
        `df` is the finalized per-query result (identical to a serial
        `execute`), `state` the merged HOST partial state (the delta-aware
        result cache stores it), `metrics` the member's own QueryMetrics
        (query_id stamped per member — serving-discipline GL1702)."""
        import time as _time

        from .metrics import QueryMetrics

        t0 = _time.perf_counter()
        n = len(queries)
        prof.note_fusion(n)  # the leader's receipt records the batch size
        query_ids = list(query_ids or [""] * n)
        members = []
        for q in queries:
            inner, shape = self._groupby_family(q, ds)
            if inner is None:
                raise ValueError(
                    f"{type(q).__name__} is not fusable (GroupBy-family "
                    "queries only)"
                )
            inner = groupby_with_time_granularity(inner)
            lowering = self._lowering_for(inner, ds)
            segs = self._segments_in_scope(inner, ds)
            members.append((q, inner, shape, lowering, segs))
        # union of member scopes, in datasource segment order; each member
        # aggregates ONLY its own in-scope subset inside the program
        member_uids = [frozenset(s.uid for s in m[4]) for m in members]
        union_segs = [
            s
            for s in ds.segments
            if any(s.uid in u for u in member_uids)
        ]
        names = dict.fromkeys(
            c for m in members for c in m[3].columns
        )
        strategies = tuple(
            self._resolve_strategy(m[3].num_groups) for m in members
        )
        batch_m = QueryMetrics(query_type="fused")  # h2d/compile accumulator
        self._m = batch_m
        acc: List[Any] = [None] * n
        acc_sk: List[Dict[str, Any]] = [{} for _ in range(n)]

        def fold(outs):
            for i, (s, mn, mx, sk) in enumerate(outs):
                if s is None:
                    continue
                if acc[i] is None:
                    acc[i] = (s, mn, mx)
                else:
                    ps, pmn, pmx = acc[i]
                    acc[i] = (
                        ps + s,
                        jnp.minimum(pmn, mn),
                        jnp.maximum(pmx, mx),
                    )
                _merge_sketch_states(members[i][3].la, acc_sk[i], sk)

        try:
            from .pipeline import CanonicalFold

            batches = list(self._segment_batches(union_segs, list(names)))
            # one-dispatch arena (exec/arena.py, ISSUE 14): the fused
            # micro-batch executes against ONE shared arena — every
            # member's fold runs inside the same scanned program, with
            # per-block membership flags as DATA (one compiled program
            # serves any member->segment mapping).  Remainder batches
            # fall through to the per-batch fused loop below.
            plan = run = None
            if self.arena_execution and not any(
                m[3].la.sketch_aggs for m in members
            ):
                from . import arena as _arena

                if not _arena.query_disabled():
                    plan = _arena.plan_for(self, batches, list(names))
            if plan is not None:
                # the fused deadline contract, checked once up front: an
                # expiry re-routes every member to its own serial
                # (partial-capable) path — exactly what the loop's
                # per-batch checkpoint would do
                checkpoint("engine.fused_loop")
                run = self._pipeline.start(ds, plan.remainder, list(names))
                run.advance(-1)
                memb = np.array(
                    [
                        [s.uid in u for u in member_uids]
                        for s in plan.segs
                    ],
                    dtype=bool,
                )
                fn = self._arena_fused_program(members, ds, strategies)
                try:
                    # run_plan stamps batch_m's compile attribution on
                    # the first (trace+compile) dispatch
                    carries, _done = _arena.run_plan(
                        self, ds, plan, list(names), fn,
                        [m[3] for m in members], memb=memb,
                        single_chunk=True,
                    )
                except BaseException:
                    run.cancel()
                    raise
                for i in range(n):
                    # membership is host-known: a member with no covered
                    # block keeps acc[i] = None (the loop's None-skip)
                    if len(plan.segs) and memb[:, i].any():
                        s, mn, mx, _live = _arena.finish_member(
                            carries[i]
                        )
                        acc[i] = (s, mn, mx)
                batches = plan.remainder
            # transfer pipeline: resident batches dispatch first, cold
            # batches' columns stream behind the fused compute; the
            # per-member fold stays pinned to canonical batch order
            # (byte-identical to the serial path)
            if run is None:
                run = self._pipeline.start(ds, batches, list(names))
            folder = CanonicalFold(fold)
            for pos, bi in enumerate(run.order):
                # deadline checkpoint between fused batch dispatches; an
                # expiry here surfaces to the scheduler, which re-routes
                # every member to its own serial (partial-capable) path
                try:
                    checkpoint("engine.fused_loop")
                except BaseException:
                    run.cancel()
                    raise
                batch = batches[bi]
                sel = tuple(
                    tuple(
                        j
                        for j, seg in enumerate(batch)
                        if seg.uid in member_uids[i]
                    )
                    for i in range(n)
                )
                with span(SPAN_H2D, batch=bi, segments=len(batch)):
                    cols_list = [
                        self._cols_for_segment(seg, ds, list(names))
                        for seg in batch
                    ]
                run.advance(pos)
                fn = self._fused_program(members, ds, strategies, sel)
                with span(
                    SPAN_SEGMENT_DISPATCH, batch=bi, segments=len(batch),
                    fused=n,
                ):
                    t_c = (
                        _time.perf_counter()
                        if not batch_m.program_cache_hit
                        and batch_m.compile_ms == 0
                        else None
                    )
                    t_call = _time.perf_counter()
                    outs = fn(cols_list)
                    outs = prof.dispatch_sync(outs, t_call)
                    if t_c is not None:
                        batch_m.compile_ms = (
                            (_time.perf_counter() - t_c) * 1e3
                        )
                        prof.note_compile(batch_m.compile_ms)
                folder.add(bi, outs)
            folder.drain()
        finally:
            self._m = None
        # members whose whole scope was pruned hold no accumulated state:
        # fill with empty partials, fetched in the SAME single round trip
        # as the live states (a per-member fetch would re-pay the device
        # round trip the fused batch exists to amortize)
        empties = {
            i: empty_partials(m[3].la, m[3].num_groups)
            for i, m in enumerate(members)
            if acc[i] is None
        }
        with span(SPAN_DEVICE_FETCH, fused=n):
            prof.fetch_sync(acc)
            host = jax.device_get((acc, acc_sk, empties))
        acc_h, sk_h, empties_h = host
        out = []
        elapsed_ms = (_time.perf_counter() - t0) * 1e3
        # graftlint: disable=checkpoint-coverage -- demux loop: all device states are already fetched; discarding finished answers at expiry would re-pay the whole batch
        for i, (q, inner, shape, lowering, segs) in enumerate(members):
            la, G = lowering.la, lowering.num_groups
            if acc_h[i] is None:
                sums, mins, maxs, sk = empties_h[i]
            else:
                sums, mins, maxs = acc_h[i]
                sk = sk_h[i]
            state = _pack_host_state(sums, mins, maxs, sk)
            with span(SPAN_FINALIZE, member=i):
                df = shape(finalize_groupby(
                    inner, lowering.dims, la,
                    state["sums"], state["mins"], state["maxs"],
                    state["sketches"],
                ))
            try:
                qt = q.to_druid().get("queryType", type(q).__name__)
            except Exception:  # fault-ok: metrics labeling only
                qt = type(q).__name__
            rows, _delta = _row_counts(segs)
            m = QueryMetrics(
                query_type=qt,
                strategy=strategies[i],
                datasource=ds.name,
                query_id=query_ids[i],
                rows_scanned=rows,
                bytes_scanned=_bytes_scanned(segs, lowering.columns),
                segments=len(segs),
                num_groups=G,
                # the batch's shared h2d/compile split evenly: the fused
                # program moved ONE column set for all members
                h2d_bytes=batch_m.h2d_bytes // n,
                h2d_ms=batch_m.h2d_ms / n,
                compile_ms=batch_m.compile_ms,
                total_ms=elapsed_ms,
                fused_batch=n,
                program_cache_hit=batch_m.program_cache_hit,
            )
            record_query_metrics(m, "ok")
            out.append((df, state, m))
        self.last_metrics = out[-1][2] if out else None
        return out

    def _fused_program(self, members, ds, strategies, sel) -> Callable:
        """One jitted program computing EVERY member's partial state over
        one segment batch.  Cached in the engine's program cache under the
        `("fused-batch", ...)` family — anchored on the first member's
        `_query_key` plus the remaining members' query identities, the
        resolved strategies, and the batch's member->segment selection, so
        no other key family can spell the same tuple (jit-collision
        GL1301)."""
        import json as _json

        key = _query_key(members[0][1], ds) + (
            "fused-batch",
            tuple(
                _json.dumps(m[1].to_druid(), sort_keys=True, default=str)
                for m in members[1:]
            ),
            strategies,
            sel,
        )
        cached = self._query_fn_cache.get(key)
        if cached is not None:
            if self._m is not None:
                self._m.program_cache_hit = True
            prof.note_program_cache("fused-batch", hit=True)
            return cached
        prof.note_program_cache("fused-batch", hit=False)
        fire("compile")  # fault-injection site: new program build
        # common-subexpression plan over the member lowerings (ROADMAP
        # 1(a)): members sharing filter/dimension sub-lowerings reuse one
        # traced mask/gid per segment inside the program.  A pure
        # function of the member JSONs already serialized into the key,
        # so it is computed ONLY on a miss (the hot serving path's cache
        # hits skip the per-member to_druid + json passes).  Lazy
        # import: serve/ imports exec/ at module load, not the reverse.
        from ..serve.fusion import shared_row_plan

        share = shared_row_plan([m[1] for m in members])
        lowerings = [m[3] for m in members]

        @jax.jit
        def fused_fn(cols_list):
            outs = []
            memo: Dict[Any, Any] = {}  # per-trace CSE memo (mask/gid)
            for i, lowering in enumerate(lowerings):
                sums = mins = maxs = None
                sk: Dict[str, Any] = {}
                for j in sel[i]:
                    s, mn, mx, skj = _segment_partials(
                        lowering, strategies[i], cols_list[j],
                        memo=memo, share=share[i] + (j,),
                    )
                    sums = s if sums is None else sums + s
                    mins = mn if mins is None else jnp.minimum(mins, mn)
                    maxs = mx if maxs is None else jnp.maximum(maxs, mx)
                    _merge_sketch_states(lowering.la, sk, skj)
                outs.append((sums, mins, maxs, sk))
            return outs

        self._query_fn_cache[key] = fused_fn
        return fused_fn

    def _arena_fused_program(self, members, ds, strategies) -> Callable:
        """The one-dispatch arena program for a fused micro-batch (exec/
        arena.py): every member's fold over the stacked scope inside one
        scanned program.  Unlike `_fused_program`, the member->segment
        selection is NOT in the key — membership rides as data, so one
        compiled program serves every batch shape of the same member
        set."""
        import json as _json

        from . import arena as _arena

        key = _query_key(members[0][1], ds) + (
            "arena-fused",
            tuple(
                _json.dumps(m[1].to_druid(), sort_keys=True, default=str)
                for m in members[1:]
            ),
            strategies,
        )
        cached = self._query_fn_cache.get(key)
        if cached is not None:
            if self._m is not None:
                self._m.program_cache_hit = True
            prof.note_program_cache("arena-fused", hit=True)
            return cached
        prof.note_program_cache("arena-fused", hit=False)
        fire("compile")  # fault-injection site: new program build
        from ..serve.fusion import shared_row_plan

        share = shared_row_plan([m[1] for m in members])
        fn = _arena.build_arena_program(
            [m[3] for m in members], strategies, share=share
        )
        self._query_fn_cache[key] = fn
        return fn

    # -- host partial-state surface (delta-aware result cache, ISSUE 8) -----

    @contextlib.contextmanager
    def state_capture(self):
        """Capture the merged HOST partial state of the next execution on
        this thread (the dense resolve path stashes it just before
        finalize).  Yields a dict whose "state" key holds the capture —
        None when the execution took a path with no dense state (sparse/
        adaptive/fallback) or was deadline-truncated (a partial state
        must never seed the delta-aware result cache)."""
        holder = {"state": None}
        self._m_local.capture = holder
        try:
            yield holder
        finally:
            self._m_local.capture = None

    def groupby_partials_host(
        self, q: Q.QuerySpec, ds: DataSource, within_uids=None
    ):
        """Merged HOST partial state of a GroupBy-family query, restricted
        to in-scope segments whose uid is in `within_uids` (None = the
        full scope).  The delta-aware result cache calls this with the
        freshly-appended uids so a dashboard refresh after an append
        scans ONLY the delta.  Returns (state, rows_scanned)."""
        inner, _ = self._groupby_family(q, ds)
        if inner is None:
            raise ValueError(f"{type(q).__name__} has no partial state")
        inner = groupby_with_time_granularity(inner)
        lowering = self._lowering_for(inner, ds)
        segs = self._segments_in_scope(inner, ds)
        if within_uids is not None:
            within_uids = frozenset(within_uids)
            segs = [s for s in segs if s.uid in within_uids]
        dims, la, G, sums, mins, maxs, sk = self._partials_for_query(
            inner, ds, lowering=lowering, segs=segs
        )
        sums, mins, maxs, sk = jax.device_get((sums, mins, maxs, sk))
        state = _pack_host_state(sums, mins, maxs, sk)
        return state, sum(s.num_rows for s in segs)

    def merge_groupby_states(self, q: Q.QuerySpec, ds: DataSource, a, b):
        """⊕ of two host partial states of the SAME query over the same
        dictionary domain (the partial-aggregate-state algebra): sums
        add, mins/maxs fold, sketches merge by type.  Raises ValueError
        on a shape mismatch (a dictionary change reshapes G — callers
        treat that as a cache miss)."""
        if a["sums"].shape != b["sums"].shape:
            raise ValueError(
                f"partial-state shape mismatch {a['sums'].shape} vs "
                f"{b['sums'].shape} (dictionary domain changed)"
            )
        inner, _ = self._groupby_family(q, ds)
        lowering = self._lowering_for(
            groupby_with_time_granularity(inner), ds
        )
        merged = {
            "sums": a["sums"] + b["sums"],
            "mins": np.minimum(a["mins"], b["mins"]),
            "maxs": np.maximum(a["maxs"], b["maxs"]),
            "sketches": dict(a["sketches"]),
        }
        _merge_sketch_states(lowering.la, merged["sketches"], b["sketches"])
        merged["sketches"] = {
            k: np.asarray(v) for k, v in merged["sketches"].items()
        }
        return merged

    def finalize_groupby_state(self, q: Q.QuerySpec, ds: DataSource, state):
        """Host partial state -> the query's final result frame (the same
        finalize the live execution path runs)."""
        inner, shape = self._groupby_family(q, ds)
        inner = groupby_with_time_granularity(inner)
        lowering = self._lowering_for(inner, ds)
        with span(SPAN_FINALIZE):
            df = finalize_groupby(
                inner, lowering.dims, lowering.la,
                np.asarray(state["sums"]),
                np.asarray(state["mins"]),
                np.asarray(state["maxs"]),
                {k: np.asarray(v) for k, v in state["sketches"].items()},
            )
        return shape(df)

    def _execute_groupby(self, q: Q.GroupByQuery, ds: DataSource):
        """GroupBy with idempotent re-dispatch on transient device failure
        — the analog of Spark retrying a DruidRDD partition (SURVEY.md §5
        failure-detection row: queries are read-only, so a retry is always
        safe) — generalized (resilience.run_device_attempts) into
        retry-with-backoff under a budget, with every outcome reported to
        the circuit breaker.  Static errors (RewriteError / ValueError,
        NotImplementedError — a RuntimeError subclass — and
        DeadlineExceeded) propagate immediately and never touch the
        breaker."""
        from ..resilience import run_device_attempts

        # normalize ONCE so the retry evicts under the same cache identity
        # the execution cached under (granularity adds a __time dimension)
        q = groupby_with_time_granularity(q)
        return run_device_attempts(
            self,
            lambda: self._execute_groupby_once(q, ds),
            lambda: self._evict_query_state(q, ds),
        )

    def _evict_query_state(self, q: Q.GroupByQuery, ds: DataSource):
        """Drop everything a failed dispatch may have poisoned: this query's
        compiled programs and lowering (staged device constants) plus the
        datasource's resident columns (buffers may be orphaned if the
        backend restarted)."""
        base = _query_key(q, ds)
        for k in [k for k in self._query_fn_cache if k[:2] == base]:
            self._query_fn_cache.pop(k)
        self._lowering_cache.pop(base)
        uids = {seg.uid for seg in ds.segments}
        for k in [k for k in self._device_cache if k[0] in uids]:
            self._device_cache.pop(k)
            self._note_resident_drop(k)

    def _execute_groupby_once(self, q: Q.GroupByQuery, ds: DataSource):
        return self._dispatch_groupby_once(q, ds)()

    def execute_groupby_batch(self, queries, ds: DataSource, set_labels=None):
        """Execute N GroupBy queries with overlapped device round trips:
        dispatch every query's program first (async), then resolve in
        order, so the fetch latency of query i hides the compute of i+1..N.
        This is what a grouping-set (CUBE/ROLLUP) expansion calls — behind
        a network-tunneled TPU, N sequential executions would pay N full
        round trips.  Per-query transient failures fall back to the normal
        retrying execution path, serially (rare; correctness first).

        `set_labels` (ROADMAP 3(c)): per-query labels for the partial
        collector's per-grouping-set accounting — each sub-query's pass
        archives under its own set instead of erasing its predecessor."""
        pc = current_partial()

        def _label(i):
            if pc is not None and set_labels is not None:
                pc.set_label = set_labels[i]

        resolves = []
        for i, q in enumerate(queries):
            _label(i)
            try:
                resolves.append(self._dispatch_groupby_once(q, ds))
            except NotImplementedError:
                raise
            except RuntimeError as err:
                log.warning(
                    "batch dispatch failed (%s: %s); query will run on the "
                    "serial path", type(err).__name__, err,
                )
                self._evict_query_state(
                    groupby_with_time_granularity(q), ds
                )
                resolves.append(None)
        out = []
        for i, (q, resolve) in enumerate(zip(queries, resolves)):
            _label(i)  # sparse/adaptive re-passes attribute to their set
            resolves[i] = None  # release the closure (and its device state)
            if resolve is None:
                out.append(self._execute_groupby(q, ds))
                continue
            try:
                out.append(resolve())
            except NotImplementedError:
                raise
            except RuntimeError as err:
                log.warning(
                    "transient device failure in batch resolve (%s: %s); "
                    "evicting cached state and re-dispatching once",
                    type(err).__name__, err,
                )
                self._evict_query_state(
                    groupby_with_time_granularity(q), ds
                )
                out.append(self._execute_groupby(q, ds))
        return out

    def _dispatch_groupby_once(self, q: Q.GroupByQuery, ds: DataSource):
        """Phase 1 of one GroupBy execution: build/launch the device
        programs (async dispatch, no fetch) and return `resolve() -> df`,
        which fetches, finalizes, and publishes metrics.  The synchronous
        path is `self._dispatch_groupby_once(q, ds)()`; batch callers
        dispatch all queries before resolving any."""
        import time as _time

        from .metrics import QueryMetrics

        t_total = _time.perf_counter()
        with span(SPAN_LOWER):
            q = groupby_with_time_granularity(q)
            lowering = self._lowering_for(q, ds)
            segs = self._segments_in_scope(q, ds)
        # learned-memo identity: segment-set independent (memo_key), so a
        # streamed append neither forgets learned rungs nor grows the
        # memo dicts per batch
        qkey = memo_key(q, ds)
        m = self._m = QueryMetrics(
            query_type="groupBy",
            strategy=self._resolve_strategy(lowering.num_groups),
            datasource=ds.name,
            query_id=current_query_id(),
            rows_scanned=sum(s.num_rows for s in segs),
            bytes_scanned=_bytes_scanned(segs, lowering.columns),
            segments=len(segs),
            num_groups=lowering.num_groups,
        )

        # In batch mode resolve() runs long after dispatch, with other
        # queries' fetch+finalize in between — timings anchored at dispatch
        # would absorb all of it.  So: phase 1 records its own elapsed time,
        # and resolve() measures from its own entry (for the synchronous
        # path resolve starts immediately after dispatch, so the split is
        # equivalent to the old dispatch-anchored measurement).
        dispatch_ms = 0.0
        t_resolve = None
        outcome = {"v": "ok"}  # finish() publishes it; except paths set it

        def finish():
            now = _time.perf_counter()
            if t_resolve is not None:
                m.total_ms = dispatch_ms + (now - t_resolve) * 1e3
            else:  # phase-1 failure: resolve never started
                m.total_ms = (now - t_total) * 1e3
            m.bytes_resident = self.bytes_resident()
            # deadline-bounded partial answer: stamp the coverage the
            # collector accounted (partial-result discipline: a
            # partial=True result ALWAYS carries its coverage fraction)
            pc = current_partial()
            if pc is not None and pc.is_partial:
                m.partial = True
                m.coverage = pc.coverage()
                m.rows_seen = pc.rows_seen
                m.delta_rows_seen = pc.delta_rows_seen
                if outcome["v"] == "ok":
                    outcome["v"] = "partial"
            self.last_metrics = m
            self._m = None
            # every completed execution publishes into the process metrics
            # registry (obs/): fleet-level counts + phase histograms
            record_query_metrics(m, outcome["v"])
            log.info("%s", m.describe())

        adaptive_resolve = None
        sparse_resolve = None
        dense_state = None
        try:
            # adaptive dictionary-domain compaction first: it covers sketch
            # aggs too and repeats skip its presence pass via the kept-set
            # cache.  A None return means it declined at dispatch time and
            # the sparse/dense paths proceed as before.
            if (
                self._adaptive_eligible(lowering)
                and segs
                and qkey not in self._adaptive_declined
            ):
                adaptive_resolve = self._dispatch_groupby_adaptive(
                    q, ds, lowering
                )
                if adaptive_resolve is not None:
                    m.strategy = "adaptive"
            if adaptive_resolve is None and (
                self._sparse_eligible(lowering)
                and segs
                and qkey not in self._sparse_disabled
            ):
                m.strategy = "sparse"
                sparse_resolve = self._dispatch_groupby_sparse(
                    q, ds, lowering
                )
            elif adaptive_resolve is None:
                dense_state = self._partials_for_query(
                    q, ds, lowering=lowering
                )
        except BaseException as err:
            from ..resilience import DeadlineExceeded

            if isinstance(err, DeadlineExceeded):
                m.deadline_exceeded = True
                outcome["v"] = "deadline"
            else:
                outcome["v"] = "error"
            finish()
            raise
        dispatch_ms = (_time.perf_counter() - t_total) * 1e3
        # h2d/compile recorded so far belong to the phase-1 dispatch window;
        # anything recorded later (the sparse-declined dense fallback inside
        # resolve) is outside both timing windows and must not be subtracted
        phase1_h2d_ms = m.h2d_ms
        phase1_compile_ms = m.compile_ms

        def resolve():
            nonlocal dense_state, t_resolve
            self._m = m
            t_resolve = _time.perf_counter()
            try:
                # deadline checkpoint between dispatch and the blocking
                # fetch: a budget blown during dispatch cancels before
                # paying the device round trip — unless partials are
                # collected, in which case every batch has already been
                # dispatched and draining the fetch yields the complete
                # answer (is_partial stays False)
                checkpoint_partial("engine.resolve")
                if adaptive_resolve is not None:
                    out, reason = adaptive_resolve()
                    if out is not None:
                        m.device_ms = (
                            (_time.perf_counter() - t_resolve) * 1e3
                            + dispatch_ms
                        )
                        return out
                    # adaptive failed at resolve time: serial dense fallback
                    # for THIS execution (no pin — transient errors only;
                    # deterministic declines happened at dispatch time)
                    m.strategy = self._resolve_strategy(lowering.num_groups)
                    log.warning(
                        "adaptive path failed (%s); falling back to %s",
                        reason, m.strategy,
                    )
                    dense_state = self._partials_for_query(
                        q, ds, lowering=lowering
                    )
                if sparse_resolve is not None:
                    out, reason = sparse_resolve()
                    if out is not None:
                        m.device_ms = (
                            (_time.perf_counter() - t_resolve) * 1e3
                            + dispatch_ms
                        )
                        self._sparse_error_counts.pop(qkey, None)
                        return out
                    pinned = False
                    if reason == "overflow":
                        # deterministic: more distinct groups than slots
                        self._sparse_disabled.add(qkey)
                        pinned = True
                    elif reason != "declined":
                        # "declined" = nothing dispatched (a partial-drain
                        # pass), not a sparse failure: never error-count it
                        # toward the pin
                        n = self._sparse_error_counts.get(qkey, 0) + 1
                        self._sparse_error_counts[qkey] = n
                        if n >= _SPARSE_ERROR_PIN_AFTER:
                            self._sparse_disabled.add(qkey)
                            pinned = True
                    m.strategy = self._resolve_strategy(lowering.num_groups)
                    log.warning(
                        "sparse path declined (%s); falling back to %s%s",
                        reason,
                        m.strategy,
                        " (pinned)" if pinned else "",
                    )
                    # serial fallback dispatch (rare): sparse declined, so
                    # the dense program launches now
                    dense_state = self._partials_for_query(
                        q, ds, lowering=lowering
                    )
                t_fetch = _time.perf_counter()
                dims, la, G, sums, mins, maxs, sketch_states = dense_state
                dense_state = None  # free the device partials promptly
                # ONE device_get for everything: each separate host fetch
                # of a device buffer pays a full round trip (dozens of ms
                # when the TPU sits behind a network tunnel); a single
                # pytree fetch pays one.
                with span(SPAN_DEVICE_FETCH):
                    # sampled query: separate device-wait from the host
                    # copy inside the fetch span (obs/prof.py)
                    prof.fetch_sync((sums, mins, maxs, sketch_states))
                    sums, mins, maxs, sketch_states = jax.device_get(
                        (sums, mins, maxs, sketch_states)
                    )
                # state capture (serve/result_cache.py delta-aware reuse):
                # stash the merged HOST state for the caller — only on
                # this dense path (sparse/adaptive returned above) and
                # only when the scan was NOT deadline-truncated (a
                # partial state must never seed the cache)
                holder = getattr(self._m_local, "capture", None)
                pc_cap = current_partial()
                if holder is not None and (
                    pc_cap is None or not pc_cap.triggered
                ):
                    holder["state"] = _pack_host_state(
                        sums, mins, maxs, sketch_states
                    )
                # the phase-1 dispatch share (minus its h2d/compile) plus
                # this query's own fetch wait is the device time; overlap
                # hidden behind other queries' resolves is deliberately NOT
                # attributed here
                m.device_ms = max(
                    0.0,
                    (_time.perf_counter() - t_fetch) * 1e3
                    + dispatch_ms
                    - phase1_h2d_ms
                    - phase1_compile_ms,
                )
                t0 = _time.perf_counter()
                with span(SPAN_FINALIZE):
                    out = finalize_groupby(
                        q, dims, la,
                        np.asarray(sums), np.asarray(mins), np.asarray(maxs),
                        {k: np.asarray(v) for k, v in sketch_states.items()},
                    )
                m.finalize_ms = (_time.perf_counter() - t0) * 1e3
                return out
            except BaseException as err:
                from ..resilience import DeadlineExceeded

                if isinstance(err, DeadlineExceeded):
                    m.deadline_exceeded = True
                    outcome["v"] = "deadline"
                else:
                    outcome["v"] = "error"
                raise
            finally:
                finish()

        return resolve

    # -- timeseries: a groupby whose only dimension is the time bucket -------

    def _execute_timeseries(self, q: Q.TimeseriesQuery, ds: DataSource):
        df = self._execute_groupby(timeseries_to_groupby(q), ds)
        return finalize_timeseries(df, q, ds)

    # -- topn: single-dim groupby + rank (exact; Druid's is approximate) -----

    def _execute_topn(self, q: Q.TopNQuery, ds: DataSource):
        df = self._execute_groupby(topn_to_groupby(q), ds)
        return finalize_topn(df, q)

    # -- scan / search -------------------------------------------------------

    def _execute_scan(self, q: Q.ScanQuery, ds: DataSource):
        import pandas as pd

        filter_fn = compile_filter(q.filter, ds) if q.filter is not None else None
        vcol_fns = {
            v.name: _decoded_expr_fn(v.expression, ds)
            for v in q.virtual_columns
        }
        order_cols = [c.dimension for c in q.order_by]
        if "__time" in order_cols and not ds.time_column:
            # legacy wire `order` implies time ordering; a timeless table
            # cannot honor it — clean error, not a KeyError from the fetch
            raise Q.QueryValidationError(
                f"scan ordering by __time: datasource {ds.name!r} has no "
                "time column"
            )
        sortable = (
            set(q.columns)
            | {c.name for c in ds.columns}
            | set(vcol_fns)
            | {"__time"}
        )
        for c in order_cols:
            # wire queries arrive unplanned — validate here so a bad
            # orderBy is a clean 400, not a KeyError mid-fetch
            if c not in sortable:
                raise Q.QueryValidationError(
                    f"scan orderBy unknown column {c!r}"
                )
        fetch_list = list(
            dict.fromkeys(list(q.columns) + order_cols)
        )
        need = [c for c in fetch_list if c not in vcol_fns and c != "__time"]
        if q.filter is not None:
            need += [c for c in _filter_columns(q.filter) if c != "__time"]
        for v in q.virtual_columns:
            need += [c for c in v.expression.columns() if c != "__time"]
        if ds.time_column:
            need.append(ds.time_column)
        need = dict.fromkeys(need)
        frames = []
        # early per-segment truncation only when no ordering (an ordered
        # scan must see every surviving row before sorting); with an offset
        # the first `offset` rows still have to be produced before skipping
        remaining = (
            None
            if q.order_by
            else (q.limit + q.offset if q.limit is not None else None)
        )
        scan_segs = self._segments_in_scope(q, ds)
        pc = current_partial()
        if pc is not None:
            pc.begin_pass()
            pc.add_scope(len(scan_segs), *_row_counts(scan_segs))
        # prefetch-only pipeline (reorder=False): scan row order is part
        # of the result contract, so dispatch order stays canonical and
        # only the NEXT segments' columns stream behind the current fetch
        run = self._pipeline.start(
            ds, [[s] for s in scan_segs], list(need), reorder=False
        )
        for pos, seg in enumerate(scan_segs):
            # partial-aware checkpoint: a scan past its deadline returns
            # the rows fetched so far (a row subset IS the scan's natural
            # partial) with a coverage fraction
            if checkpoint_partial("engine.scan_loop"):
                run.cancel()
                break
            cols = self._device_cols(seg, need, ds_name=ds.name)
            run.advance(pos)
            if ds.time_column and ds.time_column in cols:
                cols["__time"] = cols[ds.time_column]
            for name, fn in vcol_fns.items():
                cols[name] = jnp.asarray(fn(cols))
            mask = cols["__valid"]
            if q.intervals:
                t = cols["__time"]
                im = jnp.zeros(t.shape, jnp.bool_)
                for a, b in q.intervals:
                    im = im | ((t >= a) & (t < b))
                mask = mask & im
            if filter_fn is not None:
                mask = mask & filter_fn(cols)
            # one round trip for the mask + all projected columns
            with span(SPAN_DEVICE_FETCH):
                fetched = jax.device_get(
                    {"__mask": mask, **{c: cols[c] for c in fetch_list}}
                )
            keep = fetched.pop("__mask")
            data = {}
            for c in fetch_list:
                arr = fetched[c][keep]
                if c in ds.dicts:
                    arr = ds.dicts[c].decode(arr)
                data[c] = arr
            f = pd.DataFrame(data)
            if remaining is not None:
                f = f.head(remaining)
                remaining -= len(f)
            elif q.order_by and q.limit is not None:
                # ordered + limited: only each segment's top-(limit+offset)
                # can appear in the global result — truncate before concat
                # so a small LIMIT never materializes the whole table
                f = apply_limit_spec(
                    f, Q.LimitSpec(q.limit + q.offset, q.order_by, 0)
                )
            frames.append(f)
            if pc is not None:
                pc.add_seen(1, *_row_counts((seg,)))
            if remaining is not None and remaining <= 0:
                run.cancel()  # LIMIT satisfied: stop prefetch issue too
                break
        out = (
            pd.concat(frames, ignore_index=True)
            if frames
            else pd.DataFrame(columns=fetch_list)
        )
        out = apply_limit_spec(
            out, Q.LimitSpec(q.limit, q.order_by, q.offset)
        )
        return out[list(q.columns)].reset_index(drop=True)

    def _execute_time_boundary(self, q: Q.TimeBoundaryQuery, ds: DataSource):
        """Druid `timeBoundary` — answered from segment metadata (the
        reference learned these bounds from the coordinator, SURVEY.md §3.1);
        no kernel dispatch."""
        import pandas as pd

        iv = ds.interval()
        if iv is None:
            return pd.DataFrame(columns=["minTime", "maxTime"])
        lo, hi = iv
        row = {}
        if q.bound in (None, "minTime"):
            row["minTime"] = np.datetime64(int(lo), "ms")
        if q.bound in (None, "maxTime"):
            row["maxTime"] = np.datetime64(int(hi), "ms")
        return pd.DataFrame([row])

    def _execute_datasource_metadata(
        self, q: "Q.DataSourceMetadataQuery", ds: DataSource
    ):
        """Druid `dataSourceMetadata` — newest ingested event time from
        segment metadata; no kernel dispatch."""
        import pandas as pd

        iv = ds.interval()
        if iv is None:
            return pd.DataFrame(columns=["maxIngestedEventTime"])
        return pd.DataFrame(
            [{"maxIngestedEventTime": np.datetime64(int(iv[1]), "ms")}]
        )

    def _execute_segment_metadata(
        self, q: Q.SegmentMetadataQuery, ds: DataSource
    ):
        """Druid `segmentMetadata` — the catalog rendered per segment (the
        query the reference's metadata cache bootstraps from)."""
        import pandas as pd

        from ..models.filters import _ms_to_iso

        # schema is datasource-level: one columns dict shared by all segments
        cols = {
            c.name: {
                "type": c.kind,
                "dtype": c.dtype,
                "cardinality": c.cardinality,
            }
            for c in ds.columns
        }
        rows = []
        # graftlint: disable=checkpoint-coverage -- segmentMetadata renders catalog dicts, no column data touched
        for seg in self._segments_in_scope(q, ds):
            rows.append(
                {
                    "id": seg.segment_id,
                    "intervals": (
                        [
                            "%s/%s"
                            % (
                                _ms_to_iso(int(seg.interval[0])),
                                _ms_to_iso(int(seg.interval[1])),
                            )
                        ]
                        if seg.interval is not None
                        else []
                    ),
                    "numRows": seg.num_rows,
                    "columns": cols,
                }
            )
        return pd.DataFrame(
            rows, columns=["id", "intervals", "numRows", "columns"]
        )

    def _execute_search(self, q: Q.SearchQuery, ds: DataSource):
        """Dimension-value search: candidate values come from the (host)
        dictionaries, but the Druid wire contract includes a per-value
        `count` of MATCHING ROWS — so rows in scope (intervals, zone maps,
        filter) are counted per code, and zero-count values are omitted,
        exactly like Druid's broker response."""
        import pandas as pd

        # candidate codes come from the host dictionaries FIRST: a needle
        # matching nothing (or nothing beyond earlier dimensions' limit)
        # must not pay a row scan
        needle = q.query.lower()
        matching = {
            dim: [
                code
                for code, v in enumerate(ds.dicts[dim].values)
                if needle in str(v).lower()
            ]
            for dim in q.dimensions
        }
        live_dims = [d for d in q.dimensions if matching[d]]
        if not live_dims:
            return pd.DataFrame(columns=["dimension", "value", "count"])
        segs = self._segments_in_scope(q, ds)
        fmask_fn = (
            compile_filter(q.filter, ds) if q.filter is not None else None
        )
        counts = {
            dim: np.zeros(ds.dicts[dim].cardinality, np.int64)
            for dim in live_dims
        }
        pc = current_partial()
        if pc is not None:
            pc.begin_pass()
            pc.add_scope(len(segs), *_row_counts(segs))
        for seg in segs:
            # per-segment filter evaluation + bincount is real work on a
            # wide segment: honor the deadline between segments (partial
            # counts over the segments seen so far are a safe answer)
            if checkpoint_partial("engine.search_loop"):
                break
            base = np.asarray(seg.valid)
            if q.intervals and seg.time is not None:
                t = np.asarray(seg.time)
                im = np.zeros(base.shape, bool)
                for a, b in q.intervals:
                    im |= (t >= a) & (t < b)
                base = base & im
            if fmask_fn is not None:
                # ride the residency cache (transfer-discipline/GL19xx):
                # repeated searches hit instead of re-moving the filter
                # columns every time
                cols = self._device_cols(
                    seg, list(_filter_columns(q.filter)), ds_name=ds.name
                )
                base = base & np.asarray(fmask_fn(cols))
            for dim in live_dims:
                sel = np.asarray(seg.dims[dim])[base]
                sel = sel[sel >= 0]
                counts[dim] += np.bincount(
                    sel, minlength=len(counts[dim])
                )
            if pc is not None:
                pc.add_seen(1, *_row_counts((seg,)))
        rows = []
        for dim in live_dims:
            if len(rows) >= q.limit:
                break
            d = ds.dicts[dim]
            for code in matching[dim]:
                if counts[dim][code] > 0:
                    rows.append(
                        {
                            "dimension": dim,
                            "value": d.values[code],
                            "count": int(counts[dim][code]),
                        }
                    )
                    if len(rows) >= q.limit:
                        break
        return pd.DataFrame(rows, columns=["dimension", "value", "count"])

    # -- progressive execution (chunked refinement, ISSUE 7 tentpole (b)) ----

    def execute_progressive(self, q: Q.QuerySpec, ds: DataSource):
        """Generator of progressively-refined results for one aggregate
        query: after each segment-batch dispatch the running partial
        state is fetched and finalized, yielding `(df, info)` where
        `info` carries {"sequence", "coverage", "rows_seen", "rows_total",
        "final"}.  The LAST emission is the exact answer (coverage 1.0)
        — unless an armed deadline expires mid-scan, in which case the
        last emission is the best-effort partial, flagged via
        info["partial"]=True.

        Interactive exploration over SF100 sees the first refinement
        after ONE batch (milliseconds of scan) and watches the answer
        converge; the per-batch device fetch + finalize is the price of
        visibility, so this path is opt-in (`context.progressive` on the
        wire).  Non-aggregate query types have no mergeable state to
        refine: they execute normally and emit once."""
        if isinstance(q, Q.TimeseriesQuery):
            inner = timeseries_to_groupby(q)
            shape = lambda df: finalize_timeseries(df, q, ds)  # noqa: E731
        elif isinstance(q, Q.TopNQuery):
            inner = topn_to_groupby(q)
            shape = lambda df: finalize_topn(df, q)  # noqa: E731
        elif isinstance(q, Q.GroupByQuery):
            inner = q
            shape = lambda df: df  # noqa: E731
        else:
            df = self.execute(q, ds)
            # no mergeable state to refine, but execute() can still have
            # drained to a deadline partial (e.g. the scan loop under an
            # armed collector): the single emission must carry the real
            # partial/coverage stamp, not claim exactness (GL16xx)
            info = {
                "sequence": 0, "coverage": 1.0, "final": True,
                "partial": False,
            }
            pc = current_partial()
            if pc is not None and pc.is_partial:
                d = pc.to_dict()
                info.update(
                    partial=True, coverage=d["coverage"],
                    rows_seen=d["rows_seen"], rows_total=d["rows_total"],
                )
            yield df, info
            return

        import time as _time

        from .metrics import QueryMetrics

        t0 = _time.perf_counter()
        inner = groupby_with_time_granularity(inner)
        with span(SPAN_LOWER):
            lowering = self._lowering_for(inner, ds)
            segs = self._segments_in_scope(inner, ds)
        dims, la, G = lowering.dims, lowering.la, lowering.num_groups
        need = lowering.columns
        rows_total, delta_total = _row_counts(segs)
        m = self._m = QueryMetrics(
            query_type="progressive",
            strategy=self._resolve_strategy(G),
            datasource=ds.name,
            query_id=current_query_id(),
            rows_scanned=rows_total,
            segments=len(segs),
            num_groups=G,
        )
        pc = current_partial()
        if pc is not None:
            pc.begin_pass()
            pc.add_scope(len(segs), rows_total, delta_total)
        sums = mins = maxs = None
        sketch_states: Dict[str, Any] = {}
        rows_seen = 0
        seen_segs = 0
        seq = 0
        truncated = False
        try:
            if segs:
                seg_fn = self._segment_program(inner, ds, lowering)
                batches = list(self._segment_batches(segs, need))
                # prefetch-only (reorder=False): the refinement sequence
                # is user-visible, so batches dispatch in canonical order
                # while the next batches' columns stream behind compute
                run = self._pipeline.start(ds, batches, need, reorder=False)
                for bi, batch in enumerate(batches):
                    if checkpoint_partial("engine.progressive_loop"):
                        run.cancel()
                        truncated = True
                        break
                    with span(SPAN_H2D, batch=bi, segments=len(batch)):
                        cols_list = [
                            self._cols_for_segment(seg, ds, need)
                            for seg in batch
                        ]
                    run.advance(bi)
                    with span(
                        SPAN_SEGMENT_DISPATCH, batch=bi,
                        segments=len(batch),
                    ):
                        (s, mn, mx, sk), seg_fn = (
                            self._call_segment_program(
                                inner, ds, lowering, seg_fn, cols_list
                            )
                        )
                    sums = s if sums is None else sums + s
                    mins = mn if mins is None else jnp.minimum(mins, mn)
                    maxs = mx if maxs is None else jnp.maximum(maxs, mx)
                    _merge_sketch_states(la, sketch_states, sk)
                    br, bd = _row_counts(batch)
                    rows_seen += br
                    seen_segs += len(batch)
                    if pc is not None:
                        pc.add_seen(len(batch), br, bd)
                    final = bi + 1 == len(batches)
                    with span(SPAN_DEVICE_FETCH, batch=bi):
                        # graftlint: disable=trace-purity -- per-batch fetch IS progressive streaming: each refinement ships the running state to the client
                        hs, hmn, hmx, hsk = jax.device_get(
                            (sums, mins, maxs, sketch_states)
                        )
                    with span(SPAN_FINALIZE, batch=bi):
                        df = shape(finalize_groupby(
                            inner, dims, la,
                            np.asarray(hs), np.asarray(hmn),
                            np.asarray(hmx),
                            {k: np.asarray(v) for k, v in hsk.items()},
                        ))
                    yield df, {
                        "sequence": seq,
                        "coverage": (
                            rows_seen / rows_total if rows_total else 1.0
                        ),
                        "rows_seen": rows_seen,
                        "rows_total": rows_total,
                        "segments_seen": seen_segs,
                        "segments_total": len(segs),
                        "final": final,
                        "partial": False,
                    }
                    seq += 1
            if not segs or truncated or sums is None:
                # empty scope, or a deadline cut the scan short: emit the
                # (possibly empty) merged state as the final answer with
                # its honest coverage
                if sums is None:
                    sums, mins, maxs, sketch_states = empty_partials(la, G)
                hs, hmn, hmx, hsk = jax.device_get(
                    (sums, mins, maxs, sketch_states)
                )
                with span(SPAN_FINALIZE):
                    df = shape(finalize_groupby(
                        inner, dims, la,
                        np.asarray(hs), np.asarray(hmn), np.asarray(hmx),
                        {k: np.asarray(v) for k, v in hsk.items()},
                    ))
                cov = rows_seen / rows_total if rows_total else (
                    None if truncated else 1.0
                )
                yield df, {
                    "sequence": seq,
                    "coverage": cov,
                    "rows_seen": rows_seen,
                    "rows_total": rows_total,
                    "segments_seen": seen_segs,
                    "segments_total": len(segs),
                    "final": True,
                    "partial": truncated,
                }
        finally:
            m.total_ms = (_time.perf_counter() - t0) * 1e3
            if pc is not None and pc.is_partial:
                m.partial = True
                m.coverage = pc.coverage()
                m.rows_seen = pc.rows_seen
                m.delta_rows_seen = pc.delta_rows_seen
            self.last_metrics = m
            self._m = None
            record_query_metrics(
                m, "partial" if m.partial else "ok"
            )


