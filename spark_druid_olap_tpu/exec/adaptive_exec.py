"""Adaptive dictionary-domain compaction for huge combined group domains.

The OLAP reality behind SSB q3/q4-class queries: the COMBINED dictionary
domain is huge (c_city x s_city x d_year = 504K cells) but the filter admits
only a few codes per dimension (c_nation = 'UNITED STATES' leaves 10 of 250
cities).  Raw scatter pays the full-domain price per row (cache-missing
state) and per segment (one [G, M] state each); the sort-compaction path
pays a per-segment sort.  Both ignore what the dictionaries already know.

This tier measures the per-dimension PRESENT code sets first, then runs the
normal aggregation over the compacted domain:

  phase A  one fused pass: per-dim presence counts under the query's row
           mask — a tiny GroupBy per dimension (cardinality-sized states,
           one data read for all dims), merged across segments.
  host     kept_d = codes with count > 0;  G' = prod(|kept_d|).  If G' is
           small enough, build the remap code -> compact code (-1 = absent).
  phase B  the UNMODIFIED segment program machinery over a *compacted
           lowering*: same query, same aggs (sketches included), dims
           rewritten through the remap (identity / unrolled compare-select
           chain / LUT gather by kept-set size — see compacted_lowering) —
           so the kernel runs dense/Pallas at G' instead of scatter at G.

Soundness: presence is computed under exactly the row mask phase B applies,
so every masked-in row's codes are in kept_d by construction; a -1 from the
remap can only occur on rows the mask already excludes (combine_group_ids
clamps them into slot 0, which the mask keeps out of every aggregate).

The kept sets are cached per (query, datasource-version): repeat queries
skip phase A entirely and run ONE compact-domain pass — dashboard-shaped
workloads converge to the speed of a low-cardinality GroupBy.

Reference parity: Druid's historicals get the same effect from per-segment
dictionary scans + bitmap indexes (SURVEY.md §1 L1 row `[U]`); this is the
TPU-native equivalent where the "index" is a presence bitmap measured on
device at full scan speed.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..catalog.segment import DataSource
from ..models import query as Q
from ..resilience import DeadlineExceeded, current_partial, fire
from ..utils.log import get_logger
from .finalize import finalize_groupby
from .lowering import (
    GroupByLowering,
    ResolvedDim,
    _query_key,
    empty_partials,
    memo_key,
)

log = get_logger("exec.adaptive")

# Decline compaction when the compacted domain is still bigger than this:
# past it the dense/one-hot inner gains nothing and phase A was the only
# cost (one scan), which the decline memo makes one-time.
ADAPTIVE_MAX_COMPACT_GROUPS = 1 << 17

# ... and when the domain barely shrinks, compaction cannot pay for its
# extra pass even once.
ADAPTIVE_MIN_SHRINK = 0.5

def _compare_chain_max() -> int:
    """Kept-sets at or under this size remap codes via an unrolled
    compare-select chain instead of a device LUT gather (see
    compacted_lowering): ~0.3 ms per compare over 52M rows vs ~360 ms for
    one gather on the round-5 TPU.  On CPU the inversion is the other way
    — a small LUT gather is one L1-resident load per row while 64 fused
    compares are 64 ALU ops — so the chain is capped near the width XLA
    itself would select-lower."""
    import jax

    return 64 if jax.default_backend() == "tpu" else 4


def presence_columns(q, lowering: GroupByLowering, ds=None):
    """Columns phase A reads: only what the mask + dim codes need —
    aggregate input columns stay on the host until phase B.  The PHYSICAL
    time column must survive the cut whenever the lowering fetches it:
    row_mask reads cols["__time"], which the engines alias from
    ds.time_column (review r5: dropping it made every interval-scoped
    query KeyError out of phase A and silently decline adaptive).  Shared
    by the local mixin and parallel/distributed.py."""
    from .lowering import _filter_columns

    keep = {"__valid", "__time"}
    tc = getattr(ds, "time_column", None) if ds is not None else None
    if tc:
        keep.add(tc)
    for d in lowering.dims:
        keep.add(d.spec.dimension)
    if q.filter is not None:
        keep.update(_filter_columns(q.filter))
    for v in q.virtual_columns:
        keep.update(v.expression.columns())
    return [c for c in lowering.columns if c in keep]


def filter_derived_kept(
    q, lowering: GroupByLowering, ds
) -> Optional[List[np.ndarray]]:
    """Phase A WITHOUT the scan: per-dim kept-code sets derived from the
    query's own filter, evaluated over the host-side dictionaries.

    When every grouping dim is directly pinned by a dictionary-evaluable
    conjunct (Selector / In / Bound on that dim), the accepted-code sets
    are computable in O(cardinality) host work — the Druid bitmap-index /
    dictionary-pruning analog (SURVEY.md §1 L1 row) with zero device
    passes.  Soundness: every masked-in row satisfies every conjunct, so
    its code for a pinned dim lies in that conjunct's accepted set; the
    derived kept is a (possibly proper) SUPERSET of measured presence,
    which only costs a few empty compact slots.  Returns None when any
    dim is unpinned or not dictionary-backed — callers fall back to the
    measured presence pass.

    Only AND-conjuncts pin a dim: disjunctions/negations/expressions may
    admit rows their sub-predicates reject, so they derive nothing (the
    dim counts as unpinned unless another conjunct covers it)."""
    from ..models import filters as F

    conjuncts: List[object] = []

    def collect(f):
        if isinstance(f, F.And):
            for c in f.fields:
                collect(c)
        elif f is not None:
            conjuncts.append(f)

    collect(getattr(q, "filter", None))

    kept: List[np.ndarray] = []
    for d in lowering.dims:
        spec = d.spec
        if (
            spec.dimension == "__time"
            or getattr(spec, "granularity", None) is not None
            or getattr(spec, "extraction", None) is not None
            or spec.dimension not in getattr(ds, "dicts", {})
        ):
            return None  # not a plain dictionary dim: cannot derive
        dic = ds.dicts[spec.dimension]
        # Accepted codes per derivable conjunct, intersected.  Every
        # branch MIRRORS the device filter compiler's translation
        # (ops/filters.py) exactly — kept must be a superset of the
        # device-true codes, so the two sides must agree on how literals
        # map into code space (review r5: values.index() diverged from
        # code_of on numeric dictionaries and silently dropped rows).
        acc: Optional[set] = None
        for f in conjuncts:
            if getattr(f, "dimension", None) != spec.dimension:
                continue
            cur: Optional[set] = None
            if isinstance(f, F.Selector):
                if f.value is None:
                    cur = {d.cardinality - 1}  # the null slot
                else:
                    c = dic.code_of(f.value)
                    cur = set() if c is None else {c}
            elif isinstance(f, F.InFilter):
                cur = {
                    c
                    for c in (dic.code_of(v) for v in f.values)
                    if c is not None
                }
            elif isinstance(f, F.Bound):
                cur = _bound_accepted_codes(f, dic)
            if cur is not None:
                acc = cur if acc is None else (acc & cur)
        if acc is None:
            return None  # unpinned dim: a device presence pass is needed
        kept.append(np.array(sorted(acc), dtype=np.int32))
    return kept


def _bound_accepted_codes(f, dic) -> Optional[set]:
    """Dictionary codes a Bound conjunct accepts, mirroring the device
    compile branch-for-branch (ops/filters.py Bound handling); None when
    the branch cannot be mirrored soundly (the conjunct then derives
    nothing and the dim falls back to the presence scan)."""
    import numpy as _np

    from ..ops.filters import numeric_dict_code_bounds

    nv = dic.numeric_values
    card = dic.cardinality
    if nv is not None:
        cb = numeric_dict_code_bounds(f, _np.asarray(nv))
        if cb is not None:
            lo_c, hi_c = cb
            lo_c = 0 if lo_c is None else lo_c
            hi_c = card - 1 if hi_c is None else hi_c
            return set(range(max(0, lo_c), min(card - 1, hi_c) + 1))
        # non-numeric literal: device compares STRINGIFIED values
        vals = [str(v) for v in dic.values]
        ok = set(range(card))
        if f.lower is not None:
            lo_s = str(f.lower)
            ok = {
                i for i in ok
                if (vals[i] > lo_s if f.lower_strict else vals[i] >= lo_s)
            }
        if f.upper is not None:
            hi_s = str(f.upper)
            ok = {
                i for i in ok
                if (vals[i] < hi_s if f.upper_strict else vals[i] <= hi_s)
            }
        return ok
    if f.ordering == "lexicographic":
        vals = _np.asarray(dic.values, dtype=str)
        lo_c, hi_c = 0, card - 1
        if f.lower is not None:
            side = "right" if f.lower_strict else "left"
            lo_c = int(_np.searchsorted(vals, f.lower, side=side))
        if f.upper is not None:
            side = "left" if f.upper_strict else "right"
            hi_c = int(_np.searchsorted(vals, f.upper, side=side)) - 1
        return set(range(max(0, lo_c), min(card - 1, hi_c) + 1))
    # string dictionary + numeric ordering: the device falls through to a
    # raw-CODE numeric compare (a degenerate legacy semantic) — decline
    # rather than risk a kept set narrower than the device mask
    return None


def compacted_lowering(
    lowering: GroupByLowering, kept: List[np.ndarray]
) -> GroupByLowering:
    """The same lowered query over the compacted code domain.

    Each dim's codes_fn remaps original -> compact codes by one of three
    equivalent strategies: identity (every code kept), an unrolled
    compare-select chain (small kept-sets — the common case compaction
    exists for; a TPU LUT gather cost ~360 ms/dim over 52M rows, profiled
    round 5), or a device LUT gather (large kept-sets).  All three emit -1
    for absent codes, which only masked-out rows can carry; decode() maps
    compact codes back through kept_d then the original decoder — so
    finalize_groupby and every kernel work unchanged."""
    new_dims: List[ResolvedDim] = []
    G = 1
    for d, kd in zip(lowering.dims, kept):
        if len(kd) == d.cardinality:
            # identity remap (every code present): no rewrite at all
            codes_fn = d.codes_fn
        elif len(kd) <= _compare_chain_max():
            # Unrolled compare-select instead of a table gather.  On TPU a
            # gather through even a 250-entry LUT runs ~0.36 s per dim over
            # 52M rows (profiled round 5: tools/profile_adaptive_phaseb.py
            # — it was 97% of q3_2's 1117 ms phase B), while |kept| vector
            # compares fuse into the scan for ~free; XLA only does this
            # lowering itself for tables of ~32 entries.  Compaction exists
            # precisely because |kept| is small, so this is the common case.
            def codes_fn(cols, base=d.codes_fn, kd_list=kd.tolist()):
                c = base(cols)
                acc = jnp.zeros(c.shape, jnp.int32)
                for i, k in enumerate(kd_list):
                    acc = acc + jnp.where(c == k, jnp.int32(i + 1), 0)
                return acc - 1  # absent codes -> -1, same as the LUT
        else:
            lut = np.full(d.cardinality, -1, np.int32)
            lut[kd] = np.arange(len(kd), dtype=np.int32)
            lut_dev = jnp.asarray(lut)

            def codes_fn(cols, base=d.codes_fn, lut_dev=lut_dev):
                return lut_dev[base(cols)]

        def decode(codes, base=d.decode, kd=kd):
            return base(kd[np.asarray(codes, dtype=np.int64)])

        new_dims.append(ResolvedDim(d.spec, len(kd), codes_fn, decode))
        G *= len(kd)
    return dataclasses.replace(lowering, dims=new_dims, num_groups=G)


class AdaptiveDomainMixin:
    """Engine mixin (exec/engine.py): the adaptive-compaction dispatch.

    Attributes it relies on are created in Engine.__init__:
    `_adaptive_kept` (qkey -> kept code arrays), `_adaptive_declined`
    (qkey set).  Everything else reuses the engine's program machinery.
    """

    def _adaptive_eligible(self, lowering: GroupByLowering) -> bool:
        from ..ops.groupby import SCATTER_CUTOVER
        from ..ops.pallas_groupby import pallas_available

        # explicit kernel requests ('segment', 'sparse', 'dense', 'pallas')
        # are honored as such (the ADVICE r1 rule the sparse tier follows):
        # adaptive runs when the cost model chose it, or under 'auto' —
        # where one probe pass is cheap insurance on any backend once G is
        # past the scatter cutover, since the kept-set cache makes repeats
        # a single compact-domain pass.
        if self.strategy not in ("auto", "adaptive"):
            return False
        return (
            lowering.num_groups > SCATTER_CUTOVER
            and bool(lowering.dims)
            # an unfiltered query keeps every present code; compaction can
            # still win (populated << domain), so no filter requirement
        )

    def _presence_columns(self, q, lowering: GroupByLowering, ds=None):
        return presence_columns(q, lowering, ds)

    def _adaptive_main_strategy(self, ds: DataSource, g_compact: int) -> str:
        from ..config import SessionConfig
        from ..ops.groupby import SCATTER_CUTOVER
        from ..ops.pallas_groupby import pallas_available
        from ..plan.cost import choose_kernel_strategy

        cfg = getattr(self, "_calibrated_cfg", None)
        if cfg is None:
            cfg = SessionConfig.load_calibrated()
            self._calibrated_cfg = cfg
        strat = choose_kernel_strategy(ds.num_rows, g_compact, cfg)
        if (
            strat == "dense"
            and g_compact <= SCATTER_CUTOVER
            and pallas_available()
            and not self._pallas_broken
        ):
            strat = "pallas"
        return strat

    def _presence_program(self, q, ds, lowering: GroupByLowering):
        """Fused per-segment program: presence COUNTS per grouping dim under
        the query's row mask — one data read covers every dim."""
        from ..ops.groupby import partial_aggregate
        from ..ops.pallas_groupby import pallas_available

        pallas_ok = not self._pallas_broken and pallas_available()
        # pallas_ok participates in the key: after a Mosaic failure flips
        # _pallas_broken, the rebuilt program must not reuse the cached one
        # with Pallas strategies baked in
        key = _query_key(q, ds) + ("adaptive-presence", pallas_ok)
        from ..obs import prof

        cached = self._query_fn_cache.get(key)
        if cached is not None:
            prof.note_program_cache("adaptive-presence", hit=True)
            return cached
        prof.note_program_cache("adaptive-presence", hit=False)

        # same inner convention as the sparse tier: one-hot kernels on a
        # TPU backend (within the one-hot domain cap), scatter everywhere
        # else (a card-sized scatter state is cache-resident on CPU; the
        # static auto-resolver would pick the dense one-hot there —
        # measured 55 s for one SF10 presence pass vs ~0.5 s on scatter)
        from ..ops.groupby import SCATTER_CUTOVER

        strategies = [
            "pallas"
            if pallas_ok and d.cardinality <= SCATTER_CUTOVER
            else "segment"
            for d in lowering.dims
        ]

        @jax.jit
        def seg_fn(cols_list):
            counts = None
            for cols in cols_list:
                cols = lowering.add_virtual(dict(cols))
                mask = lowering.row_mask(cols)
                ones = mask.astype(jnp.float32)[:, None]
                zero_mm = jnp.zeros((ones.shape[0], 0), jnp.float32)
                zero_mmm = jnp.zeros((ones.shape[0], 0), jnp.bool_)
                per = []
                for d, strat in zip(lowering.dims, strategies):
                    s, _, _ = partial_aggregate(
                        d.codes_fn(cols), mask, ones, zero_mm, zero_mmm,
                        num_groups=d.cardinality, num_min=0, num_max=0,
                        strategy=strat,
                    )
                    per.append(s[:, 0])
                counts = (
                    per
                    if counts is None
                    else [a + b for a, b in zip(counts, per)]
                )
            return counts

        self._query_fn_cache[key] = seg_fn
        return seg_fn

    def _adaptive_kept_codes(
        self, q, ds, lowering: GroupByLowering, segs
    ) -> Optional[List[np.ndarray]]:
        """Phase A: measure (or recall) per-dim present code sets.  Returns
        None when compaction should be declined for this query."""
        # The memo keys segment-set-independently (lowering.memo_key) so
        # continuous streamed ingest neither forgets query shapes nor
        # leaks one entry per published delta — but a MEASURED kept set
        # is only valid for the exact segment set it scanned (a fresh
        # delta may contain codes the old scan never saw; reusing the
        # stale set would silently drop those rows), so measured entries
        # carry their segment signature and miss-and-REPLACE when it
        # moved.  Dictionary-DERIVED kept sets are supersets by
        # construction and stay valid across appends (only a dictionary
        # extension, which changes the memo key, retires them).
        qkey = memo_key(q, ds)
        seg_sig = tuple(s.uid for s in segs)
        entry = self._adaptive_kept.get(qkey)
        kept = None
        if entry is not None:
            if entry[0] == "derived":
                kept = entry[1]
            elif entry[1] == seg_sig:
                kept = entry[2]
        if kept is None:
            # dictionary-derived shortcut: when the filter itself pins
            # every grouping dim, phase A needs NO device pass at all —
            # O(cardinality) host work over the dictionaries replaces the
            # full presence scan (and its dispatch round-trip)
            kept = filter_derived_kept(q, lowering, ds)
            if kept is not None:
                self._adaptive_kept[qkey] = ("derived", kept)
        if kept is None:
            need = self._presence_columns(q, lowering, ds)

            def run_presence():
                from ..obs import SPAN_ADAPTIVE_PROBE, span
                from ..resilience import checkpoint

                seg_fn = self._presence_program(q, ds, lowering)
                counts = None
                for bi, batch in enumerate(
                    self._segment_batches(segs, need)
                ):
                    # phase A dispatches the full segment scope too: a
                    # deadlined query cancels between presence batches
                    # (checkpoint-coverage/GL901)
                    checkpoint("adaptive.presence_loop")
                    with span(SPAN_ADAPTIVE_PROBE, batch=bi):
                        import time as _time

                        from ..obs import prof

                        cols_list = [
                            self._cols_for_segment(seg, ds, need)
                            for seg in batch
                        ]
                        t_call = _time.perf_counter()
                        out = seg_fn(cols_list)
                        out = prof.dispatch_sync(out, t_call)
                    counts = (
                        out
                        if counts is None
                        else [a + b for a, b in zip(counts, out)]
                    )
                return counts

            # fault-injection site: phase A dispatches the presence
            # program to the device (phase B goes through the engine's
            # _call_segment_program, which has its own site).  OUTSIDE
            # the try below: an injected transient must decline this
            # dispatch (caller falls through to sparse/dense, whose
            # sites feed the retry/breaker machinery) — not be misread
            # as a Mosaic failure that memo-declines the query shape or
            # pins _pallas_broken for the engine's lifetime.
            fire("device_dispatch")
            try:
                counts = run_presence()
            except DeadlineExceeded:
                # a deadline is a property of THIS request, not of the
                # query shape: memo-declining would pin adaptive off for
                # every later (unbudgeted) run of the same query
                raise
            except Exception:
                # mirror _call_segment_program: a Mosaic failure of a
                # Pallas presence kernel downgrades to the XLA strategies
                # once; anything else (or a second failure) memo-declines
                # so the broken pass is not re-dispatched every execution
                from ..ops.pallas_groupby import pallas_available

                if self._pallas_broken or not pallas_available():
                    self._adaptive_declined.add(qkey)
                    raise
                self._pallas_broken = True
                try:
                    counts = run_presence()
                except DeadlineExceeded:
                    raise
                except Exception:
                    self._adaptive_declined.add(qkey)
                    raise
            kept = [
                np.nonzero(np.asarray(c) > 0)[0].astype(np.int32)
                for c in counts
            ]
            self._adaptive_kept[qkey] = ("measured", seg_sig, kept)
        Gc = 1
        for kd in kept:
            Gc *= len(kd)
        if Gc > ADAPTIVE_MAX_COMPACT_GROUPS or (
            Gc > ADAPTIVE_MIN_SHRINK * lowering.num_groups
        ):
            log.info(
                "adaptive compaction declined: G'=%d of G=%d",
                Gc, lowering.num_groups,
            )
            self._adaptive_declined.add(qkey)
            self._adaptive_kept.pop(qkey, None)
            return None
        return kept

    def _dispatch_groupby_adaptive(
        self, q: Q.GroupByQuery, ds: DataSource, lowering: GroupByLowering
    ):
        """Adaptive-compaction attempt.  Returns None when declining at
        dispatch time (caller falls through to the sparse/scatter paths in
        the same phase), else resolve() -> (df, "ok"|"error")."""
        segs = self._segments_in_scope(q, ds)
        if not segs:
            return None
        try:
            kept = self._adaptive_kept_codes(q, ds, lowering, segs)
        except DeadlineExceeded as err:
            # the deadline expired during phase A: there are no aggregate
            # partials yet (presence counts are not an answer).  With a
            # partial collector armed, trigger it and decline — the dense
            # path then drains immediately to the well-formed
            # zero-coverage answer; without one, expiry stays an error.
            pc = current_partial()
            if pc is None:
                raise
            pc.trigger(err.site or "adaptive.presence_loop")
            return None
        except Exception:
            log.warning("adaptive presence pass failed", exc_info=True)
            return None
        if kept is None:
            return None
        if any(len(kd) == 0 for kd in kept):
            # some grouping dim has NO present code under the filter: the
            # exact result is the empty grouped frame.  The presence pass
            # scanned the full scope to prove it, so account the pass as
            # fully seen — a deadline trigger later in the lifecycle must
            # stamp coverage 1.0, not flag the exact answer partial.
            pc = current_partial()
            if pc is not None:
                from .engine import _row_counts

                pc.begin_pass()
                pc.add_scope(len(segs), *_row_counts(segs))
                pc.add_seen(len(segs), *_row_counts(segs))
            la = lowering.la
            sums, mins, maxs, sketch_states = empty_partials(la, 0)
            df = finalize_groupby(
                q, lowering.dims, la,
                np.asarray(sums), np.asarray(mins), np.asarray(maxs),
                {k: np.asarray(v) for k, v in sketch_states.items()},
            )
            return lambda: (df, "ok")

        clow = compacted_lowering(lowering, kept)
        cards = tuple(d.cardinality for d in clow.dims)
        # the compact program's kernel comes from the CALIBRATED cost
        # model at the compacted cardinality — the engine's static "auto"
        # resolver picks the dense one-hot below the cutover, which on a
        # CPU backend is the wrong side of a ~200x inversion (measured:
        # a 60M-row phase B at G'=600 ran 49 s dense vs sub-second
        # scatter; on TPU the same choice lands on Pallas/dense)
        strat = self._adaptive_main_strategy(ds, clow.num_groups)
        try:
            state = self._partials_for_query(
                q, ds, lowering=clow, key_extra=("adaptive",) + cards,
                strategy_override=strat,
            )
        except DeadlineExceeded:
            raise  # partial-capable loops absorb expiry; a raise is real
        except Exception:
            log.warning("adaptive compact dispatch failed", exc_info=True)
            return None

        def resolve():
            try:
                dims, la, G, sums, mins, maxs, sketch_states = state
                sums, mins, maxs, sketch_states = jax.device_get(
                    (sums, mins, maxs, sketch_states)
                )
                df = finalize_groupby(
                    q, dims, la,
                    np.asarray(sums), np.asarray(mins), np.asarray(maxs),
                    {k: np.asarray(v) for k, v in sketch_states.items()},
                )
                return df, "ok"
            except DeadlineExceeded:
                raise  # never demote a deadline to an adaptive decline
            except Exception:
                log.warning("adaptive resolve failed", exc_info=True)
                return None, "error"

        return resolve
