"""Query-lifecycle resilience: deadlines, admission control, circuit
breaker, error classification, and deterministic fault injection.

Reference parity: the reference's one robustness stance is that a failed
*rewrite* is never an error — the query silently runs as a vanilla Spark
plan (SURVEY.md §3.2, exec/fallback.py here).  This module extends that
stance to *runtime* failure, which the reference delegated to Spark task
re-execution and a human watching the Druid cluster:

  * **Deadlines** — every query may carry a wall-clock budget
    (`SessionConfig.query_timeout_ms`, or Druid-native `context.timeout`
    on the wire).  Long loops (segment batches, stream chunks, the
    fallback interpreter) call `checkpoint(site)` so cancellation is
    cooperative and prompt rather than best-effort at the end.
  * **Admission control** — the serving layer holds a bounded slot pool;
    a full pool rejects with 503 + Retry-After instead of piling threads
    onto `ThreadingHTTPServer` until the process wedges.
  * **Circuit breaker** — consecutive *transient* device failures trip
    the breaker; while open, queries route straight to the host-fallback
    executor (degraded but correct — the same "never an error" stance),
    and after a cooldown a half-open probe decides recovery.
  * **Error taxonomy** — `classify_error` splits failures into
    `transient` (retry/degrade: device blips, injected faults, OS I/O),
    `static` (surface immediately: planning/validation/logic), and
    `deadline` (stop now, never retry slower).
  * **Fault injection** — `FaultInjector` arms named sites
    (`device_dispatch`, `h2d`, `compile`, `fallback_decode`) to raise,
    delay, or truncate deterministically, from tests or the
    `SDOL_FAULTS` env flag, so every degradation path above is
    exercisable on CPU in CI.

Every decision is observable: `QueryMetrics` gains `retries`,
`degraded`, `deadline_exceeded`, `circuit_state`; `/status/health`
reports breaker state and slots in use.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from .obs import SPAN_RETRY, get_registry, span
from .utils.log import get_logger

log = get_logger("resilience")


def _count(name: str, help_text: str, labels=(), **labelvals) -> None:
    """Publish one event into the process metrics registry (obs/)."""
    fam = get_registry().counter(name, help_text, labels=labels)
    (fam.labels(**labelvals) if labels else fam).inc()


# ---------------------------------------------------------------------------
# Error taxonomy
# ---------------------------------------------------------------------------


class DeadlineExceeded(Exception):
    """A query ran past its deadline.  Deliberately NOT a RuntimeError:
    the engine's transient-retry path catches RuntimeError, and a timed-out
    query must never be retried (it would only time out slower)."""

    def __init__(self, site: str, timeout_ms: float):
        super().__init__(
            f"query deadline of {timeout_ms:.0f}ms exceeded at {site!r}"
        )
        self.site = site
        self.timeout_ms = timeout_ms


class InjectedDeadline(DeadlineExceeded):
    """Deterministic deadline expiry raised by an armed fault site.  A
    DeadlineExceeded subclass so it walks the exact deadline path wall-clock
    expiry walks (never retried, partial-collection trigger, 504 taxonomy)
    — but constructible with the fire()-style single-message signature the
    injector uses, and armable with `skip=K` so a test can pin expiry to
    the K-th checkpoint of a scan instead of racing a real clock."""

    def __init__(self, msg: str):
        Exception.__init__(self, msg)
        self.site = msg
        self.timeout_ms = 0.0


class InjectedFault(RuntimeError):
    """Deterministic fault raised by an armed FaultInjector site.  A
    RuntimeError subclass on purpose: injected device faults must walk the
    EXACT transient-failure path a real device blip walks (engine retry,
    breaker accounting, host-fallback degradation)."""


class CircuitOpenError(RuntimeError):
    """Device execution refused because the circuit breaker is open and no
    host fallback is available to degrade to."""


def classify_error(exc: BaseException) -> str:
    """"transient" | "static" | "deadline".

    transient -> safe to retry / degrade (queries are read-only, so a
    re-dispatch is always idempotent); static -> a property of the query
    or the code, retrying re-pays the same failure; deadline -> stop now.
    NotImplementedError is a RuntimeError subclass but describes a static
    capability gap; unknown exception types default to static (no retry
    loops around logic bugs)."""
    if isinstance(exc, DeadlineExceeded):
        return "deadline"
    if isinstance(exc, NotImplementedError):
        return "static"
    if isinstance(exc, (RuntimeError, OSError, ConnectionError)):
        return "transient"
    return "static"


# ---------------------------------------------------------------------------
# Deadlines (cooperative cancellation)
# ---------------------------------------------------------------------------


class Deadline:
    __slots__ = ("expires_at", "timeout_ms")

    def __init__(self, timeout_ms: float):
        self.timeout_ms = float(timeout_ms)
        self.expires_at = time.monotonic() + self.timeout_ms / 1e3

    def remaining_ms(self) -> float:
        return (self.expires_at - time.monotonic()) * 1e3

    def expired(self) -> bool:
        return time.monotonic() >= self.expires_at

    def check(self, site: str) -> None:
        if self.expired():
            raise DeadlineExceeded(site, self.timeout_ms)


_active_deadline: contextvars.ContextVar[Optional[Deadline]] = (
    contextvars.ContextVar("sdol_active_deadline", default=None)
)


def current_deadline() -> Optional[Deadline]:
    return _active_deadline.get()


@contextlib.contextmanager
def deadline_scope(timeout_ms: Optional[float]):
    """Arm a deadline for the enclosed block.  No-op when `timeout_ms` is
    falsy OR a deadline is already active (the outermost scope wins: a
    server-set per-request `context.timeout` must not be extended by the
    session default inside `ctx.sql`)."""
    if not timeout_ms or timeout_ms <= 0 or _active_deadline.get() is not None:
        yield current_deadline()
        return
    token = _active_deadline.set(Deadline(timeout_ms))
    try:
        yield _active_deadline.get()
    finally:
        _active_deadline.reset(token)


def checkpoint(site: str) -> None:
    """Cooperative cancellation + fault-injection point.  Called from the
    engine segment loop, the streaming chunk loop, and the fallback
    interpreter; costs one contextvar read when nothing is armed.

    Every checkpoint is ALSO a named fault site (`fire(site)`): arming
    e.g. `engine.segment_loop` with `error_type=InjectedDeadline` and
    `skip=K` makes "the deadline expired at exactly the K-th batch"
    a deterministic, clock-free test fixture.

    When a partial-result collector is armed and ALREADY TRIGGERED, the
    deadline check is suppressed: the query is draining — merging the
    partials it has and finalizing a best-effort answer — and re-raising
    at every remaining checkpoint would turn the safe partial back into
    an error."""
    fire(site)
    d = _active_deadline.get()
    if d is None:
        return
    pc = _active_partial.get()
    if pc is not None and pc.triggered:
        return
    d.check(site)


def checkpoint_partial(site: str) -> bool:
    """Deadline checkpoint for executor loops that can answer with the
    partials accumulated so far (Partial Partial Aggregates: every
    aggregate state in the engine is mergeable, so "the rows seen so
    far" is a safe answer).  Returns True when the loop must STOP
    dispatching and merge what it has — either because the deadline just
    expired here (the collector is triggered, and all later checkpoints
    become no-ops so the drain completes), or because an earlier site
    already triggered it.  Without an armed collector this is exactly
    `checkpoint` (expiry raises)."""
    pc = current_partial()  # None when the scope is an explicit opt-out
    if pc is not None and pc.triggered:
        return True
    try:
        checkpoint(site)
    except DeadlineExceeded as err:
        if pc is None:
            raise
        pc.trigger(err.site or site)
        return True
    return False


# ---------------------------------------------------------------------------
# Partial-result collection (deadline-bounded best-effort answers)
# ---------------------------------------------------------------------------


class PartialCollector:
    """Per-query accounting for deadline-bounded partial answers.

    Armed by `partial_scope` around a query; executors report the scope
    they intend to scan (`begin_pass` + `add_scope`) and what they
    actually merged (`add_seen`, per dispatched batch / decoded segment,
    with the delta-vs-historical row split).  When a deadline expires at
    a `checkpoint_partial` site the collector is `trigger`ed: the
    executor stops dispatching, every later `checkpoint` becomes a no-op
    (the drain must finish), and the merged partials flow through the
    normal finalize path stamped with a coverage fraction.

    Coverage is rows_seen / rows_total of the current pass (segments as
    the fallback denominator; None when no scope was ever declared, e.g.
    an unbounded stream).  A DECLARED zero-row scope is different from
    an undeclared one: pruning every segment (or a presence pass proving
    no group survives the filter) means the empty answer IS the complete
    answer, so coverage is 1.0 and a later trigger must not flag it
    partial.  `is_partial` is False when the trigger fired only after
    every batch had already been dispatched — the drained answer is then
    complete and must not be flagged down."""

    __slots__ = (
        "enabled", "triggered_site", "in_fallback", "scope_declared",
        "segments_total", "segments_seen",
        "rows_total", "rows_seen",
        "delta_rows_total", "delta_rows_seen",
        "collect_sets", "set_label", "set_records", "_pass_label",
        "_lock",
    )

    def __init__(self, enabled: bool = True):
        # a DISABLED collector still occupies the scope: an explicit
        # opt-out at the server boundary (context.partialResults=false)
        # must not be silently re-armed by ctx.sql's session default
        self.enabled = enabled
        self.triggered_site: Optional[str] = None
        # set while the host-fallback interpreter owns the pass: its
        # device-assist subtrees run engine executors whose begin_pass
        # would otherwise zero the interpreter's multi-table accounting
        self.in_fallback = False
        self.scope_declared = False
        self.segments_total = 0
        self.segments_seen = 0
        self.rows_total = 0
        self.rows_seen = 0
        self.delta_rows_total = 0
        self.delta_rows_seen = 0
        # per-grouping-set coverage attribution (ROADMAP 3(c)): a CUBE
        # expansion runs one pass per grouping set; with collect_sets
        # armed, begin_pass ARCHIVES the superseded pass (labeled by
        # set_label) instead of erasing it, and coverage/is_partial/
        # to_dict aggregate across every archived set plus the live one
        # — the expansion's coverage describes ALL sets, not whichever
        # subquery ran last
        self.collect_sets = False
        self.set_label: Optional[str] = None
        # the label the LIVE pass started under: the expansion updates
        # set_label before each sub-query's begin_pass, so archiving
        # must use the label captured at the pass's START, not the one
        # already pointing at the next set
        self._pass_label: Optional[str] = None
        self.set_records: list = []
        self._lock = threading.Lock()

    @property
    def triggered(self) -> bool:
        return self.triggered_site is not None

    def trigger(self, site: str) -> None:
        with self._lock:
            if self.triggered_site is None:
                self.triggered_site = site
        log.warning(
            "deadline expired at %r; answering with the partials merged "
            "so far (best-effort)", site,
        )

    def begin_pass(self) -> None:
        """A fresh full scan of the query's scope supersedes earlier
        accounting (the sparse tier declining into a dense rescan must
        not double-count).  No-op inside a fallback-owned pass: the
        interpreter accumulates across its tables and assist subtrees.
        With `collect_sets` armed (a grouping-set expansion) the
        superseded pass is ARCHIVED under its set label first — a repeat
        pass for the SAME label (sparse decline -> dense rescan)
        replaces its record rather than double-counting."""
        if self.in_fallback:
            return
        with self._lock:
            if self.collect_sets and self.scope_declared:
                self._archive_pass_locked()
            self.scope_declared = False
            self.segments_total = self.segments_seen = 0
            self.rows_total = self.rows_seen = 0
            self.delta_rows_total = self.delta_rows_seen = 0
            self._pass_label = self.set_label

    def _archive_pass_locked(self) -> None:
        cov = None
        if self.rows_total > 0:
            cov = min(1.0, self.rows_seen / self.rows_total)
        elif self.segments_total > 0:
            cov = min(1.0, self.segments_seen / self.segments_total)
        elif self.scope_declared:
            cov = 1.0
        rec = {
            "set": self._pass_label,
            "coverage": round(cov, 6) if cov is not None else None,
            "segments_seen": self.segments_seen,
            "segments_total": self.segments_total,
            "rows_seen": self.rows_seen,
            "rows_total": self.rows_total,
            "delta_rows_seen": self.delta_rows_seen,
            "delta_rows_total": self.delta_rows_total,
        }
        # a same-label rescan SUPERSEDES its earlier record wherever it
        # sits (labels are unique per set): adjacent for a sparse
        # decline -> dense rescan, non-adjacent when a batch-dispatch
        # failure re-runs one set serially after later sets archived —
        # appending would double-count the set's rows in the aggregate
        for i, old in enumerate(self.set_records):
            if old.get("set") == rec["set"]:
                self.set_records[i] = rec
                return
        self.set_records.append(rec)

    def arm_set_collection(self) -> None:
        """Arm per-grouping-set archiving (`collect_sets`) under the
        collector's lock.  The expansion code used to flip the flag
        directly through an untyped local — invisible to the static
        lock-ownership inference AND an off-lock write to a `_lock`-owned
        field, which the runtime witness (tools/graftsan) flags."""
        with self._lock:
            self.collect_sets = True

    def finish_sets(self) -> list:
        """Close grouping-set collection: archive the live pass and zero
        the live counters so the aggregate (coverage / to_dict) reads
        purely from the per-set records.  Returns the records."""
        with self._lock:
            if self.scope_declared:
                self._archive_pass_locked()
            self.collect_sets = False
            self.scope_declared = False
            self.segments_total = self.segments_seen = 0
            self.rows_total = self.rows_seen = 0
            self.delta_rows_total = self.delta_rows_seen = 0
            return list(self.set_records)

    def _agg_locked(self):
        """(segments_total, segments_seen, rows_total, rows_seen,
        delta_total, delta_seen, any_scope) aggregated across archived
        set records plus the live pass."""
        st, ss = self.segments_total, self.segments_seen
        rt, rs = self.rows_total, self.rows_seen
        dt, dsn = self.delta_rows_total, self.delta_rows_seen
        declared = self.scope_declared
        for r in self.set_records:
            st += r["segments_total"]
            ss += r["segments_seen"]
            rt += r["rows_total"]
            rs += r["rows_seen"]
            dt += r["delta_rows_total"]
            dsn += r["delta_rows_seen"]
            declared = True
        return st, ss, rt, rs, dt, dsn, declared

    def reset_for_drain(self) -> None:
        """Zero the accounting for a drain-RERUN (the fallback's
        interpreter-level expiry re-executes the plan over the warm
        decode caches).  The rerun's own add_scope/add_seen then describe
        exactly what the final answer saw — without this, the aborted
        pass's counters double the denominator and claim rows the rerun
        never served (an empty rerun frame would ship stamped
        coverage≈0.5).  Unlike begin_pass this applies INSIDE a
        fallback-owned pass; only the drain handler may call it."""
        with self._lock:
            self.scope_declared = False
            self.segments_total = self.segments_seen = 0
            self.rows_total = self.rows_seen = 0
            self.delta_rows_total = self.delta_rows_seen = 0

    def add_scope(self, segments: int, rows: int, delta_rows: int = 0):
        with self._lock:
            self.scope_declared = True
            self.segments_total += int(segments)
            self.rows_total += int(rows)
            self.delta_rows_total += int(delta_rows)

    def add_seen(self, segments: int, rows: int, delta_rows: int = 0):
        with self._lock:
            self.segments_seen += int(segments)
            self.rows_seen += int(rows)
            self.delta_rows_seen += int(delta_rows)

    def coverage(self) -> Optional[float]:
        with self._lock:
            st, ss, rt, rs, _dt, _ds, declared = self._agg_locked()
            if rt > 0:
                return min(1.0, rs / rt)
            if st > 0:
                return min(1.0, ss / st)
            if declared:
                return 1.0  # declared empty scope: nothing to scan
            return None

    @property
    def is_partial(self) -> bool:
        """Triggered AND genuinely incomplete.  A trigger observed after
        the last batch was dispatched drains to the exact answer."""
        if not self.triggered:
            return False
        with self._lock:
            st, ss, rt, rs, _dt, _ds, declared = self._agg_locked()
            if rt > 0:
                return rs < rt
            if st > 0:
                return ss < st
            if declared:
                return False  # declared empty scope: complete by vacuity
            return True  # unknown denominator: claim nothing

    def to_dict(self) -> dict:
        cov = self.coverage()
        with self._lock:
            st, ss, rt, rs, dt, dsn, _declared = self._agg_locked()
            d = {
                "partial": True,
                "coverage": round(cov, 6) if cov is not None else None,
                "site": self.triggered_site,
                "segments_seen": ss,
                "segments_total": st,
                "rows_seen": rs,
                "rows_total": rt,
                "delta_rows_seen": dsn,
                "delta_rows_total": dt,
            }
            if self.set_records:
                # per-grouping-set attribution: which set the deadline
                # actually truncated, not just the blended fraction
                d["sets"] = [dict(r) for r in self.set_records]
            return d


_active_partial: contextvars.ContextVar[Optional[PartialCollector]] = (
    contextvars.ContextVar("sdol_active_partial", default=None)
)


def current_partial() -> Optional[PartialCollector]:
    pc = _active_partial.get()
    return pc if pc is not None and pc.enabled else None


@contextlib.contextmanager
def partial_scope(enabled: bool = True):
    """Arm a partial-result collector for the enclosed query.  Outermost
    scope wins (same contract as `deadline_scope`: the server's wire
    scope must not be replaced by ctx.sql's inner one).  `enabled=False`
    still OCCUPIES the scope with a disabled collector — an explicit
    opt-out must hold against inner session defaults — and deadline
    expiry stays a hard error."""
    if _active_partial.get() is not None:
        yield current_partial()
        return
    token = _active_partial.set(PartialCollector(enabled=enabled))
    try:
        yield current_partial()
    finally:
        _active_partial.reset(token)


def prefetch_pressed() -> bool:
    """True when SPECULATIVE work must stop issuing: the active deadline
    has expired, or a partial drain is already triggered.  Unlike
    `checkpoint`, this never raises — the transfer pipeline (exec/
    pipeline.py) consults it before issuing each prefetch so a pending
    prefetch cancels cleanly on expiry, while the owning executor loop's
    own checkpoint stays the single place the expiry SURFACES."""
    pc = _active_partial.get()
    if pc is not None and pc.enabled and pc.triggered:
        return True
    d = _active_deadline.get()
    return d is not None and d.expired()


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------

# the named instrumentation points the runtime actually fires
SITES = (
    "device_dispatch",
    "h2d",
    "compile",
    "fallback_decode",
    # durable storage tier (ISSUE 13): every stage of the WAL/publish
    # pipeline is a kill point the crash-safety harness arms — the
    # ordering contract (journal -> fsync -> publish -> snapshot rename
    # -> retire -> truncate) is PROVEN by killing at each one
    "wal.journal_write",  # before any journal byte lands
    "wal.pre_fsync",  # bytes written, not yet durable (torn-tail zone)
    "wal.post_fsync_pre_publish",  # durable but unpublished (un-acked)
    "wal.replay_record",  # between replayed records at boot
    "persist.snapshot_rename",  # before the snapshot.json commit rename
    "compact.retire",  # before retired segment files are deleted
    "storage.replay_batch",  # before a replayed batch is re-applied
    # cluster tier (ISSUE 16): process-level chaos sites.  The broker
    # fires the first two in its scatter/gather loops (deadline +
    # injection checkpoints); the per-RPC sites simulate the network
    # and remote-process failure modes the chaos matrix proves against.
    "cluster.scatter",  # broker: before each replica fetch attempt
    "cluster.gather",  # broker: between merged replica responses
    "cluster.rpc",  # broker: inside one RPC (error=timeout, delay=slow)
    "cluster.torn_response",  # broker: partial mode truncates the body
    "cluster.historical_kill",  # historical: dies serving a partial
)


class _FaultSpec:
    __slots__ = ("mode", "times", "delay_ms", "fraction", "error_type",
                 "skip")

    def __init__(self, mode, times=None, delay_ms=0.0, fraction=1.0,
                 error_type=InjectedFault, skip=0):
        if mode not in ("error", "delay", "partial"):
            raise ValueError(f"unknown fault mode {mode!r}")
        self.mode = mode
        self.times = times  # None = every call; else fire for the first N
        self.delay_ms = float(delay_ms)
        self.fraction = float(fraction)
        self.error_type = error_type
        # pass through the first `skip` calls untouched before firing:
        # with every checkpoint being a fault site, `skip=K, times=1,
        # error_type=InjectedDeadline` pins "the deadline expired at the
        # K-th batch" deterministically — the deadline-sweep acceptance
        # runs on this, clock-free
        self.skip = int(skip)


class FaultInjector:
    """Deterministic fault injection at named sites.

    Modes:
      * `error`   — raise `error_type` (default InjectedFault, which walks
        the transient-failure machinery exactly like a real device error)
      * `delay`   — sleep `delay_ms` then continue (deadline tests)
      * `partial` — `partial_fraction(site)` returns `fraction`; the site
        truncates its output to that fraction (torn-result tests)

    `times=None` fires on every call; `times=N` fires for the first N
    calls then self-disarms — no randomness anywhere, so a test replays
    identically.  The `SDOL_FAULTS` env flag arms sites at import-use
    time: `SDOL_FAULTS="device_dispatch:error,h2d:delay:100"` with forms
    `site:error[:N]`, `site:delay:MS`, `site:partial:FRACTION`.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._sites: Dict[str, _FaultSpec] = {}
        self._fired: Dict[str, int] = {}

    # -- arming --------------------------------------------------------------

    def arm(self, site: str, mode: str = "error", times: Optional[int] = None,
            delay_ms: float = 0.0, fraction: float = 1.0,
            error_type=InjectedFault, skip: int = 0) -> None:
        with self._lock:
            self._sites[site] = _FaultSpec(
                mode, times, delay_ms, fraction, error_type, skip
            )
            self._fired.setdefault(site, 0)

    def disarm(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._sites.clear()
            else:
                self._sites.pop(site, None)

    def armed(self, site: str) -> bool:
        with self._lock:
            return site in self._sites

    def arm_from_env(self, env: Optional[str] = None) -> None:
        spec = env if env is not None else os.environ.get("SDOL_FAULTS", "")
        for part in filter(None, (p.strip() for p in spec.split(","))):
            bits = part.split(":")
            site, mode = bits[0], bits[1] if len(bits) > 1 else "error"
            arg = bits[2] if len(bits) > 2 else None
            if mode == "delay":
                self.arm(site, "delay", delay_ms=float(arg or 0))
            elif mode == "partial":
                self.arm(site, "partial", fraction=float(arg or 1.0))
            else:
                self.arm(site, "error",
                         times=int(arg) if arg is not None else None)

    # -- firing --------------------------------------------------------------

    def _take(self, site: str, partial: bool = False) -> Optional[_FaultSpec]:
        with self._lock:
            spec = self._sites.get(site)
            if spec is None or (spec.mode == "partial") != partial:
                return None
            if spec.skip > 0:
                spec.skip -= 1
                return None
            if spec.times is not None:
                if spec.times <= 0:
                    self._sites.pop(site, None)
                    return None
                spec.times -= 1
                if spec.times == 0:
                    self._sites.pop(site, None)
            self._fired[site] = self._fired.get(site, 0) + 1
            return spec

    def fire(self, site: str) -> None:
        """Raise/delay if `site` is armed; no-op otherwise.  `partial`
        specs never raise here — sites that support truncation ask
        `partial_fraction` instead.  The unarmed fast path is a single
        LOCK-FREE dict read: every `checkpoint(site)` in the per-segment
        hot loops calls this, and once the singleton exists a per-call
        lock would serialize all concurrent query threads on a path
        where (in production) nothing is ever armed.  The read is safe:
        arming happens-before the queries a test runs, and a missed
        just-armed site costs one skipped fire, never corruption."""
        if not self._sites:
            return
        spec = self._take(site)
        if spec is None:
            return
        if spec.mode == "delay":
            time.sleep(spec.delay_ms / 1e3)
            return
        err = spec.error_type(f"injected fault at site {site!r}")
        if isinstance(err, DeadlineExceeded):
            err.site = site  # partial-trigger sites must be clean names
        raise err

    def partial_fraction(self, site: str) -> Optional[float]:
        spec = self._take(site, partial=True)
        return None if spec is None else spec.fraction

    def state(self) -> dict:
        with self._lock:
            return {
                "armed": {
                    s: {"mode": sp.mode, "times": sp.times}
                    for s, sp in self._sites.items()
                },
                "fired": dict(self._fired),
            }


_injector: Optional[FaultInjector] = None
_injector_lock = threading.Lock()


def injector() -> FaultInjector:
    """Process-global injector (faults must hit every engine/context in the
    process, exactly like a real broken device would)."""
    global _injector
    if _injector is None:
        with _injector_lock:
            if _injector is None:
                inj = FaultInjector()
                if os.environ.get("SDOL_FAULTS"):
                    inj.arm_from_env()
                _injector = inj
    return _injector


def fire(site: str) -> None:
    """Module-level shorthand for the hot instrumentation points: skips
    even the singleton construction when nothing was ever armed."""
    if _sched_hook is not None:
        _sched_hook(site)
    inj = _injector
    if inj is None:
        if not os.environ.get("SDOL_FAULTS"):
            return
        inj = injector()
    inj.fire(site)


# ---------------------------------------------------------------------------
# Schedule-exploration hook (tools/graftsan)
# ---------------------------------------------------------------------------

# The runtime sanitizer's deterministic schedule explorer rides the
# SAME named sites the fault injector does: every `checkpoint`/`fire`
# call is a potential thread-interleaving point.  Unarmed cost is one
# module-global None check (the `_injector is None` idiom); the hook is
# only ever set by graftsan's installer, never by product code.
_sched_hook = None


def set_schedule_hook(hook) -> None:
    """Install (or clear, with None) the per-site scheduling hook."""
    global _sched_hook
    _sched_hook = hook


# ---------------------------------------------------------------------------
# Engine retry policy
# ---------------------------------------------------------------------------


def run_device_attempts(engine, run_once, evict, what: str = "device"):
    """Retry-with-backoff for one idempotent device execution, shared by
    the single-device and distributed engines (read-only queries make
    re-dispatch unconditionally safe).

    `engine` supplies the policy surface both engines share: `breaker`,
    `_retry_attempts`, `_retry_backoff_ms`, `last_metrics`.  `run_once`
    performs one attempt; `evict` drops whatever a failed dispatch may
    have poisoned.  Transient failures (classify_error) are counted on the
    breaker and retried under the budget with doubling backoff, failing
    FAST when the active deadline cannot afford the backoff plus another
    attempt; static errors and DeadlineExceeded propagate untouched."""
    attempts = max(1, int(engine._retry_attempts))
    for i in range(attempts):
        try:
            if i == 0:
                out = run_once()
            else:
                # re-attempts get their own span so the trace shows WHERE
                # a query's latency went when a transient failure struck
                with span(SPAN_RETRY, attempt=i, what=what):
                    out = run_once()
            if engine.breaker is not None:
                engine.breaker.record_success()
            if i and engine.last_metrics is not None:
                engine.last_metrics.retries = i
            return out
        except RuntimeError as err:
            if classify_error(err) != "transient":
                raise  # NotImplementedError et al.: a static gap
            if engine.breaker is not None:
                engine.breaker.record_failure()
            if engine.last_metrics is not None:
                engine.last_metrics.retries = i
                engine.last_metrics.error_class = type(err).__name__
            if i + 1 >= attempts:
                raise
            evict()
            backoff_ms = engine._retry_backoff_ms * (2.0 ** i)
            d = current_deadline()
            if d is not None and d.remaining_ms() <= backoff_ms:
                # the backoff alone would eat the remaining budget (and
                # the retry still has to execute after it): fail fast with
                # the real device error instead of sleeping into a
                # guaranteed DeadlineExceeded
                raise
            log.warning(
                "transient %s failure (%s: %s); evicting cached state and "
                "re-dispatching (attempt %d/%d, backoff %.0fms)",
                what, type(err).__name__, err, i + 2, attempts, backoff_ms,
            )
            if backoff_ms > 0:
                time.sleep(backoff_ms / 1e3)
    raise AssertionError("unreachable")  # loop returns or raises


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Three-state breaker over *transient device failures*.

    closed -> open after `failure_threshold` consecutive failures;
    open -> half_open once `cooldown_ms` elapses (allow() admits probes);
    half_open -> closed on a success, back to open on a failure.

    The breaker never blocks the engine itself — it informs the ROUTING
    layer (api._execute_with_resilience) which sends queries to the host
    fallback while open.  This generalizes the engine's ad-hoc
    pallas->dense pin into policy: transient errors are counted, static
    errors never touch the breaker."""

    def __init__(self, failure_threshold: int = 3, cooldown_ms: float = 2000.0,
                 clock: Callable[[], float] = time.monotonic,
                 backend: str = "device"):
        # which execution backend this breaker guards ("device" |
        # "mesh" | "fallback"): per-backend granularity so one sick path
        # never darkens the others — a broken mesh must not force
        # single-device queries onto the host interpreter, and a fallback
        # wedged on bad segments must fail fast instead of re-grinding
        self.backend = backend
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_ms = float(cooldown_ms)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._failures_total = 0
        self._successes_total = 0
        self._trips = 0
        # half-open admits ONE probe at a time: when the cooldown elapses
        # under queued traffic, releasing every waiter onto a possibly
        # still-broken device is exactly the pile-up the breaker exists to
        # prevent.  The lease goes stale after another cooldown interval
        # so a probe that dies without reporting cannot wedge the breaker.
        self._probe_started_at: Optional[float] = None

    @property
    def state(self) -> str:
        with self._lock:
            return self._peek_state()

    def _peek_state(self) -> str:
        # lock held: an elapsed cooldown shows as half_open even before a
        # probe arrives (health endpoints must not claim "open" forever)
        if self._state == "open" and (
            (self._clock() - self._opened_at) * 1e3 >= self.cooldown_ms
        ):
            return "half_open"
        return self._state

    def allow(self) -> bool:
        """May a query attempt the device path right now?  In half-open,
        only the single probe holder gets True; everyone else keeps
        degrading until the probe reports."""
        with self._lock:
            st = self._peek_state()
            if st == "open":
                return False
            if st == "half_open":
                self._state = "half_open"
                now = self._clock()
                if self._probe_started_at is not None and (
                    (now - self._probe_started_at) * 1e3 < self.cooldown_ms
                ):
                    return False  # a probe is already in flight
                self._probe_started_at = now
            return True

    def release_probe(self) -> None:
        """Hand back a probe lease WITHOUT reporting a verdict: the admitted
        query never actually touched the device (e.g. it was served from
        the result cache), so the next caller may probe immediately instead
        of waiting out the stale-lease interval."""
        with self._lock:
            self._probe_started_at = None

    def record_success(self) -> None:
        with self._lock:
            self._successes_total += 1
            self._consecutive_failures = 0
            self._probe_started_at = None
            if self._state != "closed":
                log.info(
                    "%s circuit breaker closing (probe succeeded)",
                    self.backend,
                )
                _count(
                    "sdol_breaker_transitions_total",
                    "circuit breaker state transitions",
                    labels=("to", "backend"),
                    to="closed", backend=self.backend,
                )
            self._state = "closed"

    def record_failure(self) -> None:
        with self._lock:
            self._failures_total += 1
            self._consecutive_failures += 1
            self._probe_started_at = None
            if self._state == "half_open":
                self._state = "open"
                self._opened_at = self._clock()
                self._trips += 1
                log.warning(
                    "%s circuit breaker re-opened (probe failed)",
                    self.backend,
                )
                _count(
                    "sdol_breaker_transitions_total",
                    "circuit breaker state transitions",
                    labels=("to", "backend"),
                    to="open", backend=self.backend,
                )
            elif (
                self._state == "closed"
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._state = "open"
                self._opened_at = self._clock()
                self._trips += 1
                log.warning(
                    "%s circuit breaker OPEN after %d consecutive "
                    "failures; traffic routes around it for %.0fms",
                    self.backend, self._consecutive_failures,
                    self.cooldown_ms,
                )
                _count(
                    "sdol_breaker_transitions_total",
                    "circuit breaker state transitions",
                    labels=("to", "backend"),
                    to="open", backend=self.backend,
                )

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "backend": self.backend,
                "state": self._peek_state(),
                "consecutive_failures": self._consecutive_failures,
                "failure_threshold": self.failure_threshold,
                "cooldown_ms": self.cooldown_ms,
                "failures_total": self._failures_total,
                "successes_total": self._successes_total,
                "trips": self._trips,
            }


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------


class AdmissionController:
    """Bounded slot pool with a queue-wait timeout.

    `acquire()` waits up to `queue_timeout_ms` for a slot and returns
    False on timeout — the serving layer turns that into 503 +
    Retry-After instead of letting ThreadingHTTPServer stack an unbounded
    thread pile-up behind a slow device.

    The Retry-After hint is computed from OBSERVED load, not the
    configured wait (ROADMAP resilience follow-up (d)): the pool tracks
    how many callers are currently queued (`queue_depth`) and an EWMA of
    how long admitted queries actually hold a slot; the hint is the
    estimated drain time of the queue ahead of a returning client.  An
    idle pool that rejected a burst therefore says "1s", while a pool
    behind a slow device scales the hint with real backlog instead of
    parroting `queue_timeout_ms`."""

    # EWMA weight of the newest hold-time observation: heavy enough to
    # track a phase change (cold compiles -> warm dispatch) within a few
    # queries, light enough that one outlier does not swing the hint
    _HOLD_EWMA_ALPHA = 0.2

    def __init__(self, max_concurrent: int = 8,
                 queue_timeout_ms: float = 2000.0,
                 clock: Callable[[], float] = time.monotonic,
                 lane: str = ""):
        self.max_concurrent = max(1, int(max_concurrent))
        self.queue_timeout_ms = float(queue_timeout_ms)
        # when set, this pool is one PRIORITY LANE of the serving core
        # (serve/lanes.py): decisions also publish under the
        # lane-labeled `sdol_lane_*` series so per-lane starvation is
        # visible on a dashboard
        self.lane = lane
        self._clock = clock
        self._sem = threading.BoundedSemaphore(self.max_concurrent)
        self._lock = threading.Lock()
        self._in_use = 0
        self.admitted_total = 0
        self.rejected_total = 0
        # observed-load tracking for the Retry-After estimate
        self._waiting = 0  # callers currently blocked in acquire()
        self._hold_ewma_ms: Optional[float] = None
        self._held_since: Dict[int, float] = {}  # thread id -> acquire time

    def acquire(self) -> bool:
        with self._lock:
            self._waiting += 1
        ok = self._sem.acquire(timeout=self.queue_timeout_ms / 1e3)
        with self._lock:
            self._waiting -= 1
            if ok:
                self._in_use += 1
                self.admitted_total += 1
                self._held_since[threading.get_ident()] = self._clock()
            else:
                self.rejected_total += 1
        _count(
            "sdol_admission_decisions_total",
            "admission-pool outcomes (admitted vs 503-rejected)",
            labels=("outcome",), outcome="admitted" if ok else "rejected",
        )
        if self.lane:
            _count(
                "sdol_lane_decisions_total",
                "per-lane admission outcomes (serve/lanes.py)",
                labels=("lane", "outcome"),
                lane=self.lane,
                outcome="admitted" if ok else "rejected",
            )
        return ok

    def release(self) -> None:
        with self._lock:
            self._in_use -= 1
            t0 = self._held_since.pop(threading.get_ident(), None)
            if t0 is not None:
                held_ms = (self._clock() - t0) * 1e3
                a = self._HOLD_EWMA_ALPHA
                self._hold_ewma_ms = (
                    held_ms
                    if self._hold_ewma_ms is None
                    else (1 - a) * self._hold_ewma_ms + a * held_ms
                )
        self._sem.release()

    @property
    def in_use(self) -> int:
        with self._lock:
            return self._in_use

    @property
    def queue_depth(self) -> int:
        """Callers currently blocked waiting for a slot."""
        with self._lock:
            return self._waiting

    def retry_after_s(self) -> int:
        """Client backoff hint from observed queue depth x observed hold
        time: a returning client waits for the queue ahead of it to drain
        (`depth / slots` hold intervals) plus its own slot tenure.  Before
        any hold time is observed the configured queue wait stands in.
        Clamped to [1s, 60s] — HTTP Retry-After is a coarse hint, and a
        wedged device must not tell dashboards to go away for an hour."""
        with self._lock:
            depth = self._waiting
            hold_ms = (
                self._hold_ewma_ms
                if self._hold_ewma_ms is not None
                else self.queue_timeout_ms
            )
        eta_ms = hold_ms * (depth / self.max_concurrent + 1.0)
        return max(1, min(60, int(-(-eta_ms // 1000))))

    def to_dict(self) -> dict:
        with self._lock:
            hold = self._hold_ewma_ms
            return {
                "slots_in_use": self._in_use,
                "slots_total": self.max_concurrent,
                "queue_depth": self._waiting,
                "hold_ewma_ms": round(hold, 3) if hold is not None else None,
                "queue_timeout_ms": self.queue_timeout_ms,
                "admitted_total": self.admitted_total,
                "rejected_total": self.rejected_total,
            }


# ---------------------------------------------------------------------------
# Per-context umbrella
# ---------------------------------------------------------------------------


# numeric encoding of breaker state for the `sdol_breaker_state` gauge
# (Prometheus wants numbers; alerts compare against these)
BREAKER_STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}

# the execution backends with independent breakers: a sick mesh must not
# darken single-device queries, and a fallback wedged on bad data must
# fail fast instead of re-grinding every degraded query through it
BREAKER_BACKENDS = ("device", "mesh", "fallback")


class ResilienceState:
    """One context's resilience machinery: the per-backend breakers the
    engines report to, the admission pool the server gates on, and
    failure counters the health endpoint surfaces.  The fault injector
    is process-global."""

    def __init__(self, config):
        self.breakers: Dict[str, CircuitBreaker] = {
            b: CircuitBreaker(
                failure_threshold=getattr(
                    config, "breaker_failure_threshold", 3
                ),
                cooldown_ms=getattr(config, "breaker_cooldown_ms", 2000.0),
                backend=b,
            )
            for b in BREAKER_BACKENDS
        }
        self.admission = AdmissionController(
            max_concurrent=getattr(config, "max_concurrent_queries", 8),
            queue_timeout_ms=getattr(
                config, "admission_queue_timeout_ms", 2000.0
            ),
        )
        # streamed-ingest slot pool (ISSUE 6): separate from the query
        # pool so appends and queries cannot starve each other; the
        # server's ingest route gates on it with the same 503+Retry-After
        # contract
        self.ingest_admission = AdmissionController(
            max_concurrent=getattr(config, "max_concurrent_ingests", 2),
            queue_timeout_ms=getattr(
                config, "ingest_queue_timeout_ms", 2000.0
            ),
        )
        # priority lanes (serve/lanes.py, ISSUE 8): separate slot pools
        # so cheap dashboard queries are never queued behind SF100-scale
        # scans — the server classifies each query and gates it on its
        # lane's pool, with per-lane Retry-After and sdol_lane_* metrics
        self.lanes: Dict[str, AdmissionController] = {
            "interactive": AdmissionController(
                max_concurrent=getattr(
                    config, "lane_interactive_slots", 6
                ),
                queue_timeout_ms=getattr(
                    config, "admission_queue_timeout_ms", 2000.0
                ),
                lane="interactive",
            ),
            "heavy": AdmissionController(
                max_concurrent=getattr(config, "lane_heavy_slots", 2),
                queue_timeout_ms=getattr(
                    config, "admission_queue_timeout_ms", 2000.0
                ),
                lane="heavy",
            ),
        }
        self._lock = threading.Lock()
        self.degraded_total = 0
        self.deadline_exceeded_total = 0
        self.server_errors_total = 0
        self.last_error: Optional[Dict[str, Any]] = None
        # live admission gauges: callback-read at scrape time, so the hot
        # acquire/release path pays nothing extra (obs/registry.py).  A
        # rebuilt context re-binds the callbacks and takes over the series.
        reg = get_registry()
        reg.gauge(
            "sdol_admission_queue_depth",
            "callers currently blocked waiting for an admission slot",
        ).set_function(lambda a=self.admission: a.queue_depth)
        reg.gauge(
            "sdol_admission_slots_in_use",
            "admission slots currently held by executing queries",
        ).set_function(lambda a=self.admission: a.in_use)
        # per-backend breaker state as a labeled callback gauge (scrape
        # reads the live breaker, the hot record paths pay nothing):
        # 0=closed 1=half_open 2=open
        state_gauge = reg.gauge(
            "sdol_breaker_state",
            "circuit breaker state by backend (0=closed 1=half_open "
            "2=open)",
            labels=("backend",),
        )
        for b, cb in self.breakers.items():
            state_gauge.labels(backend=b).set_function(
                lambda cb=cb: BREAKER_STATE_CODES.get(cb.state, -1)
            )
        # live per-lane gauges: callback-read at scrape time so the hot
        # acquire/release path pays nothing extra
        lane_depth = reg.gauge(
            "sdol_lane_queue_depth",
            "callers blocked waiting for a lane slot, by lane",
            labels=("lane",),
        )
        lane_in_use = reg.gauge(
            "sdol_lane_slots_in_use",
            "lane slots currently held by executing queries, by lane",
            labels=("lane",),
        )
        for name, pool in self.lanes.items():
            lane_depth.labels(lane=name).set_function(
                lambda p=pool: p.queue_depth
            )
            lane_in_use.labels(lane=name).set_function(
                lambda p=pool: p.in_use
            )

    @property
    def breaker(self) -> CircuitBreaker:
        """The single-device breaker — the pre-split name, kept so direct
        users (and the engines' default wiring) keep working."""
        return self.breakers["device"]

    def breaker_for(self, backend: str) -> CircuitBreaker:
        return self.breakers.get(backend, self.breakers["device"])

    def lane(self, name: str) -> AdmissionController:
        """The slot pool of one priority lane; unknown names gate on the
        interactive lane (never a KeyError on the serving path)."""
        return self.lanes.get(name, self.lanes["interactive"])

    def note_degraded(self) -> None:
        with self._lock:
            self.degraded_total += 1
        _count(
            "sdol_degraded_total",
            "queries answered DEGRADED on the host fallback",
        )

    def note_deadline_exceeded(self) -> None:
        with self._lock:
            self.deadline_exceeded_total += 1
        _count(
            "sdol_deadline_exceeded_total",
            "queries cancelled on their wall-clock deadline",
        )

    def note_server_error(self, exc: BaseException) -> None:
        with self._lock:
            self.server_errors_total += 1
            self.last_error = {
                "errorClass": type(exc).__name__,
                "classification": classify_error(exc),
            }
        _count(
            "sdol_server_errors_total",
            "unhandled query failures surfaced as structured 500s",
        )

    def health(self) -> dict:
        with self._lock:
            counters = {
                "degraded_total": self.degraded_total,
                "deadline_exceeded_total": self.deadline_exceeded_total,
                "server_errors_total": self.server_errors_total,
                "last_error": self.last_error,
            }
        return {
            "healthy": True,
            # "breaker" keeps naming the single-device breaker (the
            # pre-split contract load balancers already read); the full
            # per-backend matrix lives under "breakers"
            "breaker": self.breaker.to_dict(),
            "breakers": {
                b: cb.to_dict() for b, cb in self.breakers.items()
            },
            "admission": self.admission.to_dict(),
            "ingest_admission": self.ingest_admission.to_dict(),
            # per-lane pools (serve/lanes.py): a load balancer reads
            # which lane is saturated, not just "the server is busy"
            "lanes": {
                name: pool.to_dict()
                for name, pool in self.lanes.items()
            },
            "counters": counters,
            "faults": injector().state(),
        }
