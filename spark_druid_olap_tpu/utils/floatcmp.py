"""Exact f32-column vs f64-literal comparison semantics.

SQL promotes a float32 column compared against a decimal literal to double;
a TPU engine computing in f32 would silently flip rows whose f32 value
round-trips above/below the literal (e.g. f32(0.05) > 0.05 in f64, == in
f32).  Comparing correctly does NOT require f64 on device: for f32 x and f64
threshold c,

    x >  c  ⇔  x >= (smallest f32 strictly greater than c)
    x >= c  ⇔  x >= (smallest f32 >= c)
    x <  c  ⇔  x <= (largest  f32 strictly less  than c)
    x <= c  ⇔  x <= (largest  f32 <= c)
    x == c  ⇔  c exactly representable in f32 ∧ x == f32(c)

so each predicate compiles to a single f32 compare against a host-adjusted
threshold — exact double semantics at f32 speed (SURVEY.md §7 hard-part #2).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

_INF32 = np.float32(np.inf)


def _up(c: float) -> np.float32:
    """Smallest f32 strictly greater than f64 c."""
    f = np.float32(c)
    return f if float(f) > c else np.nextafter(f, _INF32)


def _ceil(c: float) -> np.float32:
    f = np.float32(c)
    return f if float(f) >= c else np.nextafter(f, _INF32)


def _down(c: float) -> np.float32:
    f = np.float32(c)
    return f if float(f) < c else np.nextafter(f, -_INF32)


def _floor(c: float) -> np.float32:
    f = np.float32(c)
    return f if float(f) <= c else np.nextafter(f, -_INF32)


def f32_compare_threshold(op: str, c: float) -> Tuple[str, np.float32]:
    """(new_op, f32 threshold) such that `x new_op threshold` in f32 equals
    `x op c` in f64, for all f32 x."""
    if op == ">":
        return ">=", _up(c)
    if op == ">=":
        return ">=", _ceil(c)
    if op == "<":
        return "<=", _down(c)
    if op == "<=":
        return "<=", _floor(c)
    raise ValueError(op)


def f32_representable(c: float) -> bool:
    return float(np.float32(c)) == float(c)


def f32_adjusted_compare(op: str, c: float):
    """Precompiled comparison `x op c` with f64-exact semantics for f32 x.
    Returns fn(x_f32_array) -> bool array; all threshold math happens here,
    once, at compile time (shared by plan/expr.py and ops/filters.py)."""
    import jax.numpy as jnp

    if op in ("==", "!="):
        if not f32_representable(c):
            if op == "==":
                return lambda x: jnp.zeros(x.shape, jnp.bool_)
            return lambda x: jnp.ones(x.shape, jnp.bool_)
        cv = np.float32(c)
        if op == "==":
            return lambda x: x == cv
        return lambda x: x != cv
    adj_op, thr = f32_compare_threshold(op, c)
    if adj_op == ">=":
        return lambda x: x >= thr
    return lambda x: x <= thr
