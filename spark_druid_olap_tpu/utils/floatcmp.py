"""Exact f32-column vs f64-literal comparison semantics.

SQL promotes a float32 column compared against a decimal literal to double;
a TPU engine computing in f32 would silently flip rows whose f32 value
round-trips above/below the literal (e.g. f32(0.05) > 0.05 in f64, == in
f32).  Comparing correctly does NOT require f64 on device: for f32 x and f64
threshold c,

    x >  c  ⇔  x >= (smallest f32 strictly greater than c)
    x >= c  ⇔  x >= (smallest f32 >= c)
    x <  c  ⇔  x <= (largest  f32 strictly less  than c)
    x <= c  ⇔  x <= (largest  f32 <= c)
    x == c  ⇔  c exactly representable in f32 ∧ x == f32(c)

so each predicate compiles to a single f32 compare against a host-adjusted
threshold — exact double semantics at f32 speed (SURVEY.md §7 hard-part #2).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

_INF32 = np.float32(np.inf)


def _up(c: float) -> np.float32:
    """Smallest f32 strictly greater than f64 c."""
    f = np.float32(c)
    return f if float(f) > c else np.nextafter(f, _INF32)


def _ceil(c: float) -> np.float32:
    f = np.float32(c)
    return f if float(f) >= c else np.nextafter(f, _INF32)


def _down(c: float) -> np.float32:
    f = np.float32(c)
    return f if float(f) < c else np.nextafter(f, -_INF32)


def _floor(c: float) -> np.float32:
    f = np.float32(c)
    return f if float(f) <= c else np.nextafter(f, -_INF32)


def f32_compare_threshold(op: str, c: float) -> Tuple[str, np.float32]:
    """(new_op, f32 threshold) such that `x new_op threshold` in f32 equals
    `x op c` in f64, for all f32 x."""
    if op == ">":
        return ">=", _up(c)
    if op == ">=":
        return ">=", _ceil(c)
    if op == "<":
        return "<=", _down(c)
    if op == "<=":
        return "<=", _floor(c)
    raise ValueError(op)


def f32_representable(c: float) -> bool:
    return float(np.float32(c)) == float(c)


def f32_adjusted_compare(op: str, c: float):
    """Precompiled comparison `x op c` with f64-exact semantics for f32 x.
    Returns fn(x_f32_array) -> bool array; all threshold math happens here,
    once, at compile time (shared by plan/expr.py and ops/filters.py)."""
    import jax.numpy as jnp

    if op in ("==", "!="):
        if not f32_representable(c):
            if op == "==":
                return lambda x: jnp.zeros(x.shape, jnp.bool_)
            return lambda x: jnp.ones(x.shape, jnp.bool_)
        cv = np.float32(c)
        if op == "==":
            return lambda x: x == cv
        return lambda x: x != cv
    adj_op, thr = f32_compare_threshold(op, c)
    if adj_op == ">=":
        return lambda x: x >= thr
    return lambda x: x <= thr


# f32-vs-f64 accumulation tolerance for comparing a device result against a
# float64 host result (the fallback interpreter / a pandas oracle): the
# engine's partial sums accumulate in f32, so equal queries agree to ~1e-5
# relative.  Shared by the fault-injection differential suite and ad-hoc
# parity checks so "identical within tolerance" means ONE thing repo-wide.
F32_ACCUM_RTOL = 2e-5


def frames_allclose(a, b, rtol: float = F32_ACCUM_RTOL, atol: float = 1e-8):
    """Order-insensitive DataFrame equivalence under f32-accumulation
    tolerance: same columns, same rows after sorting by every column
    (string columns compared exactly, numeric within rtol/atol, NaN==NaN).
    Returns (ok, message)."""
    import numpy as np
    import pandas as pd

    if list(a.columns) != list(b.columns):
        return False, f"columns differ: {list(a.columns)} vs {list(b.columns)}"
    if len(a) != len(b):
        return False, f"row counts differ: {len(a)} vs {len(b)}"
    if not len(a):
        return True, ""

    def _norm(df):
        out = df.copy()
        for c in out.columns:
            v = out[c]
            if v.dtype.kind in "fc":
                # quantize floats so near-ties sort identically both sides
                out[c] = np.round(v.astype(np.float64), 4)
        return out

    order = list(a.columns)
    ai = _norm(a).sort_values(order, kind="stable").index
    bi = _norm(b).sort_values(order, kind="stable").index
    a = a.loc[ai].reset_index(drop=True)
    b = b.loc[bi].reset_index(drop=True)
    for c in a.columns:
        av, bv = np.asarray(a[c]), np.asarray(b[c])
        if av.dtype.kind in "fc" or bv.dtype.kind in "fc":
            av = av.astype(np.float64)
            bv = bv.astype(np.float64)
            both_nan = np.isnan(av) & np.isnan(bv)
            close = np.isclose(av, bv, rtol=rtol, atol=atol) | both_nan
            if not close.all():
                i = int(np.argmin(close))
                return False, (
                    f"column {c!r} row {i}: {av[i]!r} != {bv[i]!r} "
                    f"(rtol={rtol})"
                )
        else:
            sa, sb = pd.Series(av), pd.Series(bv)
            na_a, na_b = np.asarray(sa.isna()), np.asarray(sb.isna())
            eq = (np.asarray(sa.eq(sb)) & ~na_a & ~na_b) | (na_a & na_b)
            if not eq.all():
                i = int(np.argmin(eq))
                return False, f"column {c!r} row {i}: {av[i]!r} != {bv[i]!r}"
    return True, ""
