"""Bounded caches for device residency and compiled programs.

Reference parity: the reference's metadata cache is explicitly clearable but
unbounded — acceptable for cluster metadata, not for HBM.  Round 1's engine
caches grew forever (VERDICT r1 weak #7: a long session over many datasources
OOMs HBM with no eviction).  Two policies:

* `ByteBudgetCache` — LRU keyed on array byte size; evicts least-recently-
  used entries until under budget.  Used for device column residency (HBM)
  and distributed row shards.  Dropping the reference frees the device
  buffer (JAX arrays are refcounted).
* `CountBudgetCache` — LRU on entry count, for compiled-program caches
  (each entry pins a traced executable).

Both are dict-shaped (getitem/setitem/contains/del/iteration) so call sites
read like the plain dicts they replace.  Operations are individually
thread-safe (an RLock guards the OrderedDict) because the HTTP server runs
queries from handler threads; hot paths must use the atomic `get`/`pop`
(check-then-`[]` from separate calls can race an eviction into KeyError).
Cross-operation atomicity (get-then-insert) is NOT provided — the caches
hold idempotent values (compiled programs, device columns keyed by content),
so a racing double-insert is waste, not corruption.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Iterator


class ByteBudgetCache:
    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self._od: "OrderedDict[Any, Any]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.RLock()
        # observability hook (obs/prof.py residency accounting): called
        # as on_evict(key, value) for every BUDGET-PRESSURE eviction —
        # explicit pops/clears are the caller's own bookkeeping.  Must
        # be cheap and non-raising (it runs under the cache lock).
        self.on_evict = None

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._od

    def __getitem__(self, key):
        with self._lock:
            v = self._od[key]
            self._od.move_to_end(key)
            return v

    def __setitem__(self, key, arr):
        with self._lock:
            if key in self._od:
                self._bytes -= int(self._od[key].nbytes)
                del self._od[key]
            self._od[key] = arr
            self._bytes += int(arr.nbytes)
            self._evict()

    def __delitem__(self, key):
        with self._lock:
            self._bytes -= int(self._od[key].nbytes)
            del self._od[key]

    def get(self, key, default=None):
        """Atomic hit-or-default (check-then-[] from another thread can race
        an eviction; this cannot)."""
        with self._lock:
            if key not in self._od:
                return default
            v = self._od[key]
            self._od.move_to_end(key)
            return v

    def pop(self, key, default=None):
        with self._lock:
            if key not in self._od:
                return default
            v = self._od[key]
            self._bytes -= int(v.nbytes)
            del self._od[key]
            return v

    def __iter__(self) -> Iterator:
        with self._lock:
            return iter(list(self._od))

    def __len__(self) -> int:
        return len(self._od)

    def values(self):
        with self._lock:
            return list(self._od.values())

    def clear(self):
        with self._lock:
            self._od.clear()
            self._bytes = 0

    def _evict(self):
        # never evict the just-inserted entry: a single over-budget column
        # must still execute (the caller holds a live reference anyway).
        # Takes the (reentrant) lock itself rather than assuming the caller
        # holds it — implicit caller-holds-the-lock contracts rot
        # (graftlint lock-discipline/GL501)
        with self._lock:
            while self._bytes > self.budget_bytes and len(self._od) > 1:
                key, old = self._od.popitem(last=False)
                self._bytes -= int(old.nbytes)
                if self.on_evict is not None:
                    try:
                        self.on_evict(key, old)
                    except Exception:  # fault-ok: hooks never break eviction
                        pass


class CountBudgetCache:
    def __init__(self, budget_entries: int):
        self.budget_entries = int(budget_entries)
        self._od: "OrderedDict[Any, Any]" = OrderedDict()
        self._lock = threading.RLock()

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._od

    def __getitem__(self, key):
        with self._lock:
            v = self._od[key]
            self._od.move_to_end(key)
            return v

    def __setitem__(self, key, v):
        with self._lock:
            if key in self._od:
                del self._od[key]
            self._od[key] = v
            while len(self._od) > self.budget_entries:
                self._od.popitem(last=False)

    def __delitem__(self, key):
        with self._lock:
            del self._od[key]

    def get(self, key, default=None):
        """Atomic hit-or-default (check-then-[] from another thread can race
        an eviction; this cannot)."""
        with self._lock:
            if key not in self._od:
                return default
            v = self._od[key]
            self._od.move_to_end(key)
            return v

    def pop(self, key, default=None):
        with self._lock:
            if key not in self._od:
                return default
            v = self._od[key]
            del self._od[key]
            return v

    def __iter__(self) -> Iterator:
        with self._lock:
            return iter(list(self._od))

    def __len__(self) -> int:
        return len(self._od)

    def values(self):
        with self._lock:
            return list(self._od.values())

    def resize(self, budget_entries: int):
        """Set a new budget and evict LRU entries down to it (a 0 budget
        keeps nothing)."""
        with self._lock:
            self.budget_entries = max(int(budget_entries), 0)
            while len(self._od) > self.budget_entries:
                self._od.popitem(last=False)

    def clear(self):
        with self._lock:
            self._od.clear()
