"""Vectorized 32-bit hashing on device — shared by HLL and theta sketches.

TPU note: JAX runs with x64 disabled (int64 lowers poorly on TPU), so sketch
hashing uses 32-bit murmur3-finalizer-style mixing on uint32 lanes.  Classic
HyperLogLog was specified on 32-bit hashes (Flajolet et al.) with a large-range
correction, so this is faithful; KMV/theta on 32-bit space carries ~n²/2³³
collision bias (≈1% at n=10⁸), documented in ops/theta.py.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def mix32(x: jnp.ndarray, seed: int = 0) -> jnp.ndarray:
    """murmur3 fmix32 over uint32 lanes; decorrelated by seed."""
    h = x.astype(jnp.uint32) ^ jnp.uint32(
        (seed * 0x9E3779B9 + 0x85EBCA6B) & 0xFFFFFFFF
    )
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def hash_column(col: jnp.ndarray, seed: int = 0) -> jnp.ndarray:
    """Hash one column (int codes / int64-ms times / float metrics) to uint32."""
    if col.dtype in (jnp.float32, jnp.bfloat16, jnp.float16):
        bits = jnp.asarray(col, jnp.float32).view(jnp.uint32)
        # normalize -0.0 / 0.0 so equal SQL values hash equal
        bits = jnp.where(col == 0, jnp.uint32(0), bits)
        return mix32(bits, seed)
    if col.dtype == jnp.int64:
        lo = (col & 0xFFFFFFFF).astype(jnp.uint32)
        hi = ((col >> 32) & 0xFFFFFFFF).astype(jnp.uint32)
        return mix32(lo ^ mix32(hi, seed + 1), seed)
    return mix32(col.astype(jnp.uint32), seed)


def combine_hashes(hashes) -> jnp.ndarray:
    """Order-dependent combine for multi-column (byRow) cardinality."""
    acc = hashes[0]
    for h in hashes[1:]:
        acc = mix32(acc * jnp.uint32(31) + h)
    return acc


def mix32_np(x: np.ndarray, seed: int = 0) -> np.ndarray:
    """Numpy twin of mix32 (for oracle tests)."""
    h = x.astype(np.uint32) ^ np.uint32((seed * 0x9E3779B9 + 0x85EBCA6B) & 0xFFFFFFFF)
    h = h ^ (h >> 16)
    h = (h * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
    h = h ^ (h >> 13)
    h = (h * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
    h = h ^ (h >> 16)
    return h
