"""Time granularity — bucket boundary computation (host-side).

Reference parity: Druid granularities ("second".."year", ISO periods) used by
Timeseries queries and time-bucketed GROUP BY dimensions (SURVEY.md §2
AggregateTransform row: "grouping exprs → DimensionSpec (incl. time-granularity
buckets)" `[U]`).  Boundaries are computed on host (tiny: one entry per bucket
in the query interval) and shipped to device as a sorted int64 array; the
kernel buckets rows with one vectorized `searchsorted`.  This handles calendar
granularities (month/quarter/year, variable length) exactly, where a fixed
period division cannot.
"""

from __future__ import annotations

import datetime
import re
from typing import Optional

import numpy as np

_FIXED_MS = {
    "second": 1_000,
    "minute": 60_000,
    "fifteen_minute": 15 * 60_000,
    "thirty_minute": 30 * 60_000,
    "hour": 3_600_000,
    "six_hour": 6 * 3_600_000,
    "day": 86_400_000,
    "week": 7 * 86_400_000,
}

_ISO = re.compile(r"^P(?:(\d+)Y)?(?:(\d+)M)?(?:(\d+)W)?(?:(\d+)D)?"
                  r"(?:T(?:(\d+)H)?(?:(\d+)M)?(?:(\d+)S)?)?$")


def granularity_period_ms(gran: str) -> Optional[int]:
    """Fixed period in ms, or None for calendar granularities (month/…)."""
    g = gran.lower()
    if g in _FIXED_MS:
        return _FIXED_MS[g]
    if g in ("month", "quarter", "year", "all"):
        return None
    m = _ISO.match(gran)
    if m:
        y, mo, w, d, h, mi, s = (int(x) if x else 0 for x in m.groups())
        if y or mo:
            return None  # calendar
        return ((w * 7 + d) * 86_400 + h * 3_600 + mi * 60 + s) * 1_000
    raise ValueError(f"unknown granularity {gran!r}")


def _iso_calendar_months(gran: str) -> int:
    g = gran.lower()
    if g == "month":
        return 1
    if g == "quarter":
        return 3
    if g == "year":
        return 12
    m = _ISO.match(gran)
    if m:
        y, mo = (int(x) if x else 0 for x in m.groups()[:2])
        return y * 12 + mo
    raise ValueError(gran)


def bucket_starts(lo_ms: int, hi_ms: int, gran: str) -> np.ndarray:
    """Sorted int64 bucket start times covering [lo_ms, hi_ms]."""
    if gran.lower() == "all":
        return np.array([lo_ms], dtype=np.int64)
    period = granularity_period_ms(gran)
    if period is not None:
        # Druid aligns weeks to Monday; epoch (1970-01-01) is a Thursday, and
        # the nearest Monday boundary is 1969-12-29 = epoch - 3d ≡ +4d (mod 7d).
        offset = 4 * 86_400_000 if period == 7 * 86_400_000 else 0
        first = ((lo_ms - offset) // period) * period + offset
        n = (hi_ms - first) // period + 1
        if n > (1 << 24):
            raise ValueError(
                f"granularity {gran} over interval produces {n} buckets"
            )
        return (first + period * np.arange(n, dtype=np.int64)).astype(np.int64)
    months = _iso_calendar_months(gran)
    lo = datetime.datetime.fromtimestamp(lo_ms / 1000.0, tz=datetime.timezone.utc)
    start_month = (lo.year * 12 + (lo.month - 1)) // months * months
    out = []
    m = start_month
    while True:
        y, mm = divmod(m, 12)
        t = datetime.datetime(y, mm + 1, 1, tzinfo=datetime.timezone.utc)
        ms = int(t.timestamp() * 1000)
        out.append(ms)
        if ms > hi_ms:
            break
        m += months
    return np.array(out[:-1] if out[-1] > hi_ms and len(out) > 1 else out,
                    dtype=np.int64)
