"""Package logging (SURVEY.md §5 metrics/logging row).

Reference parity: the reference threads Spark's log4j `Logging` trait through
planner and client code — plan decisions at debug, query dispatch at info.
Here the standard `logging` module plays that role under the
`spark_druid_olap_tpu` namespace; nothing configures the root logger (library
etiquette), so output appears only when the application enables it:

    import logging
    logging.getLogger("spark_druid_olap_tpu").setLevel(logging.INFO)
    logging.basicConfig()

Conventions: plan/rewrite decisions -> DEBUG; per-query completion with the
QueryMetrics one-liner -> INFO; retries/fallbacks (pallas downgrade, sparse
overflow, transient re-dispatch) -> WARNING.
"""

from __future__ import annotations

import logging


def get_logger(name: str) -> logging.Logger:
    """Child logger under the package namespace: get_logger("exec.engine")
    -> "spark_druid_olap_tpu.exec.engine"."""
    return logging.getLogger(f"spark_druid_olap_tpu.{name}")
