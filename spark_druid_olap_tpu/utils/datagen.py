"""Synthetic benchmark data generators (TPC-H lineitem, SSB flat star).

The reference's test/benchmark corpus is the TPC-H *flattened star*
(`orderLineItemPartSupplier` over Druid datasource `tpch`, SURVEY.md §4 `[U]`)
and the driver targets add SSB (BASELINE.md).  No network in this environment,
so we generate statistically-shaped synthetic tables with the real schemas,
cardinalities and value ranges — parity tests compare TPU results against a
float64 numpy oracle over the *same* generated columns, so correctness testing
is independent of whether the rows match official dbgen output.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

_MS_DAY = 86_400_000


def _days(date: str) -> int:
    return int(np.datetime64(date, "D").astype(int))


def gen_lineitem(scale: float = 0.01, seed: int = 0) -> Dict[str, np.ndarray]:
    """TPC-H lineitem columns used by Q1 (SF1 ≈ 6M rows)."""
    n = int(6_001_215 * scale)
    rng = np.random.default_rng(seed)
    shipdate_days = rng.integers(
        _days("1992-01-02"), _days("1998-12-01"), size=n
    )
    # l_returnflag correlates with shipdate in real data; synthetic keeps the
    # 3-value domain and rough mass distribution.
    returnflag = rng.choice(np.array(["A", "N", "R"]), size=n, p=[0.25, 0.5, 0.25])
    linestatus = np.where(
        shipdate_days < _days("1995-06-17"), "F", "O"
    ).astype(object)
    return {
        "l_returnflag": returnflag.astype(object),
        "l_linestatus": linestatus,
        "l_quantity": rng.integers(1, 51, size=n).astype(np.float32),
        "l_extendedprice": (rng.random(n).astype(np.float32) * 100_000 + 900),
        "l_discount": rng.integers(0, 11, size=n).astype(np.float32) / 100,
        "l_tax": rng.integers(0, 9, size=n).astype(np.float32) / 100,
        "l_shipdate": (shipdate_days.astype(np.int64) * _MS_DAY),
        "l_orderkey": rng.integers(1, int(1_500_000 * max(scale, 1e-3)) * 4,
                                   size=n).astype(np.int64),
    }


def gen_ssb_lineorder_flat(scale: float = 0.01, seed: int = 1) -> Dict[str, np.ndarray]:
    """SSB denormalized lineorder (the star pre-joined, Druid-style).

    SF1 lineorder ≈ 6M rows.  Columns cover Q1.x–Q4.x: date attributes,
    customer/supplier region+nation+city, part mfgr/category/brand, and the
    measures."""
    n = int(6_000_000 * scale)
    rng = np.random.default_rng(seed)

    d = rng.integers(_days("1992-01-01"), _days("1998-08-03"), size=n)
    dt = d.astype("datetime64[D]")
    years = dt.astype("datetime64[Y]").astype(int) + 1970
    months = dt.astype("datetime64[M]").astype(int) % 12 + 1
    yearmonthnum = years * 100 + months
    # SSB weeknuminyear
    day_of_year = (dt - dt.astype("datetime64[Y]")).astype(int) + 1
    weeknum = (day_of_year - 1) // 7 + 1

    regions = np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"])
    nations_by_region = {
        "AFRICA": ["ALGERIA", "ETHIOPIA", "KENYA", "MOROCCO", "MOZAMBIQUE"],
        "AMERICA": ["ARGENTINA", "BRAZIL", "CANADA", "PERU", "UNITED STATES"],
        "ASIA": ["CHINA", "INDIA", "INDONESIA", "JAPAN", "VIETNAM"],
        "EUROPE": ["FRANCE", "GERMANY", "ROMANIA", "RUSSIA", "UNITED KINGDOM"],
        "MIDDLE EAST": ["EGYPT", "IRAN", "IRAQ", "JORDAN", "SAUDI ARABIA"],
    }

    def geo(prefix, rng):
        reg = rng.choice(regions, size=n)
        nation = np.empty(n, dtype=object)
        for r in regions:
            m = reg == r
            nation[m] = rng.choice(np.array(nations_by_region[r]), size=int(m.sum()))
        city = np.char.add(
            np.asarray(nation, dtype=str),
            rng.integers(0, 10, size=n).astype(str),
        )
        return reg.astype(object), nation, city.astype(object)

    c_region, c_nation, c_city = geo("c", rng)
    s_region, s_nation, s_city = geo("s", rng)

    mfgr = np.char.add("MFGR#", rng.integers(1, 6, size=n).astype(str))
    category = np.char.add(
        np.asarray(mfgr, dtype=str),
        rng.integers(1, 6, size=n).astype(str),
    )
    brand = np.char.add(
        np.asarray(category, dtype=str),
        rng.integers(1, 41, size=n).astype(str),
    )

    quantity = rng.integers(1, 51, size=n).astype(np.float32)
    extendedprice = rng.random(n).astype(np.float32) * 55_450 + 90
    discount = rng.integers(0, 11, size=n).astype(np.float32)
    revenue = extendedprice * (1 - discount / 100)
    supplycost = extendedprice * 0.6

    return {
        "lo_orderdate": d.astype(np.int64) * _MS_DAY,
        "d_year": years.astype(np.int32),
        "d_yearmonthnum": yearmonthnum.astype(np.int32),
        "d_yearmonth": np.array(
            [f"{y}-{m:02d}" for y, m in zip(years, months)], dtype=object
        ),
        "d_weeknuminyear": weeknum.astype(np.int32),
        "c_region": c_region,
        "c_nation": c_nation,
        "c_city": c_city,
        "s_region": s_region,
        "s_nation": s_nation,
        "s_city": s_city,
        "p_mfgr": np.asarray(mfgr, dtype=object),
        "p_category": np.asarray(category, dtype=object),
        "p_brand1": np.asarray(brand, dtype=object),
        "lo_quantity": quantity,
        "lo_extendedprice": extendedprice,
        "lo_discount": discount,
        "lo_revenue": revenue.astype(np.float32),
        "lo_supplycost": supplycost.astype(np.float32),
    }


LINEITEM_DIMS = ["l_returnflag", "l_linestatus"]
LINEITEM_METRICS = [
    "l_quantity", "l_extendedprice", "l_discount", "l_tax", "l_orderkey",
]

SSB_DIMS = [
    "d_year", "d_yearmonthnum", "d_yearmonth", "d_weeknuminyear",
    "c_region", "c_nation", "c_city",
    "s_region", "s_nation", "s_city",
    "p_mfgr", "p_category", "p_brand1",
]
SSB_METRICS = [
    "lo_quantity", "lo_extendedprice", "lo_discount", "lo_revenue",
    "lo_supplycost",
]


# ---------------------------------------------------------------------------
# Event stream (BASELINE config #4: hourly rollup over a 1B-row stream)
# ---------------------------------------------------------------------------

EVENT_SITES = 32          # site dimension cardinality
EVENT_KINDS = 8           # event-kind dimension cardinality
EVENT_T0 = "2024-01-01"   # stream start
EVENT_SPAN_HOURS = 24 * 7  # one week of events


def event_stream_schema():
    """Schema-only datasource for the event stream (exec/streaming.py).

    Dimension dictionaries are the dense integer domains 0..K-1, so raw
    generated values ARE their rank codes — chunks need no re-encoding."""
    from ..catalog.segment import DimensionDict, schema_datasource

    return schema_datasource(
        "events",
        dims={
            "site": DimensionDict(values=tuple(range(EVENT_SITES))),
            "kind": DimensionDict(values=tuple(range(EVENT_KINDS))),
        },
        metric_cols={"value": "double", "latency": "double"},
        time_col="ts",
    )


def event_stream_interval():
    lo = int(np.datetime64(EVENT_T0, "ms").astype(np.int64))
    return (lo, lo + EVENT_SPAN_HOURS * 3_600_000)


def gen_event_chunk(chunk_idx: int, rows: int) -> Dict[str, np.ndarray]:
    """Deterministic chunk `chunk_idx` of the synthetic event stream.

    Chunks are independent draws (seeded by index), so a 1B-row stream is
    just `for i in range(1_000_000_000 // rows): yield gen_event_chunk(i, rows)`
    with O(rows) host memory."""
    rng = np.random.default_rng(1000 + chunk_idx)
    lo, hi = event_stream_interval()
    return {
        "ts": rng.integers(lo, hi, size=rows, dtype=np.int64),
        "site": rng.integers(0, EVENT_SITES, size=rows, dtype=np.int32),
        "kind": rng.integers(0, EVENT_KINDS, size=rows, dtype=np.int32),
        "value": (rng.random(rows) * 100.0).astype(np.float32),
        "latency": (rng.gamma(2.0, 15.0, size=rows)).astype(np.float32),
    }
