"""User-facing surface: register tables, run SQL, explain rewrites.

Reference parity (SURVEY.md L6 `[U]`): the reference's surface is
`CREATE TEMPORARY TABLE ... USING org.sparklinedata.druid OPTIONS(...)` +
ordinary Spark SQL with `DruidPlanner` strategies injected, plus the
`EXPLAIN DRUID REWRITE` command.  Here:

    import spark_druid_olap_tpu as sd
    ctx = sd.TPUOlapContext()
    ctx.register_table("lineitem", cols, dimensions=[...], metrics=[...],
                       time_column="l_shipdate", star_schema=...)
    df  = ctx.sql("SELECT l_returnflag, sum(l_quantity) FROM lineitem "
                  "GROUP BY l_returnflag")
    print(ctx.explain("SELECT ..."))      # EXPLAIN DRUID REWRITE analog
    ctx.clear_cache()                      # clear-metadata-cache analog

Execution routes through the planner's PhysicalPlan: local Engine or
DistributedEngine (mesh), with grouping-set (CUBE/ROLLUP) expansion and
host-side residual having/projection evaluation handled here — the
"projection fixup over the scan node" role of the reference's DruidStrategy.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from .catalog.cache import MetadataCache
from .catalog.segment import DataSource, build_datasource
from .catalog.star import StarSchemaInfo
from .config import SessionConfig, TableOptions
from .exec.engine import Engine
from .models import query as Q
from .obs import (
    SPAN_DEGRADED,
    SPAN_EXECUTE,
    SPAN_FALLBACK,
    SPAN_PARTIAL,
    SPAN_PLAN,
    Tracer,
    current_query_id,
    record_partial,
    record_query_metrics,
    span,
    span_event,
)
from .plan import expr as E
from .plan import logical as L
from .plan.planner import Planner, Rewrite, RewriteError
from .sql.parser import parse_sql
from .utils.log import get_logger

log = get_logger("api")


def _breaker_observation(br) -> dict:
    """Small JSON-able snapshot of the circuit breaker as the routing
    layer saw it — what degraded-path span events carry.  Carries which
    BACKEND's breaker was consulted (device / mesh / fallback): with
    per-backend granularity, "why did this query degrade" needs to name
    the breaker that said no."""
    d = br.to_dict()
    return {
        "backend": d["backend"],
        "state": d["state"],
        "consecutive_failures": d["consecutive_failures"],
        "trips": d["trips"],
    }


class TPUOlapContext:
    def __init__(self, config: Optional[SessionConfig] = None):
        # Default to measured cost constants (calibration.json when it was
        # produced on this backend, else the platform profile): the class
        # defaults are v5e-flavoured and route CPU kernels pathologically
        # (an uncalibrated CPU session would run a G=8008 GroupBy dense —
        # ~200x slower than scatter there).
        self.config = config or SessionConfig.load_calibrated()
        self.catalog = MetadataCache()
        self.engine = Engine()
        # overlapped h2d transfer pipeline (exec/pipeline.py, ISSUE 10):
        # prefetch depth / speculation byte cap / on-off come from config
        self.engine.configure_pipeline(self.config)
        self._dist_engine = None
        self._last_engine_metrics = None  # metrics of the engine that last ran
        # query-lifecycle resilience (resilience.py): the breaker every
        # engine reports transient failures to, the admission pool the
        # serving layer gates on, and the health counters
        from .resilience import ResilienceState

        self.resilience = ResilienceState(self.config)
        self._sync_engine_resilience(self.engine)
        # per-query span tracing (obs/): the ring buffer behind
        # GET /druid/v2/trace/{query_id}; the metrics registry itself is
        # process-global (obs.registry.get_registry)
        self.tracer = Tracer(
            capacity=self.config.trace_ring_capacity,
            otlp_path=self.config.otlp_export_path,
            prof_sample_rate=self.config.prof_sample_rate,
        )
        # SQL-text -> Rewrite cache (the reference re-plans every Catalyst
        # round; locally a repeated dashboard query should pay parse+plan
        # once).  Keyed on catalog version + config so any re-registration
        # or session-flag change invalidates.
        from .utils.lru import CountBudgetCache

        self._plan_cache = CountBudgetCache(256)
        # async serving core (serve/, ISSUE 8): micro-batch query fusion,
        # the delta-aware version-keyed result cache, and SQL lane
        # classification.  The result cache (Druid broker result cache
        # analog) lives inside it: identical (query, schema) pairs skip
        # execution entirely, and on append it serves (cached historical
        # partial) ⊕ (fresh delta partials) instead of invalidating.
        from .serve import ServingCore

        self.serve = ServingCore(self)
        # CREATE VIEW registry: view name -> defining SELECT text; the
        # parser expands references as derived tables
        self.views: Dict[str, str] = {}
        # real-time ingestion tier (ingest/): streamed appends -> delta
        # segments, plus the versioned background compactor.  Retired
        # segment uids (compaction, dictionary-extension remaps) evict
        # from the engine's device residency immediately.
        from .ingest import Compactor, IngestManager

        self.ingest = IngestManager(self.catalog, self.config)
        self.ingest.on_segments_dropped = self._on_segments_dropped
        self.compactor = Compactor(
            self.ingest,
            rows_per_segment=self.config.compaction_rows_per_segment,
            min_delta_rows=self.config.compaction_min_delta_rows,
            interval_s=self.config.compaction_interval_s,
            sys_retention_s=self.config.sys_retention_s,
        )
        # cluster tier (cluster/, ISSUE 16): set by ClusterClient.attach
        # when this context runs as a BROKER — the serving paths scatter
        # covered queries to historicals instead of executing locally.
        # None (default) keeps every query in-process.
        self.cluster = None
        # durable storage tier (storage.py, ISSUE 13): append WAL +
        # crash-safe persistent segment snapshots.  Opt-in via
        # config.storage_dir; recovery runs NOW, before the context is
        # handed to callers — a restarted process serves the pre-crash
        # state (snapshot mmap + WAL replay) from its first query.
        self.storage = None
        if self.config.storage_dir:
            from .storage import DurableStorage

            self.storage = DurableStorage(
                self.config.storage_dir,
                self.catalog,
                self.ingest,
                fsync=self.config.storage_fsync,
            )
            self.ingest.storage = self.storage
            self.compactor.storage = self.storage
            self.storage.recover(self.resilience)
            if self.config.snapshot_flush_s > 0:
                self.storage.start_flush_sweep(
                    self.config.snapshot_flush_s
                )
        # self-hosted telemetry (obs/telemetry.py, ISSUE 19): registry ->
        # `__sys` datasource through the ingest/WAL tier.  Built lazily —
        # config.sys_sampler_s > 0 starts the daemon tick loop here;
        # start_sys_sampler() is the manual/test entry point.
        self.sys_sampler = None
        if self.config.sys_sampler_s > 0:
            self.start_sys_sampler()

    # -- registration (CREATE TABLE ... USING ... OPTIONS analog) -----------

    def register_table(
        self,
        name: str,
        source,
        dimensions: Sequence[str] = (),
        metrics: Sequence[str] = (),
        time_column: Optional[str] = None,
        star_schema: Optional[StarSchemaInfo] = None,
        column_mapping: Optional[Mapping[str, str]] = None,
        rows_per_segment: int = 1 << 22,
        dicts: Optional[Mapping] = None,
        sort_by: Sequence[str] = (),
        rollup_granularity: Optional[str] = None,
    ) -> DataSource:
        """Register a datasource from a pandas DataFrame, a dict of numpy
        columns, or a parquet/csv path (catalog/ingest.py).  `dicts` supplies
        pre-built dimension dictionaries for already-encoded columns.

        `sort_by` orders rows by the named columns before segmenting (the
        Druid secondary-partitioning analog): filters on those columns then
        prune whole segments via zone maps instead of masking rows.

        `rollup_granularity` opts the datasource into Druid-style
        ingest-time rollup: streamed appends pre-aggregate under the
        declared granularity (time truncated to the bucket, metrics
        summed per distinct dimension tuple) BEFORE journaling/publish.
        Fixed-period granularities only ('second' .. 'week'); requires a
        time column.  Changes count(*) semantics to "rolled-up rows" —
        the documented Druid rollup trade."""
        from .catalog.ingest import to_columns_encoded

        cols, native_dicts = to_columns_encoded(source)
        if column_mapping:
            cols = {column_mapping.get(k, k): v for k, v in cols.items()}
            native_dicts = {
                column_mapping.get(k, k): v for k, v in native_dicts.items()
            }
        if dicts:
            # caller-supplied dictionaries win — by re-encoding the raw
            # values, never by reinterpreting native rank codes under a
            # different domain (codes are ranks over the FILE's domain)
            for k in [k for k in native_dicts if k in dicts]:
                cols[k] = native_dicts.pop(k).decode(np.asarray(cols[k]))
        if time_column and time_column in native_dicts:
            # a string-typed time column arrived as rank codes; translate
            # through the (tiny) dictionary: parse each distinct value once
            d = native_dicts.pop(time_column)
            codes = np.asarray(cols[time_column])
            try:
                ms = np.asarray(d.values, dtype="datetime64[ms]").astype(
                    np.int64
                )
                if (codes < 0).any():
                    raise ValueError("null time values")
                cols[time_column] = ms[codes]
            except Exception:
                # non-datetime strings: surface the raw values so
                # build_datasource fails loudly (pandas-path behavior)
                cols[time_column] = d.decode(codes)
        if native_dicts:
            merged = dict(native_dicts)
            if dicts:
                merged.update(dicts)
            dicts = merged
            if not dimensions and not metrics:
                # encoded string columns are int32 codes now; classify the
                # ones with a native dictionary as dimensions
                dims, mets = _infer_schema(cols, time_column)
                dims += [m for m in mets if m in native_dicts]
                mets = [m for m in mets if m not in native_dicts]
                dimensions, metrics = dims, mets
        if not dimensions and not metrics:
            dimensions, metrics = _infer_schema(cols, time_column)
        if sort_by:
            missing = [c for c in sort_by if c not in cols]
            if missing:
                raise ValueError(f"sort_by names unknown columns {missing}")

            def sort_keys(c):
                # null-safe keys: object columns with None cannot lexsort
                # directly; nulls order LAST (flag more significant than
                # value, so it follows the value key in the lexsort tuple)
                a = np.asarray(cols[c])
                if a.dtype.kind == "O":
                    nulls = np.array([v is None for v in a])
                    vals = np.array(
                        [("" if v is None else str(v)) for v in a]
                    )
                    return [vals, nulls]
                if c in (dicts or {}) and a.dtype.kind in "iu":
                    # pre-encoded dimension codes: null codes are negative
                    # and would cluster FIRST on a raw sort — add the same
                    # nulls-last flag key the object path uses
                    return [a, a < 0]
                return [a]

            # stable lexsort (last key primary); encoded dims sort by code,
            # which is value order (dictionaries are sorted)
            keys: list = []
            for c in reversed(sort_by):
                keys.extend(sort_keys(c))
            order = np.lexsort(tuple(keys))
            cols = {k: np.asarray(v)[order] for k, v in cols.items()}
        ds = build_datasource(
            name,
            cols,
            dimension_cols=list(dimensions),
            metric_cols=list(metrics),
            time_col=time_column,
            rows_per_segment=rows_per_segment,
            dicts=dicts,
        )
        if rollup_granularity is not None:
            from .utils.granularity import granularity_period_ms

            if time_column is None:
                raise ValueError(
                    "rollup_granularity requires a time column"
                )
            if granularity_period_ms(rollup_granularity) is None:
                raise ValueError(
                    f"rollup_granularity {rollup_granularity!r} has no "
                    "fixed period; use second/minute/.../week"
                )
            ds = dataclasses.replace(
                ds, rollup_granularity=str(rollup_granularity).lower()
            )
        if star_schema is not None and not isinstance(star_schema, StarSchemaInfo):
            star_schema = StarSchemaInfo.from_json(star_schema)
        # put() stamps the monotonic per-datasource version; return the
        # stamped snapshot so callers observe the same object queries see
        published = self.catalog.put(ds, star_schema)
        if self.storage is not None:
            # registration is durable too: the snapshot commits before
            # the call returns, so a post-registration crash restores
            # the table by mmap instead of demanding a re-ingest
            self.storage.flush(name)
        return published

    def register_datasource(self, ds: DataSource, star_schema=None):
        """Register an ALREADY-BUILT DataSource (streamed/chunked ingest via
        catalog.segment.build_datasource_streamed, or one loaded from
        catalog.persist) under its own name."""
        if star_schema is not None and not isinstance(star_schema, StarSchemaInfo):
            star_schema = StarSchemaInfo.from_json(star_schema)
        published = self.catalog.put(ds, star_schema)
        if self.storage is not None:
            self.storage.flush(ds.name)
        return published

    # -- streamed ingest (the Druid realtime-node analog, ISSUE 6) ----------

    def append_rows(self, name: str, rows) -> dict:
        """Append streamed rows (list of row dicts or a column mapping) to
        a registered datasource.  The rows are queryable by the very next
        query — delta partials merge with historical partials through the
        same mergeable-aggregate machinery every executor already uses.
        Returns an ack: {"appended", "datasourceVersion", "totalRows"}."""
        with self.tracer.query_trace(
            query_type="ingest", slow_ms=self.config.slow_query_ms
        ):
            return self.ingest.append_rows(name, rows)

    def compact(self, name: str) -> dict:
        """Roll `name`'s delta segments into tiled historical segments now
        (the background compactor does this on a sweep; this is the
        synchronous entry point).  Query results are preserved verbatim;
        the datasource version bumps so result caches invalidate."""
        with self.tracer.query_trace(
            query_type="compaction", slow_ms=self.config.slow_query_ms
        ):
            return self.compactor.compact(name)

    def start_compaction(self):
        """Start the background compaction sweep (daemon thread)."""
        self.compactor.start()
        return self

    def stop_compaction(self):
        self.compactor.stop()

    def start_sys_sampler(self, interval_s: Optional[float] = None):
        """Start (or restart) the `__sys` telemetry sampler: the metrics
        registry flushes into the `__sys` datasource every tick so
        operational history is SQL-queryable (obs/telemetry.py)."""
        from .obs.telemetry import SysSampler

        if self.sys_sampler is None:
            self.sys_sampler = SysSampler(
                self,
                interval_s=(
                    interval_s
                    if interval_s is not None
                    else self.config.sys_sampler_s or 5.0
                ),
                max_series=self.config.sys_sampler_max_series,
            )
        self.sys_sampler.start()
        return self.sys_sampler

    def stop_sys_sampler(self):
        if self.sys_sampler is not None:
            self.sys_sampler.stop()

    def _on_segments_dropped(self, uids):
        self.engine.evict_segments(uids)
        # the fallback's decoded-frame cache keys on the same uids
        from .exec.fallback import evict_decoded_segments

        evict_decoded_segments(uids)
        if self._dist_engine is not None:
            # shard assemblies key on the full segment-uid signature, so
            # stale entries can never be SERVED — but they still pin HBM
            # until LRU pressure; a retired segment set clears them now
            self._dist_engine.clear_cache()

    def register_lookup(self, name: str, mapping: Mapping[str, str]):
        """Register a query-time lookup table (Druid lookup extraction):
        `LOOKUP(dim, 'name')` in GROUP BY maps dimension values through it
        host-side (a dictionary rewrite — never per-row string work)."""
        self.catalog.put_lookup(name, dict(mapping))

    def save_table(self, name: str, directory: str) -> str:
        """Persist a registered datasource (encoded segments + dictionaries
        + star schema) to a directory; `load_table` or `CREATE TABLE ...
        USING tpu_olap OPTIONS (path '<dir>')` restores it without
        re-ingest/re-encode (the Druid-index-as-persistence analog)."""
        from .catalog.persist import save_datasource

        ds = self.catalog.get(name)
        if ds is None:
            raise KeyError(f"table {name!r} does not exist")
        return save_datasource(ds, directory, self.catalog.star_schema(name))

    def load_table(self, directory: str, name: Optional[str] = None):
        from .catalog.persist import load_datasource

        ds, star = load_datasource(directory, name=name)
        # drop first: put() only overwrites the star when one is provided,
        # and a star-less load over an existing starred table must not keep
        # the stale star (it describes different data)
        self.catalog.drop(ds.name)
        return self.catalog.put(ds, star)

    def drop_table(self, name: str):
        self.catalog.drop(name)

    @property
    def _result_cache(self):
        """The serving core's result cache under its pre-serve name —
        `SET result_cache_entries` (sql/commands.py) resizes through
        this, and existing callers keep working."""
        return self.serve.result_cache

    def clear_cache(self):
        """Reference's clear-metadata-cache command + HBM residency drop."""
        self.catalog.clear()
        self.engine.clear_cache()
        self._plan_cache.clear()
        self.serve.result_cache.clear()
        if self._dist_engine is not None:
            self._dist_engine.clear_cache()

    # -- planning ------------------------------------------------------------

    def _planner(self) -> Planner:
        import jax

        return Planner(self.catalog, self.config, n_devices=len(jax.devices()))

    def plan_sql(self, sql_text: str) -> Rewrite:
        lp, _, _ = parse_sql(sql_text, views=self.views)
        return self._planner().plan(lp)

    def explain(self, sql_text: str) -> str:
        """EXPLAIN DRUID REWRITE analog: logical plan -> chosen query spec
        JSON -> physical plan."""
        lp, _, _ = parse_sql(sql_text, views=self.views)
        return self._planner().explain(lp)

    @property
    def last_metrics(self):
        """QueryMetrics of the most recent execution (exec/metrics.py) —
        rows/sec, H2D bytes streamed, compile/device/collective/finalize
        phase times — from whichever engine ran it."""
        # _last_engine_metrics is stamped on every completed execution —
        # engine runs (execute_rewrite) AND host-fallback runs — so it is
        # the authoritative "most recent"; the engine objects are only a
        # fallback for direct engine.execute() use outside the context
        if self._last_engine_metrics is not None:
            return self._last_engine_metrics
        dm = self._dist_engine.last_metrics if self._dist_engine else None
        em = self.engine.last_metrics
        return em if dm is None else (dm if em is None else em)

    def explain_analyze(self, sql_text: str):
        """EXPLAIN ANALYZE analog: run the query, return (DataFrame,
        explain text + measured QueryMetrics + the span tree).  Bypasses
        the result cache — the metrics must describe THIS execution, not
        a cache lookup."""
        from .obs import current_trace

        lp, _, _ = parse_sql(sql_text, views=self.views)
        planner = self._planner()
        # finishing pins the root duration so the appended render shows a
        # real total — but only when WE opened the trace (a joined outer
        # trace must not be truncated mid-request)
        owned = current_trace() is None
        with self.tracer.query_trace(
            query_type="explain_analyze", slow_ms=self.config.slow_query_ms
        ) as tr:
            try:
                with span(SPAN_PLAN):
                    rw = planner.plan(lp)
            except RewriteError as err:
                df = self._run_fallback(lp, err)
                text = f"== Host Fallback ==\nrewrite failed: {err}"
                m = self.last_metrics
                if m is not None:
                    text += "\n\n== Execution Metrics ==\n" + m.describe()
                if owned:
                    tr.finish()
                return df, text + "\n\n== Span Tree ==\n" + tr.render()
            with span(SPAN_EXECUTE):
                df = self.execute_rewrite(rw, use_result_cache=False)
            text = planner.explain(lp)
            m = self.last_metrics
            if m is not None:
                text += "\n\n== Execution Metrics ==\n" + m.describe()
            if owned:
                tr.finish()
            return df, text + "\n\n== Span Tree ==\n" + tr.render()

    # -- execution -----------------------------------------------------------

    def _plan_cache_key(self, sql_text: str):
        import jax

        return (
            sql_text,
            self.catalog.version,
            tuple(sorted(self.views.items())),  # view redefinition invalidates
            repr(self.config),
            len(jax.devices()),
        )

    def sql(self, sql_text: str):
        from .resilience import deadline_scope, partial_scope
        from .sql.commands import parse_command, run_command

        cmd = parse_command(sql_text)
        if cmd is not None:
            return run_command(self, cmd)
        # per-query deadline: the session default arms here unless an outer
        # scope (the server's wire `context.timeout`) is already active.
        # The query trace joins the server's when one is active (outermost
        # wins, same contract as deadline_scope); a direct ctx.sql call
        # gets its own generated query_id.  The partial-result collector
        # arms under the same outermost-wins rule: deadline expiry then
        # degrades to a coverage-stamped best-effort answer.
        with self.tracer.query_trace(
            query_type="sql", slow_ms=self.config.slow_query_ms
        ), deadline_scope(self.config.query_timeout_ms), partial_scope(
            self.config.partial_results
        ):
            plan_err = None
            with span(SPAN_PLAN):
                key = self._plan_cache_key(sql_text)
                cached = self._plan_cache.get(key)
                if cached is not None:
                    rw, lp = cached
                else:
                    lp, explain, out_names = parse_sql(
                        sql_text, views=self.views
                    )
                    planner = self._planner()
                    if explain:
                        import pandas as pd

                        return pd.DataFrame(
                            {"plan": planner.explain(lp).split("\n")}
                        )
                    try:
                        rw = planner.plan(lp)
                    except RewriteError as err:
                        rw, plan_err = None, err
                    else:
                        self._plan_cache[key] = (rw, lp)
            if rw is None:
                return self._stamp_receipt(
                    self._stamp_partial(self._run_fallback(lp, plan_err))
                )
            with span(SPAN_EXECUTE):
                df = self._stamp_partial(
                    self._execute_with_resilience(rw, lp)
                )
            # receipt stamped OUTSIDE the execute span so its live
            # snapshot sees the span closed (device/host split complete)
            return self._stamp_receipt(df)

    def sql_progressive(self, sql_text: str):
        """Progressive execution of one SQL statement (ROADMAP 3(b)): a
        generator of `(df, info)` refinements converging to the exact
        answer — the SQL-surface twin of the native route's
        `context.progressive`.  Returns None when the statement cannot
        stream (commands, EXPLAIN, unplannable/fallback shapes, grouping
        sets, exact-distinct, mesh-routed) — the caller then answers
        buffered.  Each refinement passes through the SAME host post-
        processing (`_post_process`) the buffered path applies, so a
        stream's final frame is exactly `ctx.sql`'s answer."""
        from .sql.commands import parse_command

        if parse_command(sql_text) is not None:
            return None
        key = self._plan_cache_key(sql_text)
        cached = self._plan_cache.get(key)
        if cached is not None:
            rw, _lp = cached
        else:
            lp, explain, _ = parse_sql(sql_text, views=self.views)
            if explain:
                return None
            try:
                rw = self._planner().plan(lp)
            except RewriteError:
                return None  # fallback shapes answer buffered
            self._plan_cache[key] = (rw, lp)
        if rw.exact_distinct is not None or rw.grouping_sets:
            return None
        q = rw.query
        if not isinstance(
            q, (Q.GroupByQuery, Q.TimeseriesQuery, Q.TopNQuery)
        ):
            return None
        if isinstance(q, Q.GroupByQuery) and q.subtotals:
            return None
        if self._backend_for(rw) == "mesh":
            return None  # mesh execution has no per-batch refinement yet
        # an open device breaker must not be bypassed just because the
        # client asked for a stream: decline here and the buffered path
        # (ctx.sql -> _execute_with_resilience) degrades properly
        if not self.resilience.breaker_for(
            self._backend_for(rw)
        ).allow():
            return None
        ds = self.catalog.get(rw.datasource)
        if ds is None:
            return None
        engine = self._engine_for(rw)

        def refinements():
            for df, info in engine.execute_progressive(q, ds):
                yield self._post_process(rw, ds, df), info
            self._last_engine_metrics = getattr(
                engine, "last_metrics", None
            )

        return refinements()

    def _sync_engine_resilience(self, engine, backend: str = "device"):
        """Point an engine at this context's breaker for `backend`
        ("device" for the local engine, "mesh" for the distributed one —
        per-backend breakers mean a sick mesh cannot darken the
        single-device path) and sync the retry budget from the session
        config (engines construct with standalone defaults so direct
        Engine() use keeps working)."""
        engine.breaker = self.resilience.breaker_for(backend)
        engine._retry_attempts = self.config.retry_max_attempts
        engine._retry_backoff_ms = self.config.retry_backoff_ms

    def _backend_for(self, rw: Rewrite) -> str:
        """Which execution backend this rewrite will run on — the same
        decision _engine_for makes, shared so breaker routing and engine
        selection can never disagree."""
        phys = rw.physical
        if phys.distributed and phys.mesh_shape is not None:
            import jax

            if len(jax.devices()) >= phys.mesh_shape[0] * phys.mesh_shape[1]:
                return "mesh"
        return "device"

    def _execute_with_resilience(self, rw: Rewrite, lp):
        """Device execution under the target backend's circuit breaker,
        degrading to the host fallback on an open circuit or a transient
        failure that survived the engine's retry budget — the runtime
        extension of the reference's 'a failed rewrite is never an error'
        stance.  Static errors surface unchanged; deadline expiry
        degrades to a coverage-stamped PARTIAL answer when the collector
        is armed (config.partial_results), and surfaces otherwise
        (retrying a timed-out query would only time out slower)."""
        from .resilience import classify_error, current_partial

        res = self.resilience
        backend = self._backend_for(rw)
        br = res.breaker_for(backend)
        can_degrade = (
            lp is not None
            and self.config.fallback_execution
        )
        if can_degrade and not br.allow():
            # an open circuit must not cost a cached answer: the result
            # cache holds exact device-quality frames that need NO device
            # allow_delta=False: a delta refresh dispatches device work,
            # and the breaker just said the device is sick
            hit = self._cached_result(rw, allow_delta=False)
            if hit is not None:
                return hit
            log.warning(
                "%s circuit open; answering on the host fallback", backend
            )
            with span(SPAN_DEGRADED, reason="circuit_open"):
                # the breaker state OBSERVED at routing time: the trace
                # must show WHY the fallback was chosen, not leave the
                # reader to reconstruct it from counters (ROADMAP obs
                # follow-up (c))
                span_event("breaker_state", **_breaker_observation(br))
                df = self._run_fallback(
                    lp, None, reason=f"{backend} circuit open"
                )
            self._stamp_degraded(None, backend=backend)
            return df
        try:
            df = self.execute_rewrite(rw)
        except Exception as err:
            kind = classify_error(err)
            if kind == "deadline":
                pc = current_partial()
                if pc is not None:
                    # partial-aware caching (ROADMAP 3(d)): before
                    # draining a low-coverage best-effort answer, serve
                    # a cached EXACT one if a concurrent identical query
                    # populated the cache since this one started — a
                    # complete cached frame beats any partial, and a
                    # cached-exact hit is never stamped partial
                    hit = self._cached_result(rw, allow_delta=False)
                    if hit is not None:
                        return hit
                    # the deadline expired OUTSIDE the partial-capable
                    # loops (planning, a blocking fetch, a ladder rung):
                    # trigger the collector and drain-rerun — every
                    # checkpoint is now a no-op and the executor loops
                    # stop at their first batch, so the rerun costs one
                    # lowering + an empty finalize and yields the
                    # well-formed zero-or-low-coverage answer instead of
                    # a 504
                    pc.trigger(getattr(err, "site", "") or "deadline")
                    log.warning(
                        "deadline expired outside a partial-capable "
                        "loop (%s); draining a best-effort answer", err,
                    )
                    return self.execute_rewrite(rw)
                res.note_deadline_exceeded()
                err._sdol_counted = True  # the server layer must not re-count
                m = self.last_metrics
                if m is not None:
                    m.deadline_exceeded = True
                raise
            if kind != "transient" or not can_degrade:
                raise
            log.warning(
                "%s execution failed (%s: %s) after retries; "
                "degrading to the host fallback",
                backend, type(err).__name__, err,
            )
            with span(SPAN_DEGRADED, reason="device_failed"):
                span_event(
                    "breaker_state",
                    error_class=type(err).__name__,
                    **_breaker_observation(br),
                )
                df = self._run_fallback(
                    lp, err, reason=f"{backend} execution failed"
                )
            self._stamp_degraded(err, backend=backend)
            return df
        m = self.last_metrics
        # report to the breaker for EVERY query type: the GroupBy engines
        # record internally, but a half-open probe served by a timeseries/
        # topN/scan (or a result-cache hit that never touched the device)
        # must not leave the lease dangling and the breaker half-open on a
        # healthy device
        if m is not None and m.strategy == "result-cache":
            br.release_probe()
        else:
            br.record_success()
        if m is not None and not m.circuit_state:
            m.circuit_state = br.state
        return df

    def _stamp_degraded(self, err, backend: str = "device"):
        """Mark the (fallback) metrics of a degraded answer and count it."""
        self.resilience.note_degraded()
        m = self.last_metrics
        if m is not None:
            m.degraded = True
            m.circuit_state = self.resilience.breaker_for(backend).state
            if err is not None:
                m.error_class = type(err).__name__

    def _stamp_partial(self, df):
        """Stamp a deadline-bounded PARTIAL answer: the result frame's
        attrs carry {"partial": True, "coverage": ...} (the SQL-surface
        contract; the server folds the same dict into
        X-Druid-Response-Context), the metrics carry partial/coverage,
        and the `partial` span + coverage histogram record it for the
        trace and the fleet (partial-result discipline, GL16xx).  A
        no-op for complete answers."""
        from .resilience import current_partial

        pc = current_partial()
        if pc is None or not pc.is_partial:
            return df
        # partial-aware caching (ROADMAP 3(d)): a result served FROM the
        # cache is an exact answer computed before the deadline existed —
        # a triggered collector describes the aborted execution, not the
        # cached frame, and must never stamp it down to partial
        m_hit = self._last_engine_metrics
        if m_hit is not None and str(
            getattr(m_hit, "strategy", "")
        ).startswith("result-cache"):
            return df
        info = pc.to_dict()
        with span(
            SPAN_PARTIAL,
            coverage=info["coverage"],
            site=info["site"],
            rows_seen=info["rows_seen"],
            rows_total=info["rows_total"],
        ):
            record_partial(
                info["coverage"], site=info["site"] or "",
                query_id=current_query_id(),
            )
        m = self.last_metrics
        if m is not None:
            m.partial = True
            m.coverage = info["coverage"]
            m.rows_seen = info["rows_seen"]
            m.delta_rows_seen = info["delta_rows_seen"]
        try:
            df.attrs.update(info)
        except AttributeError:  # fault-ok: non-pandas results skip attrs
            pass
        return df

    def _stamp_receipt(self, df):
        """Stamp the query's cost receipt (obs/prof.py, ISSUE 9) onto
        the answer: `df.attrs["receipt"]` (the SQL-surface contract) and
        `QueryMetrics.receipt` — the live snapshot; the trace doc gets
        the final recomputation at trace close.  A no-op outside a
        trace (direct engine use without a context)."""
        from .obs.prof import live_receipt

        rc = live_receipt()
        if rc is None:
            return df
        m = self.last_metrics
        if m is not None:
            m.receipt = rc
        try:
            df.attrs["receipt"] = rc
        except AttributeError:  # fault-ok: non-pandas results skip attrs
            pass
        return df

    def execute_native_degraded(
        self, q, err=None, reason: str = "native degradation",
        backend: str = "device",
    ):
        """Answer a wire-native QuerySpec on the host fallback — the
        degradation-matrix cell that used to 503.  The spec decodes to a
        logical plan (exec/wire_fallback.py, riding the WIRE_AGG_FALLBACK
        registry) and runs through the SAME `_run_fallback` gate SQL
        queries degrade through, so policy (fallback_execution, size
        ceiling, fallback breaker) cannot drift between surfaces.
        Raises WireFallbackUnsupported for specs outside the
        interpreter's coverage — the server then falls back to the old
        fail-fast 503."""
        from .exec.wire_fallback import native_to_logical, shape_native_result

        ds = self.catalog.get(q.datasource)
        if ds is None:
            raise RewriteError(f"unknown table {q.datasource!r}")
        lp = native_to_logical(q, ds)  # may raise WireFallbackUnsupported
        with span(SPAN_DEGRADED, reason="native_" + reason):
            span_event(
                "breaker_state",
                **_breaker_observation(self.resilience.breaker_for(backend)),
            )
            df = self._run_fallback(lp, err, reason=reason)
        self._stamp_degraded(err, backend=backend)
        # partial-result discipline (GL16xx): a deadline-bounded degraded
        # answer publishes its coverage (partial span + fleet counter)
        # exactly like the SQL surface — the server only adds the header.
        # Receipts survive the degraded path too (ISSUE 9 satellite):
        # the fallback's host time is attributed like any other query's.
        return shape_native_result(
            q, ds, self._stamp_receipt(self._stamp_partial(df))
        )

    def _run_fallback(self, lp, err, reason: str = "rewrite failed"):
        """The reference's vanilla-Spark fallback: a failed rewrite runs
        the logical plan host-side instead of erroring — observably
        (QueryMetrics.executor = "fallback") and size-guarded
        (SessionConfig.fallback_max_rows).  Policy rejections and a
        disabled fallback re-raise the original RewriteError — the gate
        lives HERE so every caller (sql, explain_analyze, circuit-broken
        degradation) agrees.  `err` may be None (breaker-open routing:
        there is no triggering exception)."""
        import time as _time

        from .exec.fallback import execute_fallback, plan_input_rows
        from .exec.metrics import QueryMetrics
        from .plan.transforms import RewritePolicyError

        if isinstance(err, RewritePolicyError):
            raise err  # explicit policy/validation rejection — no fallback
        if not self.config.fallback_execution:
            if err is not None:
                raise err
            raise RewriteError("fallback execution is disabled")
        # the FALLBACK breaker: the host interpreter is itself a backend
        # that can be sick (torn decodes, host I/O faults) — consecutive
        # transient failures open it, and while open a degraded query
        # fails FAST with the original error instead of re-grinding a
        # known-bad path; a half-open probe recovers it.  Per-backend
        # granularity: this breaker never touches device/mesh routing.
        fb = self.resilience.breaker_for("fallback")
        if not fb.allow():
            from .resilience import CircuitOpenError

            log.warning(
                "host-fallback circuit open; failing fast (%s)", reason
            )
            if err is not None:
                raise err
            raise CircuitOpenError(
                "host-fallback circuit open and no healthier backend "
                "remains — retry after the breaker's cooldown"
            )

        log.warning(
            "%s (%s); executing on the host fallback", reason, err
        )
        t0 = _time.perf_counter()
        assists = {"n": 0}

        def device_subplan(sub_lp):
            """Device-assist hook: offer an Aggregate subtree to the normal
            rewrite path.  Any failure means 'interpret it host-side' —
            the assist must never turn a working fallback into an error.

            The decision is COST-BASED (VERDICT r4 #6): assist engages when
            the modelled engine kernel time at the subtree's group
            cardinality (plan/cost.query_kernel_costs, calibrated) clearly
            beats rows x cost_per_row_interp.  This separates the two
            fallback shapes a row threshold cannot: a q2-class subtree
            (tiny G over a big base — engine wins 15-100x measured) from a
            q18-class one (G ~ rows/4 — the interpreter's single pass
            wins).  Small bases stay on the (float64-exact, instant)
            interpreter regardless: see device_assist_min_rows."""
            try:
                rows = plan_input_rows(sub_lp, self.catalog)
                if rows < self.config.device_assist_min_rows:
                    return None
                rw = self._planner().plan(sub_lp)
                from .exec.lowering import lower_groupby
                from .models import query as Q
                from .plan.cost import query_kernel_costs

                if rw.exact_distinct is not None or not isinstance(
                    rw.query, Q.GroupByQuery
                ):
                    # uncostable shape (timeseries/topn/exact-distinct
                    # subtree): no kernel-cost estimate exists, so apply a
                    # HIGH row bar instead — huge bases still offload (the
                    # r4 behavior), everything else interprets
                    if rows < max(
                        self.config.device_assist_min_rows, 1 << 23
                    ) and not self.config.device_assist_force:
                        return None
                else:
                    ds = self.catalog.get(rw.datasource)
                    lowering = lower_groupby(rw.query, ds)
                    G = lowering.num_groups
                    # h2d: columns of the subtree's base not yet resident in
                    # the engine's device cache must cross the host->device
                    # link first.  Negligible locally; decisive over a thin
                    # link (the round-5 tunnel measured 46 MB/s — cold data
                    # costs ~22 s/GB there).  Amortized /3 like the adaptive
                    # probe: the cache keeps columns warm across the repeat
                    # queries this workload shape is built around.
                    phys = rw.physical
                    if phys.distributed and phys.mesh_shape is not None:
                        # mesh execution: the DistributedEngine's shard
                        # residency is not visible here — price the
                        # transfer fully cold (conservative: borderline
                        # assists decline, the never-slower direction)
                        miss_bytes = 4 * (len(lowering.columns) + 1) * rows
                    else:
                        miss_bytes = self.engine.missing_resident_bytes(
                            ds, lowering.columns
                        )
                    h2d_us = (
                        miss_bytes / self.config.h2d_bytes_per_s * 1e6
                    )
                    assist_us = (
                        min(
                            query_kernel_costs(
                                rw.query, ds, G, self.config
                            ).values()
                        )
                        + self.config.cost_dispatch_us
                        + h2d_us / 3.0
                        # the assisted path re-pays host work PER RESULT
                        # GROUP (decode, frame build, downstream
                        # interpretation)
                        + G * self.config.cost_per_group_decode
                    )
                    interp_us = rows * self.config.cost_per_row_interp
                    # 3x modelled margin: q17-class subtrees (G ~ rows/20)
                    # land within noise of the boundary at 2x and measured
                    # a 0.9-1.1x wash either way — never-slower means
                    # declining the coin flips, not just the clear losses
                    if (
                        assist_us * 3 >= interp_us
                        and not self.config.device_assist_force
                    ):
                        return None
            except RewriteError:
                return None
            except Exception:
                # quirk-shaped internal subtrees (decorrelator output) may
                # crash the planner rather than decline; the assist must
                # never turn a working fallback into an error
                log.warning(
                    "device-assist planning failed; interpreting host-side",
                    exc_info=True,
                )
                return None
            try:
                out = self.execute_rewrite(rw, use_result_cache=False)
            except Exception:
                log.warning(
                    "device-assist subplan failed; interpreting host-side",
                    exc_info=True,
                )
                return None
            assists["n"] += 1
            return out

        from .resilience import DeadlineExceeded, classify_error, current_partial

        pc = current_partial()
        if pc is not None:
            # the interpreter owns ONE accounting pass spanning every
            # table it decodes; assist subtrees must not reset it
            pc.begin_pass()
            pc.in_fallback = True
        try:
            with span(SPAN_FALLBACK, reason=reason):
                df = execute_fallback(
                    lp, self.catalog,
                    max_rows=self.config.fallback_max_rows,
                    device_exec=device_subplan,
                )
        except DeadlineExceeded as dl_err:
            # expiry at an interpretation checkpoint (decode-site expiry
            # is absorbed inline by checkpoint_partial): trigger the
            # collector and drain-rerun — the decoded-frame cache makes
            # the second decode ~free, checkpoints are now no-ops, and
            # the interpreter finishes over the full frames, so the
            # "partial" usually drains to the complete answer
            if pc is None:
                raise
            pc.trigger(dl_err.site or "fallback.interp")
            # the rerun's own accounting is the truth about what the
            # final answer saw: the aborted pass's scope/seen counters
            # would double the denominator and claim rows the rerun
            # never serves (decoded_frame in drain mode only includes
            # warm-cached segments)
            pc.reset_for_drain()
            with span(SPAN_FALLBACK, reason="deadline_drain"):
                df = execute_fallback(
                    lp, self.catalog,
                    max_rows=self.config.fallback_max_rows,
                    device_exec=device_subplan,
                )
            fb.record_success()
        except Exception as fb_err:
            # only TRANSIENT failures (decode faults, host I/O) count on
            # the fallback breaker — a static plan/shape gap is a
            # property of the query, not of the backend's health
            if classify_error(fb_err) == "transient":
                fb.record_failure()
            raise
        else:
            fb.record_success()
        finally:
            if pc is not None:
                pc.in_fallback = False
        from .exec.fallback import plan_tables

        tables = sorted(plan_tables(lp))
        m = QueryMetrics(
            query_type="fallback",
            strategy="host-pandas",
            executor="device+fallback" if assists["n"] else "fallback",
            datasource=tables[0] if len(tables) == 1 else "",
            query_id=current_query_id(),
            rows_scanned=plan_input_rows(lp, self.catalog),
            total_ms=(_time.perf_counter() - t0) * 1e3,
            assist_subplans=assists["n"],
        )
        if pc is not None and pc.is_partial:
            # partial-result discipline (GL16xx): partial=True always
            # travels with its coverage fraction
            m.partial = True
            m.coverage = pc.coverage()
            m.rows_seen = pc.rows_seen
            m.delta_rows_seen = pc.delta_rows_seen
        self._last_engine_metrics = m
        # the host interpreter publishes into the process registry like
        # the device engines do (obs/): fallback traffic must be visible
        # in the fleet-level counts, not just last_metrics
        record_query_metrics(m, "partial" if m.partial else "ok")
        return df

    def _result_key(self, rw: Rewrite, ds=None):
        """Result-cache key of a rewrite, or None when it isn't cacheable
        (unknown table / exact-distinct outer shape).  Deliberately
        EXCLUDES the segment uid set and the datasource version: entries
        carry the version they were computed at (serve/result_cache.py),
        which is what lets an append REUSE the cached historical partial
        instead of missing outright.  Dictionary content stays in the
        key — a dictionary extension remaps code spaces, and a stale
        state under extended dictionaries would decode wrong groups."""
        if rw.exact_distinct is not None:
            return None
        ds = ds or self.catalog.get(rw.datasource)
        if ds is None:
            return None
        from .exec.lowering import _dict_signature

        return (
            rw.to_json(),
            ds.name,
            _dict_signature(ds),
            repr(rw.output_columns),
            repr(rw.grouping_sets),
            repr(rw.host_post_exprs),
            repr(rw.residual_having),
            repr(self.config),
        )

    def _cached_result(self, rw: Rewrite, rkey=None, allow_delta=True):
        """Serve a result-cache hit — version-exact, or delta-aware when
        only appends separate the cached snapshot from the live one
        (serve/result_cache.py).  The serving core restamps last_metrics
        so they describe THIS query (a prior fallback would otherwise
        leave executor="fallback" pinned on a cached device hit).
        Returns None on a miss."""
        if self.config.result_cache_entries <= 0:
            return None
        ds = self.catalog.get(rw.datasource)
        if ds is None:
            return None
        rkey = rkey or self._result_key(rw, ds)
        if rkey is None:
            return None
        return self.serve.cached_result(
            rw, ds, rkey, allow_delta=allow_delta
        )

    def _post_process(self, rw: Rewrite, ds, df):
        """Host-side result shaping every engine answer passes through —
        shared by live execution, the delta-aware cache refresh, and the
        progressive SQL surface so the three can never drift."""
        # FD grouping pruning: decode the hidden max-over-codes carriers
        # back into the pruned columns BEFORE residuals/projection, so
        # downstream expressions see the restored values
        for out_name, hidden, dim_col in rw.fd_restores:
            raw = np.asarray(df[hidden], dtype=np.float64)
            codes = np.where(np.isnan(raw), -1, raw).astype(np.int64)
            df[out_name] = ds.dicts[dim_col].decode(codes)
            df = df.drop(columns=[hidden])

        # host-side residuals (the DruidStrategy projection-fixup analog)
        for name, e in rw.host_post_exprs:
            df[name] = _eval_host(e, df)
        if rw.residual_having is not None:
            mask = np.asarray(_eval_host(rw.residual_having, df), dtype=bool)
            df = df[mask].reset_index(drop=True)
        if rw.output_columns:
            cols = [c for c in rw.output_columns if c in df.columns]
            extra = [c for c in df.columns if c not in cols and c == "__grouping_id"]
            df = df[cols + extra]
        return df

    def _fusable(self, rw: Rewrite, ds) -> bool:
        """May this rewrite ride the micro-batch fusion / state-capture
        path?  GroupBy-family only, no grouping sets (their expansion
        already batches), and the executing backend's own gate — both
        the single-device engine and the mesh's unified SPMD arena
        (parallel/distributed.py) implement `fusable`, so mesh-routed
        dashboards batch exactly like local ones (sparse/adaptive tiers
        and arena-ineligible layouts decline on either backend)."""
        if rw.grouping_sets or rw.exact_distinct is not None:
            return False
        if not isinstance(
            rw.query, (Q.GroupByQuery, Q.TimeseriesQuery, Q.TopNQuery)
        ):
            return False
        return self._engine_for(rw).fusable(rw.query, ds)

    def execute_rewrite(self, rw: Rewrite, use_result_cache: bool = True):
        import pandas as pd

        if rw.exact_distinct is not None:
            return self._execute_exact_distinct(
                rw.exact_distinct, use_result_cache=use_result_cache
            )
        ds = self.catalog.get(rw.datasource)
        if ds is None:
            raise RewriteError(f"unknown table {rw.datasource!r}")

        rkey = None
        if use_result_cache and self.config.result_cache_entries > 0:
            rkey = self._result_key(rw, ds)
            hit = self._cached_result(rw, rkey)
            if hit is not None:
                return hit

        # broker mode (cluster/, ISSUE 16): a covered SQL query scatters
        # to the historicals and gathers through the merge tree; the
        # result cache above rides the broker (exact hits never leave
        # this process), fusion below stays local-only.  Partial answers
        # never enter the cache (the pc.triggered guard at the bottom).
        if self.cluster is not None and self.cluster.covers(rw.query, ds):
            if not rw.grouping_sets and rw.exact_distinct is None:
                df = self.cluster.execute(rw.query, ds)
                self._last_engine_metrics = self.cluster.last_metrics
                df = self._post_process(rw, ds, df)
                if rkey is not None:
                    from .resilience import current_partial

                    pc = current_partial()
                    if pc is None or not pc.triggered:
                        self.serve.store_result(rw, ds, rkey, df)
                return df

        engine = self._engine_for(rw)
        state = None
        fusable = self._fusable(rw, ds)
        fused = (
            self.serve.fused_execute(rw.query, ds, engine=engine)
            if fusable else None
        )
        if fused is not None:
            df, state, m = fused
            self._last_engine_metrics = m
        elif rw.grouping_sets and isinstance(rw.query, Q.GroupByQuery):
            df = execute_grouping_sets(
                rw.query, rw.grouping_sets, ds, engine
            )
            self._last_engine_metrics = getattr(
                engine, "last_metrics", None
            )
        elif (
            fusable
            and rkey is not None
            and self.config.result_cache_delta_reuse
        ):
            # capture the merged host partial state alongside the normal
            # execution: the delta-aware result cache stores it so the
            # NEXT append refreshes this answer by scanning only the
            # delta (serve/result_cache.py)
            with engine.state_capture() as cap:
                df = engine.execute(rw.query, ds)
            state = cap["state"]
            self._last_engine_metrics = getattr(
                engine, "last_metrics", None
            )
        else:
            df = engine.execute(rw.query, ds)
            self._last_engine_metrics = getattr(
                engine, "last_metrics", None
            )

        df = self._post_process(rw, ds, df)
        if rkey is not None:
            from .resilience import current_partial

            pc = current_partial()
            # a deadline-truncated answer must NEVER enter the result
            # cache: it would be served back as the exact answer to the
            # next identical (undeadlined) query
            if pc is None or not pc.triggered:
                self.serve.store_result(rw, ds, rkey, df, state=state)
        return df

    def _execute_exact_distinct(self, spec, use_result_cache: bool = True):
        """Two-phase exact COUNT(DISTINCT): run the inner rewrite (grouped by
        dims + distinct columns on device), then re-aggregate on host —
        the reference's pushHLLTODruid=false shape, where Spark finished the
        distinct exactly after the Druid scan."""
        import pandas as pd

        inner = self.execute_rewrite(
            spec.inner, use_result_cache=use_result_cache
        )
        agg_kwargs = {
            name: pd.NamedAgg(column=name, aggfunc=op)
            for name, op in spec.outer_ops
        }
        for out, col in spec.distinct_outs:
            # pandas nunique skips None/NaN — SQL COUNT(DISTINCT) semantics
            agg_kwargs[out] = pd.NamedAgg(column=col, aggfunc="nunique")
        if spec.dim_names:
            df = (
                inner.groupby(list(spec.dim_names), as_index=False, dropna=False)
                .agg(**agg_kwargs)
            )
        else:
            df = pd.DataFrame(
                {
                    name: [getattr(inner[a.column], a.aggfunc)()]
                    for name, a in agg_kwargs.items()
                }
            )
        for c in spec.count_like:
            if c in df:
                df[c] = df[c].astype(np.int64)
        for out, _ in spec.distinct_outs:
            df[out] = df[out].astype(np.int64)
        for name, s, c in spec.avg_div:
            with np.errstate(divide="ignore", invalid="ignore"):
                df[name] = np.where(
                    df[c] != 0, df[s] / np.where(df[c] == 0, 1, df[c]), np.nan
                )
        for name, e in spec.post_exprs:
            df[name] = _eval_host(e, df)
        if spec.having is not None:
            mask = np.asarray(_eval_host(spec.having, df), dtype=bool)
            df = df[mask].reset_index(drop=True)
        if spec.sort_keys:
            df = df.sort_values(
                [c for c, _ in spec.sort_keys],
                ascending=[a for _, a in spec.sort_keys],
                kind="stable",
            )
        if spec.offset:
            df = df.iloc[spec.offset:]
        if spec.limit is not None:
            df = df.head(spec.limit)
        cols = [c for c in spec.output_columns if c in df.columns]
        return df[cols].reset_index(drop=True)

    def _engine_for(self, rw: Rewrite):
        phys = rw.physical
        # ONE routing decision, shared with breaker selection: branching
        # on _backend_for here is what keeps its "can never disagree"
        # docstring true — an edit to the mesh condition lands on both
        if self._backend_for(rw) == "mesh":
            if self._dist_engine is None:
                from .parallel.distributed import DistributedEngine
                from .parallel.mesh import make_mesh

                self._dist_engine = DistributedEngine(
                    mesh=make_mesh(*phys.mesh_shape)
                )
            # route mesh kernels by the SESSION's cost constants, not
            # a fresh file load — re-synced EVERY call (same as the
            # local engine below) so a replaced ctx.config is honored
            self._dist_engine._calibrated_cfg = self.config
            # the mesh path reports to ITS OWN breaker: a sick mesh
            # trips only itself, single-device queries stay routed
            self._sync_engine_resilience(self._dist_engine, "mesh")
            return self._dist_engine
        # the engine's adaptive tier picks its compact-domain kernel from
        # the session's cost constants, not a fresh file load
        self.engine._calibrated_cfg = self.config
        self._sync_engine_resilience(self.engine)
        if self.engine.strategy != phys.strategy:
            self.engine.strategy = phys.strategy
            # strategy participates in the engine's program cache key, so
            # flipping it is safe (distinct cache entries)
        return self.engine

    # -- DataFrame-ish builder (the reference's "sourceDataframe" analog) ----

    def sql_arrow(self, sql_text: str):
        """`sql()` with the result as a `pyarrow.Table` (SURVEY §7 L-api:
        results as Arrow/pandas).  NULLs in dimension columns become Arrow
        nulls; NaN metrics stay floating-point NaN (SQL NULL for floats)."""
        return _to_arrow(self.sql(sql_text))

    def table(self, name: str) -> "TableQuery":
        return TableQuery(self, name)


def _eval_host(e: E.Expr, df) -> np.ndarray:
    """Evaluate a residual expression over the result table (aggregate
    outputs / dimensions) host-side — tiny data, numpy semantics."""
    from .plan.expr import compile_expr

    cols = {c: np.asarray(df[c]) for c in df.columns}
    # raw_strings: result columns hold decoded strings, so HAVING/post-expr
    # string comparisons use plain numpy elementwise semantics
    fn = compile_expr(_aggref_to_col(e), raw_strings=True)
    return np.asarray(fn(cols))


def execute_grouping_sets(q: Q.GroupByQuery, grouping_sets, ds, engine):
    """CUBE/ROLLUP/GROUPING SETS: one kernel pass per set, absent
    dimensions emitted as nulls, plus a __grouping_id bitmask (SQL
    GROUPING_ID semantics: bit i set => dim i aggregated away).

    Shared by the SQL path (rw.grouping_sets) and the serving path (a wire
    groupBy's subtotalsSpec, server.py) — the two must not drift."""
    import pandas as pd

    from .resilience import current_partial

    all_dims = q.dimensions
    frames = []
    k = len(all_dims)
    # the limit/order spec applies to the COMBINED result, not per set —
    # and a per-set sort would crash on sets that drop the orderBy dimension
    subs = [
        dataclasses.replace(
            q,
            dimensions=tuple(all_dims[i] for i in s),
            subtotals=(),
            limit_spec=None,
        )
        for s in grouping_sets
    ]
    # per-grouping-set coverage attribution (ROADMAP 3(c)): each set's
    # scan is its OWN accounting pass; the collector archives every pass
    # (labeled with the set's dimension list) instead of letting the
    # last subquery's begin_pass erase its predecessors — coverage and
    # the partial histogram then describe the WHOLE expansion, and
    # df.attrs carries the per-set breakdown
    pc = current_partial()
    set_labels = None
    if pc is not None:
        # under the collector's lock: collect_sets is `_lock`-owned and
        # the runtime witness enforces it (an off-lock flip here was the
        # first divergence graftsan caught on the shipped tree)
        pc.arm_set_collection()
        set_labels = [
            ",".join(all_dims[i].name for i in s) or "()"
            for s in grouping_sets
        ]
    # dispatch every set's device program before fetching any result:
    # N sequential executions behind a network-tunneled TPU pay N full
    # round trips; the batch path overlaps them
    if hasattr(engine, "execute_groupby_batch"):
        results = engine.execute_groupby_batch(
            subs, ds, set_labels=set_labels
        )
    else:
        results = []
        for i, sub in enumerate(subs):
            if pc is not None and set_labels is not None:
                pc.set_label = set_labels[i]
            results.append(engine.execute(sub, ds))
    if pc is not None:
        pc.finish_sets()
    for s, f in zip(grouping_sets, results):
        gid = 0
        present = set(s)
        for i in range(k):
            if i not in present:
                gid |= 1 << (k - 1 - i)
                f[all_dims[i].name] = None
        f["__grouping_id"] = gid
        frames.append(f)
    df = pd.concat(frames, ignore_index=True)
    order = [d.name for d in all_dims]
    rest = [c for c in df.columns if c not in order]
    df = df[order + rest]
    if q.limit_spec is not None:
        from .exec.finalize import apply_limit_spec

        df = apply_limit_spec(df, q.limit_spec).reset_index(drop=True)
    return df


def _aggref_to_col(e: E.Expr) -> E.Expr:
    if isinstance(e, E.AggRef):
        return E.Col(e.name)
    if isinstance(e, (E.Literal, E.Col)):
        return e
    kw = {}
    for f in dataclasses.fields(e):  # type: ignore[arg-type]
        v = getattr(e, f.name)
        if isinstance(v, E.Expr):
            kw[f.name] = _aggref_to_col(v)
        elif isinstance(v, tuple) and v and isinstance(v[0], E.Expr):
            kw[f.name] = tuple(_aggref_to_col(x) for x in v)
        else:
            kw[f.name] = v
    return type(e)(**kw)


def _infer_schema(cols, time_column):
    dims, mets = [], []
    for k, v in cols.items():
        if k == time_column:
            continue
        arr = np.asarray(v)
        if arr.dtype.kind in ("U", "S", "O"):
            dims.append(k)
        else:
            mets.append(k)
    return dims, mets


# ---------------------------------------------------------------------------
# Fluent DataFrame-style query builder
# ---------------------------------------------------------------------------


class TableQuery:
    """Fluent DataFrame-style API over the same planner — the analog of
    driving the reference through Spark DataFrames instead of SQL.  Every
    method returns a NEW TableQuery (immutable chaining, like DataFrames);
    `collect()` plans, executes on the device, and falls back to the host
    interpreter exactly like the SQL path when the rewrite fails."""

    def __init__(self, ctx: TPUOlapContext, table: str):
        self.ctx = ctx
        self._table = table
        self._filter: Optional[E.Expr] = None
        self._select: List[Tuple[str, E.Expr]] = []
        self._groups: List[Tuple[str, E.Expr]] = []
        self._aggs: List[L.AggExpr] = []
        self._having: Optional[E.Expr] = None
        self._sort: List[L.SortKey] = []
        self._limit: Optional[int] = None
        self._offset: int = 0

    def _copy(self) -> "TableQuery":
        out = TableQuery(self.ctx, self._table)
        out._filter = self._filter
        out._select = list(self._select)
        out._groups = list(self._groups)
        out._aggs = list(self._aggs)
        out._having = self._having
        out._sort = list(self._sort)
        out._limit = self._limit
        out._offset = self._offset
        return out

    @staticmethod
    def _as_expr(x) -> E.Expr:
        return E.Col(x) if isinstance(x, str) else x

    def filter(self, e: E.Expr) -> "TableQuery":
        out = self._copy()
        out._filter = e if out._filter is None else E.BoolOp(
            "and", (out._filter, e)
        )
        return out

    where = filter  # Spark/SQL spelling

    def select(self, *exprs, **named) -> "TableQuery":
        """Projection for non-aggregate queries: select("a", "b") or
        select(rev=E.Col("price") * E.Col("qty"))."""
        out = self._copy()
        for x in exprs:
            e = self._as_expr(x)
            out._select.append((x if isinstance(x, str) else str(e), e))
        for name, x in named.items():
            out._select.append((name, self._as_expr(x)))
        return out

    def group_by(self, *exprs, **named) -> "TableQuery":
        out = self._copy()
        for x in exprs:
            e = self._as_expr(x)
            out._groups.append((x if isinstance(x, str) else str(e), e))
        for name, x in named.items():
            out._groups.append((name, self._as_expr(x)))
        return out

    def agg(self, **named) -> "TableQuery":
        """agg(total=("sum", "revenue"), n=("count", None), ...); the arg
        may be a column name or an Expr (sum over an expression)."""
        out = self._copy()
        for name, spec in named.items():
            fn, arg = spec if isinstance(spec, tuple) else (spec, None)
            arg_e = self._as_expr(arg) if arg is not None else None
            out._aggs.append(L.AggExpr(name, fn, arg_e))
        return out

    def having(self, e: E.Expr) -> "TableQuery":
        """Filter over aggregate outputs: reference agg outputs by their
        `agg(...)` names via E.AggRef (or E.Col of the output name)."""
        out = self._copy()
        out._having = e if out._having is None else E.BoolOp(
            "and", (out._having, e)
        )
        return out

    def order_by(self, key, ascending: bool = True) -> "TableQuery":
        out = self._copy()
        out._sort.append(L.SortKey(self._as_expr(key), ascending))
        return out

    def limit(self, n: int, offset: int = 0) -> "TableQuery":
        out = self._copy()
        out._limit = n
        out._offset = offset
        return out

    def _logical(self) -> L.LogicalPlan:
        base: L.LogicalPlan = L.Scan(self._table)
        if self._filter is not None:
            base = L.Filter(self._filter, base)
        if self._groups or self._aggs:
            if self._select:
                raise ValueError(
                    "select() is for non-aggregate queries; grouped "
                    "outputs are named by group_by()/agg()"
                )
            post = tuple(
                (n, E.Col(n)) for n, _ in self._groups
            ) + tuple((a.name, E.AggRef(a.name)) for a in self._aggs)
            plan: L.LogicalPlan = L.Aggregate(
                tuple(self._groups),
                tuple(self._aggs),
                base,
                post_exprs=post,
            )
            if self._having is not None:
                plan = L.Having(_col_to_aggref(self._having, self._aggs), plan)
        else:
            if self._having is not None:
                raise ValueError("having() requires group_by()/agg()")
            plan = (
                L.Project(tuple(self._select), base) if self._select else base
            )
        if self._sort:
            keys = tuple(
                L.SortKey(_col_to_aggref(k.expr, self._aggs), k.ascending)
                for k in self._sort
            )
            plan = L.Sort(keys, plan)
        if self._limit is not None:
            plan = L.Limit(self._limit, plan, self._offset)
        return plan

    def collect(self):
        from .resilience import deadline_scope, partial_scope

        lp = self._logical()
        with self.ctx.tracer.query_trace(
            query_type="dataframe", slow_ms=self.ctx.config.slow_query_ms
        ), deadline_scope(self.ctx.config.query_timeout_ms), partial_scope(
            self.ctx.config.partial_results
        ):
            with span(SPAN_PLAN):
                try:
                    rw = self.ctx._planner().plan(lp)
                except RewriteError as err:
                    rw, plan_err = None, err
            if rw is None:
                return self.ctx._stamp_receipt(
                    self.ctx._stamp_partial(
                        self.ctx._run_fallback(lp, plan_err)
                    )
                )
            with span(SPAN_EXECUTE):
                df = self.ctx._stamp_partial(
                    self.ctx._execute_with_resilience(rw, lp)
                )
            return self.ctx._stamp_receipt(df)

    def collect_arrow(self):
        """`collect()` as a `pyarrow.Table`."""
        return _to_arrow(self.collect())

    def explain(self) -> str:
        return self.ctx._planner().explain(self._logical())


def _to_arrow(df):
    import pyarrow as pa

    return pa.Table.from_pandas(df, preserve_index=False)


def _col_to_aggref(e: E.Expr, aggs) -> E.Expr:
    """In HAVING/ORDER BY over a grouped TableQuery, a Col naming an agg
    output means the aggregate (SQL alias semantics)."""
    names = {a.name for a in aggs}
    return E.map_expr(
        e,
        lambda x: E.AggRef(x.name)
        if isinstance(x, E.Col) and x.name in names
        else x,
    )


# module-level default context (the implicit SQLContext analog)
_default_ctx: Optional[TPUOlapContext] = None


def default_context() -> TPUOlapContext:
    global _default_ctx
    if _default_ctx is None:
        _default_ctx = TPUOlapContext()
    return _default_ctx


def register_table(*a, **kw):
    return default_context().register_table(*a, **kw)


def sql(text: str):
    return default_context().sql(text)


def table(name: str) -> TableQuery:
    return default_context().table(name)


def explain(text: str) -> str:
    return default_context().explain(text)
