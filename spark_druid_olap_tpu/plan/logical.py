"""Logical plan — the planner's input language.

Reference parity: the reference pattern-matches *Catalyst* logical plans
(Aggregate / Project / Filter / Sort / Limit / Join over a relation) inside
`DruidPlanner`'s transforms (SURVEY.md §2 DruidPlanner/AggregateTransform rows
`[U]`).  We are standalone, so we define our own small logical algebra with
the same node set; the SQL frontend (sql/) and the DataFrame-style builder
(api.py) both lower to it.  Expressions inside nodes are `plan.expr.Expr`
trees (the Catalyst-expression analog).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

from .expr import Expr


class LogicalPlan:
    def children(self) -> Tuple["LogicalPlan", ...]:
        return ()

    def pretty(self, indent: int = 0) -> str:
        pad = "  " * indent
        head = pad + self._label()
        return "\n".join([head] + [c.pretty(indent + 1) for c in self.children()])

    def _label(self) -> str:
        return type(self).__name__


@dataclasses.dataclass(frozen=True)
class Scan(LogicalPlan):
    """Scan of a registered datasource (the `DruidRelation` leaf analog)."""

    table: str

    def _label(self):
        return f"Scan({self.table})"


@dataclasses.dataclass(frozen=True)
class Filter(LogicalPlan):
    condition: Expr
    child: LogicalPlan

    def children(self):
        return (self.child,)

    def _label(self):
        return f"Filter({self.condition})"


@dataclasses.dataclass(frozen=True)
class Project(LogicalPlan):
    exprs: Tuple[Tuple[str, Expr], ...]  # (output name, expression)
    child: LogicalPlan

    def children(self):
        return (self.child,)

    def _label(self):
        return "Project(" + ", ".join(n for n, _ in self.exprs) + ")"


@dataclasses.dataclass(frozen=True)
class AggExpr:
    """One aggregate output: fn over an expression, optional DISTINCT and
    FILTER (the Catalyst AggregateExpression analog)."""

    name: str
    fn: str  # sum | count | min | max | avg | count_distinct |
    #          approx_count_distinct | hll | theta | approx_quantile
    arg: Optional[Expr]  # None for count(*)
    distinct: bool = False
    filter: Optional[Expr] = None
    args: tuple = ()  # extra literal args (approx_quantile: fraction[, k])

    def __str__(self):
        inner = "*" if self.arg is None else str(self.arg)
        extra = "".join(f", {a}" for a in self.args)
        d = "DISTINCT " if self.distinct else ""
        f = f" FILTER ({self.filter})" if self.filter is not None else ""
        return f"{self.fn}({d}{inner}{extra}){f}"


@dataclasses.dataclass(frozen=True)
class Aggregate(LogicalPlan):
    group_exprs: Tuple[Tuple[str, Expr], ...]
    agg_exprs: Tuple[AggExpr, ...]
    child: LogicalPlan
    # post-aggregate projections: expressions over agg output names (AggRef)
    post_exprs: Tuple[Tuple[str, Expr], ...] = ()
    # grouping sets: tuples of indices into group_exprs; () = plain GROUP BY
    grouping_sets: Tuple[Tuple[int, ...], ...] = ()

    def children(self):
        return (self.child,)

    def _label(self):
        g = ", ".join(n for n, _ in self.group_exprs)
        a = ", ".join(str(a) for a in self.agg_exprs)
        gs = f" sets={self.grouping_sets}" if self.grouping_sets else ""
        return f"Aggregate(by=[{g}], aggs=[{a}]{gs})"


@dataclasses.dataclass(frozen=True)
class Having(LogicalPlan):
    condition: Expr  # over AggRef / group columns
    child: LogicalPlan

    def children(self):
        return (self.child,)

    def _label(self):
        return f"Having({self.condition})"


@dataclasses.dataclass(frozen=True)
class SortKey:
    expr: Expr
    ascending: bool = True


@dataclasses.dataclass(frozen=True)
class Sort(LogicalPlan):
    keys: Tuple[SortKey, ...]
    child: LogicalPlan

    def children(self):
        return (self.child,)

    def _label(self):
        return "Sort(" + ", ".join(
            f"{k.expr} {'asc' if k.ascending else 'desc'}" for k in self.keys
        ) + ")"


@dataclasses.dataclass(frozen=True)
class Limit(LogicalPlan):
    n: int
    child: LogicalPlan
    offset: int = 0

    def children(self):
        return (self.child,)

    def _label(self):
        return f"Limit({self.n}" + (f", offset={self.offset})" if self.offset else ")")


@dataclasses.dataclass(frozen=True)
class Union(LogicalPlan):
    """SQL set operation: branches aligned by position, column names from
    the first branch.  Not pushable (the reference fell back to Spark for
    every set operation); the host fallback implements the semantics.

    `op` is one of:
      union_all      bag concatenation
      union          set union (distinct rows; NULLs compare equal)
      intersect      set intersection (distinct)
      intersect_all  bag intersection (per-row multiplicity = min of counts)
      except         set difference (distinct left rows absent from right)
      except_all     bag difference (multiplicity = left count - right count)

    union_all / union / intersect / intersect_all are associative and may
    be n-ary; except / except_all are built strictly binary (left fold)."""

    branches: Tuple[LogicalPlan, ...]
    op: str = "union_all"

    def children(self):
        return self.branches

    def _label(self):
        return f"Union({self.op}, {len(self.branches)} branches)"


@dataclasses.dataclass(frozen=True)
class WindowExpr:
    """One window-function column.  `frame` is a pair of row offsets
    relative to the current row, inclusive: -N = N PRECEDING, 0 = CURRENT
    ROW, +N = N FOLLOWING, None = UNBOUNDED on that side.  A frame of
    None (no explicit frame) means the SQL default: with ORDER BY, RANGE
    UNBOUNDED PRECEDING..CURRENT ROW (peer rows included); without,
    the whole partition."""

    name: str
    fn: str
    arg: Optional["Expr"]  # None for row_number/rank/dense_rank/count(*)
    args: tuple = ()  # literal extras: NTILE n, LAG/LEAD offset + default
    filter: Optional["Expr"] = None  # FILTER (WHERE ...) on window aggs
    partition: Tuple["Expr", ...] = ()
    order_exprs: Tuple["Expr", ...] = ()
    order_asc: Tuple[bool, ...] = ()
    frame: Optional[Tuple[Optional[int], Optional[int]]] = None


@dataclasses.dataclass(frozen=True)
class Window(LogicalPlan):
    """Window-function evaluation over the child's frame (the reference
    fell back to Spark for every OVER clause; here the host fallback
    implements the semantics).  `wins` computes one hidden column per
    window call; `out_exprs` is the full SELECT-order output list — a
    plain Col(name) passes a child column through, anything else is
    evaluated over the frame (with window columns visible)."""

    wins: Tuple[WindowExpr, ...]
    out_exprs: Tuple[Tuple[str, "Expr"], ...]
    child: LogicalPlan

    def children(self):
        return (self.child,)

    def _label(self):
        fns = ", ".join(f"{w.fn}->{w.name}" for w in self.wins)
        return f"Window([{fns}])"


@dataclasses.dataclass(frozen=True)
class SubqueryScan(LogicalPlan):
    """A derived table's scope boundary: the outer query may reference ONLY
    `columns` (the subquery's SELECT list; None when it is SELECT *).  The
    planner never rewrites through it — without the boundary the planner's
    Project-collapsing walk would silently resolve renamed-away names
    against the base table."""

    child: LogicalPlan
    columns: Optional[Tuple[str, ...]]
    alias: str = ""

    def children(self):
        return (self.child,)

    def _label(self):
        cols = "*" if self.columns is None else ", ".join(self.columns)
        return f"SubqueryScan({self.alias}: [{cols}])"


@dataclasses.dataclass(frozen=True)
class Join(LogicalPlan):
    """Equi-join; the star-schema collapse (JoinTransform analog) eliminates
    these when they conform to the declared star schema."""

    left: LogicalPlan
    right: LogicalPlan
    left_keys: Tuple[str, ...]
    right_keys: Tuple[str, ...]
    how: str = "inner"

    def children(self):
        return (self.left, self.right)

    def _label(self):
        return (
            f"Join({self.how}, "
            + " AND ".join(
                f"{l}={r}" for l, r in zip(self.left_keys, self.right_keys)
            )
            + ")"
        )
