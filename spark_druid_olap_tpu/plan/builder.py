"""QueryBuilder — the immutable accumulator threaded through rewrite rules.

Reference parity: `DruidQueryBuilder` (SURVEY.md §2 `[U]`, expected
`org/sparklinedata/druid/DruidQueryBuilder.scala`): an immutable state object
each transform extends — dimensions, aggregations, post-aggs, filters,
interval, limit, output-attribute mapping, AVG-rewrite bookkeeping — with
failure at any stage dropping the rewrite candidate.  Same shape here;
`build()` picks the most specific query type (Timeseries ⊂ TopN ⊂ GroupBy,
§3.2) exactly as `DruidPlanner` does when choosing among candidates.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..models import aggregations as A
from ..models import query as Q
from ..models.dimensions import DimensionSpec
from ..models.filters import Filter
from .expr import Expr


@dataclasses.dataclass(frozen=True)
class QueryBuilder:
    datasource: str
    dimensions: Tuple[DimensionSpec, ...] = ()
    aggregations: Tuple[A.Aggregation, ...] = ()
    post_aggregations: Tuple[A.PostAggregation, ...] = ()
    filter: Optional[Filter] = None
    intervals: Tuple[Tuple[int, int], ...] = ()
    having: Optional[Q.Having] = None
    limit_spec: Optional[Q.LimitSpec] = None
    virtual_columns: Tuple[Q.VirtualColumn, ...] = ()
    granularity: str = "all"
    # TopN candidate state (LimitTransform)
    topn_metric: Optional[str] = None
    topn_threshold: Optional[int] = None
    topn_descending: bool = True
    # bookkeeping
    output_columns: Tuple[str, ...] = ()  # SELECT-order output names
    residual_having: Optional[Expr] = None  # host-evaluated HAVING residue
    host_post_exprs: Tuple[Tuple[str, Expr], ...] = ()  # host-eval projections
    grouping_sets: Tuple[Tuple[int, ...], ...] = ()

    def with_(self, **kw) -> "QueryBuilder":
        return dataclasses.replace(self, **kw)

    def add_filter(self, f: Filter) -> "QueryBuilder":
        from ..models.filters import And

        if self.filter is None:
            return self.with_(filter=f)
        return self.with_(filter=And((self.filter, f)))

    def add_interval(self, iv: Tuple[int, int]) -> "QueryBuilder":
        return self.with_(intervals=self.intervals + (iv,))

    def add_virtual(self, vc: Q.VirtualColumn) -> "QueryBuilder":
        if any(v.name == vc.name for v in self.virtual_columns):
            return self
        return self.with_(virtual_columns=self.virtual_columns + (vc,))

    # -- query-type choice ---------------------------------------------------

    @property
    def is_timeseries(self) -> bool:
        return (
            len(self.dimensions) == 1
            and self.dimensions[0].dimension == "__time"
            and self.dimensions[0].granularity is not None
            # an extraction folds buckets (EXTRACT(MONTH...)): the result is
            # keyed by the extracted value, not the bucket timeline
            and self.dimensions[0].extraction is None
            and self.topn_threshold is None
            and not self.grouping_sets
            # TimeseriesQuery has no limit/sort/having surface — emitting it
            # anyway would silently drop them (fuzz seed 31); stay GroupBy
            and self.limit_spec is None
            and self.having is None
        )

    @property
    def is_topn(self) -> bool:
        return (
            len(self.dimensions) == 1
            and self.dimensions[0].granularity is None
            and self.topn_threshold is not None
            and self.topn_metric is not None
            and self.having is None
            and not self.grouping_sets
        )

    def build(self) -> Q.QuerySpec:
        """Most specific query type wins: Timeseries ⊂ TopN ⊂ GroupBy."""
        if self.is_timeseries:
            return Q.TimeseriesQuery(
                datasource=self.datasource,
                granularity=self.dimensions[0].granularity,  # type: ignore[arg-type]
                aggregations=self.aggregations,
                post_aggregations=self.post_aggregations,
                filter=self.filter,
                intervals=self.intervals,
                virtual_columns=self.virtual_columns,
                output_name=self.dimensions[0].name,
            )
        if self.is_topn:
            return Q.TopNQuery(
                datasource=self.datasource,
                dimension=self.dimensions[0],
                metric=self.topn_metric,  # type: ignore[arg-type]
                threshold=self.topn_threshold,  # type: ignore[arg-type]
                aggregations=self.aggregations,
                post_aggregations=self.post_aggregations,
                filter=self.filter,
                intervals=self.intervals,
                granularity=self.granularity,
                virtual_columns=self.virtual_columns,
                descending=self.topn_descending,
            )
        return Q.GroupByQuery(
            datasource=self.datasource,
            dimensions=self.dimensions,
            aggregations=self.aggregations,
            post_aggregations=self.post_aggregations,
            filter=self.filter,
            having=self.having,
            limit_spec=self.limit_spec,
            intervals=self.intervals,
            granularity=self.granularity,
            virtual_columns=self.virtual_columns,
            subtotals=self.grouping_sets,
        )
