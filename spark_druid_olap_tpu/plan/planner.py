"""TPUPlanner — drives the rewrite rules over a logical plan.

Reference parity: `DruidPlanner` + `DruidStrategy` (SURVEY.md §2/§3.2 `[U]`):
fold the registered transforms over the logical plan, threading an immutable
`QueryBuilder`; a failure drops the candidate; the surviving builder picks the
most specific query type and the cost model picks the execution shape.  Where
the reference's fallback is "let vanilla Spark run the plan", our fallback for
non-aggregate plans is a Scan query (`nonAggregateQueryHandling=scan` analog);
plans we cannot rewrite raise `RewriteError` with the reason (surfaced by
`explain`, the `EXPLAIN DRUID REWRITE` analog, SURVEY.md §3.4).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

from ..catalog.segment import DataSource
from ..config import SessionConfig
from ..models import query as Q
from . import expr as E
from . import logical as L
from .builder import QueryBuilder
from ..utils.log import get_logger

log = get_logger("plan.planner")
from .cost import PhysicalPlan, choose_physical
from .transforms import (
    RewriteError,
    RewritePolicyError,
    apply_sort_limit,
    substitute,
    translate_aggregate,
    translate_filter,
    translate_group_expr,
    translate_having,
    translate_post_expr,
)


@dataclasses.dataclass
class ExactDistinctOuter:
    """Host re-aggregation spec for exact COUNT(DISTINCT) two-phase plans
    (count_distinct_mode="exact" — the reference's pushHLLTODruid=false:
    keep the distinct exact by finishing it engine-side instead of pushing a
    sketch).  The inner rewrite groups by (dims..., distinct cols...); the
    outer pass re-aggregates on host: re-aggregable aggs fold with
    `outer_ops`, distinct outputs count unique non-null values, AVG is
    recomputed from its sum/count parts."""

    inner: "Rewrite"
    dim_names: Tuple[str, ...]  # outer grouping columns
    distinct_outs: Tuple[Tuple[str, str], ...]  # (output name, inner column)
    outer_ops: Tuple[Tuple[str, str], ...]  # (column, "sum"|"min"|"max")
    count_like: Tuple[str, ...]  # columns cast back to int64 after the fold
    avg_div: Tuple[Tuple[str, str, str], ...]  # (name, sum col, count col)
    post_exprs: Tuple[Tuple[str, E.Expr], ...]
    having: Optional[E.Expr]
    sort_keys: Tuple[Tuple[str, bool], ...]  # (column, ascending)
    limit: Optional[int]
    offset: int
    output_columns: Tuple[str, ...]


@dataclasses.dataclass
class Rewrite:
    """The planner's output: query spec + everything the execution layer
    needs to finalize results (the DruidStrategy 'projection fixup' analog)."""

    datasource: str
    builder: QueryBuilder
    query: Q.QuerySpec
    physical: PhysicalPlan
    output_columns: Tuple[str, ...]
    dim_names: Tuple[str, ...]
    residual_having: Optional[E.Expr]
    host_post_exprs: Tuple[Tuple[str, E.Expr], ...]
    grouping_sets: Tuple[Tuple[int, ...], ...]
    is_scan: bool = False
    exact_distinct: Optional[ExactDistinctOuter] = None
    # FD grouping pruning: (output column, hidden dimCodeMax agg, source
    # dimension) triples the API decodes back after execution
    fd_restores: Tuple[Tuple[str, str, str], ...] = ()

    def to_json(self) -> str:
        return json.dumps(self.query.to_druid(), indent=2, default=str)


class Planner:
    def __init__(self, catalog, cfg: Optional[SessionConfig] = None,
                 n_devices: int = 1):
        self.catalog = catalog  # name -> DataSource (catalog/cache.py)
        self.cfg = cfg or SessionConfig()
        self.n_devices = n_devices

    # -- plan walking --------------------------------------------------------

    def plan(self, lp: L.LogicalPlan) -> Rewrite:
        if not self.cfg.enable_rewrites:
            raise RewriteError("rewrites disabled by config")
        if _plan_contains_subquery(lp):
            # semi-joins cannot lower to the row kernel in ANY position
            # (WHERE, HAVING, SELECT expressions, agg FILTERs); reject at
            # PLAN time so the host fallback executes the whole query —
            # a residual would only fail later, mid-execution
            raise RewriteError("subqueries require host fallback execution")
        limit: Optional[int] = None
        offset = 0
        sort_keys: List[L.SortKey] = []
        having_cond: Optional[E.Expr] = None
        top_projections: Optional[Tuple[Tuple[str, E.Expr], ...]] = None

        node = lp
        while True:
            if isinstance(node, L.Limit):
                limit, offset = node.n, node.offset
                node = node.child
            elif isinstance(node, L.Sort):
                sort_keys = list(node.keys)
                node = node.child
            elif isinstance(node, L.Having):
                having_cond = node.condition
                node = node.child
            elif isinstance(node, L.Project) and _contains_aggregate(node.child):
                top_projections = node.exprs
                node = node.child
            else:
                break

        if isinstance(node, L.Aggregate):
            return self._plan_aggregate(
                node, limit, offset, sort_keys, having_cond, top_projections
            )
        # non-aggregate query -> Scan (reference nonAggregateQueryHandling)
        if self.cfg.non_aggregate_query_handling != "scan":
            raise RewriteError("non-aggregate query (scan handling disabled)")
        return self._plan_scan(node, limit, offset, sort_keys, top_projections)

    # -- aggregate path ------------------------------------------------------

    def _collapse_below(
        self, node: L.LogicalPlan
    ) -> Tuple[str, Dict[str, E.Expr], List[E.Expr]]:
        """Walk Filter/Project chain below the Aggregate to the Scan leaf.
        Returns (table, projection env, filter conditions bottom-up).
        Join subtrees are collapsed by the star-schema transform
        (plan/star_join.py) before this walk."""
        env: Dict[str, E.Expr] = {}
        filters: List[E.Expr] = []
        while True:
            if isinstance(node, L.Scan):
                return node.table, env, filters
            if isinstance(node, L.Filter):
                filters.append(substitute(node.condition, env))
                node = node.child
                continue
            if isinstance(node, L.Project):
                for name, e in node.exprs:
                    env[name] = substitute(e, env)
                node = node.child
                continue
            if isinstance(node, L.Join):
                from .star_join import collapse_star_join

                node = collapse_star_join(node, self.catalog, self.cfg)
                continue
            raise RewriteError(
                f"cannot rewrite plan node {type(node).__name__} under Aggregate"
            )

    def _plan_aggregate(
        self,
        agg: L.Aggregate,
        limit: Optional[int],
        offset: int,
        sort_keys: List[L.SortKey],
        having_cond: Optional[E.Expr],
        top_projections,
    ) -> Rewrite:
        if self.cfg.count_distinct_mode == "exact" and any(
            _is_count_distinct(ae) for ae in agg.agg_exprs
        ):
            return self._plan_exact_distinct(
                agg, limit, offset, sort_keys, having_cond, top_projections
            )
        table, env, filters = self._collapse_below(agg.child)
        ds = self._ds(table)
        b = QueryBuilder(datasource=table)

        # ProjectFilterTransform
        for cond in filters:
            b = translate_filter(cond, ds, b)

        # AggregateTransform: grouping exprs
        dims = []
        dim_names = []
        # a getter, not a snapshot dict: copying every registered table on
        # every aggregate plan would cost O(total lookup size) per query
        # even when no LOOKUP appears
        lookups = (
            self.catalog.lookup
            if hasattr(self.catalog, "lookup")
            else None
        )
        for name, ge in agg.group_exprs:
            spec, b = translate_group_expr(
                name, substitute(ge, env), ds, b, lookups=lookups
            )
            dims.append(spec)
            dim_names.append(spec.name)
        b = b.with_(dimensions=tuple(dims))

        # AggregateTransform: aggregate functions
        aggs: List = []
        posts: List = []
        for ae in agg.agg_exprs:
            ae2 = L.AggExpr(
                ae.name,
                ae.fn,
                substitute(ae.arg, env) if ae.arg is not None else None,
                ae.distinct,
                substitute(ae.filter, env) if ae.filter is not None else None,
                ae.args,
            )
            a_list, p_list, b = translate_aggregate(ae2, ds, b, self.cfg)
            aggs.extend(a_list)
            posts.extend(p_list)
        # identical hidden aggregations collapse (frozen dataclasses hash):
        # N APPROX_QUANTILE fractions over one column emit N copies of the
        # same content-named sketch — compute it once
        aggs = list(dict.fromkeys(aggs))
        b = b.with_(
            aggregations=tuple(aggs), post_aggregations=tuple(posts)
        )

        # post-aggregate projections (SELECT exprs over agg outputs)
        host_posts: List[Tuple[str, E.Expr]] = []
        output_columns: List[str] = []
        post_names = {p.name for p in posts}
        agg_names = [a.name for a in aggs]
        if top_projections is not None:
            out_exprs = top_projections
        elif agg.post_exprs:
            out_exprs = agg.post_exprs
        else:
            out_exprs = None
        if out_exprs is not None:
            new_posts = list(b.post_aggregations)
            for name, pe in out_exprs:
                if isinstance(pe, E.Col) and pe.name in dim_names:
                    output_columns.append(pe.name)
                    continue
                if isinstance(pe, E.AggRef) and (
                    pe.name in agg_names or pe.name in post_names
                ):
                    output_columns.append(pe.name)
                    continue
                p = translate_post_expr(name, pe)
                if p is not None:
                    new_posts.append(p)
                else:
                    host_posts.append((name, pe))
                output_columns.append(name)
            b = b.with_(post_aggregations=tuple(new_posts))
        else:
            output_columns = dim_names + [
                n for n in agg_names if not _is_avg_helper(n, post_names)
            ] + list(post_names)

        # HAVING
        residual_having = None
        if having_cond is not None:
            spec, residual_having = translate_having(having_cond)
            if spec is not None:
                b = b.with_(having=spec)

        # grouping sets (CUBE/ROLLUP)
        if agg.grouping_sets:
            b = b.with_(grouping_sets=tuple(agg.grouping_sets))

        # LimitTransform.  A sort key naming a HOST-residual projection
        # (e.g. a GROUPING() bit expression) cannot be ordered on the
        # device — the column only exists after host finalize; route the
        # whole query to the fallback rather than KeyError mid-execution.
        host_post_names = {n for n, _ in host_posts}
        for k in sort_keys:
            if (
                isinstance(k.expr, (E.Col, E.AggRef))
                and k.expr.name in host_post_names
            ):
                raise RewriteError(
                    f"ORDER BY {k.expr.name} references a host-residual "
                    "projection; host fallback required"
                )
        rankable = agg_names + list(post_names)
        b = apply_sort_limit(b, sort_keys, limit, offset, self.cfg, rankable)
        b = b.with_(output_columns=tuple(output_columns))

        # guards (maxResultCardinality analog).  Declared functional
        # dependencies tighten the estimate: grouping by a dependent column
        # alongside its determinant cannot multiply the group count
        # (c_city -> c_nation: |city x nation| is really <= |city|).
        star = (
            self.catalog.star_schema(table)
            if hasattr(self.catalog, "star_schema")
            else None
        )

        # FD grouping pruning (the reference's FunctionalDependency put to
        # work): a grouped column determined by another grouped column is
        # dropped from the kernel grouping — every row of a group shares
        # one value for it, so a hidden max-over-codes aggregation carries
        # it and the API decodes it back.  TPC-H q10's GROUP BY
        # c_custkey, c_name, c_acctbal, ... would otherwise build a group
        # domain that is the PRODUCT of those cardinalities.
        fd_restores: List[Tuple[str, str, str]] = []
        if star is not None and not agg.grouping_sets and len(dims) > 1:
            limit_cols = {
                c.dimension for c in (b.limit_spec.columns if b.limit_spec else ())
            }
            deps_by_col = {}
            for fd in star.functional_dependencies:
                if fd.dependent != fd.determinant:
                    deps_by_col.setdefault(fd.dependent, set()).add(
                        fd.determinant
                    )
            kept = []
            pruned = []
            plain = {
                d.dimension
                for d in dims
                if (d.extraction is None and d.granularity is None
                    and d.dimension in ds.dicts)
            }
            pruned_names: set = set()
            for d in dims:
                # greedy in declaration order; the pruned-so-far check
                # keeps one member of any FD cycle (a->b, b->a) and
                # guarantees every pruned column's determinant chain
                # bottoms out in a KEPT dimension
                prunable = (
                    d.extraction is None
                    and d.granularity is None
                    and d.dimension in ds.dicts
                    # the code-max carrier rides f32: codes >= 2^24 would
                    # round and decode to an ADJACENT dictionary entry
                    and ds.dicts[d.dimension].cardinality < (1 << 24)
                    and d.name not in limit_cols
                    and any(
                        det in plain
                        and det != d.dimension
                        and det not in pruned_names
                        for det in deps_by_col.get(d.dimension, ())
                    )
                )
                if prunable:
                    pruned.append(d)
                    pruned_names.add(d.dimension)
                else:
                    kept.append(d)
            if pruned:
                from ..models import aggregations as A

                for d in pruned:
                    hidden = f"__fd_{d.name}"
                    aggs.append(A.DimCodeMax(hidden, d.dimension))
                    fd_restores.append((d.name, hidden, d.dimension))
                dims = kept
                b = b.with_(
                    dimensions=tuple(dims), aggregations=tuple(aggs)
                )
                log.debug(
                    "FD pruning: %s carried by hidden code aggs; kernel "
                    "dims now %s",
                    [r[0] for r in fd_restores],
                    [d.name for d in dims],
                )
        fd_dependents = set()
        if star is not None:
            grouped = {d.dimension for d in dims}
            for fd in star.functional_dependencies:
                if (
                    fd.determinant in grouped
                    and fd.dependent in grouped
                    and fd.dependent != fd.determinant
                ):
                    fd_dependents.add(fd.dependent)
        G_result = 1  # distinct output rows (FD-aware): the result guard
        G_kernel = 1  # kernel group-id domain (row-major product): cost model
        for d in dims:
            card = _estimate_dim_cardinality(d, ds)
            G_kernel *= card
            if d.dimension not in fd_dependents:
                G_result *= card
        if G_result > self.cfg.max_result_cardinality:
            raise RewritePolicyError(
                f"estimated result cardinality {G_result} exceeds "
                f"max_result_cardinality={self.cfg.max_result_cardinality}"
            )

        q = b.build()
        phys = choose_physical(q, ds, G_kernel, self.cfg, self.n_devices)
        log.debug(
            "rewrite: %s over %s -> %s strategy=%s distributed=%s groups=%d",
            type(q).__name__, table, phys, phys.strategy, phys.distributed,
            G_kernel,
        )
        return Rewrite(
            datasource=table,
            builder=b,
            query=q,
            physical=phys,
            output_columns=tuple(output_columns),
            dim_names=tuple(dim_names),
            residual_having=residual_having,
            host_post_exprs=tuple(host_posts),
            grouping_sets=tuple(agg.grouping_sets),
            fd_restores=tuple(fd_restores),
        )

    # -- exact COUNT(DISTINCT): two-phase plan -------------------------------

    def _plan_exact_distinct(
        self,
        agg: L.Aggregate,
        limit: Optional[int],
        offset: int,
        sort_keys: List[L.SortKey],
        having_cond: Optional[E.Expr],
        top_projections,
    ) -> Rewrite:
        """count_distinct_mode="exact": rewrite COUNT(DISTINCT x) by adding x
        to the inner grouping and finishing on host (pandas re-aggregation).
        Every other aggregate must be re-aggregable (sum/count -> sum,
        min/max -> min/max, avg -> recomputed from sum/count parts); approx
        sketches cannot be folded exactly and are rejected in this mode."""
        if agg.grouping_sets:
            raise RewriteError(
                "exact COUNT(DISTINCT) with CUBE/ROLLUP unsupported "
                "(set count_distinct_mode='approx')"
            )
        distinct_outs: List[Tuple[str, str]] = []
        inner_aggs: List[L.AggExpr] = []
        outer_ops: List[Tuple[str, str]] = []
        count_like: List[str] = []
        avg_div: List[Tuple[str, str, str]] = []
        extra_dims: Dict[str, E.Expr] = {}
        for ae in agg.agg_exprs:
            if ae.distinct and ae.fn in ("sum", "avg"):
                raise RewriteError(
                    f"{ae.fn.upper()}(DISTINCT) cannot re-aggregate exactly"
                )
            if _is_count_distinct(ae):
                if not isinstance(ae.arg, E.Col):
                    raise RewriteError(
                        "exact COUNT(DISTINCT) over expressions unsupported"
                    )
                if ae.filter is not None:
                    raise RewriteError(
                        "exact COUNT(DISTINCT) with FILTER unsupported"
                    )
                extra_dims.setdefault(ae.arg.name, ae.arg)
                distinct_outs.append((ae.name, ae.arg.name))
            elif ae.fn == "approx_count_distinct":
                raise RewriteError(
                    "cannot mix exact COUNT(DISTINCT) with approx sketches "
                    "in one query (sketch states do not re-aggregate "
                    "exactly); use count_distinct_mode='approx'"
                )
            elif ae.fn == "avg":
                # NOT the "__sum"/"__cnt" suffixes: the inner planner's
                # default projection drops those as AVG-rewrite helpers
                s, c = f"__ed_{ae.name}_sum", f"__ed_{ae.name}_cnt"
                inner_aggs.append(
                    L.AggExpr(s, "sum", ae.arg, False, ae.filter)
                )
                inner_aggs.append(
                    L.AggExpr(c, "count", None, False, ae.filter)
                )
                outer_ops += [(s, "sum"), (c, "sum")]
                count_like.append(c)
                avg_div.append((ae.name, s, c))
            elif ae.fn in ("sum", "count"):
                inner_aggs.append(ae)
                outer_ops.append((ae.name, "sum"))
                if ae.fn == "count":
                    count_like.append(ae.name)
            elif ae.fn in ("min", "max"):
                inner_aggs.append(ae)
                outer_ops.append((ae.name, ae.fn))
            else:
                raise RewriteError(
                    f"aggregate {ae.fn!r} cannot re-aggregate exactly "
                    "alongside exact COUNT(DISTINCT)"
                )

        inner = L.Aggregate(
            agg.group_exprs
            + tuple((f"__dist_{n}", e) for n, e in extra_dims.items()),
            tuple(inner_aggs),
            agg.child,
        )
        try:
            inner_rw = self._plan_aggregate(inner, None, 0, [], None, None)
        except RewritePolicyError:
            # preserve the subtype: a policy rejection (e.g. the inner
            # grouping exceeds the cardinality guard) must not be laundered
            # into a plain RewriteError the host fallback would swallow
            raise
        except RewriteError as e:
            raise RewriteError(
                "exact COUNT(DISTINCT) plans its argument as an inner "
                f"grouping dimension, which failed: {e} (metric-typed "
                "arguments need count_distinct_mode='approx')"
            ) from e
        distinct_outs = [
            (name, f"__dist_{col}") for name, col in distinct_outs
        ]

        dim_names = tuple(n for n, _ in agg.group_exprs)
        known = (
            set(dim_names)
            | {n for n, _ in outer_ops}
            | {n for n, _ in distinct_outs}
            | {n for n, _, _ in avg_div}
        )
        post_exprs: List[Tuple[str, E.Expr]] = []
        output_columns: List[str] = []
        out_exprs = (
            top_projections if top_projections is not None else agg.post_exprs
        )
        if out_exprs:
            for name, pe in out_exprs:
                if isinstance(pe, (E.Col, E.AggRef)) and pe.name in known:
                    output_columns.append(pe.name)
                    continue
                post_exprs.append((name, pe))
                output_columns.append(name)
        else:
            # declaration order, matching the approx path's default (column
            # order must not depend on count_distinct_mode)
            output_columns = list(dim_names) + [
                ae.name for ae in agg.agg_exprs
            ]

        skeys: List[Tuple[str, bool]] = []
        for sk in sort_keys:
            if isinstance(sk.expr, (E.Col, E.AggRef)):
                skeys.append((sk.expr.name, sk.ascending))
            else:
                raise RewriteError(
                    "exact COUNT(DISTINCT) supports ORDER BY on named "
                    "columns only"
                )

        return dataclasses.replace(
            inner_rw,
            exact_distinct=ExactDistinctOuter(
                inner=inner_rw,
                dim_names=dim_names,
                distinct_outs=tuple(distinct_outs),
                outer_ops=tuple(outer_ops),
                count_like=tuple(count_like),
                avg_div=tuple(avg_div),
                post_exprs=tuple(post_exprs),
                having=having_cond,
                sort_keys=tuple(skeys),
                limit=limit,
                offset=offset,
                output_columns=tuple(output_columns),
            ),
        )

    # -- scan path -----------------------------------------------------------

    def _plan_scan(
        self, node, limit, offset, sort_keys, top_projections
    ) -> Rewrite:
        env: Dict[str, E.Expr] = {}
        filters: List[E.Expr] = []
        proj: Optional[Tuple[Tuple[str, E.Expr], ...]] = top_projections
        while not isinstance(node, L.Scan):
            if isinstance(node, L.Filter):
                filters.append(substitute(node.condition, env))
                node = node.child
            elif isinstance(node, L.Project):
                if proj is None:
                    proj = node.exprs
                for name, e in node.exprs:
                    env[name] = substitute(e, env)
                node = node.child
            else:
                raise RewriteError(
                    f"cannot rewrite scan node {type(node).__name__}"
                )
        ds = self._ds(node.table)
        b = QueryBuilder(datasource=node.table)
        for cond in filters:
            b = translate_filter(cond, ds, b)
        columns: List[str] = []
        vcols: List[Q.VirtualColumn] = []
        if proj:
            for name, e in proj:
                e = substitute(e, env)
                if isinstance(e, E.Col):
                    columns.append(e.name)
                else:
                    vcols.append(Q.VirtualColumn(name, e))
                    columns.append(name)
        else:
            columns = [c.name for c in ds.columns]
        # ORDER BY on a row scan must be honored or rejected — silently
        # returning unsorted rows under LIMIT is wrong data
        order_by = []
        known = set(columns) | {c.name for c in ds.columns}
        for sk in sort_keys or ():
            # a SELECT alias of a computed projection is sortable as-is
            # (the engine evaluates virtual columns before sorting) —
            # check the raw name BEFORE substitution expands the alias
            if isinstance(sk.expr, E.Col) and sk.expr.name in set(columns):
                name = sk.expr.name
            else:
                e = substitute(sk.expr, env)
                if not isinstance(e, E.Col) or e.name not in known:
                    raise RewriteError(
                        f"cannot ORDER BY {sk.expr} on a non-aggregate "
                        "scan (only projected or physical columns)"
                    )
                name = e.name
            order_by.append(
                Q.OrderByColumnSpec(
                    name,
                    "ascending" if sk.ascending else "descending",
                )
            )
        q = Q.ScanQuery(
            datasource=node.table,
            columns=tuple(columns),
            filter=b.filter,
            intervals=b.intervals,
            limit=limit,
            virtual_columns=tuple(vcols),
            order_by=tuple(order_by),
            offset=offset or 0,
        )
        phys = choose_physical(q, ds, 1, self.cfg, self.n_devices)
        return Rewrite(
            datasource=node.table,
            builder=b,
            query=q,
            physical=phys,
            output_columns=tuple(columns),
            dim_names=(),
            residual_having=None,
            host_post_exprs=(),
            grouping_sets=(),
            is_scan=True,
        )

    # -- explain (EXPLAIN DRUID REWRITE analog, SURVEY.md §3.4) --------------

    def explain(self, lp: L.LogicalPlan) -> str:
        lines = ["== Logical Plan ==", lp.pretty(), ""]
        try:
            rw = self.plan(lp)
            lines += [
                "== Rewrite: %s ==" % type(rw.query).__name__,
                rw.to_json(),
                "",
                "== Physical Plan ==",
                rw.physical.describe(),
            ]
            if rw.residual_having is not None:
                lines.append(f"residual HAVING (host): {rw.residual_having}")
            if rw.host_post_exprs:
                lines.append(
                    "residual projections (host): "
                    + ", ".join(n for n, _ in rw.host_post_exprs)
                )
        except RewriteError as e:
            lines += ["== Rewrite FAILED ==", str(e)]
            if self.cfg.fallback_execution:
                lines.append(
                    "(executes on the host fallback interpreter; "
                    "QueryMetrics.executor will report 'fallback')"
                )
        return "\n".join(lines)

    def _ds(self, table: str) -> DataSource:
        ds = self.catalog.get(table)
        if ds is None:
            raise RewriteError(f"unknown table {table!r}")
        return ds


def _estimate_dim_cardinality(d, ds: DataSource) -> int:
    """Plan-time group-count estimate per dimension (drives the result-
    cardinality guard and the cost model; the engine computes exact counts
    at lowering)."""
    from ..models.dimensions import TimeFieldExtraction

    if isinstance(d.extraction, TimeFieldExtraction):
        field = d.extraction.field
        if field == "year":
            iv = ds.interval()
            if iv is not None:
                return max(1, int((iv[1] - iv[0]) // 31_536_000_000) + 2)
            return 300
        return {"month": 12, "day": 31, "hour": 24, "minute": 60,
                "second": 60}[field]
    if d.dimension in ds.dicts:
        return ds.cardinality(d.dimension) + 1
    if d.dimension == "__time" and d.granularity is not None:
        iv = ds.interval()
        from ..utils.granularity import granularity_period_ms

        try:
            p = granularity_period_ms(d.granularity)
        except ValueError:
            p = None
        if iv is not None and p:
            return max(1, int((iv[1] - iv[0]) // p) + 2)
    return 4096


def _plan_contains_subquery(lp: L.LogicalPlan) -> bool:
    """Any IN/scalar subquery in any expression position of the plan tree."""
    from .transforms import _contains_subquery

    def exprs_of(node):
        if isinstance(node, (L.Filter, L.Having)):
            yield node.condition
        elif isinstance(node, L.Project):
            for _, e in node.exprs:
                yield e
        elif isinstance(node, L.Aggregate):
            for _, e in node.group_exprs:
                yield e
            for ae in node.agg_exprs:
                if ae.arg is not None:
                    yield ae.arg
                if ae.filter is not None:
                    yield ae.filter
            for _, e in node.post_exprs:
                yield e
        elif isinstance(node, L.Sort):
            for k in node.keys:
                yield k.expr

    if any(_contains_subquery(e) for e in exprs_of(lp)):
        return True
    return any(_plan_contains_subquery(c) for c in lp.children())


def _contains_aggregate(n: L.LogicalPlan) -> bool:
    if isinstance(n, L.Aggregate):
        return True
    return any(_contains_aggregate(c) for c in n.children())


def _is_avg_helper(name: str, post_names) -> bool:
    return name.endswith("__sum") or name.endswith("__cnt")


def _is_count_distinct(ae: L.AggExpr) -> bool:
    """Both liftings of COUNT(DISTINCT x): the SQL parser produces
    fn="count_distinct"; the builder API produces fn="count" + distinct."""
    return ae.fn == "count_distinct" or (ae.fn == "count" and ae.distinct)
