"""Measure cost-model constants on the live backend.

Reference parity: the reference's `DruidQueryCostModel` ships tunable cost
constants via SQLConf with documented defaults the operator is expected to
re-tune per deployment (SURVEY.md §2 cost-model row `[U]`).  Round 1 shipped
guessed constants; this module replaces guessing with measurement: it times
the actual kernels the engine dispatches —

* dense one-hot partial aggregation (`ops/groupby.dense_partial_aggregate`)
  -> `cost_per_row_dense` (us per row per 128-wide group tile),
* scatter segment-sum                    -> `cost_per_row_scatter` (us/row),
* psum of a [G, M] state over the mesh   -> `collective_bytes_per_us`,
* a tiny end-to-end SPMD dispatch        -> `cost_dispatch_us`

— and writes `calibration.json` at the repo root, which
`SessionConfig.load_calibrated()` reads.  Run on the TPU to get real-chip
constants; on CPU the constants are CPU-honest (the planner's choices then
match the backend that will actually execute).
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Dict, Optional

import numpy as np

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_PATH = os.path.join(_REPO_ROOT, "calibration.json")


def sidecar_path(platform: str, root: Optional[str] = None) -> str:
    """calibration.<platform>.json next to DEFAULT_PATH.  Single owner of
    the per-platform sidecar naming: calibrate() writes it, and both
    SessionConfig.load_calibrated and bench._ensure_calibration read it —
    three sites that must never drift apart."""
    return os.path.join(
        root if root is not None else _REPO_ROOT,
        "calibration.%s.json" % platform,
    )


def _timeit_synced(fn, reps: int = 3) -> float:
    """Median wall seconds of fn(salt) where fn must RETURN A SCALAR jax
    array and the timer fetches its 4 bytes to the host each rep.

    Two hazards this exists for, both observed on the tunneled TPU backend
    (round 5): (a) `block_until_ready` returned in ~23 us for a 64 MiB
    reduction — 2.9 TB/s, 3.5x the chip's HBM datasheet, physically
    impossible — so completion must be proven by a device_get, and (b) a
    remote client may serve a repeated IDENTICAL dispatch from a cache, so
    every rep perturbs the input with a fresh `salt` argument.  The scalar
    return keeps the D2H leg at 4 bytes so the measurement is not polluted
    by result-transfer time."""
    import numpy as _np

    fn(0)  # warmup / compile (salt is a traced argument: no recompile)
    ts = []
    for i in range(reps):
        t0 = time.perf_counter()
        _np.asarray(fn(i + 1))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def _slope_us_per_row(
    fn,
    rows_hi: int,
    rows_lo: int,
    reps: int = 3,
    t_rtt: float = 0.0,
    floor: float = 1e-6,
) -> float:
    """Per-row cost in us from the SLOPE between two input sizes.

    fn(n, salt) -> scalar jax array, running the kernel over the first `n`
    rows.  Wall time at each size includes the backend's fixed dispatch +
    sync overhead (66 ms round-trip on the tunneled TPU — larger than most
    kernels' entire device time); the slope cancels it, which is the only
    honest way to extract per-row constants through such a floor.

    An INVERTED slope (t_hi <= t_lo: the size delta sat below timer
    jitter, or the kernel pads both sizes to one internal capacity rung)
    must not persist as "this kernel is free" — that is the silent-
    miscalibration class this module exists to kill.  Below the
    plausibility `floor` (default 1e-6 us/row: a million rows per wall-us
    exceeds any single-chip memory system; callers may raise it to a
    kernel-specific bound like "sorting cannot beat a quarter-scatter")
    the single-point estimate with the measured round-trip subtracted is
    used instead."""
    t_hi = _timeit_synced(lambda s: fn(rows_hi, s), reps=reps)
    t_lo = _timeit_synced(lambda s: fn(rows_lo, s), reps=reps)
    return _slope_or_fallback(t_hi, t_lo, rows_hi, rows_lo, t_rtt, floor)


def _slope_or_fallback(
    t_hi: float,
    t_lo: float,
    n_hi: int,
    n_lo: int,
    t_rtt: float,
    floor: float = 1e-6,
) -> float:
    """The shared inverted-slope guard (see _slope_us_per_row): per-unit
    cost from the slope when plausible, else single-point minus the
    measured round-trip.  One owner so the floor and fallback formula
    cannot silently diverge between call sites."""
    slope = (t_hi - t_lo) * 1e6 / max(n_hi - n_lo, 1)
    if slope < floor:
        slope = max((t_hi - t_rtt) * 1e6 / n_hi, floor)
    return slope


def _clamp_bandwidth(bytes_per_s: float) -> float:
    """Keep a measured bandwidth inside physical reality: no link or
    memory system this code can meet moves more than 2 TB/s, and anything
    under 1 MB/s means the measurement (not the link) failed.  An
    out-of-range value would otherwise be persisted and silently load as
    'transfers are free' (or 'impossible') in every later session."""
    return min(max(bytes_per_s, 1e6), 2e12)


def calibrate(
    rows: int = 1 << 23,
    groups: int = 1024,
    save_path: Optional[str] = DEFAULT_PATH,
    budget_s: Optional[float] = None,
) -> Dict[str, float]:
    """`budget_s` caps wall time: over a flaky tunneled accelerator a full
    sweep ran ~26 minutes (every step pays a remote compile), which can eat
    an entire bench window.  When the deadline passes, remaining steps are
    skipped, the file is marked `"partial": true`, and unmeasured constants
    stay at their platform-profile defaults (cost_per_row_compact falls
    back to the scatter floor so the schema check still sees it)."""
    import jax
    import jax.numpy as jnp

    deadline = (
        time.perf_counter() + budget_s if budget_s is not None else None
    )

    def over() -> bool:
        return deadline is not None and time.perf_counter() > deadline

    from ..catalog.segment import ROW_PAD
    from ..ops.groupby import dense_partial_aggregate

    rng = np.random.default_rng(0)
    half = rows // 2
    gid = jnp.asarray(rng.integers(0, groups, size=rows).astype(np.int32))
    mask = jnp.ones(rows, jnp.bool_)
    sv = jnp.asarray(rng.random((rows, 2)).astype(np.float32))
    mmv = jnp.zeros((rows, 0), jnp.float32)
    mmm = jnp.zeros((rows, 0), jnp.bool_)

    def _scalar(out):
        # reduce any kernel output pytree to one f32 on DEVICE so the
        # timing sync fetches 4 bytes, not the whole state
        leaves = [
            l.astype(jnp.float32).sum()
            for l in jax.tree_util.tree_leaves(out)
            if hasattr(l, "dtype")
        ]
        return functools.reduce(jnp.add, leaves)

    # measured round-trip of a near-empty dispatch: the fixed overhead every
    # query pays once (66 ms over the round-5 tunnel, ~100 us locally).
    # Doubles as the single-device cost_dispatch_us; a multi-device sweep
    # below overwrites it with the SPMD-measured value.
    tiny = jnp.ones((64,), jnp.float32)

    @jax.jit
    def _trivial(x, salt):
        return jnp.sum(x) + salt

    t_rtt = _timeit_synced(lambda s: _trivial(tiny, jnp.float32(s)))
    dispatch_overhead_us = t_rtt * 1e6

    # every measured kernel takes its arrays as ARGUMENTS — a closure-
    # captured array is an XLA constant, which (a) invites the compiler to
    # fold the whole measurement away at compile time (observed with the
    # bandwidth loop: a 400 s CPU compile measuring nothing) and (b)
    # embeds megabytes of data in every program a remote-compile backend
    # must ship.  Slices for the low size are taken ONCE, outside timing.
    gid_lo, mask_lo, sv_lo = gid[:half], mask[:half], sv[:half]
    mmv_lo, mmm_lo = mmv[:half], mmm[:half]

    # dense one-hot kernel: us / row / 128-tile, from the two-size slope.
    # G=256 (2 tiles) keeps the measurement cheap on backends where dense
    # is slow (CPU: ~1 us/row/tile); the constant is per-tile, so the
    # planner scales it to any G.
    g_dense = 256
    gid_d = jnp.asarray(rng.integers(0, g_dense, size=rows).astype(np.int32))
    gid_d_lo = gid_d[:half]
    dense_fn = functools.partial(
        dense_partial_aggregate,
        num_groups=g_dense,
        block_rows=min(rows, 1 << 15),
        num_min=0,
        num_max=0,
    )

    @jax.jit
    def dense_k(g, mk, v, mv, mm, salt):
        return _scalar(dense_fn(g, mk, v + salt, mv, mm))

    def dense_at(n, salt):
        if n == rows:
            return dense_k(gid_d, mask, sv, mmv, mmm, jnp.float32(salt))
        return dense_k(
            gid_d_lo, mask_lo, sv_lo, mmv_lo, mmm_lo, jnp.float32(salt)
        )

    tiles = max(1, -(-g_dense // 128))
    cost_per_row_dense = _slope_us_per_row(
        dense_at, rows, half, t_rtt=t_rtt
    ) / tiles

    # scatter kernel: us/row at the base domain, plus the per-group state
    # cost separated from the wide domain's INTERCEPT difference (fixed
    # overheads cancel between the two domains; per-row cost is the slope)
    @functools.partial(jax.jit, static_argnames=("n_seg",))
    def scatter_k(g, v, salt, n_seg):
        return _scalar(
            jax.ops.segment_sum(v + salt, g, num_segments=n_seg)
        )

    t_sc_hi = _timeit_synced(
        lambda s: scatter_k(gid, sv, jnp.float32(s), n_seg=groups)
    )
    t_sc_lo = _timeit_synced(
        lambda s: scatter_k(gid_lo, sv_lo, jnp.float32(s), n_seg=groups)
    )
    cost_per_row_scatter = _slope_or_fallback(
        t_sc_hi, t_sc_lo, rows, half, t_rtt
    )

    wide = 1 << 20
    gid_w = jnp.asarray(rng.integers(0, wide, size=rows).astype(np.int32))
    gid_w_lo = gid_w[:half]

    cost_per_group_state = None
    cost_per_row_scatter_hi = None
    if not over():
        t_w_hi = _timeit_synced(
            lambda s: scatter_k(gid_w, sv, jnp.float32(s), n_seg=wide)
        )
        t_w_lo = _timeit_synced(
            lambda s: scatter_k(gid_w_lo, sv_lo, jnp.float32(s), n_seg=wide)
        )
        # floor: scatter at a WIDER domain can never be cheaper per row
        cost_per_row_scatter_hi = _slope_or_fallback(
            t_w_hi, t_w_lo, rows, half, t_rtt, floor=cost_per_row_scatter
        )
        # intercepts (t minus the per-row part) isolate per-domain fixed
        # work; their difference across the two domains is the per-group
        # state cost, with the backend's dispatch overhead cancelled
        icept_wide = t_w_hi * 1e6 - rows * cost_per_row_scatter_hi
        icept_lo = t_sc_hi * 1e6 - rows * cost_per_row_scatter
        cost_per_group_state = max(
            (icept_wide - icept_lo) / max(wide - groups, 1), 0.0
        )

    # sort-compaction (sparse) path: us/row on the same wide domain
    from ..ops.sparse_groupby import sparse_partial_aggregate

    sp = functools.partial(
        sparse_partial_aggregate,
        num_groups=wide,
        num_min=0,
        num_max=0,
        inner_strategy="segment",
    )
    try:
        if over():
            raise TimeoutError

        @jax.jit
        def sparse_k(g, mk, v, mv, mm, salt):
            return _scalar(sp(g, mk, v + salt, mv, mm))

        def sparse_at_n(n, salt):
            if n == rows:
                return sparse_k(gid_w, mask, sv, mmv, mmm, jnp.float32(salt))
            return sparse_k(
                gid_w_lo, mask_lo, sv_lo, mmv_lo, mmm_lo, jnp.float32(salt)
            )

        # the sparse kernel pads its sort to a capacity RUNG, so two probe
        # sizes can land on the SAME rung and their slope collapses to
        # noise (the first slope-methodology TPU sweep measured 1e-9
        # us/row — "sorting is free" — and would have routed every query
        # to sparse).  Floor: a full sort cannot plausibly beat a
        # quarter-scatter pass over the same rows.
        cost_per_row_sparse = _slope_us_per_row(
            sparse_at_n, rows, half, t_rtt=t_rtt,
            floor=cost_per_row_scatter / 4,
        )
    except Exception:
        cost_per_row_sparse = None  # declined (overflow etc.): keep default

    # filter-compaction pass measured DIRECTLY on compact_rows (cumsum +
    # searchsorted + gathers): the round-3 by-subtraction estimate came
    # out ~3x low (it credited the tier-1 sort with time the cumsum
    # actually spent), which routed SF10 q3_2 onto a sparse plan that a
    # measured scatter beat 539 ms to 763 ms.  FLOOR at the scatter
    # per-row cost: compaction reads at least as much as a scatter pass.
    cost_per_row_compact = None
    if not over():
        from ..ops.sparse_groupby import compact_rows

        sel = 0.01
        mask_sel = jnp.asarray(rng.random(rows) < sel)
        mask_sel_lo = mask_sel[:half]
        cap = max(4096, int(rows * sel * 2))
        fc = functools.partial(compact_rows, capacity=cap)
        try:
            @jax.jit
            def compact_k(g, mk, v, mv, mm, salt):
                return _scalar(fc(g, mk, v + salt, mv, mm))

            def compact_at_n(n, salt):
                if n == rows:
                    return compact_k(
                        gid_w, mask_sel, sv, mmv, mmm, jnp.float32(salt)
                    )
                return compact_k(
                    gid_w_lo, mask_sel_lo, sv_lo, mmv_lo, mmm_lo,
                    jnp.float32(salt),
                )

            cost_per_row_compact = max(
                _slope_us_per_row(compact_at_n, rows, half, t_rtt=t_rtt),
                cost_per_row_scatter,
            )
        except Exception:
            pass

    # measured streaming bandwidth: read passes over 64 MiB vs 16 MiB f32
    # arrays (a reduction — the memory-bound shape every scan kernel bottoms
    # out at), slope in bytes so the dispatch floor cancels.  This is the
    # ROOFLINE DENOMINATOR for QueryMetrics.bytes_scanned/s; "achieved",
    # not a datasheet number.  (The round-4 single-point measurement read
    # 2.9 TB/s through the tunnel — 3.5x the HBM datasheet — because
    # block_until_ready did not prove completion there.)
    big = jnp.asarray(rng.random(1 << 24).astype(np.float32))

    # K chained passes amplify the device-side scan until it clears the
    # dispatch floor's jitter (one 64 MiB pass is ~80 us at HBM rate —
    # invisible under a 66 ms round-trip that wobbles ~1 ms; K=64 puts
    # ~5 ms of device work behind the slope).  The accumulator feeds back
    # through jnp.abs so XLA cannot factor the reduction out of the loop;
    # abs is one flop/element on a bandwidth-bound pass.
    K = 64
    stream_bytes_per_s = None
    if not over():
        # `big` must arrive as an ARGUMENT: a closure-captured array is an
        # XLA constant, and the compiler constant-folds the whole K-pass
        # loop at compile time (observed: a 400 s CPU compile producing a
        # measurement of nothing)
        @jax.jit
        def stream_k(x, salt):
            def body(_, acc):
                return acc + jnp.sum(jnp.abs(x - acc * 1e-30))

            return jax.lax.fori_loop(0, K, body, jnp.float32(salt))

        big_lo = big[: 1 << 22]
        t_bw_hi = _timeit_synced(lambda s: stream_k(big, s), reps=5)
        t_bw_lo = _timeit_synced(lambda s: stream_k(big_lo, s), reps=5)
        # bandwidths invert under jitter exactly like per-row slopes (a
        # clamped 2 TB/s 'free transfers' file was observed live in
        # review); fall back to single-point minus the measured round-trip
        stream_bytes_per_s = _clamp_bandwidth(
            K * ((1 << 24) - (1 << 22)) * 4
            / max(t_bw_hi - t_bw_lo, 1e-9)
        )
        if stream_bytes_per_s >= 2e12:
            stream_bytes_per_s = _clamp_bandwidth(
                K * (1 << 24) * 4 / max(t_bw_hi - t_rtt, 1e-9)
            )

    # host->device transfer bandwidth, slope over 64 MiB vs 16 MiB puts
    # (each synced by a 4-byte reduction fetch; a fresh salted host array
    # per rep defeats any client-side transfer cache).  On the round-5
    # tunnel this measured ~46 MB/s — the constant that prices device
    # ASSIST h2d and streaming-ingest chunk transfer honestly.
    h2d_bytes_per_s = None
    if not over():
        h2d_host = rng.random(1 << 24).astype(np.float32)

        @jax.jit
        def _touch(x):
            return jnp.sum(x)

        def h2d_at(n, salt):
            h2d_host[0] = salt
            return _touch(jax.device_put(h2d_host[:n]))

        t_h2d_hi = _timeit_synced(lambda s: h2d_at(1 << 24, s))
        t_h2d_lo = _timeit_synced(lambda s: h2d_at(1 << 22, s))
        h2d_bytes_per_s = _clamp_bandwidth(
            ((1 << 24) - (1 << 22)) * 4 / max(t_h2d_hi - t_h2d_lo, 1e-9)
        )
        if h2d_bytes_per_s >= 2e12:  # inverted slope: single-point fallback
            h2d_bytes_per_s = _clamp_bandwidth(
                (1 << 24) * 4 / max(t_h2d_hi - t_rtt, 1e-9)
            )

    out = {
        "cost_per_row_dense": cost_per_row_dense,
        "cost_per_row_scatter": cost_per_row_scatter,
        "stream_bytes_per_s": stream_bytes_per_s,
        "h2d_bytes_per_s": h2d_bytes_per_s,
        "cost_dispatch_us": dispatch_overhead_us,
        "rows": rows,
        "groups": groups,
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        # self-description (VERDICT r4 #8): every constant is the MEDIAN
        # of timed reps (one warmup compile excluded), sync-proven by a
        # 4-byte device_get and — for per-row constants — taken from a
        # two-size SLOPE so the backend's fixed dispatch overhead cancels
        # (methodology: _timeit_synced/_slope_us_per_row).  Kernel
        # constants use 3 reps per size; stream_bytes_per_s uses 5 (its
        # slope sits closest to the dispatch-jitter floor).  budget_s is
        # the wall cap the sweep ran under, None = uncapped
        "samples_per_constant": 3,
        "samples_stream_bw": 5,
        "budget_s": budget_s,
    }
    if cost_per_group_state is not None:
        out["cost_per_group_state"] = cost_per_group_state
    if cost_per_row_scatter_hi is not None:
        out["cost_per_row_scatter_hi"] = cost_per_row_scatter_hi
        out["scatter_lo_groups"] = groups
        out["scatter_hi_groups"] = wide
    if cost_per_row_sparse is not None:
        out["cost_per_row_sparse"] = cost_per_row_sparse
    # always written so consumers can distinguish "measured" from "probe
    # declined" (None) — bench's schema check keys on presence, and a
    # missing key would force recalibration on every run.  An unmeasured
    # (budget-skipped) compact pass reads at least as much as a scatter
    # pass, so the scatter cost is its honest floor
    if cost_per_row_compact is None and over():
        cost_per_row_compact = cost_per_row_scatter
    out["cost_per_row_compact"] = cost_per_row_compact
    # ALWAYS present (VERDICT r4 weak #5: the marker silently vanished in
    # round 4 when a full sweep completed): partial=True means the budget
    # clipped the sweep and unmeasured keys carry profile defaults
    out["partial"] = bool(over())

    # mesh measurements need >1 device (real chips or a CPU-forced mesh)
    n_dev = len(jax.devices())
    if n_dev > 1 and not over():
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS, make_mesh, shard_map_compat

        mesh = make_mesh(n_data=n_dev, n_groups=1)
        state_g, state_m = 4096, 64  # 1 MiB of f32 merge state
        local = jnp.asarray(
            rng.random((n_dev * state_g, state_m)).astype(np.float32)
        )
        sharded = jax.device_put(local, NamedSharding(mesh, P(DATA_AXIS)))

        # salt rides INSIDE the sharded dispatch (x + salt before the
        # collective): a repeated byte-identical program+input pair is
        # exactly what a remote dispatch cache would serve without
        # executing — hazard (b) of _timeit_synced
        @jax.jit
        @functools.partial(
            shard_map_compat,
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P()),
            out_specs=P(),
        )
        def allreduce(x, salt):
            return jnp.sum(jax.lax.psum(x + salt, DATA_AXIS))

        @jax.jit
        @functools.partial(
            shard_map_compat,
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P()),
            out_specs=P(),
        )
        def no_comm(x, salt):
            # the baseline's tiny psum carries the SALT (not a foldable
            # constant) so it survives compilation: it charges the
            # collective's fixed launch latency to the baseline, leaving
            # t_ar - t_base as pure bytes-moved time
            return jnp.sum(jax.lax.psum(salt, DATA_AXIS)) + jnp.sum(x + salt)

        t_ar = _timeit_synced(
            lambda s: allreduce(sharded, jnp.full((1,), s, jnp.float32))
        )
        t_base = _timeit_synced(
            lambda s: no_comm(sharded, jnp.full((1,), s, jnp.float32))
        )
        bytes_moved = 2.0 * (n_dev - 1) / n_dev * state_g * state_m * 4
        t_comm = max(t_ar - t_base, 1e-7)
        out["collective_bytes_per_us"] = bytes_moved / (t_comm * 1e6)

        # dispatch overhead: end-to-end tiny SPMD aggregate incl. host gather
        tiny_rows = ROW_PAD * n_dev
        tgid = jax.device_put(
            np.zeros(tiny_rows, np.int32), NamedSharding(mesh, P(DATA_AXIS))
        )
        tsv = jax.device_put(
            np.ones((tiny_rows, 1), np.float32),
            NamedSharding(mesh, P(DATA_AXIS)),
        )

        @jax.jit
        @functools.partial(
            shard_map_compat,
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P()),
            out_specs=P(),
        )
        def tiny_agg(gid, v, salt):
            return jnp.sum(
                jax.lax.psum(
                    jax.ops.segment_sum(v + salt, gid, num_segments=8),
                    DATA_AXIS,
                )
            )

        t_tiny = _timeit_synced(
            lambda s: tiny_agg(tgid, tsv, jnp.full((1, 1), s, jnp.float32))
        )
        out["cost_dispatch_us"] = t_tiny * 1e6

    if save_path:
        with open(save_path, "w") as f:
            json.dump(out, f, indent=1)
        # per-platform sidecar: CPU and TPU runs alternate on this host and
        # each overwrites the primary file; SessionConfig.load_calibrated
        # falls back to calibration.<platform>.json on a device mismatch so
        # measured constants survive runs on the other backend
        try:
            plat_path = sidecar_path(
                out["platform"], root=os.path.dirname(save_path)
            )
            with open(plat_path, "w") as f:
                json.dump(out, f, indent=1)
        except OSError:
            pass
    return out


if __name__ == "__main__":
    print(json.dumps(calibrate()))
