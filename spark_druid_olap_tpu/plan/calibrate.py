"""Measure cost-model constants on the live backend.

Reference parity: the reference's `DruidQueryCostModel` ships tunable cost
constants via SQLConf with documented defaults the operator is expected to
re-tune per deployment (SURVEY.md §2 cost-model row `[U]`).  Round 1 shipped
guessed constants; this module replaces guessing with measurement: it times
the actual kernels the engine dispatches —

* dense one-hot partial aggregation (`ops/groupby.dense_partial_aggregate`)
  -> `cost_per_row_dense` (us per row per 128-wide group tile),
* scatter segment-sum                    -> `cost_per_row_scatter` (us/row),
* psum of a [G, M] state over the mesh   -> `collective_bytes_per_us`,
* a tiny end-to-end SPMD dispatch        -> `cost_dispatch_us`

— and writes `calibration.json` at the repo root, which
`SessionConfig.load_calibrated()` reads.  Run on the TPU to get real-chip
constants; on CPU the constants are CPU-honest (the planner's choices then
match the backend that will actually execute).
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Dict, Optional

import numpy as np

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_PATH = os.path.join(_REPO_ROOT, "calibration.json")


def _timeit(fn, reps: int = 5) -> float:
    """Median wall seconds of fn() with block_until_ready semantics assumed
    inside fn; one warmup for compile."""
    fn()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def calibrate(
    rows: int = 1 << 20,
    groups: int = 1024,
    save_path: Optional[str] = DEFAULT_PATH,
    budget_s: Optional[float] = None,
) -> Dict[str, float]:
    """`budget_s` caps wall time: over a flaky tunneled accelerator a full
    sweep ran ~26 minutes (every step pays a remote compile), which can eat
    an entire bench window.  When the deadline passes, remaining steps are
    skipped, the file is marked `"partial": true`, and unmeasured constants
    stay at their platform-profile defaults (cost_per_row_compact falls
    back to the scatter floor so the schema check still sees it)."""
    import jax
    import jax.numpy as jnp

    deadline = (
        time.perf_counter() + budget_s if budget_s is not None else None
    )

    def over() -> bool:
        return deadline is not None and time.perf_counter() > deadline

    from ..catalog.segment import ROW_PAD
    from ..ops.groupby import dense_partial_aggregate

    rng = np.random.default_rng(0)
    gid = jnp.asarray(rng.integers(0, groups, size=rows).astype(np.int32))
    mask = jnp.ones(rows, jnp.bool_)
    sv = jnp.asarray(rng.random((rows, 2)).astype(np.float32))
    mmv = jnp.zeros((rows, 0), jnp.float32)
    mmm = jnp.zeros((rows, 0), jnp.bool_)

    # dense one-hot kernel: us / row / 128-tile
    dense_fn = functools.partial(
        dense_partial_aggregate,
        num_groups=groups,
        block_rows=min(rows, 1 << 15),
        num_min=0,
        num_max=0,
    )
    t_dense = _timeit(
        lambda: jax.block_until_ready(dense_fn(gid, mask, sv, mmv, mmm))
    )
    tiles = max(1, -(-groups // 128))
    cost_per_row_dense = t_dense * 1e6 / rows / tiles

    # scatter kernel: us/row at the base domain, plus the per-group state
    # slope measured from a much wider domain
    @jax.jit
    def scatter(gid, v):
        return jax.ops.segment_sum(v, gid, num_segments=groups)

    t_scatter = _timeit(lambda: jax.block_until_ready(scatter(gid, sv)))
    cost_per_row_scatter = t_scatter * 1e6 / rows

    wide = 1 << 20
    gid_w = jnp.asarray(rng.integers(0, wide, size=rows).astype(np.int32))

    cost_per_group_state = None
    cost_per_row_scatter_hi = None
    if not over():
        @jax.jit
        def scatter_wide(gid, v):
            return jax.ops.segment_sum(v, gid, num_segments=wide)

        t_wide = _timeit(
            lambda: jax.block_until_ready(scatter_wide(gid_w, sv))
        )
        # second row count at the same domain separates the per-ROW cost at
        # high G (cache-missing random writes — measured 5x the low-G cost
        # on CPU; the flat model routed SSB q3_2 SF100 onto a 12 s scatter)
        # from the per-GROUP state cost (alloc + merge traffic)
        half = rows // 2
        t_half = _timeit(
            lambda: jax.block_until_ready(
                scatter_wide(gid_w[:half], sv[:half])
            )
        )
        cost_per_row_scatter_hi = max(
            (t_wide - t_half) * 1e6 / max(rows - half, 1),
            cost_per_row_scatter,
        )
        cost_per_group_state = max(
            (t_wide * 1e6 - rows * cost_per_row_scatter_hi) / wide, 0.0
        )

    # sort-compaction (sparse) path: us/row on the same wide domain
    from ..ops.sparse_groupby import sparse_partial_aggregate

    sp = functools.partial(
        sparse_partial_aggregate,
        num_groups=wide,
        num_min=0,
        num_max=0,
        inner_strategy="segment",
    )
    try:
        if over():
            raise TimeoutError
        t_sparse = _timeit(
            lambda: jax.block_until_ready(sp(gid_w, mask, sv, mmv, mmm))
        )
        cost_per_row_sparse = t_sparse * 1e6 / rows
    except Exception:
        cost_per_row_sparse = None  # declined (overflow etc.): keep default

    # filter-compaction pass measured DIRECTLY on compact_rows (cumsum +
    # searchsorted + gathers): the round-3 by-subtraction estimate came
    # out ~3x low (it credited the tier-1 sort with time the cumsum
    # actually spent), which routed SF10 q3_2 onto a sparse plan that a
    # measured scatter beat 539 ms to 763 ms.  FLOOR at the scatter
    # per-row cost: compaction reads at least as much as a scatter pass.
    cost_per_row_compact = None
    if not over():
        from ..ops.sparse_groupby import compact_rows

        sel = 0.01
        mask_sel = jnp.asarray(rng.random(rows) < sel)
        cap = max(4096, int(rows * sel * 2))
        fc = jax.jit(functools.partial(compact_rows, capacity=cap))
        try:
            t_compact = _timeit(
                lambda: jax.block_until_ready(
                    fc(gid_w, mask_sel, sv, mmv, mmm)
                )
            )
            cost_per_row_compact = max(
                t_compact * 1e6 / rows, cost_per_row_scatter
            )
        except Exception:
            pass

    # measured streaming bandwidth: one read pass over a 64 MiB f32 array
    # (a reduction — the memory-bound shape every scan kernel bottoms out
    # at).  This is the ROOFLINE DENOMINATOR for
    # QueryMetrics.bytes_scanned/s; "achieved", not a datasheet number.
    big = jnp.asarray(rng.random(1 << 24).astype(np.float32))

    @jax.jit
    def stream(x):
        return jnp.sum(x)

    t_bw = _timeit(lambda: jax.block_until_ready(stream(big)))
    stream_bytes_per_s = big.size * 4 / max(t_bw, 1e-9)

    out = {
        "cost_per_row_dense": cost_per_row_dense,
        "cost_per_row_scatter": cost_per_row_scatter,
        "stream_bytes_per_s": stream_bytes_per_s,
        "rows": rows,
        "groups": groups,
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        # self-description (VERDICT r4 #8): every constant above is the
        # MEDIAN of this many timed reps (one warmup compile excluded);
        # budget_s is the wall cap the sweep ran under, None = uncapped
        "samples_per_constant": 5,
        "budget_s": budget_s,
    }
    if cost_per_group_state is not None:
        out["cost_per_group_state"] = cost_per_group_state
    if cost_per_row_scatter_hi is not None:
        out["cost_per_row_scatter_hi"] = cost_per_row_scatter_hi
        out["scatter_lo_groups"] = groups
        out["scatter_hi_groups"] = wide
    if cost_per_row_sparse is not None:
        out["cost_per_row_sparse"] = cost_per_row_sparse
    # always written so consumers can distinguish "measured" from "probe
    # declined" (None) — bench's schema check keys on presence, and a
    # missing key would force recalibration on every run.  An unmeasured
    # (budget-skipped) compact pass reads at least as much as a scatter
    # pass, so the scatter cost is its honest floor
    if cost_per_row_compact is None and over():
        cost_per_row_compact = cost_per_row_scatter
    out["cost_per_row_compact"] = cost_per_row_compact
    # ALWAYS present (VERDICT r4 weak #5: the marker silently vanished in
    # round 4 when a full sweep completed): partial=True means the budget
    # clipped the sweep and unmeasured keys carry profile defaults
    out["partial"] = bool(over())

    # mesh measurements need >1 device (real chips or a CPU-forced mesh)
    n_dev = len(jax.devices())
    if n_dev > 1 and not over():
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS, make_mesh

        mesh = make_mesh(n_data=n_dev, n_groups=1)
        state_g, state_m = 4096, 64  # 1 MiB of f32 merge state
        local = jnp.asarray(
            rng.random((n_dev * state_g, state_m)).astype(np.float32)
        )
        sharded = jax.device_put(local, NamedSharding(mesh, P(DATA_AXIS)))

        @jax.jit
        @functools.partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(DATA_AXIS),),
            out_specs=P(),
            check_vma=False,
        )
        def allreduce(x):
            return jax.lax.psum(x, DATA_AXIS)

        @jax.jit
        @functools.partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(DATA_AXIS),),
            out_specs=P(DATA_AXIS),
            check_vma=False,
        )
        def no_comm(x):
            return x * 2.0

        t_ar = _timeit(lambda: jax.block_until_ready(allreduce(sharded)))
        t_base = _timeit(lambda: jax.block_until_ready(no_comm(sharded)))
        bytes_moved = 2.0 * (n_dev - 1) / n_dev * state_g * state_m * 4
        t_comm = max(t_ar - t_base, 1e-7)
        out["collective_bytes_per_us"] = bytes_moved / (t_comm * 1e6)

        # dispatch overhead: end-to-end tiny SPMD aggregate incl. host gather
        tiny_rows = ROW_PAD * n_dev
        tgid = jax.device_put(
            np.zeros(tiny_rows, np.int32), NamedSharding(mesh, P(DATA_AXIS))
        )
        tsv = jax.device_put(
            np.ones((tiny_rows, 1), np.float32),
            NamedSharding(mesh, P(DATA_AXIS)),
        )

        @jax.jit
        @functools.partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(),
            check_vma=False,
        )
        def tiny_agg(gid, v):
            return jax.lax.psum(
                jax.ops.segment_sum(v, gid, num_segments=8), DATA_AXIS
            )

        t_tiny = _timeit(lambda: np.asarray(tiny_agg(tgid, tsv)))
        out["cost_dispatch_us"] = t_tiny * 1e6

    if save_path:
        with open(save_path, "w") as f:
            json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    print(json.dumps(calibrate()))
