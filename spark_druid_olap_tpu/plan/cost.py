"""Cost model: execution-strategy choice for a lowered query.

Reference parity: `DruidQueryCostModel` (SURVEY.md §2 `[U]`, expected
`org/apache/spark/sql/sources/druid/DruidQueryCostModel.scala`) chooses
between one broker scatter-gather query and N direct per-historical queries,
from tunable per-row/shuffle cost constants.  The TPU analog chooses:

* **kernel strategy** — dense one-hot matmul (MXU; cost grows with G) vs
  scatter segment-sum (VPU serial; cost per row ~constant but high);
* **execution target** — single device vs SPMD mesh (the broker-vs-
  historicals analog: one device is the "broker-only" plan, the mesh is
  "query the historicals directly and merge"), weighing the per-group
  collective bytes against per-device row savings.

Constants live in SessionConfig (the SQLConf analog) so they are tunable the
same way the reference's are.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from ..catalog.segment import DataSource
from ..config import SessionConfig
from ..models import query as Q


@dataclasses.dataclass(frozen=True)
class PhysicalPlan:
    """The planner's final execution decision for one query spec."""

    query: Q.QuerySpec
    strategy: str  # "dense" | "segment"
    distributed: bool
    mesh_shape: Optional[Tuple[int, int]]  # (data, groups) or None
    est_cost_local: float
    est_cost_dist: float
    num_groups: int
    rows: int

    def describe(self) -> str:
        tgt = (
            f"mesh(data={self.mesh_shape[0]}, groups={self.mesh_shape[1]})"
            if self.distributed and self.mesh_shape
            else "single-device"
        )
        return (
            f"TPUAggregateScan[strategy={self.strategy}, target={tgt}, "
            f"groups={self.num_groups}, rows={self.rows}, "
            f"cost(local)={self.est_cost_local:.3g}, "
            f"cost(dist)={self.est_cost_dist:.3g}]"
        )


def groupby_state_bytes(q: Q.QuerySpec, num_groups: int, cfg: SessionConfig) -> int:
    """Bytes of per-group aggregate state that must cross the merge
    collective (the analog of broker-merge payload size)."""
    from ..models import aggregations as A

    per_group = 0
    aggs = getattr(q, "aggregations", ())
    for a in aggs:
        base = a.aggregator if isinstance(a, A.FilteredAgg) else a
        if isinstance(base, (A.HyperUnique, A.CardinalityAgg)):
            per_group += 4 * (1 << base.precision)
        elif isinstance(base, A.ThetaSketch):
            per_group += 4 * base.size
        else:
            per_group += 4
    return (per_group + 4) * num_groups  # +4: hidden __rows counter


def choose_merge_tree(
    state_bytes: int,
    n_slices: int,
    nd_per_slice: int,
    cfg: SessionConfig,
) -> Tuple[str, float, float]:
    """Pick the collective merge tree for a multi-slice partial-state
    merge (the arXiv:2603.26698 playbook priced with this platform's
    calibrated constants).  Returns (tree, flat_us, hier_us) where tree
    is "flat" or "hierarchical":

    * flat — one allreduce over slice x data.  Ring cost is
      2(N-1)/N * bytes, but every hop is priced at DCN speed because the
      ring crosses the slice boundary.
    * hierarchical — slice-local allreduce over ICI (2(nd-1)/nd * bytes
      at ICI speed), then one allreduce of the already-merged state over
      the slice axis only (2(ns-1)/ns * bytes at DCN speed).

    With one slice the ring never leaves ICI (flat is priced at ICI
    speed and the trees coincide); flat wins ties so the single-program
    path stays the default."""
    n = max(1, n_slices * nd_per_slice)
    flat_bw = (
        cfg.dcn_bytes_per_us if n_slices > 1 else cfg.collective_bytes_per_us
    )
    flat_us = 2.0 * (n - 1) / n * state_bytes / max(1.0, flat_bw)
    hier_us = 2.0 * (nd_per_slice - 1) / max(1, nd_per_slice) * (
        state_bytes / max(1.0, cfg.collective_bytes_per_us)
    ) + 2.0 * (n_slices - 1) / max(1, n_slices) * (
        state_bytes / max(1.0, cfg.dcn_bytes_per_us)
    )
    tree = "hierarchical" if hier_us < flat_us else "flat"
    return tree, flat_us, hier_us


def _g_tiles(num_groups: int) -> int:
    """128-wide vector-lane tiles the one-hot block spans."""
    return max(1, -(-num_groups // 128))


def scatter_row_cost(num_groups: int, cfg: SessionConfig) -> float:
    """Per-row scatter cost at this group-domain size: log-linear
    interpolation between the calibrated low-G and high-G anchor points,
    clamped outside them.  Models the cache cliff — random scatter into a
    state that outgrows cache costs several times a cache-resident one
    (measured 0.0015 -> 0.0071 us/row from G=1K to G=2M on CPU); a flat
    per-row constant routed SSB q3_2-class queries onto a 12 s scatter."""
    import math

    lo_g = max(1, cfg.scatter_lo_groups)
    hi_g = max(lo_g + 1, cfg.scatter_hi_groups)
    lo = cfg.cost_per_row_scatter
    # a partial calibration can pair a measured lo with the profile's hi;
    # scatter must never get CHEAPER as G grows
    hi = max(cfg.cost_per_row_scatter_hi, lo)
    if num_groups <= lo_g:
        return lo
    if num_groups >= hi_g:
        return hi
    f = math.log(num_groups / lo_g) / math.log(hi_g / lo_g)
    return lo + (hi - lo) * f


def _kernel_costs(
    rows: int,
    num_groups: int,
    cfg: SessionConfig,
    sparse_ok: bool,
    selectivity: float = 1.0,
    n_segments: int = 1,
    adaptive_ok: bool = False,
    ndims: int = 1,
) -> Tuple[Tuple[str, float], ...]:
    """(strategy, modelled us) for each kernel class (inf = inapplicable).

    `selectivity` is the estimated surviving-row fraction of the query's
    filter (estimate_selectivity).  `n_segments` matters because scatter
    state and the sparse tier's sort network are paid PER SEGMENT (round 3
    modelled them once and underpriced both by ~1000x at SF100's 982
    segments).  The ADAPTIVE class models dictionary-domain compaction
    (exec/adaptive_exec.py): one probe pass measuring per-dim presence,
    then the best kernel over the compacted domain, estimated as
    G' ~ G * selectivity (per-dim admitted fractions multiply the same way
    row selectivities do)."""
    n_segments = max(1, n_segments)
    dense = (
        rows * cfg.cost_per_row_dense * _g_tiles(num_groups)
        if num_groups <= cfg.dense_max_groups
        else float("inf")
    )

    def scatter_at(g: int) -> float:
        return (
            rows * scatter_row_cost(g, cfg)
            + g * cfg.cost_per_group_state * n_segments
        )

    scatter = scatter_at(num_groups)
    # The compact constant is floored at the scatter per-row cost
    # defensively (see plan/calibrate.py — an over-subtracted constant
    # from an older calibration file must not flip large scans onto the
    # sparse path)
    compact = max(cfg.cost_per_row_compact, cfg.cost_per_row_scatter)
    if not sparse_ok:
        sparse = float("inf")
    elif selectivity >= 1.0:
        sparse = rows * cfg.cost_per_row_sparse  # full-row sort, no compact
    else:
        from ..ops.sparse_groupby import ROW_CAPACITY_LADDER

        # the engine picks the smallest capacity rung covering the
        # estimated survivors PER SEGMENT and sorts that many slots in
        # EVERY segment
        seg_rows = max(1.0, rows / n_segments)
        need = 2.0 * selectivity * seg_rows
        rung = next(
            (c for c in ROW_CAPACITY_LADDER if c >= need), seg_rows
        )
        sorted_rows = n_segments * min(seg_rows, float(rung))
        sparse = rows * compact + sorted_rows * cfg.cost_per_row_sparse
    if not adaptive_ok:
        adaptive = float("inf")
    else:
        g_c = max(1, min(num_groups, round(num_groups * selectivity)))
        probe = rows * ndims * min(
            cfg.cost_per_row_dense, cfg.cost_per_row_scatter
        )
        main = min(
            scatter_at(g_c),
            rows * cfg.cost_per_row_dense * _g_tiles(g_c)
            if g_c <= cfg.dense_max_groups
            else float("inf"),
        )
        # probe amortized over repeats: the kept-set cache (the engine's
        # analog of Druid's bitmap indexes) makes every later execution of
        # the query a single compact-domain pass, and the OLAP workload
        # shape this system exists for (dashboards; the reference's result
        # cache carries the same assumption) repeats queries.  /3 keeps a
        # one-shot query's worst case bounded at ~1.3x the best
        # alternative while routing repeat-heavy shapes onto the path
        # that wins them.  The phase-A probe is also a SEPARATE dispatch;
        # its fixed overhead (66 ms on the round-5 tunneled chip, where it
        # rivals a whole scatter pass) amortizes with the probe itself —
        # the kept-set cache skips phase A on repeats.
        adaptive = (probe + cfg.cost_dispatch_us) / 3.0 + main
    return (
        ("dense", dense),
        ("segment", scatter),
        ("sparse", sparse),
        ("adaptive", adaptive),
    )


def estimate_selectivity(filt, ds: DataSource) -> float:
    """Estimated surviving-row fraction of a filter spec — dictionary-based
    uniformity assumptions, the classic textbook estimator (the reference's
    DruidQueryCostModel reasoned from segment metadata the same coarse
    way).  Conservative: anything unmodeled estimates 1.0."""
    from ..models import filters as F

    if filt is None:
        return 1.0
    if isinstance(filt, F.And):
        s = 1.0
        for x in filt.fields:
            s *= estimate_selectivity(x, ds)
        return s
    if isinstance(filt, F.Or):
        s = 0.0
        for x in filt.fields:
            s += estimate_selectivity(x, ds)
        return min(1.0, s)
    if isinstance(filt, F.Not):
        return max(0.0, 1.0 - estimate_selectivity(filt.field, ds))
    if isinstance(filt, F.Selector):
        d = ds.dicts.get(filt.dimension)
        if d is None or not d.cardinality:
            return 1.0
        if filt.value is not None and d.code_of(filt.value) is None:
            return 0.0
        return 1.0 / d.cardinality
    if isinstance(filt, F.InFilter):
        d = ds.dicts.get(filt.dimension)
        if d is None or not d.cardinality:
            return 1.0
        hits = sum(1 for v in filt.values if d.code_of(v) is not None)
        return min(1.0, hits / d.cardinality)
    if isinstance(filt, F.Bound):
        d = ds.dicts.get(filt.dimension)
        if d is not None and d.cardinality:
            # fraction of the (sorted) code space the bound admits
            from ..ops.filters import numeric_dict_code_bounds

            nv = d.numeric_values
            if nv is not None and filt.ordering != "lexicographic":
                import numpy as np

                cb = numeric_dict_code_bounds(filt, np.asarray(nv))
                if cb is None:
                    return 1.0
                lo, hi = cb
                lo = 0 if lo is None else max(0, lo)
                hi = d.cardinality - 1 if hi is None else min(
                    d.cardinality - 1, hi
                )
                return max(0.0, (hi - lo + 1) / d.cardinality)
        return 1.0 / 3.0  # classic guess for an un-modeled range
    return 1.0


def choose_kernel_strategy(
    rows: int, num_groups: int, cfg: SessionConfig, sparse_ok: bool = False
) -> str:
    """Min-cost kernel class for a (rows, groups) shape — the strategy an
    Engine constructed outside the planner (streaming, direct execution)
    should be pinned to when calibrated constants are available."""
    return min(
        _kernel_costs(rows, num_groups, cfg, sparse_ok), key=lambda kv: kv[1]
    )[0]


def query_kernel_costs(
    q: Q.QuerySpec,
    ds: DataSource,
    num_groups: int,
    cfg: SessionConfig,
    selectivity: Optional[float] = None,
) -> dict:
    """strategy -> modelled microseconds for a PLANNED query over `ds`: the
    kernel half of `choose_physical`, factored out so the distributed and
    streaming engines route by the identical calibrated model (VERDICT r4
    #1: the mesh path hard-coding dense was the round-4 headline gap).
    Eligibility mirrors the single-device engine: sparse requires real dims
    and no sketch state to re-key; adaptive re-keys sketches transparently
    so only dims are required."""
    from ..models import aggregations as A
    from ..ops.groupby import SCATTER_CUTOVER

    rows = ds.num_rows
    aggs = getattr(q, "aggregations", ())
    has_sketch = any(
        isinstance(
            a.aggregator if isinstance(a, A.FilteredAgg) else a,
            (A.HyperUnique, A.CardinalityAgg, A.ThetaSketch),
        )
        for a in aggs
    )
    dims = getattr(q, "dimensions", ())
    sparse_ok = (
        num_groups > SCATTER_CUTOVER and not has_sketch and bool(dims)
    )
    adaptive_ok = num_groups > SCATTER_CUTOVER and bool(dims)
    segs = getattr(ds, "segments", None)
    n_segments = (
        len(segs) if segs is not None else max(1, rows // (1 << 22))
    )
    sel = (
        selectivity
        if selectivity is not None
        else estimate_selectivity(getattr(q, "filter", None), ds)
    )
    return dict(
        _kernel_costs(
            rows, num_groups, cfg, sparse_ok,
            selectivity=sel,
            n_segments=n_segments,
            adaptive_ok=adaptive_ok,
            ndims=max(1, len(dims)),
        )
    )


def choose_query_kernel(
    q: Q.QuerySpec,
    ds: DataSource,
    num_groups: int,
    cfg: SessionConfig,
    exclude: Tuple[str, ...] = (),
    costs: Optional[dict] = None,
) -> str:
    """Min-cost kernel class for a planned query — `choose_physical`'s
    strategy choice as a standalone (used by parallel/distributed.py and
    exec/streaming.py).  `exclude` masks classes the caller cannot or will
    not run (e.g. "adaptive" after a decline memo); `costs` accepts a
    precomputed query_kernel_costs dict so choose_physical does not pay
    the selectivity walk twice."""
    if costs is None:
        costs = query_kernel_costs(q, ds, num_groups, cfg)
    costs = {k: v for k, v in costs.items() if k not in exclude}
    if not cfg.cost_model_enabled:
        if num_groups <= cfg.dense_max_groups and "dense" not in exclude:
            return "dense"
        if costs.get("sparse", float("inf")) != float("inf"):
            return "sparse"
        return "segment"
    return min(costs.items(), key=lambda kv: kv[1])[0]


def choose_physical(
    q: Q.QuerySpec,
    ds: DataSource,
    num_groups: int,
    cfg: SessionConfig,
    n_devices: int = 1,
) -> PhysicalPlan:
    """Pick kernel strategy + execution target (the DruidQueryCostModel
    broker-vs-historicals analog).  All costs in microseconds, from the
    calibratable SessionConfig constants (plan/calibrate.py)."""
    rows = ds.num_rows
    # Three kernel classes, chosen by modelled cost (all constants
    # calibratable on the live backend — plan/calibrate.py):
    #   dense   one-hot matmul: cost scales with ceil(G/128) lane tiles (MXU-
    #           shaped; the winner on TPU for small/medium domains)
    #   segment raw scatter: flat per-row cost (serializes on TPU, cheap on
    #           CPU) + per-group dense-state cost
    #   sparse  sort-compaction: flat-but-sort-heavy per-row cost, no dense
    #           state — the high-cardinality path where it applies (real
    #           dims, no sketch state to re-key)
    # kernel-class eligibility + costs shared with every executor
    # (query_kernel_costs); adaptive compaction re-keys sketch states
    # transparently (the compact program IS the normal program over a
    # rewritten lowering), so sketches do not disqualify it there.  The
    # selectivity tree walk runs ONCE and feeds both the local and the
    # per-device cost evaluations below.
    sel = estimate_selectivity(getattr(q, "filter", None), ds)
    costs = query_kernel_costs(q, ds, num_groups, cfg, selectivity=sel)
    strategy = choose_query_kernel(q, ds, num_groups, cfg, costs=costs)
    local_cost = costs[strategy]

    # distributed target: since round 5 the FULL kernel ladder runs SPMD
    # (parallel/distributed.py routes dense/scatter/sparse/adaptive per
    # shard), so every GroupBy-family strategy is mesh-eligible; scans
    # stay single-device by construction
    aggregate_family = isinstance(
        q, (Q.GroupByQuery, Q.TimeseriesQuery, Q.TopNQuery)
    )
    distributed = False
    mesh_shape = None
    dist_cost = local_cost
    if n_devices > 1 and aggregate_family:
        ng = max(1, cfg.mesh_groups_axis)
        nd = cfg.mesh_data_axis or max(1, n_devices // ng)
        nd = min(nd, max(1, n_devices // ng))
        # rows shard over the data axis (replicated across the groups axis);
        # the groups axis shards the group-id domain, shrinking per-device G
        # for the one-hot block, the sketch states, AND the sparse slot
        # capacity alike.  Per-shard compute comes from the SAME model at
        # the per-device shape for every class (no duplicated formulas).
        per_device_groups = -(-num_groups // ng)
        compute = dict(
            _kernel_costs(
                max(1, rows // nd), per_device_groups, cfg,
                sparse_ok=strategy == "sparse",
                selectivity=sel,
                n_segments=1,  # one shard per device
                adaptive_ok=strategy == "adaptive",
                ndims=max(1, len(getattr(q, "dimensions", ()) or ())),
            )
        )[strategy]
        # collective bytes are what the merge ACTUALLY moves: the dense/
        # scatter rungs allreduce the full [Gl, M] state, but the sparse
        # rung all_gathers only slot-compacted state and adaptive merges
        # the compacted domain — both bounded by the POPULATED group count
        # (~ G x selectivity), not the domain (pricing the full domain
        # silently kept exactly the high-G queries the mesh ladder exists
        # for off the mesh)
        if strategy in ("sparse", "adaptive"):
            g_eff = max(
                1, min(per_device_groups, round(num_groups * sel))
            )
            state_bytes = groupby_state_bytes(q, g_eff, cfg)
            # all_gather moves ~(nd-1) x one device's state
            factor = float(nd - 1)
        else:
            state_bytes = groupby_state_bytes(q, per_device_groups, cfg)
            # ring allreduce moves ~2*(nd-1)/nd of the state
            factor = 2.0 * (nd - 1) / nd
        collective = (
            factor * state_bytes / max(cfg.collective_bytes_per_us, 1e-9)
        )
        dist_cost = compute + collective + cfg.cost_dispatch_us
        distributed = cfg.prefer_distributed and (
            not cfg.cost_model_enabled or dist_cost < local_cost
        )
        if distributed:
            mesh_shape = (nd, ng)
    return PhysicalPlan(
        query=q,
        strategy=strategy,
        distributed=distributed,
        mesh_shape=mesh_shape,
        est_cost_local=local_cost,
        est_cost_dist=dist_cost,
        num_groups=num_groups,
        rows=rows,
    )
