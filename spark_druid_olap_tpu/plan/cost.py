"""Cost model: execution-strategy choice for a lowered query.

Reference parity: `DruidQueryCostModel` (SURVEY.md §2 `[U]`, expected
`org/apache/spark/sql/sources/druid/DruidQueryCostModel.scala`) chooses
between one broker scatter-gather query and N direct per-historical queries,
from tunable per-row/shuffle cost constants.  The TPU analog chooses:

* **kernel strategy** — dense one-hot matmul (MXU; cost grows with G) vs
  scatter segment-sum (VPU serial; cost per row ~constant but high);
* **execution target** — single device vs SPMD mesh (the broker-vs-
  historicals analog: one device is the "broker-only" plan, the mesh is
  "query the historicals directly and merge"), weighing the per-group
  collective bytes against per-device row savings.

Constants live in SessionConfig (the SQLConf analog) so they are tunable the
same way the reference's are.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from ..catalog.segment import DataSource
from ..config import SessionConfig
from ..models import query as Q


@dataclasses.dataclass(frozen=True)
class PhysicalPlan:
    """The planner's final execution decision for one query spec."""

    query: Q.QuerySpec
    strategy: str  # "dense" | "segment"
    distributed: bool
    mesh_shape: Optional[Tuple[int, int]]  # (data, groups) or None
    est_cost_local: float
    est_cost_dist: float
    num_groups: int
    rows: int

    def describe(self) -> str:
        tgt = (
            f"mesh(data={self.mesh_shape[0]}, groups={self.mesh_shape[1]})"
            if self.distributed and self.mesh_shape
            else "single-device"
        )
        return (
            f"TPUAggregateScan[strategy={self.strategy}, target={tgt}, "
            f"groups={self.num_groups}, rows={self.rows}, "
            f"cost(local)={self.est_cost_local:.3g}, "
            f"cost(dist)={self.est_cost_dist:.3g}]"
        )


def groupby_state_bytes(q: Q.QuerySpec, num_groups: int, cfg: SessionConfig) -> int:
    """Bytes of per-group aggregate state that must cross the merge
    collective (the analog of broker-merge payload size)."""
    from ..models import aggregations as A

    per_group = 0
    aggs = getattr(q, "aggregations", ())
    for a in aggs:
        base = a.aggregator if isinstance(a, A.FilteredAgg) else a
        if isinstance(base, (A.HyperUnique, A.CardinalityAgg)):
            per_group += 4 * (1 << base.precision)
        elif isinstance(base, A.ThetaSketch):
            per_group += 4 * base.size
        else:
            per_group += 4
    return (per_group + 4) * num_groups  # +4: hidden __rows counter


def choose_physical(
    q: Q.QuerySpec,
    ds: DataSource,
    num_groups: int,
    cfg: SessionConfig,
    n_devices: int = 1,
) -> PhysicalPlan:
    rows = ds.num_rows
    # kernel strategy: one-hot row cost scales with G/128 vector lanes;
    # scatter cost is flat-but-large per row (serialized updates)
    dense_cost = rows * cfg.cost_per_row_dense * max(num_groups / 128.0, 1.0)
    scatter_cost = rows * cfg.cost_per_row_scatter
    if num_groups <= cfg.dense_max_groups and (
        not cfg.cost_model_enabled or dense_cost <= scatter_cost * 4
    ):
        strategy, per_row = "dense", dense_cost
    else:
        strategy, per_row = "segment", scatter_cost

    state_bytes = groupby_state_bytes(q, num_groups, cfg)
    collective_cost = (
        state_bytes / 1e6 * (n_devices - 1) / max(cfg.collective_bytes_per_us, 1e-9)
        if n_devices > 1
        else 0.0
    )
    local_cost = per_row
    dist_cost = per_row / max(n_devices, 1) + collective_cost

    distributed = cfg.prefer_distributed and n_devices > 1 and (
        not cfg.cost_model_enabled or dist_cost < local_cost
    )
    mesh_shape = None
    if distributed:
        ngroups_axis = cfg.mesh_groups_axis
        ndata = cfg.mesh_data_axis or (n_devices // max(ngroups_axis, 1))
        mesh_shape = (ndata, ngroups_axis)
    return PhysicalPlan(
        query=q,
        strategy=strategy,
        distributed=distributed,
        mesh_shape=mesh_shape,
        est_cost_local=local_cost,
        est_cost_dist=dist_cost,
        num_groups=num_groups,
        rows=rows,
    )
