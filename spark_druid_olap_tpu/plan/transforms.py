"""Rewrite rules: logical plan -> QueryBuilder extensions.

Reference parity (SURVEY.md §2/§3.2 `[U]`): mirrors the reference's transform
pipeline and order —
  * `ProjectFilterTransform` -> `translate_filter` (predicates to the
    FilterSpec tree; time-column predicates narrow the query interval instead;
    untranslatable predicates fall back to an `ExpressionFilter`, the TPU
    analog of the reference's JS-filter escape hatch — unlike the reference we
    never abort on residuals because we own the engine)
  * `AggregateTransform` -> `translate_aggregate` (grouping exprs to
    DimensionSpecs incl. time-granularity buckets and dictionary extractions;
    SUM/MIN/MAX/COUNT to AggregationSpecs; AVG to sum+count plus an arithmetic
    post-agg; COUNT(DISTINCT) to HLL/theta sketch aggs per session config;
    FILTER clauses to `filtered` aggregators)
  * post-agg / having     -> `translate_post_exprs` / `translate_having`
  * `LimitTransform`      -> `apply_sort_limit` (Sort+Limit over a
    single-dimension aggregate becomes a TopN; otherwise a LimitSpec)
Each step either extends the immutable QueryBuilder or raises
`RewriteError` — the analog of a transform dropping the rewrite candidate.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..catalog.segment import DataSource
from ..config import SessionConfig
from ..models import aggregations as A
from ..models import filters as F
from ..models import query as Q
from ..models.dimensions import (
    DimensionSpec,
    RegexExtraction,
    SubstringExtraction,
)
from . import expr as E
from .builder import QueryBuilder
from .logical import AggExpr


class RewriteError(Exception):
    """A transform could not translate this plan (candidate dropped)."""


class RewritePolicyError(RewriteError):
    """The plan was rejected by explicit policy or argument validation —
    NOT a coverage gap.  The host fallback executor must not swallow these:
    a user who set count_distinct_mode='error' (or exceeded the result-
    cardinality guard, or passed invalid arguments) asked for an error."""


# ---------------------------------------------------------------------------
# Expression utilities
# ---------------------------------------------------------------------------


def substitute(e: E.Expr, env: Dict[str, E.Expr]) -> E.Expr:
    """Inline projection-defined names (the analog of Catalyst's alias
    resolution when the reference matches Project under Aggregate)."""
    if isinstance(e, E.Col):
        if e.name in env:
            return substitute(env[e.name], {k: v for k, v in env.items()
                                            if k != e.name})
        return e
    if isinstance(e, E.Literal) or isinstance(e, E.AggRef):
        return e
    kw = {}
    for f in dataclasses.fields(e):  # type: ignore[arg-type]
        v = getattr(e, f.name)
        if isinstance(v, E.Expr):
            kw[f.name] = substitute(v, env)
        elif isinstance(v, tuple) and v and isinstance(v[0], E.Expr):
            kw[f.name] = tuple(substitute(x, env) for x in v)
        else:
            kw[f.name] = v
    return type(e)(**kw)


def _is_time_col(e: E.Expr, ds: DataSource) -> bool:
    return isinstance(e, E.Col) and (
        e.name == "__time" or e.name == ds.time_column
    )


def _literal_ms(e: E.Expr) -> Optional[int]:
    if isinstance(e, E.Literal):
        if isinstance(e.value, (int, float, np.integer)):
            return int(e.value)
        if isinstance(e.value, str):
            # ISO date/datetime string against the time column — the Druid
            # interval convention (and the reference's spark-datetime
            # predicates, SURVEY.md §2 build-deps row [U])
            try:
                return int(np.datetime64(e.value, "ms").astype(np.int64))
            except ValueError:
                return None
    return None


_MAX_MS = 1 << 62


# ---------------------------------------------------------------------------
# ProjectFilterTransform analog
# ---------------------------------------------------------------------------


def translate_filter(
    e: E.Expr, ds: DataSource, b: QueryBuilder
) -> QueryBuilder:
    """Fold one predicate into the builder: conjuncts split; time bounds
    become intervals; dimension predicates become Filter specs; anything
    else becomes an ExpressionFilter residual."""
    for conj in _conjuncts(e):
        iv = _as_interval(conj, ds)
        if iv is not None:
            b = _intersect_interval(b, iv)
            continue
        f = _as_filter_spec(conj, ds)
        if f is not None:
            b = b.add_filter(f)
            continue
        # residual: compile later on the row path (JS-codegen analog)
        _validate_columns(conj, ds)
        _reject_null_valued(conj)
        b = b.add_filter(F.ExpressionFilter(conj))
    return b


def _reject_null_valued(e: E.Expr) -> None:
    """NULL-producing VALUE expressions (NULLIF / CASE ... THEN NULL) have
    no device representation: refuse at plan time so the query routes to
    the host fallback (which has exact NULL semantics) instead of crashing
    inside the device compile."""
    if _has_null_literal(e):
        raise RewriteError(
            f"expression {e} produces NULL values; host fallback required"
        )


def _contains_subquery(e: E.Expr) -> bool:
    if isinstance(e, (E.InSubquery, E.ScalarSubquery, E.ExistsSubquery)):
        return True
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, E.Expr) and _contains_subquery(v):
            return True
        if isinstance(v, tuple) and any(
            isinstance(x, E.Expr) and _contains_subquery(x) for x in v
        ):
            return True
    return False


def _conjuncts(e: E.Expr) -> List[E.Expr]:
    if isinstance(e, E.BoolOp) and e.op == "and":
        out: List[E.Expr] = []
        for o in e.operands:
            out.extend(_conjuncts(o))
        return out
    return [e]


def _as_interval(e: E.Expr, ds: DataSource) -> Optional[Tuple[int, int]]:
    """Time-column comparisons -> half-open [lo, hi) interval (the
    reference's interval narrowing instead of a Druid filter)."""
    if not isinstance(e, E.Comparison):
        return None
    l, r, op = e.left, e.right, e.op
    if not _is_time_col(l, ds):
        if _is_time_col(r, ds):
            l, r = r, l
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "=="}[op]
        else:
            return None
    ms = _literal_ms(r)
    if ms is None:
        return None
    if op == "<":
        return (-_MAX_MS, ms)
    if op == "<=":
        return (-_MAX_MS, ms + 1)
    if op == ">":
        return (ms + 1, _MAX_MS)
    if op == ">=":
        return (ms, _MAX_MS)
    if op == "==":
        return (ms, ms + 1)
    return None


def _intersect_interval(b: QueryBuilder, iv: Tuple[int, int]) -> QueryBuilder:
    if not b.intervals:
        return b.with_(intervals=(iv,))
    out = []
    for a0, b0 in b.intervals:
        lo, hi = max(a0, iv[0]), min(b0, iv[1])
        if lo < hi:
            out.append((lo, hi))
    return b.with_(intervals=tuple(out) if out else ((0, 0),))


def _extraction_for(fn: str, args: tuple):
    """One string function -> its Druid extraction spec (LOOKUP excluded:
    it needs the session lookup registry and is handled by its caller)."""
    from ..models.dimensions import (
        CaseExtraction,
        FormatExtraction,
        StrFuncExtraction,
        StrlenExtraction,
    )

    if fn == "substr":
        start = int(args[0]) - 1  # SQL is 1-based
        length = int(args[1]) if len(args) > 1 else None
        return SubstringExtraction(start, length)
    if fn in ("upper", "lower"):
        return CaseExtraction(upper=(fn == "upper"))
    if fn == "concat":
        prefix, suffix = (tuple(args) + ("", ""))[:2]
        return FormatExtraction(str(prefix), str(suffix))
    if fn == "length":
        return StrlenExtraction()
    if fn in ("trim", "ltrim", "rtrim", "replace"):
        return StrFuncExtraction(fn, args)
    raise RewriteError(f"string function {fn!r} in GROUP BY")


def _strfunc_chain(e: E.Expr):
    """Unwrap nested StrFuncs down to a base dimension column: returns
    (column name, [(fn, args)] innermost-first) or None.  LOOKUP is
    excluded (it has registry semantics, not pure string rewriting)."""
    fns = []
    while isinstance(e, E.StrFunc) and e.fn != "lookup":
        fns.append((e.fn, e.args))
        e = e.operand
    if fns and isinstance(e, E.Col):
        return e.name, fns[::-1]
    return None


def _as_filter_spec(e: E.Expr, ds: DataSource) -> Optional[F.Filter]:
    """Dimension predicate -> Druid-style FilterSpec, when directly
    expressible.  Dictionary-order tricks make string bounds sound."""
    if isinstance(e, E.Comparison):
        l, r, op = e.left, e.right, e.op
        if isinstance(r, E.Col) and isinstance(l, E.Literal):
            l, r = r, l
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                  "==": "==", "!=": "!="}[op]
        chain = _strfunc_chain(l)
        if (
            chain is not None
            and chain[0] in ds.dicts
            and isinstance(r, E.Literal)
            and r.value is not None
        ):
            # comparison over a (possibly composed) string function of a
            # dimension: apply the chain to each DICTIONARY value once,
            # innermost first, keep matching values — the Druid
            # extraction-filter analog (O(dictionary), no row work); null
            # rows never match (InFilter is code-space membership)
            import operator as _op

            from ..plan.expr import apply_strfunc

            cmp = {"==": _op.eq, "!=": _op.ne, "<": _op.lt,
                   "<=": _op.le, ">": _op.gt, ">=": _op.ge}[op]
            name, fns = chain
            d = ds.dicts[name]
            lit = r.value
            matched = []
            for v in d.values:
                res = v if isinstance(v, str) else str(v)
                for fn, args in fns:
                    if not isinstance(res, str):
                        res = None  # e.g. UPPER(LENGTH(..)): not a string
                        break
                    res = apply_strfunc(fn, args, res)
                if isinstance(res, int) and isinstance(
                    lit, (int, float)
                ) and not isinstance(lit, bool):
                    ok = cmp(res, lit)
                elif isinstance(res, str) and isinstance(lit, str):
                    ok = cmp(res, lit)
                else:
                    ok = False
                if ok:
                    matched.append(str(v))
            return F.InFilter(name, tuple(matched))
        if not (isinstance(l, E.Col) and isinstance(r, E.Literal)):
            return None
        name, val = l.name, r.value
        is_dim = name in ds.dicts
        is_string_dim = is_dim and ds.dicts[name].numeric_values is None
        if val is None:
            # the parser's IS [NOT] NULL encoding — valid for ANY column
            # kind (numeric dictionaries included: round-3 fix, the old
            # path stringified None into a dead lexicographic bound)
            if op == "==":
                return F.Selector(name, None)
            if op == "!=":
                return F.Not(F.Selector(name, None))
            return None  # ordering vs NULL: residual (matches nothing)
        if isinstance(val, str) and not is_string_dim:
            # string literal against a numeric column/dictionary: coerce
            # (numeric string or ISO date -> epoch ms) so the Bound compiles
            # with numeric ordering — a lexicographic bound over stringified
            # numbers silently drops everything (VERDICT r1 weak #2)
            num = E.coerce_str_literal(val)
            if num is None:
                return None  # residual expression filter will raise clearly
            val = int(num) if num == int(num) else num
        sval = str(val)
        ordering = "lexicographic" if is_string_dim and isinstance(val, str) else "numeric"
        if op == "==":
            if is_string_dim:
                return F.Selector(name, sval)
            return F.Bound(name, lower=sval, upper=sval, ordering="numeric")
        if op == "!=":
            if is_string_dim:
                # SQL three-valued: NULL <> 'x' is UNKNOWN -> excluded; a
                # bare two-valued Not would keep null rows (matches the
                # expression layer's `!=` policy, plan/expr.py)
                return F.And(
                    (
                        F.Not(F.Selector(name, sval)),
                        F.Not(F.Selector(name, None)),
                    )
                )
            return F.Not(F.Bound(name, lower=sval, upper=sval, ordering="numeric"))
        if op in ("<", "<="):
            return F.Bound(name, upper=sval, upper_strict=(op == "<"),
                           ordering=ordering)
        if op in (">", ">="):
            return F.Bound(name, lower=sval, lower_strict=(op == ">"),
                           ordering=ordering)
        return None
    if isinstance(e, E.InExpr):
        if isinstance(e.operand, E.Col):
            # a literal NULL in the list never matches positively (x = NULL
            # is UNKNOWN); the flag keeps Kleene evaluation exact under
            # ANY negation depth (ops/filters.py _leaf_unknown)
            return F.InFilter(
                e.operand.name,
                tuple(str(v) for v in e.values if v is not None),
                null_in_values=any(v is None for v in e.values),
            )
        return None
    if isinstance(e, E.LikeExpr):
        if isinstance(e.operand, E.Col):
            f: F.Filter = F.LikeFilter(e.operand.name, e.pattern)
            if not e.negated:
                return f
            # SQL: NULL NOT LIKE p is UNKNOWN -> excluded (same policy as
            # the expression layer's device compile, plan/expr.py)
            return F.And(
                (F.Not(f), F.Not(F.Selector(e.operand.name, None)))
            )
        return None
    if isinstance(e, E.BoolOp):
        if e.op == "not":
            inner = _as_filter_spec(e.operands[0], ds)
            return F.Not(inner) if inner is not None else None
        subs = [_as_filter_spec(o, ds) for o in e.operands]
        if any(s is None for s in subs):
            return None
        return F.And(tuple(subs)) if e.op == "and" else F.Or(tuple(subs))
    return None


def _validate_columns(e: E.Expr, ds: DataSource):
    for c in e.columns():
        if c == "__time":
            continue
        try:
            ds.meta(c)
        except KeyError as ke:
            raise RewriteError(str(ke)) from None


# ---------------------------------------------------------------------------
# AggregateTransform analog
# ---------------------------------------------------------------------------


def translate_group_expr(
    name: str,
    e: E.Expr,
    ds: DataSource,
    b: QueryBuilder,
    lookups=None,
) -> Tuple[DimensionSpec, QueryBuilder]:
    """Grouping expression -> DimensionSpec (+ builder extension).
    `lookups` is a callable name -> mapping-dict-or-None (the Druid lookup
    extraction, LOOKUP(dim, 'name')); a callable rather than a dict so
    planning a query with no LOOKUP never pays for copying registered
    tables."""
    if isinstance(e, E.Col):
        if e.name in ds.dicts:
            return DimensionSpec(e.name, name), b
        if _is_time_col(e, ds):
            raise RewriteError(
                "grouping by raw time requires a granularity (DATE_TRUNC)"
            )
        raise RewriteError(f"GROUP BY over metric column {e.name!r}")
    if isinstance(e, E.TimeBucket):
        if not _is_time_col(e.operand, ds):
            raise RewriteError("DATE_TRUNC over non-time column")
        return DimensionSpec("__time", name, granularity=e.granularity), b
    if isinstance(e, E.TimeExtract):
        # EXTRACT in GROUP BY plans as a dictionary-backed dimension
        # (SURVEY.md §2 DimensionSpec/timeFormat row): over the time column
        # it buckets at the field's granularity and remaps bucket starts;
        # over a numeric-dict date dimension it rewrites the dictionary.
        from ..models.dimensions import TimeFieldExtraction

        ex = TimeFieldExtraction(e.field)
        if _is_time_col(e.operand, ds):
            return (
                DimensionSpec(
                    "__time", name, extraction=ex, granularity=ex.granularity
                ),
                b,
            )
        if (
            isinstance(e.operand, E.Col)
            and e.operand.name in ds.dicts
            and ds.dicts[e.operand.name].numeric_values is not None
        ):
            return DimensionSpec(e.operand.name, name, extraction=ex), b
        raise RewriteError(
            f"EXTRACT({e.field}) in GROUP BY requires the time column or a "
            "numeric-dictionary date dimension"
        )
    if isinstance(e, E.StrFunc):
        if e.fn != "lookup":
            # single fns map to their native Druid extraction; COMPOSED
            # chains (REPLACE(TRIM(s),...)) map to Druid's `cascade`
            # extraction applied innermost-first over the dictionary
            chain = _strfunc_chain(e)
            if chain is None or chain[0] not in ds.dicts:
                raise RewriteError(f"{e.fn} over non-dimension in GROUP BY")
            dim, fns = chain
            exts = tuple(_extraction_for(fn, args) for fn, args in fns)
            if len(exts) == 1:
                ext = exts[0]
            else:
                from ..models.dimensions import CascadeExtraction

                ext = CascadeExtraction(exts)
            return DimensionSpec(dim, name, extraction=ext), b
        if not isinstance(e.operand, E.Col) or e.operand.name not in ds.dicts:
            raise RewriteError(f"{e.fn} over non-dimension in GROUP BY")
        dim = e.operand.name
        if e.fn == "lookup":
            from ..models.dimensions import LookupExtraction

            lname = str(e.args[0])
            table = lookups(lname) if lookups is not None else None
            if table is None:
                raise RewritePolicyError(f"unknown lookup table {lname!r}")
            # Druid SQL: LOOKUP(expr, name[, replaceMissingValueWith]) — an
            # unmapped key becomes NULL (the null group) unless the optional
            # third argument replaces it
            replace = str(e.args[1]) if len(e.args) > 1 else None
            return (
                DimensionSpec(
                    dim,
                    name,
                    extraction=LookupExtraction.from_mapping(
                        lname, table, replace_missing=replace
                    ),
                ),
                b,
            )
        raise RewriteError(f"string function {e.fn!r} in GROUP BY")
    raise RewriteError(f"cannot group by expression {e}")


def _has_null_literal(e) -> bool:
    """Does a VALUE expression contain a NULL literal (e.g. NULLIF's
    desugared CASE arm)?  Excludes the `== Literal(None)` IS-NULL
    comparison encoding, which is boolean and device-safe."""
    found = False

    def look(x):
        nonlocal found
        if isinstance(x, E.Literal) and x.value is None:
            found = True
        return x

    def strip_isnull(x):
        if (
            isinstance(x, E.Comparison)
            and x.op in ("==", "!=")
            and any(
                isinstance(s, E.Literal) and s.value is None
                for s in (x.left, x.right)
            )
        ):
            return E.Literal(True)  # boolean, not a NULL value
        return x

    E.map_expr(E.map_expr(e, strip_isnull), look)
    return found


def translate_aggregate(
    agg: AggExpr, ds: DataSource, b: QueryBuilder, cfg: SessionConfig
) -> Tuple[List[A.Aggregation], List[A.PostAggregation], QueryBuilder]:
    """One SQL aggregate -> engine aggregations (+post-aggs for AVG)."""
    name = agg.name
    extra_filter = None
    if agg.filter is not None:
        spec = _as_filter_spec(agg.filter, ds)
        if spec is None:
            _validate_columns(agg.filter, ds)
            _reject_null_valued(agg.filter)
            spec = F.ExpressionFilter(agg.filter)
        extra_filter = spec

    def wrap(a: A.Aggregation) -> A.Aggregation:
        return A.FilteredAgg(extra_filter, a) if extra_filter is not None else a

    fn = agg.fn.lower()
    arg = agg.arg

    if fn == "count" and not agg.distinct:
        return [wrap(A.Count(name))], [], b

    if fn in (
        "count_distinct",
        "approx_count_distinct",
        "approx_count_distinct_ds_theta",
        "approx_count_distinct_ds_hll",
    ) or (fn == "count" and agg.distinct):
        if not isinstance(arg, E.Col):
            raise RewriteError("COUNT(DISTINCT) over expressions unsupported")
        if cfg.count_distinct_mode == "error" and fn in (
            "count_distinct",
            "count",
        ):
            # explicit approx_count_distinct*() is always allowed; bare
            # COUNT(DISTINCT) honors the mode (the SQL parser lifts it to
            # fn="count_distinct", the builder API to fn="count"+distinct)
            raise RewritePolicyError("COUNT(DISTINCT) disabled by config")
        # Druid SQL's DataSketches variants pin the sketch family and take
        # an optional size/precision argument
        if fn == "approx_count_distinct_ds_theta":
            k = int(agg.args[0]) if agg.args else cfg.theta_size
            if k < 1:
                raise RewritePolicyError("theta sketch size must be >= 1")
            return [wrap(A.ThetaSketch(name, arg.name, size=k))], [], b
        if fn == "approx_count_distinct_ds_hll":
            p = int(agg.args[0]) if agg.args else cfg.hll_precision
            if not 4 <= p <= 18:
                raise RewritePolicyError(
                    "HLL precision must be in [4, 18]"
                )
            return (
                [wrap(A.HyperUnique(name, arg.name, precision=p))],
                [],
                b,
            )
        sketch = cfg.approx_count_distinct_sketch
        if sketch == "theta":
            return [wrap(A.ThetaSketch(name, arg.name, size=cfg.theta_size))], [], b
        return (
            [wrap(A.HyperUnique(name, arg.name, precision=cfg.hll_precision))],
            [],
            b,
        )

    if fn == "approx_quantile":
        # APPROX_QUANTILE(col, fraction[, k]) -> hidden quantiles sketch +
        # quantile-extracting post-agg (the Druid SQL APPROX_QUANTILE_DS
        # lowering: quantilesDoublesSketch + ...ToQuantile)
        if not isinstance(arg, E.Col):
            raise RewriteError("APPROX_QUANTILE over expressions unsupported")
        try:
            meta = ds.meta(arg.name)
        except KeyError:
            raise RewriteError(f"unknown column {arg.name!r}")
        if arg.name in ds.dicts:
            # dimension columns hold dictionary CODES on device; a quantile
            # over codes is not a quantile over values — reject rather than
            # silently answer the wrong question
            raise RewritePolicyError(
                "APPROX_QUANTILE requires a numeric metric column"
            )
        if not agg.args:
            raise RewritePolicyError("APPROX_QUANTILE requires a fraction")
        frac = float(agg.args[0])
        if not 0.0 <= frac <= 1.0:
            raise RewritePolicyError("APPROX_QUANTILE fraction must be in [0, 1]")
        k = int(agg.args[1]) if len(agg.args) > 1 else cfg.quantiles_k
        if k < 1:
            # k=0 would build a zero-width sample and return NaN for every
            # group — a silent wrong answer, not an error
            raise RewritePolicyError("APPROX_QUANTILE k must be >= 1")
        # content-keyed sketch name: N fractions over the same (column, k)
        # share ONE sketch (the planner dedupes identical aggregations), as
        # Druid SQL does — a per-output name would triple device state and
        # per-row sort work for a p10/p50/p90 query.  A FILTER clause makes
        # the sketch query-output-specific again.
        if agg.filter is None:
            sk_name = f"__qsk_{arg.name}_{k}"
        else:
            sk_name = f"{name}__qsk"
        return (
            [wrap(A.QuantilesSketch(sk_name, arg.name, size=k))],
            [A.QuantileFromSketch(name, sk_name, frac)],
            b,
        )

    if agg.distinct and fn in ("sum", "avg"):
        # MIN/MAX(DISTINCT) == MIN/MAX and passes through; SUM/AVG(DISTINCT)
        # would silently double-count duplicates — refuse, never wrong data
        raise RewriteError(
            f"{fn.upper()}(DISTINCT) is not pushable (duplicates cannot be "
            "eliminated in partial aggregation)"
        )

    if fn == "avg":
        sum_name, cnt_name = f"{name}__sum", f"{name}__cnt"
        aggs, _, b = translate_aggregate(
            AggExpr(sum_name, "sum", arg, filter=agg.filter), ds, b, cfg
        )
        cnt: A.Aggregation = A.Count(cnt_name)
        if arg is not None and not isinstance(arg, E.Literal):
            # COUNT over the arg (non-null count); columns here are non-null
            # metrics so plain count matches SQL AVG semantics
            pass
        aggs.append(wrap(cnt))
        post = A.Arithmetic(
            name,
            "/",
            (A.FieldAccess(f"{name}__fa_s", sum_name),
             A.FieldAccess(f"{name}__fa_c", cnt_name)),
        )
        return aggs, [post], b

    if fn in ("sum", "min", "max"):
        if arg is None:
            raise RewriteError(f"{fn} requires an argument")
        if isinstance(arg, E.Col):
            meta = None
            try:
                meta = ds.meta(arg.name)
            except KeyError:
                raise RewriteError(f"unknown column {arg.name!r}")
            is_long = meta.dtype == "long"
            cls = {
                ("sum", True): A.LongSum,
                ("sum", False): A.DoubleSum,
                ("min", True): A.LongMin,
                ("min", False): A.DoubleMin,
                ("max", True): A.LongMax,
                ("max", False): A.DoubleMax,
            }[(fn, is_long)]
            return [wrap(cls(name, arg.name))], [], b
        # expression argument -> ExpressionAgg (fused virtual column)
        _validate_columns(arg, ds)
        if _has_null_literal(arg):
            # NULL-producing row expressions (NULLIF / CASE ... THEN NULL)
            # have no device value representation — the host fallback
            # computes them with exact NULL-skipping aggregate semantics
            raise RewriteError(
                f"aggregate argument {arg} produces NULL values; "
                "host fallback required"
            )
        base = {"sum": "doubleSum", "min": "doubleMin", "max": "doubleMax"}[fn]
        return [wrap(A.ExpressionAgg(name, arg, base=base))], [], b

    raise RewriteError(f"aggregate function {agg.fn!r}")


# ---------------------------------------------------------------------------
# Post-aggregate projections & HAVING
# ---------------------------------------------------------------------------


def translate_post_expr(
    name: str, e: E.Expr
) -> Optional[A.PostAggregation]:
    """Expression over aggregate outputs -> arithmetic PostAggregationSpec
    (None => host-evaluated residual)."""
    if isinstance(e, E.AggRef):
        return A.FieldAccess(name, e.name)
    if isinstance(e, E.Literal):
        return A.ConstantPost(name, float(e.value))
    if isinstance(e, E.BinaryOp) and e.op in ("+", "-", "*", "/", "pow"):
        # Druid arithmetic post-aggregator fn set: + - * / quotient pow
        l = translate_post_expr(f"{name}__l", e.left)
        r = translate_post_expr(f"{name}__r", e.right)
        if l is None or r is None:
            return None
        return A.Arithmetic(name, e.op, (l, r))
    return None


def translate_having(e: E.Expr) -> Tuple[Optional[Q.Having], Optional[E.Expr]]:
    """HAVING over aggregate outputs -> HavingSpec; residual stays host-side.

    Returns (spec, residual_expr) — exactly one is non-None unless both
    (split conjunction)."""
    spec, residual = _having_rec(e)
    return spec, residual


def _having_rec(e: E.Expr):
    if isinstance(e, E.Comparison):
        if isinstance(e.left, E.AggRef) and isinstance(e.right, E.Literal):
            return Q.HavingCompare(e.left.name, e.op, float(e.right.value)), None
        if isinstance(e.right, E.AggRef) and isinstance(e.left, E.Literal):
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                    "==": "==", "!=": "!="}[e.op]
            return Q.HavingCompare(e.right.name, flip, float(e.left.value)), None
        return None, e
    if isinstance(e, E.BoolOp) and e.op == "and":
        specs, residuals = [], []
        for o in e.operands:
            s, r = _having_rec(o)
            if s is not None:
                specs.append(s)
            if r is not None:
                residuals.append(r)
        spec = Q.HavingAnd(tuple(specs)) if len(specs) > 1 else (
            specs[0] if specs else None
        )
        if not residuals:
            return spec, None
        res = residuals[0]
        for r in residuals[1:]:
            res = E.BoolOp("and", (res, r))
        return spec, res
    if isinstance(e, E.BoolOp) and e.op == "or":
        subs = [_having_rec(o) for o in e.operands]
        if all(s is not None and r is None for s, r in subs):
            return Q.HavingOr(tuple(s for s, _ in subs)), None
        return None, e
    return None, e


# ---------------------------------------------------------------------------
# LimitTransform analog
# ---------------------------------------------------------------------------


def apply_sort_limit(
    b: QueryBuilder,
    sort_keys: Sequence,  # List[logical.SortKey] resolved to output names
    limit: Optional[int],
    offset: int,
    cfg: SessionConfig,
    agg_output_names: Sequence[str],
) -> QueryBuilder:
    """Sort+Limit over a single-dimension aggregate -> TopN; else LimitSpec
    (reference LimitTransform, SURVEY.md §2 `[U]`)."""
    cols = []
    for k in sort_keys:
        if not isinstance(k.expr, (E.Col, E.AggRef)):
            raise RewriteError(f"ORDER BY expression {k.expr} unsupported")
        cols.append(Q.OrderByColumnSpec(
            k.expr.name, "ascending" if k.ascending else "descending"
        ))
    if (
        cfg.enable_topn_rewrite
        and limit is not None
        and offset == 0
        and len(b.dimensions) == 1
        and b.dimensions[0].granularity is None
        and len(cols) == 1
        and cols[0].dimension in agg_output_names
        and b.having is None
        and not b.grouping_sets
    ):
        return b.with_(
            topn_metric=cols[0].dimension,
            topn_threshold=limit,
            topn_descending=(cols[0].direction == "descending"),
        )
    if limit is None and not cols:
        return b
    return b.with_(limit_spec=Q.LimitSpec(limit, tuple(cols), offset))