"""Star-schema join collapse (JoinTransform analog) — see catalog/star.py.

Reference parity: `JoinTransform` (SURVEY.md §2 `[U]`): multi-way joins that
conform to the declared `StarSchema` are eliminated — the Druid index is
pre-joined/denormalized, so dimension-table columns map through to fact-table
dimensions, guarded by declared functional dependencies (SURVEY.md §7 hard
part #6: this is where silent wrong answers come from, so every elimination
is validated against the declared join graph before collapsing).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..config import SessionConfig
from . import logical as L
from .transforms import RewriteError


def collapse_star_join(node: L.Join, catalog, cfg: SessionConfig) -> L.LogicalPlan:
    """Collapse a Join subtree over a star schema into a single Scan of the
    fact datasource, remapping dimension-table columns.  Implemented in
    catalog/star.py's StarSchema.collapse — this wrapper resolves the schema
    from the catalog."""
    if not cfg.enable_join_collapse:
        raise RewriteError("join collapse disabled by config")
    from ..catalog.star import try_collapse_join

    result = try_collapse_join(node, catalog)
    if result is None:
        raise RewriteError(
            "join does not conform to any registered star schema "
            "(declare one in register_table(star_schema=...))"
        )
    return result
