"""Scalar expression tree + compiler to jittable XLA element-wise functions.

Reference parity: this is the TPU-native replacement for the reference's
JS-codegen layer (`JSCodeGenerator`, `JSExpr` — SURVEY.md §2/L0, expected
`org/sparklinedata/druid/jscodegen/` `[U]`).  The reference widened pushdown
by compiling Catalyst expressions (arithmetic, casts, date/string functions)
into JavaScript snippets embedded in Druid query JSON, interpreted row-by-row
by Druid's Rhino engine.  We compile the same expression class into *fused XLA
element-wise ops* over device-resident columns — traced once under jit, fused
into the aggregation kernel, zero interpretation cost.

Expressions are also the SQL/DataFrame AST for projections, predicates, and
virtual columns; the planner walks them (plan/transforms.py) to decide
pushability.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

import jax.numpy as jnp
import numpy as np


class Expr:
    def __add__(self, o):
        return BinaryOp("+", self, _lit(o))

    def __radd__(self, o):
        return BinaryOp("+", _lit(o), self)

    def __sub__(self, o):
        return BinaryOp("-", self, _lit(o))

    def __rsub__(self, o):
        return BinaryOp("-", _lit(o), self)

    def __mul__(self, o):
        return BinaryOp("*", self, _lit(o))

    def __rmul__(self, o):
        return BinaryOp("*", _lit(o), self)

    def __truediv__(self, o):
        return BinaryOp("/", self, _lit(o))

    def __rtruediv__(self, o):
        return BinaryOp("/", _lit(o), self)

    def __neg__(self):
        return UnaryOp("-", self)

    def __gt__(self, o):
        return Comparison(">", self, _lit(o))

    def __ge__(self, o):
        return Comparison(">=", self, _lit(o))

    def __lt__(self, o):
        return Comparison("<", self, _lit(o))

    def __le__(self, o):
        return Comparison("<=", self, _lit(o))

    def eq(self, o):
        return Comparison("==", self, _lit(o))

    def ne(self, o):
        return Comparison("!=", self, _lit(o))

    def and_(self, o):
        return BoolOp("and", (self, _lit(o)))

    def or_(self, o):
        return BoolOp("or", (self, _lit(o)))

    def not_(self):
        return BoolOp("not", (self,))

    # PySpark-style boolean operators for the DataFrame API
    __and__ = and_
    __or__ = or_
    __invert__ = not_

    def isin(self, values):
        return InExpr(self, tuple(values))

    def between(self, lo, hi):
        return BoolOp("and", (Comparison(">=", self, _lit(lo)),
                              Comparison("<=", self, _lit(hi))))

    def columns(self) -> Tuple[str, ...]:
        """All column names referenced (planner uses this for pushability)."""
        out: list = []
        _collect_cols(self, out)
        return tuple(dict.fromkeys(out))


def apply_strfunc(fn: str, args: tuple, s: str):
    """One string-function application over a non-null string — shared by
    the host compile (fallback rows) and the planner's dictionary-space
    filter rewrite (ONE implementation, so the two can never drift)."""
    if fn == "upper":
        return s.upper()
    if fn == "lower":
        return s.lower()
    if fn == "substr":
        start = int(args[0]) - 1  # SQL is 1-based
        if len(args) > 1:
            return s[start : start + int(args[1])]
        return s[start:]
    if fn == "concat":
        return f"{args[0]}{s}{args[1]}"
    if fn == "length":
        return len(s)
    # Druid / standard SQL TRIM strips SPACES only (chars=' '), not all
    # whitespace — a trailing tab survives
    if fn == "trim":
        return s.strip(" ")
    if fn == "ltrim":
        return s.lstrip(" ")
    if fn == "rtrim":
        return s.rstrip(" ")
    if fn == "replace":
        return s.replace(str(args[0]), str(args[1]))
    raise ValueError(f"unsupported string fn {fn!r}")


def map_expr(e, fn):
    """Bottom-up structural map over an expression tree: children are
    mapped first, the node is rebuilt, then `fn` transforms the result.
    THE one place that knows how Expr dataclasses hold children (direct
    Expr fields and tuples of Exprs) — rewriters use this instead of
    hand-rolling the dataclass walk (review finding: three divergent
    copies risk silently skipping nodes as the Expr vocabulary grows).
    Opaque fields (e.g. a subquery's `stmt`) are not descended."""
    if not isinstance(e, Expr):
        return e
    kw = {}
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, Expr):
            kw[f.name] = map_expr(v, fn)
        elif isinstance(v, tuple) and v and isinstance(v[0], Expr):
            kw[f.name] = tuple(map_expr(x, fn) for x in v)
    return fn(dataclasses.replace(e, **kw) if kw else e)


def any_node(e, pred) -> bool:
    """True when `pred` holds for any node of an expression tree — the
    read-only sibling of `map_expr` (the only other place allowed to know
    how Expr dataclasses hold children); "contains X" predicates build on
    this instead of hand-rolling the dataclass walk."""
    if not isinstance(e, Expr):
        return False
    if pred(e):
        return True
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, Expr):
            if any_node(v, pred):
                return True
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, Expr) and any_node(x, pred):
                    return True
    return False


def _collect_cols(e: Expr, out: list):
    if isinstance(e, Col):
        out.append(e.name)
    refs = getattr(e, "outer_refs", None)
    if refs:
        # a correlated subquery reads OUTER columns: the outer plan must
        # decode (and the planner must see) their bare names
        out.extend(_outer_bare(refs))
    for f in dataclasses.fields(e):  # type: ignore[arg-type]
        v = getattr(e, f.name)
        if isinstance(v, Expr):
            _collect_cols(v, out)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, Expr):
                    _collect_cols(x, out)


def _lit(x) -> Expr:
    return x if isinstance(x, Expr) else Literal(x)


@dataclasses.dataclass(frozen=True, eq=True)
class Col(Expr):
    name: str

    def __str__(self):
        return self.name


@dataclasses.dataclass(frozen=True, eq=True)
class Literal(Expr):
    value: Any

    def __str__(self):
        # str() output must RE-PARSE under the SQL expression grammar (the
        # wire form of expression post-aggs / virtual columns): repr(None)
        # would read back as a column named "None"
        if self.value is None:
            return "null"
        return repr(self.value)


@dataclasses.dataclass(frozen=True, eq=True)
class BinaryOp(Expr):
    op: str  # + - * / % pow
    left: Expr
    right: Expr

    def __str__(self):
        if self.op == "pow":
            # Druid's native expression spelling — round-trips through the
            # wire expression grammar (which re-parses as SQL POW(a, b))
            return f"pow({self.left}, {self.right})"
        return f"({self.left} {self.op} {self.right})"


@dataclasses.dataclass(frozen=True, eq=True)
class UnaryOp(Expr):
    op: str  # - abs floor ceil sqrt exp ln round
    operand: Expr

    def __str__(self):
        return f"{self.op}({self.operand})"


@dataclasses.dataclass(frozen=True, eq=True)
class Comparison(Expr):
    op: str  # > >= < <= == !=
    left: Expr
    right: Expr

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclasses.dataclass(frozen=True, eq=True)
class BoolOp(Expr):
    op: str  # and or not
    operands: Tuple[Expr, ...]

    def __str__(self):
        if self.op == "not":
            return f"not({self.operands[0]})"
        return "(" + f" {self.op} ".join(str(o) for o in self.operands) + ")"


@dataclasses.dataclass(frozen=True, eq=True)
class InExpr(Expr):
    operand: Expr
    values: Tuple[Any, ...]

    def __str__(self):
        return f"({self.operand} in {self.values})"


def _outer_bare(outer_refs) -> tuple:
    """Bare outer column names a correlated subquery reads (its outer refs
    are QUALIFIED, `alias.col`; the host frames carry bare names)."""
    return tuple(q.split(".", 1)[1] for q in (outer_refs or ()))


@dataclasses.dataclass(frozen=True, eq=True)
class InSubquery(Expr):
    """`x IN (SELECT c FROM ...)` — a semi-join.  The device planner
    rejects it at plan time (RewriteError -> host fallback); the fallback
    resolves the inner statement to a value set before evaluation, with
    three-valued NOT IN semantics when the set contains NULLs.  `stmt` is
    a sql.parser.SelectStmt (typed Any to keep plan/ independent of the
    SQL layer).  `outer_refs` (qualified outer columns) marks CORRELATION:
    the fallback then evaluates per distinct outer binding."""

    operand: Expr
    stmt: Any
    aliases: Any = None  # alias->table mapping captured at parse time
    outer_refs: Any = None  # tuple of "alias.col" correlation references

    def columns(self):
        return tuple(self.operand.columns()) + _outer_bare(self.outer_refs)

    def __str__(self):
        # the id disambiguates DIFFERENT subqueries under the analyzer's
        # string-keyed aggregate dedup (two distinct correlated subqueries
        # must not collapse into one aggregate); subqueries never travel
        # on the wire, so re-parseability does not apply
        return f"({self.operand} IN (<subquery#{id(self.stmt):x}>))"


@dataclasses.dataclass(frozen=True, eq=True)
class ExistsSubquery(Expr):
    """`EXISTS (SELECT ...)` — resolved by the host fallback to a truth
    value: constant when uncorrelated, per distinct outer binding when
    `outer_refs` is set."""

    stmt: Any
    aliases: Any = None
    outer_refs: Any = None

    def columns(self):
        return _outer_bare(self.outer_refs)

    def __str__(self):
        return f"EXISTS(<subquery#{id(self.stmt):x}>)"


@dataclasses.dataclass(frozen=True, eq=True)
class ScalarSubquery(Expr):
    """`(SELECT agg FROM ...)` in expression position — resolved by the
    host fallback to a Literal (uncorrelated) or a per-row value column
    (correlated; one column; zero rows -> NULL)."""

    stmt: Any
    aliases: Any = None
    outer_refs: Any = None

    def columns(self):
        return _outer_bare(self.outer_refs)

    def __str__(self):
        return f"(<scalar subquery#{id(self.stmt):x}>)"


@dataclasses.dataclass(frozen=True, eq=True)
class IfExpr(Expr):
    cond: Expr
    then: Expr
    otherwise: Expr

    def __str__(self):
        return f"if({self.cond}, {self.then}, {self.otherwise})"


@dataclasses.dataclass(frozen=True, eq=True)
class Cast(Expr):
    operand: Expr
    to: str  # "double" | "long" | "bool"

    def __str__(self):
        return f"cast({self.operand} as {self.to})"


@dataclasses.dataclass(frozen=True, eq=True)
class TimeBucket(Expr):
    """floor(__time to granularity) — the expression behind Timeseries
    bucketing and GROUP BY date_trunc.  In a GROUP BY position the planner
    turns it into a time DimensionSpec (bucketing via host-computed boundary
    searchsorted, exact for calendar granularities); on the row path it
    compiles to int64 arithmetic, which requires a fixed period."""

    operand: Expr
    granularity: str  # "hour", "month", ISO period, ...

    @property
    def period_ms(self) -> Optional[int]:
        from ..utils.granularity import granularity_period_ms

        return granularity_period_ms(self.granularity)

    def __str__(self):
        return f"time_floor({self.operand}, {self.granularity})"


@dataclasses.dataclass(frozen=True, eq=True)
class LikeExpr(Expr):
    """SQL LIKE — translatable to a dictionary-evaluated filter on dims."""

    operand: Expr
    pattern: str
    negated: bool = False

    def __str__(self):
        return f"({self.operand} {'NOT ' if self.negated else ''}LIKE {self.pattern!r})"


@dataclasses.dataclass(frozen=True, eq=True)
class StrFunc(Expr):
    """String function over a dimension (SUBSTR/UPPER/LOWER) — only legal in
    GROUP BY / filter positions, where it becomes a host-side dictionary
    rewrite (models/dimensions.py extraction fns); never row-path device code."""

    fn: str  # substr | upper | lower
    operand: Expr
    args: Tuple[Any, ...] = ()

    def __str__(self):
        a = ", ".join(str(x) for x in (self.operand,) + self.args)
        return f"{self.fn}({a})"


@dataclasses.dataclass(frozen=True, eq=True)
class TimeExtract(Expr):
    """EXTRACT(field FROM time) — YEAR/MONTH/DAY/HOUR...; on the row path
    compiles to civil-calendar integer arithmetic on the int64 ms column."""

    field: str  # year | month | day | hour | minute
    operand: Expr

    def __str__(self):
        return f"extract({self.field} from {self.operand})"


@dataclasses.dataclass(frozen=True, eq=True)
class AggRef(Expr):
    """Reference to an aggregation output by name — appears in HAVING and in
    post-aggregation expressions (`sum_x / count_x`), never on the row path."""

    name: str

    def __str__(self):
        return f"agg:{self.name}"


_UNARY = {
    "-": lambda x: -x,
    "abs": jnp.abs,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "sqrt": jnp.sqrt,
    "exp": jnp.exp,
    "ln": jnp.log,
    # SQL ROUND is half-away-from-zero; jnp.round is half-to-even
    "round": lambda x: jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5),
}

_BINARY = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "%": lambda a, b: a % b,
    "pow": lambda a, b: a ** b,
}

_CMP = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


_FLIP = {">": "<", ">=": "<=", "<": ">", "<=": ">=", "==": "==", "!=": "!="}


def coerce_str_literal(s: str) -> Optional[float]:
    """Numeric value for a string literal compared against a numeric/time
    column: plain number, or ISO date/timestamp -> epoch ms.  None when
    neither parse applies."""
    try:
        return float(s)
    except (TypeError, ValueError):
        pass
    try:
        return float(np.datetime64(s, "ms").astype(np.int64))
    except ValueError:
        return None


def _is_string_dict(dicts, name: str) -> bool:
    d = dicts.get(name) if dicts else None
    return d is not None and d.numeric_values is None


def _compile_str_comparison(e: "Comparison", dicts):
    """Handle `Col <op> 'literal'` (either orientation).  Returns a compiled
    fn, a numeric rewrite of the expression, or None when no string literal is
    involved.  Raises on unresolvable string comparisons — the round-1 bug was
    this case silently evaluating all-False (VERDICT r1 weak #1)."""
    if isinstance(e.right, Literal) and isinstance(e.left, Col):
        name, litv, op = e.left.name, e.right.value, e.op
    elif isinstance(e.left, Literal) and isinstance(e.right, Col):
        name, litv, op = e.right.name, e.left.value, _FLIP[e.op]
    else:
        return None
    if not isinstance(litv, str):
        return None
    d = dicts.get(name) if dicts else None
    if d is not None and d.numeric_values is None:
        # Sorted string dictionary: codes are order-preserving ranks
        # (catalog/segment.py DimensionDict), so every comparison translates
        # to integer code space; null codes (-1) never satisfy any predicate.
        if op == "==":
            code = d.code_of(litv)
            if code is None:
                return lambda cols: jnp.zeros(jnp.shape(cols[name]), jnp.bool_)
            return lambda cols: cols[name] == jnp.int32(code)
        if op == "!=":
            code = d.code_of(litv)
            if code is None:
                return lambda cols: cols[name] >= 0
            return lambda cols: (cols[name] >= 0) & (
                cols[name] != jnp.int32(code)
            )
        vals = np.asarray(d.values, dtype=str)
        if op in (">", ">="):
            lo = int(np.searchsorted(vals, litv, side="right" if op == ">" else "left"))
            return lambda cols: cols[name] >= jnp.int32(lo)
        hi = int(np.searchsorted(vals, litv, side="left" if op == "<" else "right")) - 1
        if hi < 0:
            return lambda cols: jnp.zeros(jnp.shape(cols[name]), jnp.bool_)
        return lambda cols: (cols[name] >= 0) & (cols[name] <= jnp.int32(hi))
    # Numeric-dictionary / metric / time column vs string literal: coerce the
    # literal (numeric string or ISO date) and rewrite as a numeric compare —
    # expression columns arrive value-decoded via DecodedView.
    v = coerce_str_literal(litv)
    if v is None:
        raise ValueError(
            f"cannot compare column {name!r} against string literal {litv!r}: "
            "not a dictionary dimension and the literal is neither numeric "
            "nor an ISO date"
        )
    return Comparison(op, Col(name), Literal(int(v) if v == int(v) else v))


def _null_guarded(base, name: str):
    """AND the compiled comparison with `raw codes >= 0` for a numeric-dict
    dimension: DecodedView decodes null codes (-1) to -1, which would
    otherwise satisfy <, <=, != predicates (SQL: NULL compare excludes)."""

    def fn(cols, base=base, name=name):
        m = base(cols)
        raw = getattr(cols, "raw", None)
        if raw is not None:
            m = m & (raw(name) >= 0)
        return m

    return fn


def _compile_comparison(e: "Comparison", dicts, raw_strings: bool = False):
    """Numeric/generic comparison compile: f32 columns vs f64 literals get
    exact double semantics via host-adjusted thresholds (utils/floatcmp);
    everything else is an elementwise compare."""

    def _num_lit(v):
        return isinstance(v, (int, float)) and not isinstance(v, bool)

    # SQL: an ordering comparison with NULL is UNKNOWN -> matches nothing
    # (a NULL literal arrives from e.g. a zero-row scalar subquery).
    # Equality stays: `== Literal(None)` is the IS NULL encoding.
    if e.op in (">", ">=", "<", "<=") and any(
        isinstance(s, Literal) and s.value is None for s in (e.left, e.right)
    ):
        other = e.right if (
            isinstance(e.left, Literal) and e.left.value is None
        ) else e.left
        of = compile_expr(other, dicts, raw_strings=raw_strings)

        def never(cols, of=of):
            if raw_strings:
                return np.zeros(np.shape(np.asarray(of(cols))), dtype=bool)
            return jnp.zeros(jnp.shape(jnp.asarray(of(cols))), jnp.bool_)

        return never

    if (
        raw_strings
        and e.op in ("==", "!=")
        and any(
            isinstance(s, Literal) and s.value is None
            for s in (e.left, e.right)
        )
    ):
        # IS [NOT] NULL over HOST frames: nulls appear as None (object
        # columns) or NaN (decoded metrics) — a plain object `!=` would
        # see NaN != None as True and mis-route COALESCE branches
        other = e.right if (
            isinstance(e.left, Literal) and e.left.value is None
        ) else e.left
        of = compile_expr(other, dicts, raw_strings=True)
        eq = e.op == "=="

        def isnull_host(cols, of=of, eq=eq):
            import pandas as pd

            isn = np.asarray(pd.isna(np.asarray(of(cols))))
            return isn if eq else ~isn

        return isnull_host

    lit_side = None
    if isinstance(e.right, Literal) and _num_lit(e.right.value):
        lit_side, lit_val, other = "right", e.right.value, e.left
    elif isinstance(e.left, Literal) and _num_lit(e.left.value):
        lit_side, lit_val, other = "left", e.left.value, e.right
    if lit_side is not None and e.op in (">", ">=", "<", "<=", "==", "!="):
        from ..utils.floatcmp import f32_adjusted_compare

        of = compile_expr(other, dicts, raw_strings=raw_strings)
        op_name = e.op
        if lit_side == "left" and op_name in (">", ">=", "<", "<="):
            op_name = _FLIP[op_name]
        # all threshold adjustment precomputed at compile time
        cmp32 = f32_adjusted_compare(op_name, float(lit_val))

        def cmp_fn(cols, of=of, op_name=op_name, lit_val=lit_val, cmp32=cmp32):
            if raw_strings:
                # host mode: decoded dimension columns are object dtype
                # (python ints with None for null); SQL three-valued logic
                # — null/non-numeric never matches a numeric comparison
                xo = np.asarray(of(cols))
                if xo.dtype.kind == "O":
                    valid = np.array(
                        [
                            isinstance(v, (int, float))
                            and not isinstance(v, bool)
                            and not (isinstance(v, float) and np.isnan(v))
                            for v in xo
                        ],
                        dtype=bool,
                    )
                    res = np.zeros(xo.shape, dtype=bool)
                    if valid.any():
                        res[valid] = _CMP[op_name](
                            xo[valid].astype(np.float64), lit_val
                        )
                    return res
            x = jnp.asarray(of(cols))
            if x.dtype == jnp.float32:
                return cmp32(x)
            return _CMP[op_name](x, lit_val)

        return cmp_fn
    lf = compile_expr(e.left, dicts, raw_strings=raw_strings)
    rf = compile_expr(e.right, dicts, raw_strings=raw_strings)
    op = _CMP[e.op]
    if raw_strings:
        # host mode: a string literal compared against a NUMERIC column
        # (int64 time ms vs an ISO date string is the common case) coerces
        # at evaluation time — the device path does this in the filter
        # translation, which the fallback executor bypasses
        str_lit = None
        if isinstance(e.right, Literal) and isinstance(e.right.value, str):
            str_lit, of, flip = e.right.value, lf, False
        elif isinstance(e.left, Literal) and isinstance(e.left.value, str):
            str_lit, of, flip = e.left.value, rf, True

        if str_lit is not None:
            num = coerce_str_literal(str_lit)

            def cmp_mixed(cols, of=of, num=num, flip=flip):
                x = np.asarray(of(cols))
                if x.dtype.kind in ("i", "u", "f") and num is not None:
                    a, b = (num, x) if flip else (x, num)
                    return op(a, b)
                if x.dtype.kind == "O":
                    # SQL three-valued logic: NULL <op> literal is not a
                    # match; also keeps numpy from comparing None/NaN
                    # against str
                    valid = np.array(
                        [isinstance(v, str) for v in x], dtype=bool
                    )
                    res = np.zeros(x.shape, dtype=bool)
                    if valid.any():
                        vx = x[valid].astype(str)
                        a, b = (str_lit, vx) if flip else (vx, str_lit)
                        res[valid] = op(a, b)
                    return res
                a, b = (str_lit, x) if flip else (x, str_lit)
                return op(a, b)

            return cmp_mixed
    return lambda cols: op(lf(cols), rf(cols))


def compile_expr(
    e: Expr,
    dicts: Optional[Mapping[str, Any]] = None,
    *,
    raw_strings: bool = False,
) -> Callable[[Mapping[str, Any]], Any]:
    """Compile an Expr tree into `fn(columns_dict) -> array`, jit-traceable.

    The returned function is pure and shape-preserving: it maps a dict of
    row-aligned column arrays to one array.  XLA fuses the whole tree into the
    consuming kernel.

    `dicts` (dimension name -> DimensionDict) enables string-literal
    comparisons over dictionary-encoded dimensions: equality/ranges translate
    into integer code space at compile time (sorted dicts make codes
    order-preserving).  Without it, any string comparison raises — never the
    silent all-False of round 1 (VERDICT r1 weak #1).

    `raw_strings=True` is the HOST-side mode (api._eval_host): columns hold
    decoded numpy string/object arrays, so string comparisons use plain
    elementwise numpy semantics instead of code-space translation.  Never use
    it on the device path — device dimension columns are int32 codes.
    """
    if isinstance(e, Col):
        name = e.name
        return lambda cols: cols[name]
    if isinstance(e, Literal):
        v = e.value
        return lambda cols: v
    if isinstance(e, BinaryOp):
        lf = compile_expr(e.left, dicts, raw_strings=raw_strings)
        rf = compile_expr(e.right, dicts, raw_strings=raw_strings)
        op = _BINARY[e.op]
        return lambda cols: op(lf(cols), rf(cols))
    if isinstance(e, UnaryOp):
        f = compile_expr(e.operand, dicts, raw_strings=raw_strings)
        op = _UNARY[e.op]
        return lambda cols: op(f(cols))
    if isinstance(e, Comparison):
        if not raw_strings:
            sc = _compile_str_comparison(e, dicts)
            if isinstance(sc, Comparison):
                e = sc  # coerced to a numeric compare; fall through
            elif sc is not None:
                return sc
            for side in (e.left, e.right):
                if isinstance(side, Literal) and isinstance(side.value, str):
                    raise ValueError(
                        f"unresolvable string comparison {e}: string literals "
                        "require a bare dictionary-dimension column on the "
                        "other side (pass `dicts` from the datasource)"
                    )
            for side in (e.left, e.right):
                if isinstance(side, Col) and _is_string_dict(dicts, side.name):
                    raise ValueError(
                        f"comparison {e} reads string-dictionary column "
                        f"{side.name!r} in value position; only `dim <op> "
                        "'literal'` comparisons are translatable to code space"
                    )
            # numeric-dict dims decode nulls to -1 (DecodedView); a bare
            # `dim <op> literal` compare must not let null rows satisfy the
            # predicate (SQL: NULL compare -> NULL -> excluded)
            guard_col = None
            for side, other in ((e.left, e.right), (e.right, e.left)):
                if (
                    isinstance(side, Col)
                    and isinstance(other, Literal)
                    and dicts
                    and side.name in dicts
                    and dicts[side.name].numeric_values is not None
                ):
                    guard_col = side.name
            if guard_col is not None:
                base = _compile_comparison(e, dicts)
                return _null_guarded(base, guard_col)
        return _compile_comparison(e, dicts, raw_strings=raw_strings)
    if isinstance(e, BoolOp):
        fs = [compile_expr(o, dicts, raw_strings=raw_strings) for o in e.operands]
        if e.op == "not":
            f0 = fs[0]
            return lambda cols: jnp.logical_not(f0(cols))
        if e.op == "and":
            return lambda cols: _fold(jnp.logical_and, fs, cols)
        return lambda cols: _fold(jnp.logical_or, fs, cols)
    if isinstance(e, InExpr):
        if any(isinstance(v, str) for v in e.values):
            if raw_strings:
                # host mode: decoded string columns, plain numpy membership
                f = compile_expr(e.operand, dicts, raw_strings=True)
                vals = list(e.values)
                return lambda cols: np.isin(
                    np.asarray(f(cols), dtype=object), vals
                )
            if not isinstance(e.operand, Col):
                raise ValueError(
                    f"IN over string values requires a bare column: {e}"
                )
            name = e.operand.name
            if _is_string_dict(dicts, name):
                d = dicts[name]
                codes = np.array(
                    [
                        c
                        for c in (d.code_of(v) for v in e.values)
                        if c is not None
                    ],
                    dtype=np.int32,
                )
                if len(codes) == 0:
                    return lambda cols: jnp.zeros(
                        jnp.shape(cols[name]), jnp.bool_
                    )
                return lambda cols: jnp.isin(cols[name], codes)
            coerced = []
            for v in e.values:
                c = coerce_str_literal(v) if isinstance(v, str) else float(v)
                if c is None:
                    raise ValueError(
                        f"IN value {v!r} over non-dictionary column {name!r} "
                        "is neither numeric nor an ISO date"
                    )
                coerced.append(c)
            vals = np.asarray(coerced)
            vals = vals.astype(np.int64) if (vals == vals.astype(np.int64)).all() else vals
            return lambda cols: jnp.isin(jnp.asarray(cols[name]), vals)
        f = compile_expr(e.operand, dicts, raw_strings=raw_strings)
        if raw_strings:
            # host mode: decoded columns may be object dtype (numeric
            # dictionaries decode to python ints; nulls are None) — plain
            # numpy membership, no JAX array coercion
            # NaN values can never match (np.isin semantics: NaN != NaN);
            # dropping them up front lets the hash path below stay exact
            values = [
                v
                for v in e.values
                if not (isinstance(v, float) and v != v)
            ]

            def host_in(cols, f=f, values=values):
                import pandas as pd

                x = np.asarray(f(cols))
                # pandas isin is hash-based O(n+m) for every dtype; np.isin
                # degrades to a per-pair scan on object arrays (measured
                # 15.8s for 100K rows x 21K values on a q18-class IN)
                return pd.Series(x).isin(values).to_numpy()

            return host_in
        vals = np.asarray(e.values)
        return lambda cols: jnp.isin(f(cols), vals)
    if isinstance(e, IfExpr):
        cf = compile_expr(e.cond, dicts, raw_strings=raw_strings)
        tf = compile_expr(e.then, dicts, raw_strings=raw_strings)
        of = compile_expr(e.otherwise, dicts, raw_strings=raw_strings)
        if raw_strings:
            # host mode: branches may be OBJECT arrays (decoded strings /
            # None) that jnp.where cannot interpret
            def host_if(cols, cf=cf, tf=tf, of=of):
                c = np.asarray(cf(cols)).astype(bool)
                t, o = np.asarray(tf(cols)), np.asarray(of(cols))
                t, o, _ = np.broadcast_arrays(t, o, c)
                return np.where(c, t, o)

            return host_if
        return lambda cols: jnp.where(cf(cols), tf(cols), of(cols))
    if isinstance(e, Cast):
        f = compile_expr(e.operand, dicts, raw_strings=raw_strings)
        dt = {"double": jnp.float32, "long": jnp.int32, "bool": jnp.bool_}[e.to]
        return lambda cols: jnp.asarray(f(cols)).astype(dt)
    if isinstance(e, TimeBucket):
        f, p = compile_expr(e.operand, dicts, raw_strings=raw_strings), e.period_ms
        if p is None:
            if raw_strings:
                # host mode (fallback executor): calendar truncation is
                # exact via numpy month arithmetic — handles month/quarter/
                # year AND ISO calendar periods (P3M, P1Y), same helper the
                # device bucket tables use
                from ..utils.granularity import _iso_calendar_months

                k = _iso_calendar_months(e.granularity)

                def cal_trunc(cols, f=f, k=k):
                    t = np.asarray(f(cols)).astype("datetime64[ms]")
                    months = t.astype("datetime64[M]").astype(np.int64)
                    b = ((months // k) * k).astype("datetime64[M]")
                    return b.astype("datetime64[ms]").astype(np.int64)

                return cal_trunc
            raise ValueError(
                f"calendar granularity {e.granularity!r} has no fixed period; "
                "only legal in GROUP BY position (dimension bucketing)"
            )
        # graftlint: disable=dtype-x64 -- time bucketing is int64 ms by contract
        return lambda cols: (jnp.asarray(f(cols)) // p * p).astype(jnp.int64)
    if isinstance(e, TimeExtract):
        if e.field not in _EXTRACT_FIELDS:
            raise ValueError(
                f"EXTRACT field {e.field!r}; supported: {sorted(_EXTRACT_FIELDS)}"
            )
        f, field = compile_expr(e.operand, dicts, raw_strings=raw_strings), e.field
        return lambda cols: _time_extract(jnp.asarray(f(cols)), field)
    if isinstance(e, LikeExpr):
        if raw_strings:
            import re as _re

            from ..ops.filters import _like_to_regex

            rx = _re.compile(_like_to_regex(e.pattern))
            f = compile_expr(e.operand, dicts, raw_strings=True)
            neg = e.negated

            def like_host(cols, f=f, rx=rx, neg=neg):
                vals = np.asarray(f(cols), dtype=object)
                m = np.array(
                    [v is not None and bool(rx.search(str(v))) for v in vals],
                    dtype=bool,
                )
                if not neg:
                    return m
                # SQL three-valued: NULL NOT LIKE p is NULL -> excluded,
                # matching the device path below
                nn = np.array([v is not None for v in vals], dtype=bool)
                return nn & ~m

            return like_host
        if isinstance(e.operand, Col) and _is_string_dict(
            dicts, e.operand.name
        ):
            # shared dictionary->code-set translation (ops/filters.py):
            # pattern runs over the dictionary once at compile time; the
            # device sees an int32 code-set membership test
            from ..ops.filters import like_match_codes

            codes = like_match_codes(dicts[e.operand.name], e.pattern)
            name, neg = e.operand.name, e.negated
            if len(codes) == 0:
                if neg:  # NOT LIKE matching nothing = all non-null rows
                    return lambda cols: cols[name] >= 0
                return lambda cols: jnp.zeros(
                    jnp.shape(cols[name]), jnp.bool_
                )
            if neg:  # SQL: NULL NOT LIKE p is NULL -> excluded
                return lambda cols: (cols[name] >= 0) & ~jnp.isin(
                    cols[name], codes
                )
            return lambda cols: jnp.isin(cols[name], codes)
        raise ValueError(
            "LIKE over a non-dictionary operand cannot compile to a device "
            "row expression (dictionary dimensions translate to code sets)"
        )
    if isinstance(e, StrFunc):
        if raw_strings and e.fn != "lookup":
            # host (fallback) mode evaluates string functions directly on
            # the decoded object column — nulls stay NULL
            f = compile_expr(e.operand, dicts, raw_strings=True)

            def str_host(cols, f=f, fn=e.fn, a=e.args):
                import pandas as pd

                def ap(v):
                    if pd.isna(v):
                        return None
                    return apply_strfunc(
                        fn, a, v if isinstance(v, str) else str(v)
                    )

                x = np.asarray(f(cols))
                return np.array([ap(v) for v in x], dtype=object)

            return str_host
        raise ValueError(
            "StrFunc is dictionary-evaluated (filter / GROUP BY "
            "position only); it cannot compile to a device row expression"
        )
    if isinstance(e, AggRef):
        name = e.name
        return lambda cols: cols[name]
    raise TypeError(f"cannot compile expression {e!r}")


def _fold(op, fs, cols):
    acc = fs[0](cols)
    for f in fs[1:]:
        acc = op(acc, f(cols))
    return acc


_EXTRACT_FIELDS = {"year", "month", "day", "hour", "minute", "second"}


def _time_extract(t_ms: Any, field: str):
    """Civil-calendar field from int64 epoch-ms — pure integer ops (vector-
    friendly); days-to-(y,m,d) via the standard era/cycle decomposition."""
    if field == "second":
        return ((t_ms // 1_000) % 60).astype(jnp.int32)
    if field == "minute":
        return ((t_ms // 60_000) % 60).astype(jnp.int32)
    if field == "hour":
        return ((t_ms // 3_600_000) % 24).astype(jnp.int32)
    days = t_ms // 86_400_000
    z = days + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = y + (m <= 2)
    if field == "year":
        return y.astype(jnp.int32)
    if field == "month":
        return m.astype(jnp.int32)
    if field == "day":
        return d.astype(jnp.int32)
    raise ValueError(f"EXTRACT field {field!r}")


def col(name: str) -> Col:
    return Col(name)


def lit(v) -> Literal:
    return Literal(v)
