"""Scalar expression tree + compiler to jittable XLA element-wise functions.

Reference parity: this is the TPU-native replacement for the reference's
JS-codegen layer (`JSCodeGenerator`, `JSExpr` — SURVEY.md §2/L0, expected
`org/sparklinedata/druid/jscodegen/` `[U]`).  The reference widened pushdown
by compiling Catalyst expressions (arithmetic, casts, date/string functions)
into JavaScript snippets embedded in Druid query JSON, interpreted row-by-row
by Druid's Rhino engine.  We compile the same expression class into *fused XLA
element-wise ops* over device-resident columns — traced once under jit, fused
into the aggregation kernel, zero interpretation cost.

Expressions are also the SQL/DataFrame AST for projections, predicates, and
virtual columns; the planner walks them (plan/transforms.py) to decide
pushability.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

import jax.numpy as jnp
import numpy as np


class Expr:
    def __add__(self, o):
        return BinaryOp("+", self, _lit(o))

    def __radd__(self, o):
        return BinaryOp("+", _lit(o), self)

    def __sub__(self, o):
        return BinaryOp("-", self, _lit(o))

    def __rsub__(self, o):
        return BinaryOp("-", _lit(o), self)

    def __mul__(self, o):
        return BinaryOp("*", self, _lit(o))

    def __rmul__(self, o):
        return BinaryOp("*", _lit(o), self)

    def __truediv__(self, o):
        return BinaryOp("/", self, _lit(o))

    def __rtruediv__(self, o):
        return BinaryOp("/", _lit(o), self)

    def __neg__(self):
        return UnaryOp("-", self)

    def __gt__(self, o):
        return Comparison(">", self, _lit(o))

    def __ge__(self, o):
        return Comparison(">=", self, _lit(o))

    def __lt__(self, o):
        return Comparison("<", self, _lit(o))

    def __le__(self, o):
        return Comparison("<=", self, _lit(o))

    def eq(self, o):
        return Comparison("==", self, _lit(o))

    def ne(self, o):
        return Comparison("!=", self, _lit(o))

    def and_(self, o):
        return BoolOp("and", (self, _lit(o)))

    def or_(self, o):
        return BoolOp("or", (self, _lit(o)))

    def not_(self):
        return BoolOp("not", (self,))

    def isin(self, values):
        return InExpr(self, tuple(values))

    def between(self, lo, hi):
        return BoolOp("and", (Comparison(">=", self, _lit(lo)),
                              Comparison("<=", self, _lit(hi))))

    def columns(self) -> Tuple[str, ...]:
        """All column names referenced (planner uses this for pushability)."""
        out: list = []
        _collect_cols(self, out)
        return tuple(dict.fromkeys(out))


def _collect_cols(e: Expr, out: list):
    if isinstance(e, Col):
        out.append(e.name)
    for f in dataclasses.fields(e):  # type: ignore[arg-type]
        v = getattr(e, f.name)
        if isinstance(v, Expr):
            _collect_cols(v, out)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, Expr):
                    _collect_cols(x, out)


def _lit(x) -> Expr:
    return x if isinstance(x, Expr) else Literal(x)


@dataclasses.dataclass(frozen=True, eq=True)
class Col(Expr):
    name: str

    def __str__(self):
        return self.name


@dataclasses.dataclass(frozen=True, eq=True)
class Literal(Expr):
    value: Any

    def __str__(self):
        return repr(self.value)


@dataclasses.dataclass(frozen=True, eq=True)
class BinaryOp(Expr):
    op: str  # + - * / %
    left: Expr
    right: Expr

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclasses.dataclass(frozen=True, eq=True)
class UnaryOp(Expr):
    op: str  # - abs floor ceil sqrt exp ln
    operand: Expr

    def __str__(self):
        return f"{self.op}({self.operand})"


@dataclasses.dataclass(frozen=True, eq=True)
class Comparison(Expr):
    op: str  # > >= < <= == !=
    left: Expr
    right: Expr

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclasses.dataclass(frozen=True, eq=True)
class BoolOp(Expr):
    op: str  # and or not
    operands: Tuple[Expr, ...]

    def __str__(self):
        if self.op == "not":
            return f"not({self.operands[0]})"
        return "(" + f" {self.op} ".join(str(o) for o in self.operands) + ")"


@dataclasses.dataclass(frozen=True, eq=True)
class InExpr(Expr):
    operand: Expr
    values: Tuple[Any, ...]

    def __str__(self):
        return f"({self.operand} in {self.values})"


@dataclasses.dataclass(frozen=True, eq=True)
class IfExpr(Expr):
    cond: Expr
    then: Expr
    otherwise: Expr

    def __str__(self):
        return f"if({self.cond}, {self.then}, {self.otherwise})"


@dataclasses.dataclass(frozen=True, eq=True)
class Cast(Expr):
    operand: Expr
    to: str  # "double" | "long" | "bool"

    def __str__(self):
        return f"cast({self.operand} as {self.to})"


@dataclasses.dataclass(frozen=True, eq=True)
class TimeBucket(Expr):
    """floor(__time to granularity) — device-side int64 arithmetic on the time
    column; the expression behind Timeseries bucketing and GROUP BY
    date_trunc."""

    operand: Expr
    period_ms: int

    def __str__(self):
        return f"time_floor({self.operand}, {self.period_ms}ms)"


@dataclasses.dataclass(frozen=True, eq=True)
class AggRef(Expr):
    """Reference to an aggregation output by name — appears in HAVING and in
    post-aggregation expressions (`sum_x / count_x`), never on the row path."""

    name: str

    def __str__(self):
        return f"agg:{self.name}"


_UNARY = {
    "-": lambda x: -x,
    "abs": jnp.abs,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "sqrt": jnp.sqrt,
    "exp": jnp.exp,
    "ln": jnp.log,
}

_BINARY = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "%": lambda a, b: a % b,
}

_CMP = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def compile_expr(e: Expr) -> Callable[[Mapping[str, Any]], Any]:
    """Compile an Expr tree into `fn(columns_dict) -> array`, jit-traceable.

    The returned function is pure and shape-preserving: it maps a dict of
    row-aligned column arrays to one array.  XLA fuses the whole tree into the
    consuming kernel.
    """
    if isinstance(e, Col):
        name = e.name
        return lambda cols: cols[name]
    if isinstance(e, Literal):
        v = e.value
        return lambda cols: v
    if isinstance(e, BinaryOp):
        lf, rf, op = compile_expr(e.left), compile_expr(e.right), _BINARY[e.op]
        return lambda cols: op(lf(cols), rf(cols))
    if isinstance(e, UnaryOp):
        f, op = compile_expr(e.operand), _UNARY[e.op]
        return lambda cols: op(f(cols))
    if isinstance(e, Comparison):
        lf, rf, op = compile_expr(e.left), compile_expr(e.right), _CMP[e.op]
        return lambda cols: op(lf(cols), rf(cols))
    if isinstance(e, BoolOp):
        fs = [compile_expr(o) for o in e.operands]
        if e.op == "not":
            f0 = fs[0]
            return lambda cols: jnp.logical_not(f0(cols))
        if e.op == "and":
            return lambda cols: _fold(jnp.logical_and, fs, cols)
        return lambda cols: _fold(jnp.logical_or, fs, cols)
    if isinstance(e, InExpr):
        f = compile_expr(e.operand)
        vals = np.asarray(e.values)
        return lambda cols: jnp.isin(f(cols), vals)
    if isinstance(e, IfExpr):
        cf, tf, of = compile_expr(e.cond), compile_expr(e.then), compile_expr(e.otherwise)
        return lambda cols: jnp.where(cf(cols), tf(cols), of(cols))
    if isinstance(e, Cast):
        f = compile_expr(e.operand)
        dt = {"double": jnp.float32, "long": jnp.int32, "bool": jnp.bool_}[e.to]
        return lambda cols: jnp.asarray(f(cols)).astype(dt)
    if isinstance(e, TimeBucket):
        f, p = compile_expr(e.operand), e.period_ms
        return lambda cols: (jnp.asarray(f(cols)) // p).astype(jnp.int64)
    if isinstance(e, AggRef):
        name = e.name
        return lambda cols: cols[name]
    raise TypeError(f"cannot compile expression {e!r}")


def _fold(op, fs, cols):
    acc = fs[0](cols)
    for f in fs[1:]:
        acc = op(acc, f(cols))
    return acc


def col(name: str) -> Col:
    return Col(name)


def lit(v) -> Literal:
    return Literal(v)
