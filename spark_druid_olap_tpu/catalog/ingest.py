"""Ingest: external data -> host columns ready for dictionary encoding.

Reference parity: the reference's data lives in Druid (indexed out-of-band);
its `sourceDataframe` option points at the raw Spark table for parity checks
(SURVEY.md §2 DefaultSource row `[U]`).  Our framework owns ingest: pandas
DataFrames, dicts of numpy arrays, and parquet/CSV paths all normalize to a
dict of row-aligned numpy columns; datetimes become int64 epoch-ms (the Druid
time convention).  The C++ fast path for CSV decode + dictionary encoding
lives in native/ (ctypes), with this pure-python fallback always available.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np


def to_columns(source) -> Dict[str, np.ndarray]:
    if isinstance(source, dict):
        return {k: np.asarray(v) for k, v in source.items()}
    try:
        import pandas as pd

        if isinstance(source, pd.DataFrame):
            return _from_pandas(source)
    except ImportError:  # pragma: no cover
        pass
    if type(source).__module__.split(".")[0] == "pyarrow":
        # pyarrow Table / RecordBatch (SURVEY §7 L-api: Arrow in/out);
        # NaN-as-string-null in the resulting object columns is handled by
        # the dictionary encoder (catalog.segment._is_null).  Non-tabular
        # pyarrow values (Array/Scalar) fall through to the TypeError.
        import pyarrow as pa

        if isinstance(source, (pa.Table, pa.RecordBatch)):
            return _from_pandas(source.to_pandas())
    if isinstance(source, str):
        if source.endswith(".parquet"):
            import pandas as pd

            return _from_pandas(pd.read_parquet(source))
        if source.endswith(".csv"):
            return read_csv_columns(source)
        raise ValueError(f"unsupported source path {source!r}")
    raise TypeError(f"unsupported source type {type(source).__name__}")


def to_columns_encoded(source):
    """source -> (columns, dicts).

    The single dispatch point for ingest (register_table calls only this).
    CSV paths use the native C++ single-pass parse + dictionary-encode when
    the toolchain is available: string columns come back as int32 rank codes
    with their `DimensionDict` in `dicts`.  Any native failure — missing
    toolchain, parse error, ragged rows the stricter C parser rejects —
    falls back to `to_columns` (pandas), which returns no prebuilt
    dictionaries."""
    if isinstance(source, str) and source.endswith(".csv"):
        try:
            from ..native import csv_decode

            return csv_decode.read_csv_encoded(source)
        except Exception:
            pass
    return to_columns(source), {}


def _from_pandas(df) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for c in df.columns:
        s = df[c]
        if str(s.dtype).startswith("datetime64"):
            out[c] = s.values.astype("datetime64[ms]").astype(np.int64)
        elif s.dtype == object or str(s.dtype) in ("string", "category"):
            out[c] = s.astype(object).values
        else:
            out[c] = s.values
    return out


def read_csv_columns(path: str) -> Dict[str, np.ndarray]:
    """CSV -> columns.  Uses the native C++ decoder when built (native/),
    else pandas."""
    try:
        from ..native import csv_decode  # ctypes binding

        return csv_decode.read_csv(path)
    except Exception:
        import pandas as pd

        return _from_pandas(pd.read_csv(path))
