"""Metadata cache — the registry of datasources and their stats.

Reference parity: `DruidMetadataCache` (SURVEY.md §2 metadata-cache row `[U]`,
expected `org/sparklinedata/druid/metadata/`): a process-wide cache of
datasource schemas, segment lists and server assignments, refreshed from the
Druid coordinator and guarded by JVM synchronization.  Locally there is no
remote cluster: datasources are registered (ingested) into the cache, entries
are immutable-by-construction (frozen dataclasses holding arrays nobody
mutates — SURVEY.md §5 race-detection note), and the explicit `clear()`
mirrors the reference's clear-metadata-cache SQL command.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional

from .segment import DataSource
from .star import StarSchemaInfo


class MetadataCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._tables: Dict[str, DataSource] = {}
        self._stars: Dict[str, StarSchemaInfo] = {}
        # query-time lookup tables (Druid lookup extraction): name -> map
        self._lookups: Dict[str, dict] = {}
        # monotonically bumped on every mutation; plan caches key on it so a
        # re-registered table invalidates cached rewrites
        self.version = 0
        # per-datasource segment-set version (ISSUE 6): monotonic across
        # registration, delta appends, and compaction — NEVER reset by a
        # re-register, so result caches keyed on it can't collide across a
        # drop/re-create cycle.  `put` stamps it onto the DataSource.
        self._ds_versions: Dict[str, int] = {}

    def put_lookup(self, name: str, mapping: dict):
        with self._lock:
            self._lookups[name] = dict(mapping)
            self.version += 1

    def lookup(self, name: str):
        with self._lock:
            return self._lookups.get(name)

    def put(self, ds: DataSource, star: Optional[StarSchemaInfo] = None):
        """Publish a datasource snapshot.  The ONLY write path for tables:
        every publish bumps the per-datasource version and stamps it on
        the (immutable) DataSource, so downstream caches observe each
        segment-set change — the invalidation hook compaction and the
        result cache share.  Returns the stamped DataSource."""
        with self._lock:
            v = self._ds_versions.get(ds.name, 0) + 1
            self._ds_versions[ds.name] = v
            ds = dataclasses.replace(ds, version=v)
            self._tables[ds.name] = ds
            if star is not None:
                self._stars[ds.name] = star
            self.version += 1
        return ds

    def datasource_version(self, name: str) -> int:
        """Monotonic segment-set version of a datasource (0 = never
        registered).  Survives drop/re-register."""
        with self._lock:
            return self._ds_versions.get(name, 0)

    def seed_version(self, name: str, version: int) -> None:
        """Raise the per-datasource version floor (never lowers it).
        Boot recovery (storage.DurableStorage) seeds the floor from the
        persisted snapshot BEFORE republishing, so versions stay
        monotonic ACROSS process restarts — a result cached against
        pre-crash version N can never collide with a different
        post-restart segment set stamped N again."""
        with self._lock:
            cur = self._ds_versions.get(name, 0)
            self._ds_versions[name] = max(cur, int(version))

    def get(self, name: str) -> Optional[DataSource]:
        with self._lock:
            return self._tables.get(name)

    def star_schema(self, name: str) -> Optional[StarSchemaInfo]:
        with self._lock:
            return self._stars.get(name)

    def star_schemas(self) -> Dict[str, StarSchemaInfo]:
        with self._lock:
            return dict(self._stars)

    def tables(self):
        with self._lock:
            return list(self._tables)

    def drop(self, name: str):
        with self._lock:
            self._tables.pop(name, None)
            self._stars.pop(name, None)
            self.version += 1

    def clear(self):
        """The reference's clear-metadata-cache command analog."""
        with self._lock:
            self._tables.clear()
            self._stars.clear()
            self.version += 1
