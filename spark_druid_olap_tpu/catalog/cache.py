"""Metadata cache — the registry of datasources and their stats.

Reference parity: `DruidMetadataCache` (SURVEY.md §2 metadata-cache row `[U]`,
expected `org/sparklinedata/druid/metadata/`): a process-wide cache of
datasource schemas, segment lists and server assignments, refreshed from the
Druid coordinator and guarded by JVM synchronization.  Locally there is no
remote cluster: datasources are registered (ingested) into the cache, entries
are immutable-by-construction (frozen dataclasses holding arrays nobody
mutates — SURVEY.md §5 race-detection note), and the explicit `clear()`
mirrors the reference's clear-metadata-cache SQL command.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from .segment import DataSource
from .star import StarSchemaInfo


class MetadataCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._tables: Dict[str, DataSource] = {}
        self._stars: Dict[str, StarSchemaInfo] = {}
        # query-time lookup tables (Druid lookup extraction): name -> map
        self._lookups: Dict[str, dict] = {}
        # monotonically bumped on every mutation; plan caches key on it so a
        # re-registered table invalidates cached rewrites
        self.version = 0

    def put_lookup(self, name: str, mapping: dict):
        with self._lock:
            self._lookups[name] = dict(mapping)
            self.version += 1

    def lookup(self, name: str):
        with self._lock:
            return self._lookups.get(name)

    def put(self, ds: DataSource, star: Optional[StarSchemaInfo] = None):
        with self._lock:
            self._tables[ds.name] = ds
            if star is not None:
                self._stars[ds.name] = star
            self.version += 1

    def get(self, name: str) -> Optional[DataSource]:
        with self._lock:
            return self._tables.get(name)

    def star_schema(self, name: str) -> Optional[StarSchemaInfo]:
        with self._lock:
            return self._stars.get(name)

    def star_schemas(self) -> Dict[str, StarSchemaInfo]:
        with self._lock:
            return dict(self._stars)

    def tables(self):
        with self._lock:
            return list(self._tables)

    def drop(self, name: str):
        with self._lock:
            self._tables.pop(name, None)
            self._stars.pop(name, None)
            self.version += 1

    def clear(self):
        """The reference's clear-metadata-cache command analog."""
        with self._lock:
            self._tables.clear()
            self._stars.clear()
            self.version += 1
