"""Star-schema metadata + join-elimination (the soundness-critical part).

Reference parity: `StarSchema` / `StarSchemaInfo` / `FunctionalDependency`
(SURVEY.md §2 star-schema row `[U]`, expected
`org/sparklinedata/druid/metadata/StarSchema.scala`): the user *declares* the
fact/dimension join graph and functional dependencies in the table options;
`JoinTransform` eliminates dimension-table joins because the Druid index is
pre-joined (denormalized), mapping dim-table columns through to fact
dimensions.  Identically here: the TPU datasource is the denormalized flat
table; a query written against the normalized star (joins and all) collapses
to a Scan of the fact datasource when — and only when — every join edge
matches a declared relation (equality keys and n:1 cardinality), which is
what makes the elimination sound (SURVEY.md §7 hard part #6).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

from ..plan import logical as L


@dataclasses.dataclass(frozen=True)
class FunctionalDependency:
    """determinant -> dependent within one table (e.g. c_city -> c_nation).
    Declares that grouping by `dependent` alongside `determinant` cannot
    change cardinality — used to validate collapses and (later) prune
    redundant grouping columns."""

    table: str
    determinant: str
    dependent: str

    def to_json(self):
        return {
            "table": self.table,
            "determinant": self.determinant,
            "dependent": self.dependent,
        }


@dataclasses.dataclass(frozen=True)
class StarRelationInfo:
    """One n:1 edge of the star: fact (or parent dim) joins to `table`.

    `non_null` declares the FK never null AND referentially intact (every
    parent row matches exactly one dim row) — the condition under which a
    LEFT join equals the INNER join and its elimination is sound."""

    table: str
    join_keys: Tuple[Tuple[str, str], ...]  # (parent-side col, dim-side col)
    parent: Optional[str] = None  # None => the fact table (snowflake support)
    cardinality: str = "n-1"  # n-1 | 1-1; n:1 keeps fact row multiplicity
    non_null: bool = False  # FK non-null + referential integrity declared

    def to_json(self):
        return {
            "table": self.table,
            "joinKeys": [list(k) for k in self.join_keys],
            "parent": self.parent,
            "cardinality": self.cardinality,
            "nonNull": self.non_null,
        }


@dataclasses.dataclass(frozen=True)
class StarSchemaInfo:
    """The declared star: fact table + relations + functional dependencies
    (the JSON `starSchema` option of the reference's DDL)."""

    fact_table: str
    relations: Tuple[StarRelationInfo, ...] = ()
    functional_dependencies: Tuple[FunctionalDependency, ...] = ()

    def relation_for(self, dim_table: str) -> Optional[StarRelationInfo]:
        for r in self.relations:
            if r.table == dim_table:
                return r
        return None

    def to_json(self) -> str:
        return json.dumps(
            {
                "factTable": self.fact_table,
                "relations": [r.to_json() for r in self.relations],
                "functionalDependencies": [
                    f.to_json() for f in self.functional_dependencies
                ],
            },
            indent=2,
        )

    @staticmethod
    def from_json(s) -> "StarSchemaInfo":
        d = json.loads(s) if isinstance(s, str) else s
        return StarSchemaInfo(
            fact_table=d["factTable"],
            relations=tuple(
                StarRelationInfo(
                    r["table"],
                    tuple((a, b) for a, b in r["joinKeys"]),
                    r.get("parent"),
                    r.get("cardinality", "n-1"),
                    r.get("nonNull", False),
                )
                for r in d.get("relations", ())
            ),
            functional_dependencies=tuple(
                FunctionalDependency(
                    f["table"], f["determinant"], f["dependent"]
                )
                for f in d.get("functionalDependencies", ())
            ),
        )


def _unqualify(name: str) -> Tuple[Optional[str], str]:
    if "." in name:
        t, c = name.split(".", 1)
        return t, c
    return None, name


def try_collapse_join(node: L.Join, catalog) -> Optional[L.LogicalPlan]:
    """Validate a Join subtree against registered star schemas; on success
    return the collapsed Scan(fact).

    Sound iff every join edge matches a declared n:1 relation on exactly the
    declared equality keys, hangs off its DECLARED parent (snowflake chains
    are validated against the actual tree shape, not just key names), and —
    for LEFT joins — the relation is declared `non_null` (or 1-1), since a
    left join only equals the inner join when no fact row dangles
    (SURVEY.md §7 hard part #6; VERDICT r1 weak #6)."""
    # flatten the left-deep join tree; each edge records the tables already
    # joined beneath it so parent chains can be checked against tree shape
    edges: List[Tuple[Tuple[str, ...], Tuple[Tuple[str, str], ...], str, str]] = []

    def walk(n) -> Optional[List[str]]:
        if isinstance(n, L.Scan):
            return [n.table]
        if isinstance(n, L.Join):
            if n.how not in ("inner", "left"):
                return None
            left_tables = walk(n.left)
            if left_tables is None or not isinstance(n.right, L.Scan):
                return None
            dim = n.right.table
            keys = []
            for lk, rk in zip(n.left_keys, n.right_keys):
                lt, lc = _unqualify(lk)
                rt, rc = _unqualify(rk)
                # orient: dim-side key is the one qualified by `dim`
                if rt == dim or (rt is None and lt is not None):
                    keys.append((lc, rc))
                elif lt == dim:
                    keys.append((rc, lc))
                else:
                    keys.append((lc, rc))
            edges.append((tuple(left_tables), tuple(keys), dim, n.how))
            return left_tables + [dim]
        return None

    all_tables = walk(node)
    if all_tables is None:
        return None

    # find the fact: the table with a registered star schema covering all dims
    for fact in all_tables:
        star = catalog.star_schema(fact) if hasattr(catalog, "star_schema") else None
        if star is None or star.fact_table != fact:
            continue
        ok = True
        for tables_before, keys, dim, how in edges:
            if dim == fact:
                ok = False  # the fact joined as a dim side: not a star shape
                break
            rel = star.relation_for(dim)
            if rel is None:
                ok = False
                break
            declared = {frozenset(k) for k in rel.join_keys}
            actual = {frozenset(k) for k in keys}
            if declared != actual:
                ok = False
                break
            if rel.cardinality not in ("n-1", "1-1"):
                ok = False
                break
            if how == "left" and not (
                rel.non_null or rel.cardinality == "1-1"
            ):
                ok = False  # dangling fact rows would differ from inner join
                break
            # snowflake parent validation: the declared parent must already
            # be in the joined subtree; for dim-parent edges the parent table
            # must also own the parent-side key columns.  (Fact-direct edges
            # skip the ownership check: the denormalized fact legitimately
            # drops FK columns after flattening — the declared relation is
            # the authority there.)
            expected_parent = rel.parent or fact
            if expected_parent not in tables_before:
                ok = False
                break
            if rel.parent is not None:
                pds = (
                    catalog.get(expected_parent)
                    if hasattr(catalog, "get")
                    else None
                )
                if pds is not None:
                    parent_cols = {c.name for c in pds.columns}
                    if not all(pk in parent_cols for pk, _ in keys):
                        ok = False
                        break
        if ok:
            return L.Scan(fact)
    return None
