"""Datasource persistence: save/load encoded segments to a directory.

Reference parity: Druid's index IS its persistence — the reference never
re-ingests because the segments live on historical disks (SURVEY.md §5
checkpoint row: "the state is the Druid index itself").  The local analog:
a registered datasource (dictionary-encoded columns + padding + dictionaries
+ star schema) round-trips to disk, so a session restart skips re-ingest and
re-encode entirely.

Layout of `<dir>/`:
    meta.json             name, schema, time column, dictionaries, star JSON
    segment_<i>.npz       per-segment arrays: dims/metrics/time/valid

Dictionary values serialize into meta.json (string domains) or as int lists
(numeric-rank domains).  Arrays are written padded exactly as registered, so
a loaded datasource has byte-identical segments — compiled-program cache keys
(schema_signature) differ only by the fresh segment uids, which is correct:
device residency must not alias across loads.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import numpy as np

from .segment import ColumnMeta, DataSource, DimensionDict, Segment
from .star import StarSchemaInfo

_FORMAT_VERSION = 1


def save_datasource(
    ds: DataSource, directory: str, star: Optional[StarSchemaInfo] = None
) -> str:
    """Write segments first and meta.json last (tmp+rename): meta is the
    commit point, so an interrupted save can never pair new dictionaries
    with old rank-coded arrays (silently wrong decodes).  Stale segment
    files beyond the new count are removed."""
    os.makedirs(directory, exist_ok=True)
    meta = {
        "format_version": _FORMAT_VERSION,
        "name": ds.name,
        "time_column": ds.time_column,
        "columns": [
            {
                "name": c.name,
                "kind": c.kind,
                "dtype": c.dtype,
                "cardinality": c.cardinality,
            }
            for c in ds.columns
        ],
        "dicts": {
            name: {
                "numeric": d.numeric_values is not None,
                "values": [
                    int(v) if isinstance(v, (int, np.integer)) else str(v)
                    for v in d.values
                ],
            }
            for name, d in ds.dicts.items()
        },
        "segments": [
            {
                "segment_id": s.segment_id,
                "num_rows": s.num_rows,
                "interval": list(s.interval) if s.interval else None,
                "time_name": s.time_name,
            }
            for s in ds.segments
        ],
        "star_schema": star.to_json() if star is not None else None,
    }
    for i, seg in enumerate(ds.segments):
        arrays = {f"dim__{k}": np.asarray(v) for k, v in seg.dims.items()}
        arrays.update(
            {f"met__{k}": np.asarray(v) for k, v in seg.metrics.items()}
        )
        arrays["valid"] = np.asarray(seg.valid)
        if seg.time is not None:
            arrays["time"] = np.asarray(seg.time)
        np.savez(os.path.join(directory, f"segment_{i:06d}.npz"), **arrays)
    for f in os.listdir(directory):  # stale segments from a larger old save
        if f.startswith("segment_") and f.endswith(".npz"):
            try:
                idx = int(f[len("segment_"):-len(".npz")])
            except ValueError:
                continue
            if idx >= len(ds.segments):
                os.remove(os.path.join(directory, f))
    tmp = os.path.join(directory, "meta.json.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(directory, "meta.json"))
    return directory


def load_datasource(
    directory: str, name: Optional[str] = None
) -> Tuple[DataSource, Optional[StarSchemaInfo]]:
    with open(os.path.join(directory, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("format_version") != _FORMAT_VERSION:
        raise ValueError(
            f"unsupported datasource format {meta.get('format_version')!r}"
        )
    dicts = {
        dim: DimensionDict(
            values=tuple(
                int(v) if spec["numeric"] else str(v)
                for v in spec["values"]
            )
        )
        for dim, spec in meta["dicts"].items()
    }
    columns = tuple(
        ColumnMeta(c["name"], c["kind"], c["dtype"], c["cardinality"])
        for c in meta["columns"]
    )
    segments = []
    for i, sm in enumerate(meta["segments"]):
        with np.load(os.path.join(directory, f"segment_{i:06d}.npz")) as z:
            dims = {
                k[len("dim__"):]: z[k] for k in z.files if k.startswith("dim__")
            }
            metrics = {
                k[len("met__"):]: z[k] for k in z.files if k.startswith("met__")
            }
            valid = z["valid"]
            time = z["time"] if "time" in z.files else None
        from .segment import _SEGMENT_UIDS, compute_segment_stats

        segments.append(
            Segment(
                segment_id=sm["segment_id"],
                num_rows=int(sm["num_rows"]),
                dims=dims,
                metrics=metrics,
                time=time,
                valid=valid,
                interval=tuple(sm["interval"]) if sm["interval"] else None,
                time_name=sm.get("time_name"),
                uid=next(_SEGMENT_UIDS),
                # zone maps recompute at load (one min/max pass — cheaper
                # than versioning them into the on-disk format)
                stats=compute_segment_stats(dims, metrics, valid),
            )
        )
    ds = DataSource(
        name=name or meta["name"],
        columns=columns,
        dicts=dicts,
        segments=tuple(segments),
        time_column=meta["time_column"],
    )
    star = (
        StarSchemaInfo.from_json(meta["star_schema"])
        if meta.get("star_schema")
        else None
    )
    if star is not None and ds.name != meta["name"]:
        # loading under a new name: the star's fact reference must follow,
        # or the collapse check (catalog/star.py fact_table != fact) would
        # silently reject every star join against the renamed table
        import dataclasses

        star = dataclasses.replace(star, fact_table=ds.name)
    return ds, star
