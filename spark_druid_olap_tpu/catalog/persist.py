"""Datasource persistence: save/load encoded segments to a directory.

Reference parity: Druid's index IS its persistence — the reference never
re-ingests because the segments live on historical disks (SURVEY.md §5
checkpoint row: "the state is the Druid index itself").  The local analog:
a registered datasource (dictionary-encoded columns + padding + dictionaries
+ star schema) round-trips to disk, so a session restart skips re-ingest and
re-encode entirely.

Layout of `<dir>/`:
    meta.json             name, schema, time column, dictionaries, star JSON
    segment_<i>.npz       per-segment arrays: dims/metrics/time/valid

Dictionary values serialize into meta.json (string domains) or as int lists
(numeric-rank domains).  Arrays are written padded exactly as registered, so
a loaded datasource has byte-identical segments — compiled-program cache keys
(schema_signature) differ only by the fresh segment uids, which is correct:
device residency must not alias across loads.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from .segment import ColumnMeta, DataSource, DimensionDict, Segment, as_delta
from .star import StarSchemaInfo

_FORMAT_VERSION = 1
# versioned snapshot store (ISSUE 13 tentpole (b)) — a SEPARATE format
# from the legacy save_table layout above: per-column raw .npy files
# (np.load(mmap_mode="r") restores them as memmaps, so boot reads
# headers, not data) + a snapshot.json commit point carrying everything
# a query plan needs without touching a column (schema, dicts, zone
# maps, intervals, the datasource version, and the WAL watermark)
_SNAPSHOT_VERSION = 1
SNAPSHOT_NAME = "snapshot.json"


# ---------------------------------------------------------------------------
# Atomic write helpers — THE way storage-tier bytes reach disk
# (graftlint storage-discipline/GL2002: a segment/snapshot write outside
# these helpers can be torn by a crash and is flagged)
# ---------------------------------------------------------------------------


def atomic_write_bytes(path: str, payload: bytes) -> str:
    """tmp + flush + fsync + os.replace: a crash at any point leaves
    either the old whole file or the new whole file, never a torn one."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def atomic_write_json(path: str, obj) -> str:
    return atomic_write_bytes(path, json.dumps(obj).encode())


def atomic_write_array(path: str, arr: np.ndarray) -> str:
    """One column to one raw .npy file, atomically."""
    import io

    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr))
    return atomic_write_bytes(path, buf.getvalue())


# ---------------------------------------------------------------------------
# Lazy disk-backed columns — the third residency tier (disk -> host RAM
# -> HBM).  A restored Segment's dims/metrics are a LazyColumnMap: the
# snapshot load opens NOTHING; the first `seg.column(name)` opens the
# one .npy as a read-only memmap (header read only — pages fault in as
# kernels/transfers actually touch them), and the engine's byte-budget
# cache + transfer pipeline treat the result like any host array.
# ---------------------------------------------------------------------------


class LazyColumnMap(Mapping):
    """name -> ndarray Mapping over per-column .npy files, loaded (as
    memmaps) on first access and cached on the map."""

    def __init__(self, directory: str, files: Dict[str, str]):
        self._dir = directory
        self._files = dict(files)
        self._loaded: Dict[str, np.ndarray] = {}

    def __getitem__(self, name: str) -> np.ndarray:
        arr = self._loaded.get(name)
        if arr is None:
            path = os.path.join(self._dir, self._files[name])
            arr = np.load(path, mmap_mode="r")
            note_disk_open(arr.nbytes)
            self._loaded[name] = arr
        return arr

    def __iter__(self):
        return iter(self._files)

    def __len__(self):
        return len(self._files)

    def loaded_names(self) -> Tuple[str, ...]:
        """Which columns have actually been opened (test/obs hook)."""
        return tuple(self._loaded)


def is_disk_backed(arr) -> bool:
    """Is this host array still the disk tier (a memmap whose pages may
    not be resident)?  The engine promotes such columns to real host RAM
    before device transfer and accounts the movement."""
    return isinstance(arr, np.memmap)


def materialize(arr: np.ndarray) -> np.ndarray:
    """Disk tier -> host RAM tier: copy a memmap into an owned array
    (no-op for arrays already in RAM)."""
    if is_disk_backed(arr):
        return np.array(arr)
    return arr


def note_disk_open(nbytes: int) -> None:
    """Account one cold-column open in the process registry (import is
    deferred: persist must stay importable without obs side effects at
    module-load time)."""
    try:
        from ..obs import record_storage_load

        record_storage_load(int(nbytes))
    except Exception:  # fault-ok: accounting must never fail a load
        pass


def save_datasource(
    ds: DataSource, directory: str, star: Optional[StarSchemaInfo] = None
) -> str:
    """Write segments first and meta.json last (tmp+rename): meta is the
    commit point, so an interrupted save can never pair new dictionaries
    with old rank-coded arrays (silently wrong decodes).  Stale segment
    files beyond the new count are removed."""
    os.makedirs(directory, exist_ok=True)
    meta = {
        "format_version": _FORMAT_VERSION,
        "name": ds.name,
        "time_column": ds.time_column,
        "columns": [
            {
                "name": c.name,
                "kind": c.kind,
                "dtype": c.dtype,
                "cardinality": c.cardinality,
            }
            for c in ds.columns
        ],
        "dicts": {
            name: {
                "numeric": d.numeric_values is not None,
                "values": [
                    int(v) if isinstance(v, (int, np.integer)) else str(v)
                    for v in d.values
                ],
            }
            for name, d in ds.dicts.items()
        },
        "segments": [
            {
                "segment_id": s.segment_id,
                "num_rows": s.num_rows,
                "interval": list(s.interval) if s.interval else None,
                "time_name": s.time_name,
            }
            for s in ds.segments
        ],
        "star_schema": star.to_json() if star is not None else None,
    }
    for i, seg in enumerate(ds.segments):
        arrays = {f"dim__{k}": np.asarray(v) for k, v in seg.dims.items()}
        arrays.update(
            {f"met__{k}": np.asarray(v) for k, v in seg.metrics.items()}
        )
        arrays["valid"] = np.asarray(seg.valid)
        if seg.time is not None:
            arrays["time"] = np.asarray(seg.time)
        np.savez(os.path.join(directory, f"segment_{i:06d}.npz"), **arrays)
    for f in os.listdir(directory):  # stale segments from a larger old save
        if f.startswith("segment_") and f.endswith(".npz"):
            try:
                idx = int(f[len("segment_"):-len(".npz")])
            except ValueError:
                continue
            if idx >= len(ds.segments):
                os.remove(os.path.join(directory, f))
    tmp = os.path.join(directory, "meta.json.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(directory, "meta.json"))
    return directory


def load_datasource(
    directory: str, name: Optional[str] = None
) -> Tuple[DataSource, Optional[StarSchemaInfo]]:
    with open(os.path.join(directory, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("format_version") != _FORMAT_VERSION:
        raise ValueError(
            f"unsupported datasource format {meta.get('format_version')!r}"
        )
    dicts = {
        dim: DimensionDict(
            values=tuple(
                int(v) if spec["numeric"] else str(v)
                for v in spec["values"]
            )
        )
        for dim, spec in meta["dicts"].items()
    }
    columns = tuple(
        ColumnMeta(c["name"], c["kind"], c["dtype"], c["cardinality"])
        for c in meta["columns"]
    )
    segments = []
    for i, sm in enumerate(meta["segments"]):
        with np.load(os.path.join(directory, f"segment_{i:06d}.npz")) as z:
            dims = {
                k[len("dim__"):]: z[k] for k in z.files if k.startswith("dim__")
            }
            metrics = {
                k[len("met__"):]: z[k] for k in z.files if k.startswith("met__")
            }
            valid = z["valid"]
            time = z["time"] if "time" in z.files else None
        from .segment import _SEGMENT_UIDS, compute_segment_stats

        segments.append(
            Segment(
                segment_id=sm["segment_id"],
                num_rows=int(sm["num_rows"]),
                dims=dims,
                metrics=metrics,
                time=time,
                valid=valid,
                interval=tuple(sm["interval"]) if sm["interval"] else None,
                time_name=sm.get("time_name"),
                uid=next(_SEGMENT_UIDS),
                # zone maps recompute at load (one min/max pass — cheaper
                # than versioning them into the on-disk format)
                stats=compute_segment_stats(dims, metrics, valid),
            )
        )
    ds = DataSource(
        name=name or meta["name"],
        columns=columns,
        dicts=dicts,
        segments=tuple(segments),
        time_column=meta["time_column"],
    )
    star = (
        StarSchemaInfo.from_json(meta["star_schema"])
        if meta.get("star_schema")
        else None
    )
    if star is not None and ds.name != meta["name"]:
        # loading under a new name: the star's fact reference must follow,
        # or the collapse check (catalog/star.py fact_table != fact) would
        # silently reject every star join against the renamed table
        star = dataclasses.replace(star, fact_table=ds.name)
    return ds, star


# ---------------------------------------------------------------------------
# Versioned snapshot store (the durable-storage tier, ISSUE 13)
# ---------------------------------------------------------------------------


def _dicts_to_json(dicts) -> dict:
    return {
        name: {
            "numeric": d.numeric_values is not None,
            "values": [
                int(v) if isinstance(v, (int, np.integer)) else str(v)
                for v in d.values
            ],
        }
        for name, d in dicts.items()
    }


def _dicts_from_json(spec: dict) -> Dict[str, DimensionDict]:
    return {
        dim: DimensionDict(
            values=tuple(
                int(v) if s["numeric"] else str(v) for v in s["values"]
            )
        )
        for dim, s in spec.items()
    }


def _safe_col(name: str) -> str:
    return "".join(c if (c.isalnum() or c in "_-") else "_" for c in name)


def save_snapshot(
    ds: DataSource,
    directory: str,
    star: Optional[StarSchemaInfo] = None,
    wal_watermark: int = -1,
) -> dict:
    """Persist a datasource as the versioned snapshot store: one raw
    .npy per column per segment (named by the PR 6 datasource version,
    so two generations never collide on a filename), committed by an
    atomic tmp+rename of snapshot.json.

    Ordering is the whole point: column files land durably FIRST, the
    snapshot that references them renames LAST — a crash anywhere
    in between leaves the previous snapshot fully intact (its files
    are never touched here; see `gc_snapshot_files` for retirement).
    The `persist.snapshot_rename` crash site sits between the tmp
    write and the rename: the kill-and-restart matrix proves a death
    there recovers to the OLD snapshot + WAL, exactly."""
    from ..resilience import checkpoint

    os.makedirs(directory, exist_ok=True)
    seg_metas: List[dict] = []
    for i, seg in enumerate(ds.segments):
        prefix = f"v{ds.version:08d}_s{i:06d}"
        files: Dict[str, str] = {}
        dim_files: Dict[str, str] = {}
        met_files: Dict[str, str] = {}
        for k, v in seg.dims.items():
            fname = f"{prefix}__dim__{_safe_col(k)}.npy"
            atomic_write_array(os.path.join(directory, fname), np.asarray(v))
            dim_files[k] = fname
        for k, v in seg.metrics.items():
            fname = f"{prefix}__met__{_safe_col(k)}.npy"
            atomic_write_array(os.path.join(directory, fname), np.asarray(v))
            met_files[k] = fname
        files["valid"] = f"{prefix}__valid.npy"
        atomic_write_array(
            os.path.join(directory, files["valid"]), np.asarray(seg.valid)
        )
        if seg.time is not None:
            files["time"] = f"{prefix}__time.npy"
            atomic_write_array(
                os.path.join(directory, files["time"]), np.asarray(seg.time)
            )
        seg_metas.append(
            {
                "segment_id": seg.segment_id,
                "num_rows": seg.num_rows,
                "interval": list(seg.interval) if seg.interval else None,
                "time_name": seg.time_name,
                "delta_seq": getattr(seg, "seq", None),
                # zone maps ride in the snapshot so boot never touches a
                # column to rebuild them (the mmap-restore speedup
                # depends on reading headers, not data)
                "stats": (
                    {k: [float(a), float(b)]
                     for k, (a, b) in seg.stats.items()}
                    if seg.stats is not None
                    else None
                ),
                "dims": dim_files,
                "mets": met_files,
                "files": files,
            }
        )
    snap = {
        "snapshot_version": _SNAPSHOT_VERSION,
        "name": ds.name,
        "time_column": ds.time_column,
        "rollup_granularity": getattr(ds, "rollup_granularity", None),
        "columns": [
            {"name": c.name, "kind": c.kind, "dtype": c.dtype,
             "cardinality": c.cardinality}
            for c in ds.columns
        ],
        "dicts": _dicts_to_json(ds.dicts),
        "ds_version": ds.version,
        "wal_watermark": int(wal_watermark),
        "star_schema": star.to_json() if star is not None else None,
        "segments": seg_metas,
    }
    path = os.path.join(directory, SNAPSHOT_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f)
        f.flush()
        os.fsync(f.fileno())
    # the commit point: everything before this line is invisible to a
    # restarted process, everything after it is the new truth
    checkpoint("persist.snapshot_rename")
    os.replace(tmp, path)
    return snap


def load_snapshot(
    directory: str,
) -> Tuple[DataSource, Optional[StarSchemaInfo], int]:
    """Restore a datasource from the snapshot store WITHOUT re-encoding
    or reading column data: columns come back as LazyColumnMaps over
    .npy files (first access memmaps them), zone maps/intervals load
    from the snapshot, and the stamped datasource version is preserved.
    Returns (datasource, star, wal_watermark)."""
    from .segment import _SEGMENT_UIDS

    with open(os.path.join(directory, SNAPSHOT_NAME)) as f:
        snap = json.load(f)
    if snap.get("snapshot_version") != _SNAPSHOT_VERSION:
        raise ValueError(
            f"unsupported snapshot version {snap.get('snapshot_version')!r}"
        )
    columns = tuple(
        ColumnMeta(c["name"], c["kind"], c["dtype"], c["cardinality"])
        for c in snap["columns"]
    )
    segments: List[Segment] = []
    for sm in snap["segments"]:
        valid = np.load(
            os.path.join(directory, sm["files"]["valid"]), mmap_mode="r"
        )
        time = (
            np.load(os.path.join(directory, sm["files"]["time"]),
                    mmap_mode="r")
            if sm["files"].get("time")
            else None
        )
        seg = Segment(
            segment_id=sm["segment_id"],
            num_rows=int(sm["num_rows"]),
            dims=LazyColumnMap(directory, sm["dims"]),
            metrics=LazyColumnMap(directory, sm["mets"]),
            time=time,
            valid=valid,
            interval=tuple(sm["interval"]) if sm["interval"] else None,
            time_name=sm.get("time_name"),
            uid=next(_SEGMENT_UIDS),
            stats=(
                {k: (v[0], v[1]) for k, v in sm["stats"].items()}
                if sm.get("stats") is not None
                else None
            ),
        )
        if sm.get("delta_seq") is not None:
            seg = as_delta(seg, seq=int(sm["delta_seq"]))
        segments.append(seg)
    ds = DataSource(
        name=snap["name"],
        columns=columns,
        dicts=_dicts_from_json(snap["dicts"]),
        segments=tuple(segments),
        time_column=snap["time_column"],
        version=int(snap["ds_version"]),
    )
    if snap.get("rollup_granularity") is not None:
        ds = dataclasses.replace(
            ds, rollup_granularity=snap["rollup_granularity"]
        )
    star = (
        StarSchemaInfo.from_json(snap["star_schema"])
        if snap.get("star_schema")
        else None
    )
    return ds, star, int(snap.get("wal_watermark", -1))


def snapshot_referenced_files(directory: str) -> frozenset:
    """Filenames the CURRENT committed snapshot references."""
    path = os.path.join(directory, SNAPSHOT_NAME)
    if not os.path.exists(path):
        return frozenset()
    with open(path) as f:
        snap = json.load(f)
    refs = {SNAPSHOT_NAME}
    for sm in snap.get("segments", ()):
        refs.update(sm.get("dims", {}).values())
        refs.update(sm.get("mets", {}).values())
        refs.update(sm.get("files", {}).values())
    return frozenset(refs)


def gc_snapshot_files(directory: str) -> List[str]:
    """Delete .npy files the committed snapshot no longer references —
    compaction-retired segments leave the disk HERE, strictly AFTER the
    new snapshot's rename committed (ISSUE 13 small-fix): a crash before
    this point leaves retired files as harmless orphans the next GC
    sweep removes; there is no window where both old and new state are
    gone.  The `compact.retire` crash site pins that ordering in the
    kill-and-restart matrix."""
    from ..resilience import checkpoint

    refs = snapshot_referenced_files(directory)
    removed: List[str] = []
    if not refs:
        return removed
    checkpoint("compact.retire")
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith(".npy"):
            continue
        if fname in refs:
            continue
        try:
            os.remove(os.path.join(directory, fname))
            removed.append(fname)
        except OSError:  # fault-ok: GC is reclamation, never correctness
            pass
    return removed


# ---------------------------------------------------------------------------
# Cluster assignment manifest (cluster/assignment.py, ISSUE 16) — the
# broker's segment -> historical replica map, committed next to the
# snapshots it indexes so a broker restart resumes the SAME epoch (and
# rebalance history) instead of reshuffling every segment.
# ---------------------------------------------------------------------------

ASSIGNMENT_MANIFEST_NAME = "cluster_assignment.json"


def save_assignment_manifest(directory: str, doc: dict) -> str:
    """Atomically commit the assignment manifest (same torn-write
    contract as the snapshot commit point)."""
    os.makedirs(directory, exist_ok=True)
    return atomic_write_json(
        os.path.join(directory, ASSIGNMENT_MANIFEST_NAME), doc
    )


def load_assignment_manifest(directory: str) -> Optional[dict]:
    path = os.path.join(directory, ASSIGNMENT_MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)
