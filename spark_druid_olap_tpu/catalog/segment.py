"""Columnar segment format for TPU-resident OLAP data.

Reference parity: the reference (spark-druid-olap, Sparkline BI Accelerator)
delegates storage to Druid *segments* — immutable, time-partitioned, columnar
shards with dictionary-encoded string dimensions and numeric metric columns
(see SURVEY.md L1/L2; reference mount was empty, paths unverified `[U]`,
expected `org/sparklinedata/druid/metadata/`).  This module is the TPU-native
analog: a `Segment` is a bundle of device-ready numpy/JAX arrays —
dictionary-encoded int32 dimension columns, float32/int32 metric columns, and
an int64 millisecond time column — padded to TPU-friendly tile multiples so
Pallas/XLA kernels see static, (8,128)-aligned shapes.

Design notes (TPU-first):
  * Strings never reach the device: dimensions are dictionary-encoded at
    ingest (Druid does the same) and only int32 codes are transferred.
  * Segments are immutable-by-construction (plain frozen dataclasses holding
    arrays we never mutate) — the reference guards its metadata cache with JVM
    synchronization; we simply never write.
  * All rows are padded to a multiple of `ROW_PAD` with a validity mask, so
    every kernel sees a static shape and XLA compiles exactly once per
    (schema, block-size), not once per segment length.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

# Process-unique segment identity: device-residency and compiled-program
# caches key on this, so re-ingesting a same-name datasource (same segment
# ids, different arrays) can never hit a stale cache entry.
_SEGMENT_UIDS = itertools.count(1)

# Row-count padding granularity.  8*128 = one float32 VMEM tile lane*sublane
# footprint; keeping row blocks a multiple of this keeps Pallas BlockSpecs and
# XLA tiling aligned.
ROW_PAD = 1024

NULL_ID = -1  # dictionary code for null dimension values


@dataclasses.dataclass(frozen=True)
class DimensionDict:
    """Dictionary for one dimension: sorted unique values <-> int32 ids.

    Sorted order is load-bearing: it makes dictionary codes order-preserving,
    so range/bound filters on strings can be pushed down as integer range
    filters on codes (the reference pushes Druid `bound` filters with
    lexicographic ordering; sorted dicts give us the same for free).

    Integer-typed dimensions (years, yearmonth codes, bucket ids, ...) keep
    their values as python ints sorted numerically, and their codes are the
    dense rank in the *actual* value domain — NOT the raw value.  This keeps
    the combined group-id domain tight (d_year spans 7 codes, not 1999), which
    is what lets the dense one-hot kernel cover the common OLAP case.  Filters
    translate numeric literals into code space (ops/filters.py); expressions
    see decoded values via `DecodedView`.
    """

    values: Tuple  # str (string dims, sorted) or int (numeric dims, sorted)

    @property
    def cardinality(self) -> int:
        return len(self.values)

    @functools.cached_property
    def content_key(self) -> int:
        """Stable hash of the value domain.  Rank codes are data-dependent
        (code 0 = smallest actual value), so any compiled-program cache keyed
        on a datasource MUST include this — two same-cardinality dictionaries
        with different domains give the same codes different meanings."""
        return hash(self.values)

    @functools.cached_property
    def numeric_values(self) -> Optional[np.ndarray]:
        """int64 value array when this is a numeric dictionary, else None."""
        if self.values and all(
            isinstance(v, (int, np.integer)) and not isinstance(v, bool)
            for v in self.values
        ):
            return np.asarray(self.values, dtype=np.int64)
        return None

    def code_of(self, value) -> Optional[int]:
        """Exact-match dictionary code for a literal (str or numeric), or
        None when the literal is not in the domain."""
        nv = self.numeric_values
        if nv is not None:
            try:
                x = float(value)
            except (TypeError, ValueError):
                return None
            if x != int(x):
                return None
            i = int(np.searchsorted(nv, int(x)))
            if i < len(nv) and int(nv[i]) == int(x):
                return i
            return None
        try:
            return self.values.index(value)
        except ValueError:
            return None

    def encode_numeric(self, arr: np.ndarray) -> np.ndarray:
        """Rank-encode an int column; negatives and out-of-domain -> NULL_ID."""
        nv = self.numeric_values
        a = np.asarray(arr).astype(np.int64)
        if nv is None or len(nv) == 0:
            # empty domain (all-null / zero-row column): everything is null
            return np.full(len(a), NULL_ID, dtype=np.int32)
        idx = np.clip(np.searchsorted(nv, a), 0, len(nv) - 1)
        ok = (nv[idx] == a) & (a >= 0)
        return np.where(ok, idx, NULL_ID).astype(np.int32)

    @property
    def _values_str(self) -> np.ndarray:
        # cached str-typed values array: encode() is called once per chunk
        # per dimension during streamed ingest, and rebuilding this per
        # call dominated large-SF ingest profiles
        cached = self.__dict__.get("_values_str_cache")
        if cached is None:
            cached = np.asarray(self.values, dtype=str)
            object.__setattr__(self, "_values_str_cache", cached)
        return cached

    def encode(self, col: Sequence[Optional[str]]) -> np.ndarray:
        import pandas as pd

        arr = np.asarray(col, dtype=object)
        # vectorized null scan (None or float NaN): the per-value Python
        # loop here cost ~500s of SF100 ingest (3M-row dimension tables)
        mask = ~pd.isna(arr)
        out = np.full(len(arr), NULL_ID, dtype=np.int32)
        if mask.any():
            vals = arr[mask].astype(str)
            idx = np.searchsorted(self._values_str, vals)
            idx = np.clip(idx, 0, max(len(self.values) - 1, 0))
            found = self._values_str[idx] == vals
            codes = np.where(found, idx, NULL_ID).astype(np.int32)
            out[mask] = codes
        return out

    def decode(self, ids: np.ndarray) -> np.ndarray:
        vals = np.asarray(self.values, dtype=object)
        out = np.empty(len(ids), dtype=object)
        ok = ids >= 0
        out[ok] = vals[ids[ok]]
        out[~ok] = None
        return out

    @staticmethod
    def build(col: Sequence[Optional[str]]) -> "DimensionDict":
        import pandas as pd

        arr = np.asarray(col, dtype=object)
        uniq = sorted(pd.unique(arr[~pd.isna(arr)]).tolist())
        return DimensionDict(values=tuple(uniq))


def _is_null(v) -> bool:
    """None OR float NaN — Arrow/pandas surface string nulls as NaN floats
    inside object columns; both must dictionary-encode as NULL."""
    return v is None or (isinstance(v, float) and v != v)


@dataclasses.dataclass(frozen=True)
class ColumnMeta:
    """Schema entry for one column of a datasource."""

    name: str
    kind: str  # "dimension" | "metric" | "time"
    dtype: str  # "string" | "long" | "double" | "timestamp"
    cardinality: Optional[int] = None  # dimensions only

    @property
    def is_dimension(self) -> bool:
        return self.kind == "dimension"

    @property
    def is_metric(self) -> bool:
        return self.kind == "metric"


def code_dtype(cardinality: int) -> np.dtype:
    """Smallest signed dtype holding codes [-1, cardinality).

    Dimension columns dominate scan bytes on wide GroupBys (SSB q4_1 reads
    ~14 GB at SF100, mostly int32 codes); storing codes at their natural
    width cuts the memory-bound scan roughly in half for typical
    cardinalities.  Device kernels cast to int32 on entry (sub-word
    arithmetic is not the goal — HBM/stream bytes are), and hashing is
    value-preserving across widths (utils/hashing.hash_column sign-extends
    through uint32), so sketches keep bit-parity."""
    if cardinality - 1 <= 127:  # stored codes span [-1, cardinality-1]
        return np.dtype(np.int8)
    if cardinality - 1 <= 32767:
        return np.dtype(np.int16)
    return np.dtype(np.int32)


def _pad_rows(a: np.ndarray, n_padded: int, fill) -> np.ndarray:
    if len(a) == n_padded:
        return a
    pad = np.full(n_padded - len(a), fill, dtype=a.dtype)
    return np.concatenate([a, pad])


@dataclasses.dataclass(frozen=True)
class Segment:
    """One immutable columnar shard, padded to ROW_PAD rows.

    Arrays are host numpy; `exec.engine` moves them to device (and caches
    residency).  `valid` marks real rows vs padding — kernels fold it into
    their filter mask so padding never contributes to an aggregate.
    """

    segment_id: str
    num_rows: int  # real (unpadded) rows
    dims: Mapping[str, np.ndarray]  # name -> int32[n_padded]
    metrics: Mapping[str, np.ndarray]  # name -> float32/int32[n_padded]
    time: Optional[np.ndarray]  # int64 millis[n_padded] or None
    valid: np.ndarray  # bool[n_padded]
    interval: Optional[Tuple[int, int]] = None  # [min_ms, max_ms] of time col
    time_name: Optional[str] = None  # source column name of the time column
    uid: int = 0  # process-unique identity (see _SEGMENT_UIDS)
    # zone maps (SURVEY.md §2 metadata row: per-segment "stats"): column ->
    # (min, max) over REAL rows — dimension columns in CODE space (nulls
    # excluded), metrics in value space.  Lets the engine prune segments a
    # filter provably cannot match, the way the time interval already does.
    stats: Optional[Mapping[str, Tuple[float, float]]] = None

    @property
    def num_rows_padded(self) -> int:
        return len(self.valid)

    def column(self, name: str) -> np.ndarray:
        if name in self.dims:
            return self.dims[name]
        if name in self.metrics:
            return self.metrics[name]
        if self.time is not None and name in ("__time", self.time_name):
            return self.time
        raise KeyError(f"segment {self.segment_id} has no column {name!r}")


@dataclasses.dataclass(frozen=True)
class DeltaSegment(Segment):
    """An append-only delta shard (ISSUE 6): rows that arrived via streamed
    ingest and have not been compacted into tiled historical segments yet.

    Same columnar layout and immutability contract as `Segment` — one
    published DeltaSegment never mutates; each append batch publishes its
    own delta segment(s) under the ingest lock and compaction later rolls
    them up — so every executor (engine, sparse/adaptive tiers, mesh, host
    fallback) merges its partials through the exact cross-segment
    machinery historical segments use.
    The subclass exists so compaction and observability can tell the two
    tiers apart; `seq` orders deltas within a datasource."""

    seq: int = 0


def as_delta(seg: Segment, seq: int) -> DeltaSegment:
    """Rewrap a built Segment as a DeltaSegment (same arrays, same uid)."""
    return DeltaSegment(
        **{f.name: getattr(seg, f.name) for f in dataclasses.fields(seg)},
        seq=seq,
    )


@dataclasses.dataclass(frozen=True)
class DataSource:
    """A named datasource: schema + dictionaries + a list of segments.

    Analog of the reference's `DruidDataSource` metadata + segment list
    (SURVEY.md §2 metadata cache row, `[U]`).

    `version` is the monotonic segment-set version (stamped by
    `catalog.cache.MetadataCache.put`): every publish — registration,
    delta append, compaction — bumps it, and result/plan caches key on it
    so a compacted or appended datasource can never serve a stale frame.
    """

    name: str
    columns: Tuple[ColumnMeta, ...]
    dicts: Mapping[str, DimensionDict]
    segments: Tuple[Segment, ...]
    time_column: Optional[str] = None
    version: int = 0
    # ingest-time rollup (ISSUE 13 tentpole (d), the Druid `rollup` spec
    # analog): a fixed-period granularity name ("minute", "hour", ...)
    # declared at registration.  Appends pre-aggregate under it — time
    # truncates to the bucket, rows group by (all dimensions, bucket),
    # metrics SUM — before the batch is journaled or encoded, shrinking
    # WAL volume and query-time delta scans.  Like Druid, opting in
    # changes count(*) semantics: it counts ROLLED rows; declare an
    # explicit count metric to preserve event counts.  None = exact rows.
    rollup_granularity: Optional[str] = None

    @property
    def num_rows(self) -> int:
        return sum(s.num_rows for s in self.segments)

    def meta(self, name: str) -> ColumnMeta:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(f"datasource {self.name} has no column {name!r}")

    def cardinality(self, dim: str) -> int:
        return self.dicts[dim].cardinality

    def interval(self) -> Optional[Tuple[int, int]]:
        ivs = [s.interval for s in self.segments if s.interval is not None]
        if not ivs:
            return None
        return (min(i[0] for i in ivs), max(i[1] for i in ivs))

    def delta_segments(self) -> Tuple["DeltaSegment", ...]:
        return tuple(
            s for s in self.segments if isinstance(s, DeltaSegment)
        )

    def historical_segments(self) -> Tuple[Segment, ...]:
        return tuple(
            s for s in self.segments if not isinstance(s, DeltaSegment)
        )

    @property
    def delta_rows(self) -> int:
        return sum(s.num_rows for s in self.delta_segments())


# ---------------------------------------------------------------------------
# Dictionary extension + code remap (the streamed-ingest novel-value path)
# ---------------------------------------------------------------------------


def extend_dict(
    old: DimensionDict, new_values
) -> Tuple[DimensionDict, Optional[np.ndarray]]:
    """Extend a sorted dictionary with `new_values` (novel values only are
    added), returning `(new_dict, lut)` where `lut[old_code] = new_code`.

    Both domains are sorted, and the old domain is a subset of the new, so
    the LUT is STRICTLY MONOTONE — code order keeps meaning value order,
    which is what lets (a) zone maps remap as `(lut[min], lut[max])` and
    (b) range-filter pushdown keep translating bounds into code space.
    `lut` is None when nothing was novel (the overwhelmingly common append:
    dictionaries converge after the first few batches)."""
    novel = [
        v for v in set(new_values)
        if not _is_null(v) and old.code_of(v) is None
    ]
    if not novel:
        return old, None
    if old.numeric_values is not None or (
        not old.values and all(
            isinstance(v, (int, np.integer)) and not isinstance(v, bool)
            for v in novel
        )
    ):
        merged = sorted({int(v) for v in old.values} | {int(v) for v in novel})
    else:
        merged = sorted({str(v) for v in old.values} | {str(v) for v in novel})
    new = DimensionDict(values=tuple(merged))
    # vectorized old->new LUT via the new dictionary's own encoders (the
    # per-value code_of loop was O(card^2) for string domains — a single
    # novel value on a 1M-value dimension stalled the append for minutes
    # while holding the ingest lock)
    if not old.values:
        lut = np.empty(1, dtype=np.int32)
    elif new.numeric_values is not None:
        lut = new.encode_numeric(np.asarray(old.values, dtype=np.int64))
    else:
        lut = new.encode(list(old.values))
    return new, lut


def remap_segment_codes(
    seg: Segment,
    luts: Mapping[str, np.ndarray],
    cards: Mapping[str, int],
) -> Segment:
    """A segment with the dimension columns in `luts` re-encoded into the
    extended code space (`new = lut[old]`, nulls stay NULL_ID) and its
    code-space zone maps shifted through the same (monotone) LUTs.

    Returns a NEW segment with a fresh uid — device-residency and program
    caches key on uid, so stale codes can never be served from cache."""
    dims = dict(seg.dims)
    stats = dict(seg.stats) if seg.stats is not None else None
    for name, lut in luts.items():
        if name not in dims:
            continue
        codes = np.asarray(dims[name])
        dtype = code_dtype(cards[name])
        out = np.where(codes >= 0, lut[np.maximum(codes, 0)], NULL_ID)
        dims[name] = out.astype(dtype, copy=False)
        if stats is not None and name in stats:
            lo, hi = stats[name]
            stats[name] = (float(lut[int(lo)]), float(lut[int(hi)]))
    return dataclasses.replace(
        seg, dims=dims, stats=stats, uid=next(_SEGMENT_UIDS)
    )


def schema_datasource(
    name: str,
    dims: Mapping[str, "DimensionDict"],
    metric_cols: Mapping[str, str],
    time_col: Optional[str] = None,
) -> DataSource:
    """A zero-segment DataSource carrying only schema + dictionaries — the
    anchor for streaming execution (exec/streaming.py), where row chunks
    arrive incrementally and never materialize as catalog segments.
    `dims` values may be DimensionDicts or plain value sequences;
    `metric_cols` maps name -> "long"|"double"."""
    ddicts: Dict[str, DimensionDict] = {}
    metas: List[ColumnMeta] = []
    for d, v in dims.items():
        dd = v if isinstance(v, DimensionDict) else DimensionDict(
            values=tuple(sorted(set(v)))
        )
        ddicts[d] = dd
        dtype = "long" if dd.numeric_values is not None else "string"
        metas.append(ColumnMeta(d, "dimension", dtype, cardinality=dd.cardinality))
    for m, dtype in metric_cols.items():
        metas.append(ColumnMeta(m, "metric", dtype))
    if time_col is not None:
        metas.append(ColumnMeta(time_col, "time", "timestamp"))
    return DataSource(
        name=name,
        columns=tuple(metas),
        dicts=ddicts,
        segments=(),
        time_column=time_col,
    )


def compute_segment_stats(
    dims: Mapping[str, np.ndarray],
    metrics: Mapping[str, np.ndarray],
    valid: np.ndarray,
) -> Dict[str, Tuple[float, float]]:
    """Per-column (min, max) zone maps over real rows; dimension columns in
    code space with nulls (code < 0) excluded."""
    # padding is a suffix by construction (_pad_rows), so "real rows" is a
    # slice, not a boolean gather — the gather copied every column once and
    # was the single hottest line of bulk ingest (ISSUE 6 profile)
    n_real = int(valid.sum())
    sliced = bool(valid[:n_real].all())
    out: Dict[str, Tuple[float, float]] = {}
    for d, codes in dims.items():
        c = np.asarray(codes)
        c = c[:n_real] if sliced else c[valid]
        c = c[c >= 0]
        if len(c):
            out[d] = (float(c.min()), float(c.max()))
    for m, vals in metrics.items():
        v = np.asarray(vals)
        v = v[:n_real] if sliced else v[valid]
        if len(v):
            out[m] = (float(v.min()), float(v.max()))
    return out


def build_datasource(
    name: str,
    columns: Mapping[str, np.ndarray],
    dimension_cols: Sequence[str],
    metric_cols: Sequence[str],
    time_col: Optional[str] = None,
    rows_per_segment: int = 1 << 22,
    dicts: Optional[Mapping[str, DimensionDict]] = None,
) -> DataSource:
    """Build a DataSource from raw host columns.

    String dimension columns are dictionary-encoded; integer-typed dimension
    columns are treated as already-encoded codes (their dictionary is the
    stringified value domain).  Metric columns become float32 (or int32 when
    integral).  Rows are split into segments of `rows_per_segment` and padded.
    """
    n = None
    for cname, col in columns.items():
        if n is None:
            n = len(col)
        elif len(col) != n:
            raise ValueError(f"column {cname} length {len(col)} != {n}")
    if n is None:
        raise ValueError("no columns")

    dicts = dict(dicts) if dicts else {}
    encoded: Dict[str, np.ndarray] = {}
    metas: List[ColumnMeta] = []

    for d in dimension_cols:
        col = columns[d]
        arr = np.asarray(col)
        if arr.dtype.kind in ("U", "S", "O"):
            if d not in dicts:
                dicts[d] = DimensionDict.build(list(col))
            codes = dicts[d].encode(list(col))
        elif d in dicts:
            # caller contract: an integer column WITH a supplied dictionary is
            # already dictionary-encoded (codes), whatever the dict's kind —
            # the fast path for pre-flattened star datasources (workloads/).
            # No cast here: the shared narrowing below normalizes the width
            # (zero-copy when the caller already encodes narrow)
            codes = arr
        else:
            raw = arr.astype(np.int64)
            uniq = np.unique(raw[raw >= 0]) if len(raw) else raw
            dicts[d] = DimensionDict(values=tuple(int(v) for v in uniq))
            codes = dicts[d].encode_numeric(raw)
        dtype = "long" if dicts[d].numeric_values is not None else "string"
        narrow = codes.astype(code_dtype(dicts[d].cardinality), copy=False)
        if np.shares_memory(narrow, arr):
            # pre-encoded caller arrays must never alias into the
            # (immutable) segments: a later in-place mutation of the
            # caller's column would silently change query results
            narrow = narrow.copy()
        encoded[d] = narrow
        metas.append(
            ColumnMeta(d, "dimension", dtype, cardinality=dicts[d].cardinality)
        )

    for m in metric_cols:
        arr = np.asarray(columns[m])
        if arr.dtype.kind in ("i", "u", "b"):
            enc = arr.astype(np.int32)
            metas.append(ColumnMeta(m, "metric", "long"))
        else:
            enc = arr.astype(np.float32)
            metas.append(ColumnMeta(m, "metric", "double"))
        encoded[m] = enc

    time_arr = None
    if time_col is not None:
        time_arr = np.asarray(columns[time_col]).astype(np.int64)
        metas.append(ColumnMeta(time_col, "time", "timestamp"))

    segments: List[Segment] = []
    for si, start in enumerate(range(0, n, rows_per_segment)):
        stop = min(start + rows_per_segment, n)
        rows = stop - start
        n_padded = -(-rows // ROW_PAD) * ROW_PAD
        dims = {
            d: _pad_rows(encoded[d][start:stop], n_padded, NULL_ID)
            for d in dimension_cols
        }
        mets = {
            m: _pad_rows(encoded[m][start:stop], n_padded, 0) for m in metric_cols
        }
        tcol = None
        interval = None
        if time_arr is not None:
            t = time_arr[start:stop]
            interval = (int(t.min()), int(t.max())) if rows else None
            tcol = _pad_rows(t, n_padded, 0)
        valid = _pad_rows(np.ones(rows, dtype=bool), n_padded, False)
        segments.append(
            Segment(
                segment_id=f"{name}_{si:06d}",
                num_rows=rows,
                dims=dims,
                metrics=mets,
                time=tcol,
                valid=valid,
                interval=interval,
                time_name=time_col,
                uid=next(_SEGMENT_UIDS),
                stats=compute_segment_stats(dims, mets, valid),
            )
        )

    return DataSource(
        name=name,
        columns=tuple(metas),
        dicts=dicts,
        segments=tuple(segments),
        time_column=time_col,
    )


def build_datasource_streamed(
    name: str,
    chunks,
    dimension_cols: Sequence[str],
    metric_cols: Sequence[str],
    time_col: Optional[str] = None,
    rows_per_segment: int = 1 << 22,
    dicts: Optional[Mapping[str, DimensionDict]] = None,
) -> DataSource:
    """Build a DataSource from an ITERATOR of column-mapping chunks without
    ever materializing the whole table host-side: peak host memory is one
    chunk (plus a sub-segment remainder buffer) on top of the encoded
    segments.  This is the large-scale-factor ingest path (BASELINE.md ★
    SSB SF100 would need ~600M denormalized rows — a single flat host frame
    does not survive that; a stream of encoded segments does).

    Every dimension column must either arrive pre-encoded (integer codes)
    or have a caller-supplied dictionary in `dicts`: the code space must be
    GLOBAL across chunks, and a per-chunk dictionary build would produce
    inconsistent codes."""
    dicts = dict(dicts) if dicts else {}
    for d in dimension_cols:
        if d not in dicts:
            raise ValueError(
                f"streamed ingest needs a global dictionary for dimension "
                f"{d!r}: per-chunk dictionaries would not share a code "
                "space (pass dicts= or pre-encode the column)"
            )
    segments: List[Segment] = []
    metas = None
    buf: Optional[Dict[str, np.ndarray]] = None

    def emit(cols: Dict[str, np.ndarray], last: bool) -> None:
        nonlocal buf, metas
        if buf is not None:
            cols = {
                k: np.concatenate([buf[k], np.asarray(v)])
                for k, v in cols.items()
            }
            buf = None
        n = len(next(iter(cols.values())))
        cut = n if last else (n // rows_per_segment) * rows_per_segment
        if cut < n:
            buf = {k: v[cut:] for k, v in cols.items()}
            cols = {k: v[:cut] for k, v in cols.items()}
        if cut == 0:
            return
        part = build_datasource(
            name,
            cols,
            dimension_cols,
            metric_cols,
            time_col,
            rows_per_segment,
            dicts,
        )
        if metas is None:
            metas = part.columns
        for s in part.segments:
            segments.append(
                dataclasses.replace(
                    s, segment_id=f"{name}_{len(segments):06d}"
                )
            )

    for chunk in chunks:
        emit(dict(chunk), last=False)
    if buf is not None:
        tail, buf = buf, None
        emit(tail, last=True)
    if metas is None:
        raise ValueError("streamed ingest produced no rows")
    return DataSource(
        name=name,
        columns=metas,
        dicts=dicts,
        segments=tuple(segments),
        time_column=time_col,
    )
