"""Benchmark driver: TPC-H Q1 (SF1) end-to-end on the local device.

BASELINE config #1 — "TPC-H Q1 single-table GROUP BY (sum/avg/count on
lineitem, SF1)".  Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

`vs_baseline` compares against a single-threaded pandas/numpy groupby of the
same query on the same host — the stand-in for the reference's
"Spark-on-Parquet without acceleration" baseline (the reference's own Druid
numbers are unavailable: empty reference mount, see SURVEY.md §0/§6).
"""

import json
import sys
import time

import numpy as np


def main():
    import jax

    from spark_druid_olap_tpu.catalog.segment import build_datasource
    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.models.aggregations import (
        Count,
        DoubleSum,
        ExpressionAgg,
    )
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.filters import Bound
    from spark_druid_olap_tpu.models.query import GroupByQuery
    from spark_druid_olap_tpu.plan.expr import col
    from spark_druid_olap_tpu.utils import datagen

    scale = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    cols = datagen.gen_lineitem(scale=scale, seed=0)
    n_rows = len(cols["l_quantity"])

    ds = build_datasource(
        "tpch",
        cols,
        dimension_cols=datagen.LINEITEM_DIMS,
        metric_cols=["l_quantity", "l_extendedprice", "l_discount", "l_tax"],
        time_col="l_shipdate",
        rows_per_segment=1 << 23,
    )

    cutoff = (
        np.datetime64("1998-09-02").astype("datetime64[D]").astype(int) + 1
    ) * 86_400_000
    q = GroupByQuery(
        datasource="tpch",
        dimensions=(
            DimensionSpec("l_returnflag"),
            DimensionSpec("l_linestatus"),
        ),
        aggregations=(
            DoubleSum("sum_qty", "l_quantity"),
            DoubleSum("sum_base_price", "l_extendedprice"),
            ExpressionAgg(
                "sum_disc_price",
                col("l_extendedprice") * (1 - col("l_discount")),
            ),
            ExpressionAgg(
                "sum_charge",
                col("l_extendedprice")
                * (1 - col("l_discount"))
                * (1 + col("l_tax")),
            ),
            DoubleSum("sum_disc", "l_discount"),
            Count("count_order"),
        ),
        filter=Bound("l_shipdate", upper=str(int(cutoff)), ordering="numeric"),
    )

    eng = Engine()
    out = eng.execute(q, ds)  # warmup: compile + device transfer
    assert len(out) == 6, out

    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        eng.execute(q, ds)
        times.append(time.perf_counter() - t0)
    p50 = sorted(times)[len(times) // 2]
    rows_per_sec = n_rows / p50

    # pandas oracle baseline (single-threaded host groupby, float64)
    import pandas as pd

    t0 = time.perf_counter()
    m = cols["l_shipdate"] <= cutoff
    df = pd.DataFrame(
        {
            "f": cols["l_returnflag"][m],
            "s": cols["l_linestatus"][m],
            "q": cols["l_quantity"][m].astype(np.float64),
            "p": cols["l_extendedprice"][m].astype(np.float64),
            "d": cols["l_discount"][m].astype(np.float64),
            "t": cols["l_tax"][m].astype(np.float64),
        }
    )
    df["dp"] = df.p * (1 - df.d)
    df["ch"] = df.dp * (1 + df.t)
    df.groupby(["f", "s"]).agg(
        {"q": "sum", "p": "sum", "dp": "sum", "ch": "sum", "d": "sum"}
    )
    pandas_time = time.perf_counter() - t0

    print(
        json.dumps(
            {
                "metric": "tpch_q1_sf%g_rows_per_sec_per_chip" % scale,
                "value": round(rows_per_sec),
                "unit": "rows/s",
                "vs_baseline": round(pandas_time / p50, 2),
                "detail": {
                    "p50_s": round(p50, 5),
                    "pandas_baseline_s": round(pandas_time, 5),
                    "device": str(jax.devices()[0]),
                    "rows": n_rows,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
