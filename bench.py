"""Benchmark driver.  Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

Modes (argv[1], default "ssb" — the BASELINE.md north star ★):

    ssb        [scale=1.0]   SSB Q1.1-Q4.3 p50 latency + rows/sec/chip
    tpch_q1    [scale=1.0]   config #1: single-table GROUP BY on lineitem
    topn_hll   [scale=1.0]   config #3: top-100 city by revenue + HLL distinct
    timeseries [chunks=12]   config #4: hourly rollup over a streamed event
                             stream (2M-row chunks; 1B rows = chunks=512)
    cube_theta [scale=0.25]  config #5: GROUP BY CUBE + approx_count_distinct

`vs_baseline` compares against single-threaded pandas/numpy float64 on the
same host and the same columns — the stand-in for the reference's
"Spark-on-Parquet without acceleration" baseline (the reference's own Druid
numbers are unavailable: empty reference mount, SURVEY.md §0/§6).  >1 means
the TPU path is faster.
"""

import json
import statistics
import sys
import time


def _device() -> str:
    import jax

    return str(jax.devices()[0])


# ---------------------------------------------------------------------------
# Crash-safe artifacts: every BENCH_*.json is written atomically (tmp +
# os.replace — the oracle-cache pattern), and long per-query sweeps flush
# each completed query to a *_partial.json sidecar incrementally, so a
# watchdog kill mid-window leaves the completed queries' numbers instead of
# a zero-length .tmp (the round-5 SF100 wound).
# ---------------------------------------------------------------------------


def _atomic_write(path: str, payload):
    """Durable atomic file write (str or bytes): tmp + fsync + os.replace,
    so a kill at ANY point leaves the previous artifact whole."""
    import os as _os

    tmp = path + ".tmp"
    mode = "wb" if isinstance(payload, bytes) else "w"
    with open(tmp, mode) as f:
        f.write(payload)
        f.flush()
        _os.fsync(f.fileno())
    _os.replace(tmp, path)


# set by _run_child to BENCH_<tag>_partial.json; bench modes call
# _note_partial after each completed query
_PARTIAL = {"path": None, "mode": None, "items": {}}


def _partial_path(tag: str) -> str:
    import os as _os

    root = _os.environ.get("SD_BENCH_DETAIL_DIR") or _os.path.dirname(
        _os.path.abspath(__file__)
    )
    return _os.path.join(root, "BENCH_%s_partial.json" % tag)


def _note_partial(name, record):
    """Flush one completed query's numbers to the partial sidecar (atomic;
    best-effort — a failed flush must never fail the bench)."""
    if _PARTIAL["path"] is None:
        return
    _PARTIAL["items"][name] = record
    try:
        _atomic_write(
            _PARTIAL["path"],
            json.dumps(
                {
                    "mode": _PARTIAL["mode"],
                    "completed": _PARTIAL["items"],
                    "n_completed": len(_PARTIAL["items"]),
                    "final": False,
                },
                indent=1,
                default=str,
            ),
        )
    except OSError:
        pass


def _timed(fn, reps=3, warmup=1):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


# ---------------------------------------------------------------------------
# ★ north star: SSB Q1.1-Q4.3
# ---------------------------------------------------------------------------


def _calibrated_ctx():
    """Context with measured cost constants (plan/calibrate.py): the planner
    then picks kernel strategy + mesh from numbers measured on THIS backend
    (e.g. on CPU the scatter kernel beats the MXU-shaped one-hot by ~200x,
    and the calibrated model routes accordingly).

    The result-level cache is DISABLED: the benchmark measures engine
    execution, and repeated reps would otherwise be served from the cache
    (the Druid-benchmark useCache=false convention)."""
    import os as _os

    import spark_druid_olap_tpu as sd
    from spark_druid_olap_tpu.config import SessionConfig

    cfg = SessionConfig.load_calibrated()
    meta = cfg.calibration_meta
    if meta and meta.get("mismatch") and (
        _os.environ.get("SD_BENCH_SKIP_CALIBRATE") != "1"
    ):
        # fail LOUDLY (VERDICT r4 #8): a benchmark must never quietly run
        # with cost constants measured on a different backend.  The one
        # sanctioned exception is the TPU-window fast path
        # (SD_BENCH_SKIP_CALIBRATE=1), where profile defaults are the
        # deliberate choice and the artifact records the mismatch.
        raise RuntimeError(
            "calibration platform mismatch: %s was measured on %s but the "
            "execution backend is %s — rerun `python bench.py calibrate` "
            "on this backend" % (meta["path"], meta["device"], _device())
        )
    cfg.result_cache_entries = 0
    return sd.TPUOlapContext(cfg)


def _ensure_calibration():
    """Calibrate once per backend (cheap on CPU; compile-dominated and slow
    over a tunneled TPU — SD_BENCH_SKIP_CALIBRATE=1 skips the measurement so
    a short hardware window is spent on the bench itself); reuse the saved
    file when it was measured on the same device kind."""
    import json as _json
    import os as _os

    if _os.environ.get("SD_BENCH_SKIP_CALIBRATE") == "1":
        return

    from spark_druid_olap_tpu.plan import calibrate as C

    try:
        import jax

        dev = str(jax.devices()[0])
        plat = jax.devices()[0].platform
        # primary file first, then the per-platform sidecar (CPU and TPU
        # runs alternate on this host; each overwrites the primary, and
        # SessionConfig.load_calibrated knows the same fallback)
        candidates = [C.DEFAULT_PATH, C.sidecar_path(plat)]
        for cp in candidates:
            if not _os.path.exists(cp):
                continue
            try:
                with open(cp) as f:
                    cal = _json.load(f)
            except (OSError, ValueError):
                # a truncated primary (killed mid-write) must not mask a
                # valid sidecar or suppress the re-sweep below
                continue
            # same device AND current schema (h2d_bytes_per_s marks the
            # round-5 slope-based methodology — earlier files measured
            # through a sync that the tunneled backend did not honor and
            # carry constants off by orders of magnitude) -> reuse.
            # .get() truthiness, not key presence: a budget-truncated sweep
            # saves null for the constants it never reached, and reusing
            # such a file forever would leave e.g. a 46 MB/s link priced
            # at the 1e10 B/s profile default
            if (
                cal.get("device") == dev
                and cal.get("stream_bytes_per_s")
                and cal.get("cost_per_row_compact")
                and cal.get("h2d_bytes_per_s")
            ):
                if cp != C.DEFAULT_PATH:
                    # promote the matching sidecar so _calibrated_ctx /
                    # _stream_bw (which read the primary path) see it
                    import shutil

                    shutil.copyfile(cp, C.DEFAULT_PATH)
                return
        # bounded: over a flaky tunneled accelerator a full sweep ran
        # ~26 min; the budget keeps implicit calibration from eating the
        # bench run (unmeasured constants stay at profile defaults)
        C.calibrate(
            rows=1 << 22,
            budget_s=float(
                _os.environ.get("SD_CALIBRATE_BUDGET_S", "600")
            ),
        )
    except Exception:
        pass  # calibration is an optimization; never fail the bench on it


def _stream_bw():
    """The calibrated streaming bandwidth of THIS backend (roofline
    denominator), or None before calibration."""
    return _cal_key("stream_bytes_per_s")


def _cal_key(key):
    """One constant out of the saved calibration file, or None."""
    import json as _json

    from spark_druid_olap_tpu.plan import calibrate as C

    try:
        with open(C.DEFAULT_PATH) as f:
            return _json.load(f).get(key)
    except Exception:
        return None


def _with_roofline(metrics_dict, bw):
    """Annotate a QueryMetrics dict with achieved-vs-streaming-bandwidth
    utilization (the number that says whether the scan is memory-bound or
    overhead-bound)."""
    if metrics_dict is None:
        return None
    if bw:
        metrics_dict["roofline_util_pct"] = round(
            100.0 * metrics_dict.get("scan_bytes_per_sec", 0) / bw, 1
        )
    return metrics_dict


def _span_tree(ctx):
    """Span tree of the context's most recent query (obs/ tracer), for
    the bench detail artifacts — `python -m tools.obs_dump
    BENCH_<tag>_detail.json` renders it as a phase/latency table.
    Carries the query's cost receipt (obs/prof.py) when one was built."""
    try:
        return ctx.tracer.last_trace_dict()
    except Exception:  # fault-ok: artifacts must not die on a trace gap
        return None


def _receipt_rep(ctx, fn):
    """One FORCE-SAMPLED rep of `fn` for the artifact's honest cost
    receipt (obs/prof.py, ISSUE 9): the sampled rep pays the dispatch
    sync points so device/host/transfer attribution is real, while the
    TIMED reps stay unsampled (their overlap untouched).  Returns
    (receipt_dict_or_None, measured_wall_ms)."""
    import time as _t

    try:
        ctx.tracer.force_sample_next()
    except Exception:  # fault-ok: profiling must never fail a bench
        pass
    t0 = _t.perf_counter()
    fn()
    wall_ms = (_t.perf_counter() - t0) * 1e3
    doc = _span_tree(ctx) or {}
    return doc.get("receipt"), round(wall_ms, 2)


def _ssb_parity(got, want) -> float:
    """Max relative error of an engine SSB result vs the (float64, exact)
    merged oracle.  Grouped results align on sorted group columns; a
    row-set mismatch returns inf."""
    import numpy as np

    if isinstance(want, float):
        g = float(got.iloc[0, -1]) if len(got) else 0.0
        if want == 0.0:
            return abs(g)
        return abs(g - want) / abs(want)
    vcol = want.columns[-1]
    g = [c for c in want.columns if c != vcol]
    got = got.sort_values(g).reset_index(drop=True)
    want = want.sort_values(g).reset_index(drop=True)
    if len(got) != len(want):
        return float("inf")
    for c in g:
        if list(got[c].astype(str)) != list(want[c].astype(str)):
            return float("inf")
    w = np.asarray(want[vcol], dtype=float)
    gv = np.asarray(got[vcol], dtype=float)
    denom = np.where(np.abs(w) > 0, np.abs(w), 1.0)
    return float(np.max(np.abs(gv - w) / denom)) if len(w) else 0.0


def _sharded_workers() -> int:
    from spark_druid_olap_tpu.ingest.shard import sharded_ingest_workers

    return sharded_ingest_workers()


def bench_ssb_streamed(scale: float):
    """SSB at LARGE scale factors: chunked datagen -> streamed encoded
    segments (never the whole flat fact host-side), chunked float64 pandas
    oracle (exact: all SSB aggregates are sums) doubling as the
    single-threaded baseline, engine parity asserted per query."""
    import time as _t

    from spark_druid_olap_tpu.workloads import ssb

    ctx = _calibrated_ctx()
    t0 = _t.perf_counter()
    tables = ssb.register_streamed(ctx, scale=scale, seed=7)
    ingest_s = _t.perf_counter() - t0
    n_rows = ctx.catalog.get("lineorder").num_rows

    # The float64 oracle is a pure function of (scale, seed) and at SF100
    # costs ~an hour of single-core pandas — it timed out a 90-minute TPU
    # window in round 5 while the device sat idle.  Cache it on disk; a
    # cached load still asserts full parity (same exact frames), only the
    # single-threaded-baseline seconds are reused from the measuring run.
    import pickle

    oracle_cache = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        ".ssb_oracle_sf%g_seed7.pkl" % scale,
    )
    # bump when the oracle computation OR the synthetic data stream
    # changes: a stale cache must recompute, never silently assert parity
    # against old expected frames.  v2: pre-sorted date generation
    # (workloads/ssb._gen_fact) changed the row<->value pairing.
    oracle_ver = 2
    want = t_pd = None
    if os.path.exists(oracle_cache):
        try:
            with open(oracle_cache, "rb") as f:
                ver, cached_want, cached_t_pd = pickle.load(f)
            # self-healing: recompute on version or query-set drift (a
            # renamed/added query must not KeyError an hour into a window)
            if ver == oracle_ver and set(cached_want) == set(ssb.QUERIES):
                want, t_pd = cached_want, cached_t_pd
        except Exception:
            want = t_pd = None
    if want is None:
        # one decode pass per chunk, all 13 oracle partials on it
        parts = {name: [] for name in ssb.QUERIES}
        t_pd = {name: 0.0 for name in ssb.QUERIES}
        for lo in ssb.fact_chunks(scale, 7, 1 << 22, tables):
            f = ssb.flat_frame_chunk(tables, lo)
            for name in ssb.QUERIES:
                t1 = _t.perf_counter()
                parts[name].append(ssb.oracle(f, name))
                t_pd[name] += _t.perf_counter() - t1
            del f, lo
        want = {n: ssb.merge_oracle_parts(parts[n]) for n in ssb.QUERIES}
        del parts
        try:
            # atomic + fsync'd (_atomic_write): a watchdog kill mid-dump
            # must leave the cache absent or whole, never truncated (a
            # broken pickle would force the hour-long recompute the cache
            # exists to avoid)
            _atomic_write(
                oracle_cache, pickle.dumps((oracle_ver, want, t_pd))
            )
        except Exception:
            pass

    # 3 reps at every scale: median-of-2 is a mean, and a single noisy
    # rep (this host's memory subsystem has ~2x run-to-run variance)
    # polluted round-4's first SF100 q4_1 reading by 3x
    reps = 3
    bw = _stream_bw()
    per_q, tpu_times, ratios, errs = {}, [], [], []
    for name in ssb.QUERIES:
        got = ctx.sql(ssb.QUERIES[name])  # warmup + parity in one
        err = _ssb_parity(got, want[name])
        errs.append(err)
        t_tpu = _timed(
            lambda n=name: ctx.sql(ssb.QUERIES[n]), reps=reps, warmup=0
        )
        # one force-sampled rep AFTER the timed ones: the honest cost
        # receipt for the artifact, without syncs perturbing the timings
        receipt, receipt_wall = _receipt_rep(
            ctx, lambda n=name: ctx.sql(ssb.QUERIES[n])
        )
        per_q[name] = {
            "tpu_ms": round(t_tpu * 1e3, 2),
            "pandas_ms": round(t_pd[name] * 1e3, 2),
            "max_rel_err": round(err, 8),
            "metrics": _with_roofline(
                ctx.last_metrics.to_dict() if ctx.last_metrics else None,
                bw,
            ),
            "receipt": receipt,
            "receipt_wall_ms": receipt_wall,
            "span_tree": _span_tree(ctx),
        }
        _note_partial(name, per_q[name])
        tpu_times.append(t_tpu)
        ratios.append(t_pd[name] / t_tpu)
    p50 = statistics.median(tpu_times)
    assert max(errs) < 1e-3, f"SSB parity failure: max_rel_err={max(errs)}"
    return {
        "metric": "ssb_sf%g_q1-q4_p50_latency" % scale,
        "value": round(p50 * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(statistics.median(ratios), 2),
        "detail": {
            "rows": n_rows,
            "rows_per_sec_per_chip": round(n_rows / p50),
            "ingest_s": round(ingest_s, 1),
            "ingest_rows_per_sec": round(n_rows / max(ingest_s, 1e-9)),
            "ingest_workers": _sharded_workers(),
            "ingest_path": "sharded",
            "oracle": "chunked float64 pandas, exact; parity asserted",
            "max_rel_err": round(max(errs), 8),
            "queries": per_q,
            "device": _device(),
        },
    }


def bench_ssb(scale: float):
    import spark_druid_olap_tpu as sd
    from spark_druid_olap_tpu.workloads import ssb

    if scale >= 4:
        # the full flat host frame (and its decoded oracle frame) does not
        # survive large SFs — switch to the streamed path
        return bench_ssb_streamed(scale)

    ctx = _calibrated_ctx()
    tables = ssb.gen_tables(scale=scale)
    ssb.register(ctx, tables=tables)
    n_rows = ctx.catalog.get("lineorder").num_rows

    f = ssb.flat_frame(tables)
    bw = _stream_bw()
    per_q = {}
    tpu_times, ratios = [], []
    for name in ssb.QUERIES:
        t_tpu = _timed(lambda n=name: ctx.sql(ssb.QUERIES[n]))
        t_pd = _timed(lambda n=name: ssb.oracle(f, n), reps=1, warmup=0)
        receipt, receipt_wall = _receipt_rep(
            ctx, lambda n=name: ctx.sql(ssb.QUERIES[n])
        )
        per_q[name] = {
            "tpu_ms": round(t_tpu * 1e3, 2),
            "pandas_ms": round(t_pd * 1e3, 2),
            "metrics": _with_roofline(
                ctx.last_metrics.to_dict() if ctx.last_metrics else None,
                bw,
            ),
            "receipt": receipt,
            "receipt_wall_ms": receipt_wall,
            "span_tree": _span_tree(ctx),
        }
        _note_partial(name, per_q[name])
        tpu_times.append(t_tpu)
        ratios.append(t_pd / t_tpu)
    p50 = statistics.median(tpu_times)
    return {
        "metric": "ssb_sf%g_q1-q4_p50_latency" % scale,
        "value": round(p50 * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(statistics.median(ratios), 2),
        "detail": {
            "rows": n_rows,
            "rows_per_sec_per_chip": round(n_rows / p50),
            "queries": per_q,
            "device": _device(),
        },
    }


def bench_ssb_mesh(scale: float):
    """SSB through the SPMD mesh (VERDICT r3 #3): queries run on BOTH the
    cost-model-routed single-device engine and the DistributedEngine over
    all visible devices, with parity asserted and the mesh-side costs
    (shard assembly, modelled collective) recorded per query.  ALL queries
    execute (VERDICT r4 #1): the SPMD program routes the same kernel
    ladder as the single-device engine — scatter / sparse sort-compaction
    / adaptive domain compaction above the one-hot domain — so no group
    cardinality gates mesh execution any more.

    On the virtual mesh, mesh-vs-single wall time measures SPMD OVERHEAD,
    not scaling (the 8 devices share the host cores); the honest scaling
    inputs for the ARCHITECTURE.md star-budget math are overhead_pct,
    shard-assembly ms, and the measured collective constants.  On a real
    v5e-8 the same mode measures true scaling."""
    import jax

    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.exec.lowering import lower_groupby
    from spark_druid_olap_tpu.models import query as Q
    from spark_druid_olap_tpu.models.aggregations import (
        Count as A_Count,
        DoubleSum as A_DoubleSum,
    )
    from spark_druid_olap_tpu.parallel.distributed import DistributedEngine
    from spark_druid_olap_tpu.parallel.mesh import make_mesh
    from spark_druid_olap_tpu.plan.cost import _g_tiles, choose_physical
    from spark_druid_olap_tpu.sql.parser import parse_sql
    from spark_druid_olap_tpu.workloads import ssb

    n_dev = len(jax.devices())
    if n_dev < 2:
        raise RuntimeError(
            f"ssb_mesh needs >=2 devices, found {n_dev} (the orchestrator "
            "sets xla_force_host_platform_device_count for the CPU child)"
        )
    ctx = _calibrated_ctx()
    if scale >= 4:
        # the sharded ingest pipeline uses THREADS, so a live JAX
        # backend (jax.devices() above) is no longer a hazard
        ssb.register_streamed(ctx, scale=scale, seed=7)
    else:
        ssb.register(ctx, tables=ssb.gen_tables(scale=scale))
    n_rows = ctx.catalog.get("lineorder").num_rows
    dist = DistributedEngine(mesh=make_mesh(n_data=n_dev))
    cfg = ctx.config

    per_q = {}
    meshes, overheads, errs = [], [], []
    for name in ssb.QUERIES:
        lp, _, _ = parse_sql(ssb.QUERIES[name])
        rw = ctx._planner().plan(lp)
        ds = ctx.catalog.get(rw.datasource)
        q = rw.query
        if not isinstance(q, Q.GroupByQuery):
            continue
        G = lower_groupby(q, ds).num_groups
        phys = choose_physical(q, ds, G, cfg, n_devices=1)
        rec = {"num_groups": G, "single_strategy": phys.strategy}
        eng = Engine(strategy=phys.strategy)
        single_df = eng.execute(q, ds)  # warmup + parity source
        t_single = _timed(lambda: eng.execute(q, ds), reps=2, warmup=0)
        rec["single_ms"] = round(t_single * 1e3, 2)
        # All 13 queries EXECUTE on the mesh (VERDICT r4 #1): the SPMD
        # program routes the same kernel ladder as the single-device
        # engine (scatter/sparse/adaptive above the one-hot domain), so
        # no G gates execution any more.  Keep the dense modelled cost as
        # context for the strategy the router rejected.
        est_us = (
            ds.num_rows / n_dev * cfg.cost_per_row_dense * _g_tiles(G)
        )
        rec["mesh_dense_modelled_ms"] = round(est_us / 1e3, 1)
        mesh_df = dist.execute(q, ds)  # warmup/compile + shard placement
        dm = dist.last_metrics
        rec["mesh_strategy"] = dm.strategy
        rec["shard_assembly_ms"] = round(dm.h2d_ms, 2)
        rec["est_collective_ms"] = round(dm.est_collective_ms, 3)
        t_mesh = _timed(lambda: dist.execute(q, ds), reps=2, warmup=0)
        rec["mesh_ms"] = round(t_mesh * 1e3, 2)
        rec["mesh_over_single"] = round(t_mesh / t_single, 2)
        overheads.append(t_mesh / t_single)
        meshes.append(t_mesh)
        err = _ssb_parity(mesh_df, single_df)
        rec["max_rel_err_vs_single"] = round(err, 8)
        errs.append(err)
        per_q[name] = rec
    assert errs, (
        "no query fit the virtual-mesh compute budget at this scale; "
        "nothing to assert parity on"
    )
    assert max(errs) < 1e-4, f"mesh parity failure: {errs}"

    # the streaming executor over the same mesh (VERDICT r3 #3: "and
    # through the streaming executor"): hourly rollup chunks sharded over
    # the data axis, dense SPMD per chunk, replicated [G, M] state
    from spark_druid_olap_tpu.exec.streaming import StreamExecutor
    from spark_druid_olap_tpu.utils import datagen

    tsq = Q.TimeseriesQuery(
        datasource="events",
        granularity="hour",
        aggregations=(
            A_Count("n"), A_DoubleSum("v", "value"),
        ),
        intervals=(datagen.event_stream_interval(),),
    )
    eds = datagen.event_stream_schema()
    chunk, nch = 1 << 21, 6
    staged = [datagen.gen_event_chunk(i, chunk) for i in range(nch)]
    sx_single = StreamExecutor()
    sx_mesh = StreamExecutor(mesh=dist.mesh)
    df_s = sx_single.execute(tsq, eds, iter(staged), chunk)
    t0 = time.perf_counter()
    df_s = sx_single.execute(tsq, eds, iter(staged), chunk)
    t_s = time.perf_counter() - t0
    df_m = sx_mesh.execute(tsq, eds, iter(staged), chunk)
    t0 = time.perf_counter()
    df_m = sx_mesh.execute(tsq, eds, iter(staged), chunk)
    t_m = time.perf_counter() - t0
    import numpy as np

    stream_err = float(
        np.max(
            np.abs(
                np.asarray(df_m["v"], float) - np.asarray(df_s["v"], float)
            )
            / np.maximum(np.abs(np.asarray(df_s["v"], float)), 1.0)
        )
    )
    assert stream_err < 1e-4, stream_err
    stream_rec = {
        "rows": nch * chunk,
        "single_rows_per_sec": round(nch * chunk / t_s),
        "mesh_rows_per_sec": round(nch * chunk / t_m),
        "mesh_over_single": round(t_m / t_s, 2),
        "max_rel_err": round(stream_err, 9),
    }

    p50 = statistics.median(meshes)
    overhead = statistics.median(overheads)
    return {
        "metric": "ssb_sf%g_mesh%d_p50_latency" % (scale, n_dev),
        "value": round(p50 * 1e3, 2),
        "unit": "ms",
        # ratio vs the single-device engine on the same backend: >1 means
        # the mesh is faster; on a shared-core virtual mesh expect <=1
        "vs_baseline": round(1.0 / overhead, 2),
        "detail": {
            "rows": n_rows,
            "n_devices": n_dev,
            "mesh_shape": dict(dist.mesh.shape),
            "distributed": True,
            "median_mesh_over_single": round(overhead, 3),
            "queries": per_q,
            "streaming_mesh": stream_rec,
            "device": _device(),
        },
    }


# ---------------------------------------------------------------------------
# config #1: TPC-H Q1
# ---------------------------------------------------------------------------


def bench_tpch_q1(scale: float):
    import numpy as np

    from spark_druid_olap_tpu.catalog.segment import build_datasource
    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.models.aggregations import (
        Count,
        DoubleSum,
        ExpressionAgg,
    )
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.filters import Bound
    from spark_druid_olap_tpu.models.query import GroupByQuery
    from spark_druid_olap_tpu.plan.expr import col
    from spark_druid_olap_tpu.utils import datagen

    cols = datagen.gen_lineitem(scale=scale, seed=0)
    n_rows = len(cols["l_quantity"])

    ds = build_datasource(
        "tpch",
        cols,
        dimension_cols=datagen.LINEITEM_DIMS,
        metric_cols=["l_quantity", "l_extendedprice", "l_discount", "l_tax"],
        time_col="l_shipdate",
        rows_per_segment=1 << 23,
    )

    cutoff = (
        np.datetime64("1998-09-02").astype("datetime64[D]").astype(int) + 1
    ) * 86_400_000
    q = GroupByQuery(
        datasource="tpch",
        dimensions=(
            DimensionSpec("l_returnflag"),
            DimensionSpec("l_linestatus"),
        ),
        aggregations=(
            DoubleSum("sum_qty", "l_quantity"),
            DoubleSum("sum_base_price", "l_extendedprice"),
            ExpressionAgg(
                "sum_disc_price",
                col("l_extendedprice") * (1 - col("l_discount")),
            ),
            ExpressionAgg(
                "sum_charge",
                col("l_extendedprice")
                * (1 - col("l_discount"))
                * (1 + col("l_tax")),
            ),
            DoubleSum("sum_disc", "l_discount"),
            Count("count_order"),
        ),
        filter=Bound("l_shipdate", upper=str(int(cutoff)), ordering="numeric"),
    )

    from spark_druid_olap_tpu.config import SessionConfig
    from spark_druid_olap_tpu.plan.cost import choose_kernel_strategy

    eng = Engine(
        strategy=choose_kernel_strategy(
            n_rows, 8, SessionConfig.load_calibrated()
        )
    )
    out = eng.execute(q, ds)  # warmup: compile + device transfer
    assert len(out) == 6, out
    p50 = _timed(lambda: eng.execute(q, ds), reps=5, warmup=0)
    # one traced rep for the detail artifact's span tree (direct Engine
    # use has no context tracer; the process-default one serves here)
    from spark_druid_olap_tpu.obs import default_tracer

    with default_tracer().query_trace(query_type="bench"):
        eng.execute(q, ds)
    span_tree = default_tracer().last_trace_dict()

    # pandas oracle baseline (single-threaded host groupby, float64)
    import pandas as pd

    t0 = time.perf_counter()
    m = cols["l_shipdate"] <= cutoff
    df = pd.DataFrame(
        {
            "f": cols["l_returnflag"][m],
            "s": cols["l_linestatus"][m],
            "q": cols["l_quantity"][m].astype(np.float64),
            "p": cols["l_extendedprice"][m].astype(np.float64),
            "d": cols["l_discount"][m].astype(np.float64),
            "t": cols["l_tax"][m].astype(np.float64),
        }
    )
    df["dp"] = df.p * (1 - df.d)
    df["ch"] = df.dp * (1 + df.t)
    df.groupby(["f", "s"]).agg(
        {"q": "sum", "p": "sum", "dp": "sum", "ch": "sum", "d": "sum"}
    )
    pandas_time = time.perf_counter() - t0

    return {
        "metric": "tpch_q1_sf%g_rows_per_sec_per_chip" % scale,
        "value": round(n_rows / p50),
        "unit": "rows/s",
        "vs_baseline": round(pandas_time / p50, 2),
        "detail": {
            "p50_s": round(p50, 5),
            "pandas_baseline_s": round(pandas_time, 5),
            "device": _device(),
            "rows": n_rows,
            "metrics": (
                eng.last_metrics.to_dict() if eng.last_metrics else None
            ),
            "span_tree": span_tree,
        },
    }


# ---------------------------------------------------------------------------
# config #3: TopN + HLL
# ---------------------------------------------------------------------------


def bench_topn_hll(scale: float):
    from spark_druid_olap_tpu.workloads import ssb

    ctx = _calibrated_ctx()
    tables = ssb.gen_tables(scale=scale)
    ssb.register(ctx, tables=tables)
    n_rows = ctx.catalog.get("lineorder").num_rows
    # full SQL path: the planner's TopN rewrite + HLL mapping + calibrated
    # kernel routing (a direct engine call would bypass the cost model)
    sql = (
        "SELECT c_city, sum(lo_revenue) AS revenue, "
        "approx_count_distinct(lo_custkey) AS uniq_custs "
        "FROM lineorder GROUP BY c_city ORDER BY revenue DESC LIMIT 100"
    )
    t_tpu = _timed(lambda: ctx.sql(sql))

    f = ssb.flat_frame(tables)

    def pandas_topn():
        g = f.groupby("c_city").agg(
            revenue=("lo_revenue", "sum"),
            uniq_custs=("lo_custkey", "nunique"),
        )
        return g.sort_values("revenue", ascending=False).head(100)

    t_pd = _timed(pandas_topn, reps=1, warmup=0)
    return {
        "metric": "topn100_hll_sf%g_rows_per_sec_per_chip" % scale,
        "value": round(n_rows / t_tpu),
        "unit": "rows/s",
        "vs_baseline": round(t_pd / t_tpu, 2),
        "detail": {
            "p50_s": round(t_tpu, 5),
            "pandas_baseline_s": round(t_pd, 5),
            "device": _device(),
            "rows": n_rows,
        },
    }


def bench_sketch_mesh(scale: float):
    """Sketch merges across the mesh boundary at data size (VERDICT r4 #7):
    HLL register pmax and theta KMV union fold run inside the SPMD program
    over millions of rows, and the partial STATES are compared
    register-for-register against the single-device engine — not just the
    finalized estimates.  (The dryrun covers 442K rows; this is the SF-scale
    artifact.)"""
    import jax
    import numpy as np

    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.models.aggregations import (
        DoubleSum as A_DoubleSum,
        HyperUnique,
        ThetaSketch,
    )
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.query import GroupByQuery
    from spark_druid_olap_tpu.parallel.distributed import DistributedEngine
    from spark_druid_olap_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from spark_druid_olap_tpu.workloads import ssb

    n_dev = len(jax.devices())
    if n_dev < 2:
        raise RuntimeError("sketch_mesh needs >=2 devices")
    ctx = _calibrated_ctx()
    ssb.register(ctx, tables=ssb.gen_tables(scale=scale))
    ds = ctx.catalog.get("lineorder")
    n_rows = ds.num_rows
    dist = DistributedEngine(mesh=make_mesh(n_data=n_dev))

    queries = {
        "topn_hll": GroupByQuery(
            datasource="lineorder",
            dimensions=(DimensionSpec("c_city"),),
            aggregations=(
                A_DoubleSum("revenue", "lo_revenue"),
                HyperUnique("uniq_custs", "lo_custkey"),
            ),
        ),
        "groupby_theta": GroupByQuery(
            datasource="lineorder",
            dimensions=(DimensionSpec("d_year"), DimensionSpec("c_nation")),
            aggregations=(
                A_DoubleSum("revenue", "lo_revenue"),
                ThetaSketch("uniq_custs", "lo_custkey", size=4096),
            ),
        ),
    }
    per_q = {}
    times = []
    for name, q in queries.items():
        eng = Engine()
        # raw partial sketch states from BOTH executors
        _, la, G, _, _, _, sk_single = eng._partials_for_query(q, ds)
        sk_single = {k: np.asarray(v) for k, v in jax.device_get(sk_single).items()}
        low = dist._lowering_for(q, ds)
        from spark_druid_olap_tpu.exec.metrics import QueryMetrics

        cols, padded = dist._place_shards(
            ds, low.columns, QueryMetrics(query_type="bench")
        )
        run = dist._spmd_fn(
            low, padded // dist.mesh.shape[DATA_AXIS], ds,
            tuple(cols.keys()), "dense",
        )
        _, _, _, sk_mesh = jax.device_get(run(cols))
        sk_mesh = {k: np.asarray(v) for k, v in sk_mesh.items()}
        reg_equal = {}
        for agg in la.sketch_aggs:
            a, b = sk_single[agg.name], sk_mesh[agg.name]
            if isinstance(agg, HyperUnique):
                # HLL registers: int32, pmax merge is order-free — exact
                reg_equal[agg.name] = bool(np.array_equal(a, b))
            else:
                # theta KMV: the kept k-min SET is order-free; compare the
                # retained hash sets per group (sentinel = 0xFFFFFFFF)
                sent = np.uint32(0xFFFFFFFF)
                eq = True
                for g in range(a.shape[0]):
                    sa = np.unique(a[g][a[g] != sent])
                    sb = np.unique(b[g][b[g] != sent])
                    if not np.array_equal(sa, sb):
                        eq = False
                        break
                reg_equal[agg.name] = bool(eq)
        assert all(reg_equal.values()), (name, reg_equal)
        # finalized parity + mesh timing through the public execute path
        mesh_df = dist.execute(q, ds)
        single_df = eng.execute(q, ds)
        t_mesh = _timed(lambda: dist.execute(q, ds), reps=2, warmup=0)
        t_single = _timed(lambda: eng.execute(q, ds), reps=2, warmup=0)
        key = [d.name for d in q.dimensions]
        mesh_df = mesh_df.sort_values(key).reset_index(drop=True)
        single_df = single_df.sort_values(key).reset_index(drop=True)
        assert (mesh_df["uniq_custs"] == single_df["uniq_custs"]).all(), name
        times.append(t_mesh)
        per_q[name] = {
            "num_groups": G,
            "register_equal": reg_equal,
            "single_ms": round(t_single * 1e3, 2),
            "mesh_ms": round(t_mesh * 1e3, 2),
            "mesh_over_single": round(t_mesh / max(t_single, 1e-9), 2),
            "estimate_equal": True,
        }
    p50 = statistics.median(times)
    return {
        "metric": "sketch_mesh_sf%g_p50_latency" % scale,
        "value": round(p50 * 1e3, 2),
        "unit": "ms",
        "vs_baseline": 1.0,  # parity artifact: register equality is the bar
        "detail": {
            "rows": n_rows,
            "n_devices": n_dev,
            "mesh_shape": dict(dist.mesh.shape),
            "queries": per_q,
            "device": _device(),
        },
    }


# ---------------------------------------------------------------------------
# config #4: streaming hourly rollup
# ---------------------------------------------------------------------------


def bench_timeseries(n_chunks: int):
    """Throughput counts end-to-end wall time including host chunk generation
    and H2D streaming — the honest streaming number.  2M-row chunks: a
    chunk's columns fit cache (8M-row chunks measured ~40% slower on CPU);
    1B rows = chunks=512."""
    from spark_druid_olap_tpu.exec.streaming import StreamExecutor
    from spark_druid_olap_tpu.models.aggregations import (
        Count,
        DoubleMax,
        DoubleSum,
    )
    from spark_druid_olap_tpu.models.query import TimeseriesQuery
    from spark_druid_olap_tpu.utils import datagen

    chunk = 1 << 21
    q = TimeseriesQuery(
        datasource="events",
        granularity="hour",
        aggregations=(
            Count("n"),
            DoubleSum("v", "value"),
            DoubleMax("mx", "latency"),
        ),
        intervals=(datagen.event_stream_interval(),),
    )
    ds = datagen.event_stream_schema()
    # pin the kernel class from calibrated constants (hourly buckets ~= the
    # span in hours; a direct Engine has no planner to route for it)
    from spark_druid_olap_tpu.config import SessionConfig
    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.plan.cost import choose_kernel_strategy

    strat = choose_kernel_strategy(
        chunk, datagen.EVENT_SPAN_HOURS, SessionConfig.load_calibrated()
    )
    ex = StreamExecutor(engine=Engine(strategy=strat))
    # pre-stage the chunks so BOTH sides are timed on identical, already-
    # materialized data: charging the engine (but not pandas) for rng data
    # generation understated the engine ~3x in round 3's first run
    staged = [datagen.gen_event_chunk(i, chunk) for i in range(n_chunks)]
    # touch every staged page before timing EITHER side: the first read
    # pass over tens of GB of freshly-written anonymous memory runs ~5x
    # slower than warm reads on this host (measured 17.9 vs 96.8 M rows/s
    # over the identical data), and the engine always ran first — a
    # methodology bias against it.  Warm pages also match production,
    # where chunks arrive hot from the decoder.
    warm_sink = 0.0
    for c in staged:
        for a in c.values():
            warm_sink += float(a.sum())
    # warmup / compile on one chunk
    ex.execute(q, ds, iter(staged[:1]), chunk)
    t0 = time.perf_counter()
    ex.execute(q, ds, iter(staged), chunk)
    dt = time.perf_counter() - t0
    rows = ex.stats.rows

    # pandas baseline over the same staged chunks (streamed partials, the
    # way a host engine would honestly process an unbounded stream)
    import pandas as pd

    t0 = time.perf_counter()
    parts = []
    for c in staged:
        parts.append(
            pd.DataFrame(
                {"h": c["ts"] // 3_600_000, "v": c["value"],
                 "lat": c["latency"]}
            ).groupby("h").agg(
                n=("v", "count"), v=("v", "sum"), mx=("lat", "max")
            )
        )
    merged = pd.concat(parts).groupby(level=0).agg(
        n=("n", "sum"), v=("v", "sum"), mx=("mx", "max")
    )
    assert len(merged) > 0
    t_pd = time.perf_counter() - t0
    return {
        "metric": "timeseries_hourly_rollup_%dM_rows_per_sec" % (rows // 1_000_000),
        "value": round(rows / dt),
        "unit": "rows/s",
        "vs_baseline": round(t_pd / dt, 2),
        "detail": {
            "wall_s": round(dt, 2),
            "rows": rows,
            "chunks": n_chunks,
            "pandas_s": round(t_pd, 2),
            "pipeline_stages": ex.stats.to_dict(),
            # attribute streaming losses honestly: every chunk's columns
            # cross the host->device link, so the calibrated link rate
            # bounds throughput no matter how fast the device rollup is.
            # (The round-5 tunneled chip measured ~17-46 MB/s — a floor of
            # several seconds for a 25M-row stream that a production
            # PCIe-attached host pays ~100x less for.)  h2d bytes are the
            # MEASURED post-normalization transfer (StreamStats), not a
            # guessed layout.
            "h2d_link_bytes_per_s": (h2d_bw := _cal_key("h2d_bytes_per_s")),
            "h2d_link_bound_s": (
                round(ex.stats.h2d_bytes / h2d_bw, 2) if h2d_bw else None
            ),
            "device": _device(),
        },
    }


# ---------------------------------------------------------------------------
# config #5: CUBE + theta
# ---------------------------------------------------------------------------


def bench_cube_theta(scale: float):
    from spark_druid_olap_tpu.workloads import ssb

    ctx = _calibrated_ctx()
    tables = ssb.gen_tables(scale=scale)
    ssb.register(ctx, tables=tables)
    n_rows = ctx.catalog.get("lineorder").num_rows
    sql = (
        "SELECT c_region, s_region, d_year, sum(lo_revenue) AS revenue, "
        "approx_count_distinct(lo_custkey) AS uniq_custs "
        "FROM lineorder GROUP BY CUBE (c_region, s_region, d_year)"
    )
    t_tpu = _timed(lambda: ctx.sql(sql))

    f = ssb.flat_frame(tables)

    def pandas_cube():
        import itertools

        import pandas as pd

        dims = ["c_region", "s_region", "d_year"]
        frames = []
        for r in range(len(dims) + 1):
            for sub in itertools.combinations(dims, r):
                if sub:
                    g = f.groupby(list(sub)).agg(
                        revenue=("lo_revenue", "sum"),
                        uniq_custs=("lo_custkey", "nunique"),
                    ).reset_index()
                else:
                    g = pd.DataFrame(
                        {
                            "revenue": [f.lo_revenue.sum()],
                            "uniq_custs": [f.lo_custkey.nunique()],
                        }
                    )
                frames.append(g)
        return pd.concat(frames, ignore_index=True)

    t_pd = _timed(pandas_cube, reps=1, warmup=0)
    return {
        "metric": "cube3_theta_sf%g_latency" % scale,
        "value": round(t_tpu * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(t_pd / t_tpu, 2),
        "detail": {
            "rows": n_rows,
            "grouping_sets": 8,
            "pandas_baseline_s": round(t_pd, 5),
            "device": _device(),
        },
    }


def _assist_ctx(rows: int, mode: str):
    """Fallback-workload context: mode is "auto" (the platform-aware default
    threshold — what a user gets), "off" (assist disabled), or "force"
    (assist at any size — the crossover probe)."""
    import numpy as np
    import pandas as pd

    import spark_druid_olap_tpu as sd
    from spark_druid_olap_tpu.config import SessionConfig

    cfg = SessionConfig.load_calibrated()
    cfg.result_cache_entries = 0
    if mode == "off":
        cfg.device_assist_min_rows = 1 << 62
    elif mode == "force":
        cfg.device_assist_min_rows = 1000
        cfg.device_assist_force = True  # bypass the cost gate: the curve
        # must MEASURE the losing regimes the gate exists to avoid
    cfg.fallback_max_rows = 200_000_000
    ctx = sd.TPUOlapContext(cfg)

    rng = np.random.default_rng(3)
    n_orders = max(1000, rows // 4)
    n_parts = max(500, rows // 20)
    f = pd.DataFrame(
        {
            "l_orderkey": rng.integers(0, n_orders, rows),
            "l_partkey": rng.integers(0, n_parts, rows),
            "l_quantity": rng.integers(1, 51, rows).astype(np.float64),
            "l_extendedprice": (rng.random(rows) * 55_000 + 90).round(2),
            "c_name": np.char.add(
                "Customer#", (rng.integers(0, n_orders // 8, rows)).astype(str)
            ),
            "p_brand": np.char.add(
                "Brand#", rng.integers(11, 56, rows).astype(str)
            ),
            "s_region": rng.choice(
                ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"], rows
            ),
            "p_type": np.char.add(
                "TYPE#", rng.integers(0, 150, rows).astype(str)
            ),
        }
    )
    ctx.register_table(
        "lineitem", f,
        dimensions=(
            "l_orderkey", "l_partkey", "c_name", "p_brand",
            "s_region", "p_type",
        ),
        metrics=("l_quantity", "l_extendedprice"),
    )
    return ctx


ASSIST_QUERIES = {
    # q2-class: window rank over a grouped frame
    "q2_window_rank": """
        SELECT s_region, p_type, mn, rnk FROM
          (SELECT s_region, p_type, min(l_extendedprice) AS mn,
                  RANK() OVER (PARTITION BY s_region
                               ORDER BY min(l_extendedprice)) AS rnk
           FROM lineitem GROUP BY s_region, p_type) x
        WHERE rnk = 1 ORDER BY s_region
    """,
    # q17-class: correlated scalar AVG per part
    "q17_correlated_avg": """
        SELECT sum(l_extendedprice) / 7.0 AS avg_yearly
        FROM lineitem o
        WHERE l_quantity <
              (SELECT 0.5 * avg(l_quantity) FROM lineitem
               WHERE l_partkey = o.l_partkey)
    """,
    # q18-class: IN over a grouped HAVING subquery
    "q18_in_grouped_having": """
        SELECT c_name, l_orderkey, sum(l_quantity) AS total
        FROM lineitem
        WHERE l_orderkey IN
              (SELECT l_orderkey FROM lineitem
               GROUP BY l_orderkey HAVING sum(l_quantity) > 180)
        GROUP BY c_name, l_orderkey
        ORDER BY total DESC, l_orderkey LIMIT 10
    """,
}


def bench_assist(rows: int):
    """Device-assist: never-slower under the DEFAULT auto-threshold, with a
    committed crossover curve (VERDICT r4 #6 / weak #3).

    Round 4 measured assist with the threshold forced to 1000 rows — a
    regime the platform-aware default (SessionConfig.apply_platform_profile:
    8.4M rows on CPU, where engine and interpreter share the silicon) never
    enters, so min_speedup 0.57 told users nothing about shipped behavior.
    This mode measures three things:

    1. headline: auto (default threshold) vs assist-off at `rows` — the
       never-slower guarantee users actually get (>= 1.0 modulo timer noise
       on this shared host; the paths are IDENTICAL when auto declines).
    2. crossover curve: host vs FORCED assist at several sizes — the
       committed evidence for where the threshold should sit on this
       backend (it must lie above the largest losing size).
    3. TPU-conditional projection FROM CALIBRATION: the assisted subtree's
       modelled device time (scan bytes / calibrated stream bandwidth +
       dispatch) vs the measured host interpreter time — a number, not
       prose; refreshed automatically when a TPU calibration.json lands.
    """
    from spark_druid_olap_tpu.config import SessionConfig

    cfg = SessionConfig.load_calibrated()
    per_q = {}
    # 1. the shipped configuration: auto vs off
    ctxs = {m: _assist_ctx(rows, m) for m in ("auto", "off")}
    for name, q in ASSIST_QUERIES.items():
        rec = {}
        frames = {}
        for m, ctx in ctxs.items():
            ctx.sql(q)  # warmup (compiles, decode caches)
            rec[m + "_ms"] = round(
                _timed(lambda: frames.__setitem__(m, ctx.sql(q)),
                       reps=2, warmup=0) * 1e3, 1,
            )
        rec["auto_executor"] = ctxs["auto"].last_metrics.executor
        rec["auto_assist_subplans"] = (
            ctxs["auto"].last_metrics.assist_subplans
        )
        rec["speedup_auto_vs_off"] = round(
            rec["off_ms"] / max(rec["auto_ms"], 1e-9), 2
        )
        rec["parity_rows"] = bool(len(frames["auto"]) == len(frames["off"]))
        per_q[name] = rec
    del ctxs
    min_speedup = min(r["speedup_auto_vs_off"] for r in per_q.values())

    # 2. crossover curve (forced assist vs host at growing sizes)
    curve = []
    for n in (rows // 4, rows, rows * 4):
        cxs = {m: _assist_ctx(n, m) for m in ("off", "force")}
        pt = {"rows": n}
        for name, q in ASSIST_QUERIES.items():
            ts = {}
            for m, ctx in cxs.items():
                ctx.sql(q)
                ts[m] = _timed(lambda: ctx.sql(q), reps=1, warmup=0)
            pt[name] = round(ts["off"] / max(ts["force"], 1e-9), 2)
        curve.append(pt)
        del cxs
    # the shipped guarantee, stated directly: under the default cost gate
    # no query measures slower than assist-off beyond timer noise.  (The
    # gate may still engage subtrees that are a measured WASH — e.g. a
    # G=1 global aggregate, neutral on CPU and a win on TPU — so
    # comparing auto decisions against forced-mode losses would flag
    # noise, not harm.)
    gate_ok = min_speedup >= 0.95

    # 3. TPU-conditional projection from calibration constants: the
    # q18-class aggregate base (sum over ~rows of f32 + int keys)
    scan_bytes = rows * (4 + 4 + 1)
    bw = _stream_bw()
    projection = None
    if bw:
        modelled_device_s = scan_bytes / bw + cfg.cost_dispatch_us / 1e6
        host_s = per_q["q18_in_grouped_having"]["off_ms"] / 1e3
        projection = {
            "modelled_subtree_device_s": round(modelled_device_s, 4),
            "measured_host_interpreter_s": round(host_s, 4),
            "modelled_speedup_if_assisted": round(
                host_s / max(modelled_device_s, 1e-9), 1
            ),
            "calibration_device": cfg.calibration_meta.get("device")
            if cfg.calibration_meta
            else None,
        }
    return {
        "metric": "fallback_assist_auto_min_speedup_%drows" % rows,
        "value": min_speedup,
        "unit": "x",
        # the never-slower bar itself: >= 1.0 means the default threshold
        # never makes a fallback query slower than assist-off
        "vs_baseline": min_speedup,
        "detail": {
            "rows": rows,
            "queries": per_q,
            "crossover_curve": curve,
            "auto_never_slower_within_noise": gate_ok,
            "tpu_projection": projection,
            "device": _device(),
        },
    }


# ---------------------------------------------------------------------------
# cost-model calibration (writes calibration.json; SessionConfig.load_calibrated)
# ---------------------------------------------------------------------------


def _p95(xs):
    """Nearest-rank p95 (int(0.95*n) on a 20-sample array indexed the MAX
    — p100 — so one outlier inflated the published p95)."""
    import math

    xs = sorted(xs)
    return xs[max(0, math.ceil(0.95 * len(xs)) - 1)]


def bench_ingest(rows_m: float):
    """Ingestion-tier benchmark (ISSUE 6): bulk-load throughput of the
    sharded two-phase pipeline vs the serial seed path on an SF100-shaped
    raw workload, plus streamed append->visible latency and compaction
    equivalence.

    The workload is "SF100-shaped": the SSB flat-fact schema at SF100-like
    dimension cardinalities (c_city/s_city 250, p_brand1 1000, date
    attrs), with string attributes RAW (the bulk-load input shape a CSV
    or warehouse export presents) — the serial seed path dictionary-
    encodes them row-by-row against sorted domains; the sharded pipeline
    factorizes per shard and merges dictionaries deterministically.
    `rows_m` is millions of fact rows (memory-bounded for CI; the shape,
    not the row count, is what carries to SF100)."""
    import time as _t

    import numpy as np

    from spark_druid_olap_tpu.catalog.segment import build_datasource
    from spark_druid_olap_tpu.ingest import (
        build_datasource_sharded,
        sharded_ingest_workers,
    )

    n = int(rows_m * 1e6)
    rng = np.random.default_rng(7)
    t0_ms = 820_454_400_000  # 1996-01-01, the SSB date-range anchor

    def _vals(fmt, k):
        return np.array([fmt % i for i in range(k)])

    # SF100-shaped dimension domains (SSB spec cardinalities)
    domains = {
        "c_region": _vals("REGION#%d", 5),
        "c_nation": _vals("NATION#%02d", 25),
        "c_city": _vals("CITY#%03d", 250),
        "s_region": _vals("REGION#%d", 5),
        "s_nation": _vals("NATION#%02d", 25),
        "s_city": _vals("CITY#%03d", 250),
        "p_mfgr": _vals("MFGR#%d", 5),
        "p_category": _vals("MFGR#%02d", 25),
        "p_brand1": _vals("MFGR#%04d", 1000),
    }
    dims = list(domains) + ["d_year", "d_yearmonthnum"]
    metrics = ["lo_quantity", "lo_extendedprice", "lo_revenue",
               "lo_discount"]

    def gen_chunk(lo, hi):
        m = hi - lo
        c = {
            k: v[rng.integers(0, len(v), m)].astype(object)
            for k, v in domains.items()
        }
        year = rng.integers(1992, 1999, m)
        month = rng.integers(1, 13, m)
        c["d_year"] = year.astype(np.int64)
        c["d_yearmonthnum"] = (year * 100 + month).astype(np.int64)
        c["lo_quantity"] = rng.integers(1, 51, m).astype(np.int64)
        c["lo_extendedprice"] = rng.integers(1, 6_000_000, m).astype(
            np.int64
        )
        c["lo_revenue"] = rng.integers(1, 6_000_000, m).astype(np.int64)
        c["lo_discount"] = rng.integers(0, 11, m).astype(np.int64)
        c["lo_orderdate"] = (
            t0_ms + rng.integers(0, 7 * 365, m) * 86_400_000
        )
        return c

    # chunk == segment size: the aligned (zero-copy) reshard path — how a
    # real bulk loader sizes its batches; the misaligned/ragged buffering
    # path is pinned by tests/test_ingest.py
    chunk_rows = 1 << 19
    chunks = [
        gen_chunk(lo, min(lo + chunk_rows, n))
        for lo in range(0, n, chunk_rows)
    ]
    full = {
        k: np.concatenate([c[k] for c in chunks])
        for k in chunks[0]
    }

    # -- (a) bulk load: serial seed path vs sharded pipeline ----------------
    t0 = _t.perf_counter()
    serial_ds = build_datasource(
        "lineorder", full, dims, metrics, time_col="lo_orderdate",
        rows_per_segment=1 << 19,
    )
    t_serial = _t.perf_counter() - t0
    t0 = _t.perf_counter()
    sharded_ds = build_datasource_sharded(
        "lineorder", [dict(c) for c in chunks], dims, metrics,
        time_col="lo_orderdate", rows_per_segment=1 << 19,
    )
    t_sharded = _t.perf_counter() - t0
    # parity: identical dictionaries + identical encoded rows
    for d in dims:
        assert (
            sharded_ds.dicts[d].values == serial_ds.dicts[d].values
        ), f"dictionary drift on {d}"
    assert len(sharded_ds.segments) == len(serial_ds.segments)
    probe = serial_ds.segments[0]
    probe2 = sharded_ds.segments[0]
    for d in dims:
        np.testing.assert_array_equal(probe.dims[d], probe2.dims[d])
    speedup = t_serial / t_sharded

    # -- (b) streamed append -> visible latency -----------------------------
    ctx = _calibrated_ctx()
    ctx.register_datasource(sharded_ds)
    warm = "SELECT sum(lo_revenue) AS r FROM lineorder"
    checksum_before = float(ctx.sql(warm)["r"][0])
    append_ms, visible_ms = [], []
    batch = 128
    for i in range(20):
        rows = {
            k: v[: batch] if k != "c_city" else np.full(
                batch, "CITY#%03d" % (i % 250), dtype=object
            )
            for k, v in gen_chunk(0, batch).items()
        }
        t0 = _t.perf_counter()
        ack = ctx.append_rows("lineorder", rows)
        t1 = _t.perf_counter()
        got = ctx.sql(
            "SELECT count(*) AS n FROM lineorder"
        )
        t2 = _t.perf_counter()
        assert int(got["n"][0]) == ack["totalRows"]
        append_ms.append((t1 - t0) * 1e3)
        visible_ms.append((t2 - t0) * 1e3)
    appended_rows = 20 * batch
    append_tree = _span_tree(ctx)

    # -- (c) compaction: equivalence + version bump -------------------------
    count_q = "SELECT count(*) AS n FROM lineorder"
    checksum_mid = float(ctx.sql(warm)["r"][0])
    count_mid = int(ctx.sql(count_q)["n"][0])
    v_before = ctx.catalog.datasource_version("lineorder")
    t0 = _t.perf_counter()
    summary = ctx.compact("lineorder")
    compact_ms = (_t.perf_counter() - t0) * 1e3
    checksum_after = float(ctx.sql(warm)["r"][0])
    count_after = int(ctx.sql(count_q)["n"][0])
    # counts are exact; the f32 revenue sum may shift in the last ulp
    # when compaction re-draws segment boundaries (different partial-sum
    # association) — bounded-relative, like the SSB parity gate
    assert count_mid == count_after, "compaction changed row count"
    rel = abs(checksum_after - checksum_mid) / max(abs(checksum_mid), 1.0)
    assert rel < 1e-6, f"compaction moved the checksum by {rel}"
    assert summary["datasourceVersion"] > v_before
    compact_tree = _span_tree(ctx)

    return {
        "metric": "ingest_sf100shape_%gM_bulk_rows_per_sec" % rows_m,
        "value": round(n / t_sharded),
        "unit": "rows/s",
        "vs_baseline": round(speedup, 2),
        "detail": {
            "rows": n,
            "columns": len(dims) + len(metrics) + 1,
            "ingest_s": round(t_sharded, 2),
            "ingest_rows_per_sec": round(n / t_sharded),
            "serial_seed_s": round(t_serial, 2),
            "serial_seed_rows_per_sec": round(n / t_serial),
            "bulk_speedup": round(speedup, 2),
            "ingest_workers": sharded_ingest_workers(),
            "segments": len(sharded_ds.segments),
            "append_rows_total": appended_rows,
            "append_p50_ms": round(statistics.median(append_ms), 2),
            "append_p95_ms": round(_p95(append_ms), 2),
            "append_visible_p50_ms": round(
                statistics.median(visible_ms), 2
            ),
            "append_visible_p95_ms": round(_p95(visible_ms), 2),
            "compaction_ms": round(compact_ms, 2),
            "compaction": summary,
            "checksum_rel_drift": rel,
            "pre_append_checksum": checksum_before,
            "span_tree_append": append_tree,
            "span_tree_compact": compact_tree,
            "oracle": "serial-vs-sharded segment equality asserted; "
                      "compaction checksum equivalence asserted",
            "device": _device(),
        },
    }


def bench_deadline(scale: float):
    """Anytime-answers robustness artifact (ISSUE 7): the coverage-vs-
    deadline curve.  SSB-13 runs under a sweep of wall-clock deadlines
    (fractions of each query's measured full latency, down to ~1 ms);
    every run must return a WELL-FORMED answer — exact when the budget
    suffices, coverage-stamped partial when it expires mid-scan, never
    an exception.  The artifact records, per (query, deadline): the
    deadline, the achieved coverage, partial/exact, wall time, and
    oracle equality for coverage=1.0 answers; the headline value is the
    percentage of runs that answered well-formed (target: 100 — the
    pre-ISSUE-7 engine scores 0 on any mid-scan expiry, which all
    turned into errors)."""
    import spark_druid_olap_tpu as sd  # noqa: F401  (bench convention)
    from spark_druid_olap_tpu.utils.floatcmp import frames_allclose
    from spark_druid_olap_tpu.workloads import ssb

    ctx = _calibrated_ctx()
    # every sweep point must EXECUTE: a result-cache hit would report
    # coverage 1.0 without ever testing the deadline machinery
    ctx.config.result_cache_entries = 0
    tables = ssb.gen_tables(scale=scale)
    # smaller segments than the register default so a mid-scan expiry
    # has batch boundaries to land on at any scale
    ssb.register(ctx, tables=tables, rows_per_segment=1 << 17)
    n_rows = ctx.catalog.get("lineorder").num_rows

    oracle, full_ms = {}, {}
    for name, q in ssb.QUERIES.items():
        t = _timed(lambda n=name: ctx.sql(ssb.QUERIES[n]), reps=1)
        oracle[name] = ctx.sql(q)
        full_ms[name] = t * 1e3

    # deadline sweep: fixed 1ms floor (guaranteed mid-scan expiry on any
    # backend), then fractions of each query's own measured full latency
    fractions = (0.0, 0.1, 0.25, 0.5, 1.5)
    curves = {}
    runs = wellformed = exact_checked = 0
    worst_tree = None
    for name, q in ssb.QUERIES.items():
        points = []
        for frac in fractions:
            deadline_ms = max(1.0, frac * full_ms[name])
            ctx.config.query_timeout_ms = int(deadline_ms)
            t0 = time.perf_counter()
            point = {
                "deadline_ms": round(deadline_ms, 2),
                "fraction_of_full": frac,
            }
            runs += 1
            try:
                got = ctx.sql(q)
                m = ctx.last_metrics
                cov = (
                    None
                    if m is None
                    else (m.coverage if m.partial else 1.0)
                )
                point.update(
                    {
                        "wellformed": True,
                        "partial": bool(m.partial) if m else False,
                        "coverage": cov,
                        "rows_seen": m.rows_seen if m else None,
                        "executor": m.executor if m else None,
                        "total_ms": round(
                            (time.perf_counter() - t0) * 1e3, 2
                        ),
                    }
                )
                wellformed += 1
                if cov == 1.0:
                    ok, msg = frames_allclose(got, oracle[name])
                    point["oracle_equal"] = bool(ok)
                    exact_checked += 1
                    if not ok:
                        point["oracle_diff"] = msg[:200]
                if frac == 0.0 and worst_tree is None:
                    worst_tree = _span_tree(ctx)
            except Exception as e:  # fault-ok: the artifact records the miss
                point.update(
                    {
                        "wellformed": False,
                        "error_class": type(e).__name__,
                        "error": str(e)[:200],
                    }
                )
            _note_partial("%s@%g" % (name, frac), point)
            points.append(point)
        curves[name] = points
    ctx.config.query_timeout_ms = 0
    frac_ok = wellformed / max(1, runs)
    oracle_ok = all(
        p.get("oracle_equal", True)
        for pts in curves.values()
        for p in pts
    )
    return {
        "metric": "deadline_ssb_sf%g_wellformed_pct" % scale,
        "value": round(100.0 * frac_ok, 2),
        "unit": "%",
        # 1.0 == every deadline-bounded run answered well-formed (the
        # seed engine turns every mid-scan expiry into an error: 0.0)
        "vs_baseline": round(frac_ok if oracle_ok else 0.0, 4),
        "detail": {
            "rows": n_rows,
            "runs": runs,
            "wellformed": wellformed,
            "exact_answers_checked": exact_checked,
            "oracle_equal_all": oracle_ok,
            "full_latency_ms": {
                k: round(v, 2) for k, v in full_ms.items()
            },
            "deadline_fractions": list(fractions),
            "curves": curves,
            "span_tree_tightest_deadline": worst_tree,
            "device": _device(),
        },
    }


def bench_hammer(scale: float):
    """Async-serving-core artifact (ISSUE 8): hundreds of concurrent
    mixed-priority queries through the HTTP server with micro-batch
    fusion, priority lanes, and the delta-aware result cache armed.
    Four sections:

      1. **fusion** — N compatible concurrent dashboard queries as ONE
         fused device program vs the same N as serial dispatches (wall
         time of the wave: the dispatch-amortization claim).
      2. **result cache** — an identical dashboard refresh served with
         ZERO device dispatch (the hit's span tree is recorded and must
         contain no segment_dispatch/h2d/device_fetch), plus the
         delta-aware refresh after an append (rows_scanned == the
         delta).
      3. **lane isolation** — fast-lane (topN) p50/p95/p99 while a
         storm of slow SF-scale scans saturates the heavy lane, against
         the SAME storm with lane routing effectively off (scans
         admitted interactive): the starvation the lanes exist to
         prevent, measured.
      4. **mixed hammer** — interleaved fast + heavy waves (hundreds of
         queries) with per-lane latency percentiles and zero 500s.

    Headline: fast-lane p95 under heavy-lane saturation;
    `vs_baseline` = lanes-off p95 / lanes-on p95 (the isolation
    factor)."""
    import json as _json
    import statistics as _stats
    import threading as _threading
    import time as _t
    import urllib.request as _url

    from spark_druid_olap_tpu.resilience import injector
    from spark_druid_olap_tpu.server import OlapServer
    from spark_druid_olap_tpu.workloads import ssb

    ctx = _calibrated_ctx()
    cfg = ctx.config
    cfg.result_cache_entries = 0  # sections arm it explicitly
    cfg.fusion_window_ms = 0.0
    cfg.prefer_distributed = False
    n_rows_target = int(6_000_000 * scale)
    cfg.lane_heavy_rows = max(1, n_rows_target // 8)
    ctx.serve.fusion.window_ms = 0.0
    ssb.register(ctx, scale=scale, rows_per_segment=1 << 16)
    n_rows = ctx.catalog.get("lineorder").num_rows
    srv = OlapServer(ctx, port=0).start()
    port = srv.port

    import urllib.error as _uerr

    def post_safe(path, payload, timeout=300):
        req = _url.Request(
            "http://127.0.0.1:%d%s" % (port, path),
            data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        t0 = _t.perf_counter()
        try:
            with _url.urlopen(req, timeout=timeout) as r:
                body = r.read()
                return (
                    r.status,
                    (_t.perf_counter() - t0) * 1e3,
                    dict(r.headers),
                    body,
                )
        except _uerr.HTTPError as e:
            return (
                e.code, (_t.perf_counter() - t0) * 1e3,
                dict(e.headers), e.read(),
            )

    def pcts(vals):
        if not vals:
            return {}
        s = sorted(vals)

        def q(p):
            return s[min(len(s) - 1, int(p * (len(s) - 1) + 0.5))]

        return {
            "p50_ms": round(_stats.median(s), 2),
            "p95_ms": round(q(0.95), 2),
            "p99_ms": round(q(0.99), 2),
            "n": len(s),
        }

    iv = ["1992-01-01T00:00:00Z/1999-01-01T00:00:00Z"]
    fast_specs = [
        {
            "queryType": "topN", "dataSource": "lineorder",
            "granularity": "all", "dimension": "c_region",
            "metric": "r", "threshold": 5,
            "aggregations": [
                {"type": "doubleSum", "name": "r",
                 "fieldName": "lo_revenue"},
            ],
            "intervals": iv,
        },
        {
            "queryType": "timeseries", "dataSource": "lineorder",
            "granularity": "year",
            "aggregations": [
                {"type": "doubleSum", "name": "r",
                 "fieldName": "lo_revenue"},
                {"type": "count", "name": "n"},
            ],
            "intervals": iv,
        },
        {
            "queryType": "groupBy", "dataSource": "lineorder",
            "granularity": "all", "dimensions": ["s_region"],
            "aggregations": [
                {"type": "longSum", "name": "q",
                 "fieldName": "lo_quantity"},
            ],
            "intervals": iv,
        },
        {
            "queryType": "groupBy", "dataSource": "lineorder",
            "granularity": "all", "dimensions": ["d_year"],
            "aggregations": [
                {"type": "doubleSum", "name": "r",
                 "fieldName": "lo_revenue"},
            ],
            "intervals": iv,
        },
    ]
    scan_spec = {
        "queryType": "scan", "dataSource": "lineorder",
        "columns": ["c_region", "lo_revenue"], "limit": 50,
        "intervals": iv,
    }

    def wave(specs, concurrent=True, rounds=1, ctxt=None):
        """Latency list (ms) + status counts for `rounds` waves."""
        lats, codes = [], {}
        for _ in range(rounds):
            results = {}

            def run(i, spec):
                body = dict(spec)
                if ctxt:
                    body["context"] = dict(ctxt)
                code, ms, _h, _b = post_safe("/druid/v2", body)
                results[i] = (code, ms)

            if concurrent:
                ths = [
                    _threading.Thread(target=run, args=(i, s))
                    for i, s in enumerate(specs)
                ]
                for th in ths:
                    th.start()
                for th in ths:
                    th.join()
            else:
                for i, s in enumerate(specs):
                    run(i, s)
            for code, ms in results.values():
                codes[code] = codes.get(code, 0) + 1
                if code == 200:
                    lats.append(ms)
        return lats, codes

    # -- section 1: fusion amortization ---------------------------------
    fusion_n = 8
    fused_specs = (fast_specs * 2)[:fusion_n]
    wave(fused_specs, concurrent=False)  # warm programs + residency
    t0 = _t.perf_counter()
    wave(fused_specs, concurrent=False)
    serial_wall_ms = (_t.perf_counter() - t0) * 1e3
    ctx.serve.fusion.window_ms = 6.0
    wave(fused_specs)  # warm the fused program
    fused_walls = []
    fused_lats = []
    for _ in range(3):
        t0 = _t.perf_counter()
        lats, _codes = wave(fused_specs)
        fused_walls.append((_t.perf_counter() - t0) * 1e3)
        fused_lats.extend(lats)
    fused_wall_ms = min(fused_walls)
    fusion_stats = ctx.serve.fusion.to_dict()
    ctx.serve.fusion.window_ms = 0.0

    # -- section 2: result cache (zero dispatch + delta refresh) --------
    cfg.result_cache_entries = 64
    ctx.serve.result_cache.resize(64)
    gb = fast_specs[3]
    post_safe("/druid/v2", dict(gb, context={"queryId": "hammer-warm"}))
    code, hit_ms, _h, _b = post_safe(
        "/druid/v2", dict(gb, context={"queryId": "hammer-hit"})
    )
    hit_trace = None
    for _ in range(50):  # the ring publish can trail the response bytes
        try:
            with _url.urlopen(
                "http://127.0.0.1:%d/druid/v2/trace/hammer-hit" % port,
                timeout=30,
            ) as r:
                hit_trace = _json.loads(r.read())
            break
        except Exception:
            _t.sleep(0.02)

    def span_names(node):
        out = [node["name"]]
        for c in node.get("children", ()):
            out += span_names(c)
        return out

    hit_spans = span_names(hit_trace["spans"]) if hit_trace else []
    hit_zero_dispatch = hit_trace is not None and not (
        {"segment_dispatch", "h2d", "device_fetch"} & set(hit_spans)
    )
    hit_strategy = (
        ctx.last_metrics.strategy if ctx.last_metrics else ""
    )
    # delta-aware refresh: append 3 rows, re-ask — only the delta scans.
    # Row values are drawn FROM the live dictionaries: a novel value
    # would extend a dictionary (remapping the code space), which is a
    # deliberate full miss — this section measures the append-only path
    ver_before = ctx.catalog.datasource_version("lineorder")
    dsx = ctx.catalog.get("lineorder")

    def dom(col):
        return dsx.dicts[col].values[0]

    ing_code, _ms_i, _h_i, ing_body = post_safe(
        "/druid/v2/ingest/lineorder",
        {
            "rows": [
                {
                    **{
                        c: dom(c)
                        for c in (
                            "c_region", "c_nation", "c_city",
                            "s_region", "s_nation", "s_city",
                            "p_mfgr", "p_category", "p_brand1",
                            "d_year", "d_yearmonthnum", "d_yearmonth",
                            "d_weeknuminyear",
                        )
                    },
                    "lo_orderdate": "1992-01-0%d" % (i + 1),
                    "lo_quantity": 1, "lo_extendedprice": 1.0,
                    "lo_discount": 0.0, "lo_revenue": 1.0,
                    "lo_supplycost": 1.0, "lo_custkey": 0,
                }
                for i in range(3)
            ]
        },
    )
    code, delta_ms, _h, _b = post_safe("/druid/v2", gb)
    delta_strategy = (
        ctx.last_metrics.strategy if ctx.last_metrics else ""
    )
    delta_rows_scanned = (
        ctx.last_metrics.rows_scanned if ctx.last_metrics else -1
    )
    cache_stats = ctx.serve.result_cache.to_dict()

    # -- section 3: lane isolation under a heavy-scan storm -------------
    # scans sleep at their per-segment checkpoint (injected delay —
    # deterministic slowness that releases the GIL) and the fast
    # dashboards run CACHE-WARM (zero-dispatch hits, milliseconds), so
    # the measurement isolates SLOT starvation — the thing lanes fix —
    # from single-core compute contention among the fast queries
    # themselves
    # the fast wave is the INTERACTIVE-class traffic (topN/timeseries —
    # interactive by type); the groupBy dashboards classify heavy at
    # this scale by the row-threshold policy and belong to the storm's
    # lane, not the protected one
    interactive_specs = fast_specs[:2]
    fast_wave = (interactive_specs * 6)[:12]
    wave(fast_specs, concurrent=False)  # warm every entry at this version
    wave([scan_spec], concurrent=False)  # warm the scan path (compile,
    # residency) BEFORE the delay arms: a cold first scan compiles under
    # the GIL and would pollute whichever lane configuration runs first
    injector().arm("engine.scan_loop", "delay", delay_ms=300.0)

    def storm_and_measure():
        stop = _threading.Event()
        storm_codes = {}

        def scanner():
            while not stop.is_set():
                code, _ms, _h, _b = post_safe(
                    "/druid/v2", scan_spec, timeout=600
                )
                storm_codes[code] = storm_codes.get(code, 0) + 1

        scanners = [
            _threading.Thread(target=scanner) for _ in range(6)
        ]
        for th in scanners:
            th.start()
        _t.sleep(0.3)  # let the storm occupy its lane
        lats, codes = wave(fast_wave, concurrent=True, rounds=4)
        stop.set()
        for th in scanners:
            th.join(timeout=600)
        return lats, codes, storm_codes

    baseline_lats, _codes = wave(fast_wave, concurrent=True, rounds=4)
    lanes_on_lats, lanes_on_codes, storm_on = storm_and_measure()
    # lanes OFF counterfactual: classification reads the live config —
    # with the threshold at infinity every scan admits interactive and
    # the storm occupies the interactive slots the dashboards need
    cfg.lane_heavy_rows = 1 << 62
    lanes_off_lats, lanes_off_codes, storm_off = storm_and_measure()
    cfg.lane_heavy_rows = max(1, n_rows_target // 8)
    injector().disarm("engine.scan_loop")

    # -- section 4: mixed hammer (hundreds, both lanes) -----------------
    ctx.serve.fusion.window_ms = 4.0
    mixed_fast, mixed_heavy = [], []
    mixed_codes = {}
    heavy_every = 6  # ~17% heavy traffic

    def mixed_run(i):
        heavy = i % heavy_every == 0
        spec = (
            scan_spec
            if heavy
            else interactive_specs[i % len(interactive_specs)]
        )
        code, ms, _h, _b = post_safe("/druid/v2", spec, timeout=600)
        mixed_codes[code] = mixed_codes.get(code, 0) + 1
        if code == 200:
            (mixed_heavy if heavy else mixed_fast).append(ms)

    total_mixed = 240
    batch = 24
    for lo in range(0, total_mixed, batch):
        ths = [
            _threading.Thread(target=mixed_run, args=(i,))
            for i in range(lo, min(lo + batch, total_mixed))
        ]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
    ctx.serve.fusion.window_ms = 0.0
    health = ctx.resilience.health()
    srv.shutdown()

    lanes_on = pcts(lanes_on_lats)
    lanes_off = pcts(lanes_off_lats)
    isolation = (
        round(lanes_off.get("p95_ms", 0) / lanes_on["p95_ms"], 2)
        if lanes_on.get("p95_ms")
        else 0.0
    )
    return {
        "metric": "hammer_fast_lane_p95_under_heavy_storm_ms",
        "value": lanes_on.get("p95_ms", -1.0),
        "unit": "ms",
        "vs_baseline": isolation,
        "detail": {
            "rows": n_rows,
            "scale": scale,
            "fusion": {
                "n_compatible_queries": fusion_n,
                "serial_dispatches_wall_ms": round(serial_wall_ms, 2),
                "fused_batch_wall_ms": round(fused_wall_ms, 2),
                "fused_speedup": round(
                    serial_wall_ms / max(fused_wall_ms, 1e-9), 3
                ),
                "fused_member_latency": pcts(fused_lats),
                "scheduler": fusion_stats,
                "note": "the amortized quantity is the per-dispatch "
                "device round trip; on local CPU dispatch is ~free so "
                "parity is the expected floor — the tunneled-TPU "
                "66 ms floor is where the N-way amortization pays",
            },
            "result_cache": {
                "hit_ms": round(hit_ms, 2),
                "hit_strategy": hit_strategy,
                "hit_zero_device_dispatch": hit_zero_dispatch,
                "hit_span_names": hit_spans,
                "delta_refresh_strategy": delta_strategy,
                "delta_refresh_rows_scanned": delta_rows_scanned,
                "delta_refresh_ms": round(delta_ms, 2),
                "append_status": ing_code,
                "append_ack": (
                    _json.loads(ing_body.decode())
                    if ing_body
                    else None
                ),
                "version_bumped": (
                    ctx.catalog.datasource_version("lineorder")
                    > ver_before
                ),
                "stats": cache_stats,
                "hit_span_tree": hit_trace,
            },
            "lanes": {
                "fast_baseline": pcts(baseline_lats),
                "fast_with_heavy_storm_lanes_on": lanes_on,
                "fast_with_heavy_storm_lanes_off": lanes_off,
                "isolation_factor_p95": isolation,
                "codes_lanes_on": lanes_on_codes,
                "codes_lanes_off": lanes_off_codes,
                "storm_codes_on": storm_on,
                "storm_codes_off": storm_off,
            },
            "mixed_hammer": {
                "total_queries": total_mixed,
                "fast": pcts(mixed_fast),
                "heavy": pcts(mixed_heavy),
                "codes": mixed_codes,
                "server_errors": health["counters"][
                    "server_errors_total"
                ],
            },
            "lane_health": health.get("lanes"),
            "serving": ctx.serve.to_dict(),
            "device": _device(),
        },
    }


def bench_overlap(scale: float):
    """Transfer-pipeline artifact (ISSUE 10): the pipeline-on vs
    pipeline-off counterfactual on the two link-bound paths.

    Section A — the STREAMING ROLLUP (the workload the re-anchor note
    calls link-bound at 45 MB/s): hourly rollup over staged event
    chunks, identical data both modes, receipts from a forced-sample
    trace.  With the pipeline on, chunk k+1's h2d issue precedes chunk
    k's compute dispatch (double buffering) and lands in the receipt's
    `prefetch` bucket; off, every put is a foreground stall in the
    `h2d`/transfer bucket.  Section B — SSB-13 scans, programs warm but
    residency dropped before each measured rep, so every column
    re-crosses the link: per-query receipts give transfer-stall /
    prefetch / overlap-efficiency both modes, and pipeline-on frames
    must be BYTE-identical to pipeline-off (the fold-order contract).

    Headline: mean pipeline-on overlap efficiency across SSB-13
    (device-busy over device-busy + transfer-stall, ROADMAP direction
    4's success metric); vs_baseline is the total transfer-stall ratio
    off/on (how many times less link time sits in front of compute)."""
    import spark_druid_olap_tpu as sd  # noqa: F401  (bench convention)
    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.exec.streaming import StreamExecutor
    from spark_druid_olap_tpu.models.aggregations import (
        Count,
        DoubleMax,
        DoubleSum,
    )
    from spark_druid_olap_tpu.models.query import TimeseriesQuery
    from spark_druid_olap_tpu.utils import datagen
    from spark_druid_olap_tpu.workloads import ssb

    ctx = _calibrated_ctx()
    # every measured rep must EXECUTE (a result-cache hit moves nothing)
    ctx.config.result_cache_entries = 0

    # -- section A: streaming rollup -----------------------------------------
    chunk = 1 << 19
    n_chunks = max(4, int(round(8 * scale)))
    tsq = TimeseriesQuery(
        datasource="events",
        granularity="hour",
        aggregations=(
            Count("n"),
            DoubleSum("v", "value"),
            DoubleMax("mx", "latency"),
        ),
        intervals=(datagen.event_stream_interval(),),
    )
    events = datagen.event_stream_schema()
    staged = [datagen.gen_event_chunk(i, chunk) for i in range(n_chunks)]
    warm_sink = 0.0  # touch every staged page before timing (see ssb bench)
    for c in staged:
        for a in c.values():
            warm_sink += float(a.sum())
    stream = {}
    stream_frames = {}
    for pipe_mode in ("off", "on"):
        eng = Engine()
        eng._pipeline.enabled = pipe_mode == "on"
        ex = StreamExecutor(engine=eng)
        ex.execute(tsq, events, iter(staged[:1]), chunk)  # compile warmup
        ctx.tracer.force_sample_next()
        t0 = time.perf_counter()
        with ctx.tracer.query_trace(query_type="stream_overlap"):
            stream_frames[pipe_mode] = ex.execute(
                tsq, events, iter(staged), chunk
            )
        wall_s = time.perf_counter() - t0
        doc = ctx.tracer.last_trace_dict() or {}
        rc = doc.get("receipt") or {}
        stream[pipe_mode] = {
            "wall_s": round(wall_s, 3),
            "rows": ex.stats.rows,
            "rows_per_sec": round(ex.stats.rows / max(wall_s, 1e-9)),
            "pipeline_stages": ex.stats.to_dict(),
            "transfer_stall_ms": rc.get("transfer_ms"),
            "prefetch_ms": rc.get("prefetch_ms"),
            "overlap_efficiency": rc.get("overlap_efficiency"),
        }
        _note_partial("stream_%s" % pipe_mode, stream[pipe_mode])
    stream_identical = stream_frames["on"].equals(stream_frames["off"])

    # -- section B: SSB-13 scans ---------------------------------------------
    tables = ssb.gen_tables(scale=scale)
    ssb.register(ctx, tables=tables, rows_per_segment=1 << 17)
    n_rows = ctx.catalog.get("lineorder").num_rows
    queries = {}
    stall_total = {"on": 0.0, "off": 0.0}
    eff = []
    identical_all = True
    frames = {}
    for name, sql_q in ssb.QUERIES.items():
        per = {}
        for pipe_mode in ("off", "on"):
            ctx.engine._pipeline.enabled = pipe_mode == "on"
            ctx.sql(sql_q)  # program/lowering warm
            ctx.engine.drop_residency()  # link re-cold: columns move again
            rc, wall_ms = _receipt_rep(
                ctx,
                lambda n=name, m=pipe_mode, q=sql_q: frames.__setitem__(
                    (n, m), ctx.sql(q)
                ),
            )
            rc = rc or {}
            per[pipe_mode] = {
                "wall_ms": wall_ms,
                "transfer_stall_ms": rc.get("transfer_ms"),
                "prefetch_ms": rc.get("prefetch_ms"),
                "prefetch_bytes": rc.get("prefetch_bytes"),
                "transfer_bytes": rc.get("transfer_bytes"),
                "device_ms": rc.get("device_ms"),
                "overlap_efficiency": rc.get("overlap_efficiency"),
            }
            stall_total[pipe_mode] += float(rc.get("transfer_ms") or 0.0)
        got_on, got_off = frames.pop((name, "on")), frames.pop((name, "off"))
        per["identical"] = bool(
            got_on.reset_index(drop=True).equals(
                got_off.reset_index(drop=True)
            )
        )
        identical_all = identical_all and per["identical"]
        if per["on"]["overlap_efficiency"] is not None:
            eff.append(per["on"]["overlap_efficiency"])
        queries[name] = per
        _note_partial(name, per)
    ctx.engine._pipeline.enabled = True
    mean_eff = sum(eff) / max(1, len(eff))
    stall_ratio = stall_total["off"] / max(stall_total["on"], 1e-9)
    return {
        "metric": "overlap_ssb_sf%g_pipeline_on_efficiency" % scale,
        "value": round(mean_eff, 4),
        "unit": "ratio",
        # how many times less transfer stall sits in front of compute
        # with the pipeline on (identical data, programs warm both ways)
        "vs_baseline": round(stall_ratio, 2),
        "identical": identical_all and stream_identical,
        "detail": {
            "rows": n_rows,
            "stream_rows": stream["on"]["rows"],
            "transfer_stall_ms_on": round(stall_total["on"], 2),
            "transfer_stall_ms_off": round(stall_total["off"], 2),
            "results_identical_on_vs_off": identical_all,
            "stream_identical_on_vs_off": stream_identical,
            "streaming_rollup": stream,
            "queries": queries,
            "pipeline": ctx.engine._pipeline.to_dict(),
            "device": _device(),
        },
    }


def bench_boot(scale: float):
    """Durable-storage boot benchmark (ISSUE 13 satellite): cold-boot
    re-encode vs mmap snapshot restore over the same SSB SF`scale`
    lineorder datasource, WAL replay throughput, and byte-identical
    query results across a kill-and-restart.

    Three measured paths:
      * re-encode — what a storage-less process pays EVERY boot:
        dictionary-encode + segment the raw flat columns from scratch.
      * restore — a context built over the persisted snapshot: per-column
        .npy files open as np.memmap (catalog/persist.LazyColumnMap);
        no row is re-encoded, columns page in lazily on first query.
      * restore+replay — same, with a WAL tail of streamed appends past
        the snapshot watermark replayed through the live append path
        (the crash-recovery shape; yields rows/s replay throughput)."""
    import dataclasses as _dc
    import shutil
    import tempfile
    import time as _t

    import numpy as np

    import spark_druid_olap_tpu as sd
    from spark_druid_olap_tpu.catalog.persist import LazyColumnMap
    from spark_druid_olap_tpu.config import SessionConfig
    from spark_druid_olap_tpu.workloads import ssb

    def _cfg(storage_dir=None):
        cfg = SessionConfig.load_calibrated()
        cfg.result_cache_entries = 0  # measure boots, not cache hits
        cfg.storage_dir = storage_dir
        return cfg

    def _register(ctx):
        if scale >= 4:
            # the full flat host frame does not survive large SFs
            ssb.register_streamed(ctx, scale=scale)
        else:
            ssb.register(ctx, tables=ssb.gen_tables(scale=scale))
        return ctx.catalog.get("lineorder").num_rows

    # -- (a) the storage-less baseline: re-encode at every boot --------------
    t0 = _t.perf_counter()
    ctx_cold = sd.TPUOlapContext(_cfg())
    n_rows = _register(ctx_cold)
    t_reencode = _t.perf_counter() - t0
    del ctx_cold

    # -- (b) one durable registration, then a timed snapshot restore --------
    root = tempfile.mkdtemp(prefix="sdol_boot_bench_")
    try:
        ctx = sd.TPUOlapContext(_cfg(root))
        _register(ctx)  # snapshot flush commits before this returns
        disk_bytes = sum(
            os.path.getsize(os.path.join(dp, f))
            for dp, _, fs in os.walk(root)
            for f in fs
        )
        t0 = _t.perf_counter()
        ctx_restore = sd.TPUOlapContext(_cfg(root))
        t_restore = _t.perf_counter() - t0
        ds = ctx_restore.catalog.get("lineorder")
        disk_backed = all(
            isinstance(s.dims, LazyColumnMap)
            for s in ds.historical_segments()
        )

        # -- (c) WAL tail: streamed appends past the watermark, then a
        # restore that must replay them ---------------------------------
        # domain-value rows (decoded attrs, original int metrics) — the
        # wire shape POST /druid/v2/ingest presents; frame rows align
        # with the raw fact arrays (flat_frame preserves fact order)
        small = ssb.gen_tables(scale=0.01)
        frame = ssb.flat_frame(small)
        batch = 512
        append_cols = {}
        for c in ssb.FLAT_DIMS:
            v = frame[c].to_numpy()[:batch]
            append_cols[c] = (
                v.astype(object) if v.dtype.kind in "UO" else v
            )
        for m in ssb.FLAT_METRICS:
            append_cols[m] = np.asarray(
                small["lineorder"][m]
            )[:batch]
        append_cols["lo_orderdate"] = frame["lo_orderdate"].to_numpy()[
            :batch
        ]
        n_batches = 16
        for _i in range(n_batches):
            ctx_restore.append_rows("lineorder", dict(append_cols))
        queries = list(ssb.QUERIES)[:4]
        pre = {q: ctx_restore.sql(ssb.QUERIES[q]) for q in queries}

        t0 = _t.perf_counter()
        ctx_replayed = sd.TPUOlapContext(_cfg(root))
        t_restore_replay = _t.perf_counter() - t0
        recovery = dict(ctx_replayed.storage.last_recovery or {})
        replay_rows = int(recovery.get("replayed_rows", 0))
        replay_s = max(t_restore_replay - t_restore, 1e-9)

        # byte-identical answers across the restart (the acceptance bar)
        identical = all(
            pre[q].equals(ctx_replayed.sql(ssb.QUERIES[q]))
            for q in queries
        )
        assert identical, "restart changed query results"
        assert replay_rows == n_batches * batch, (
            "WAL replay lost rows: %d != %d"
            % (replay_rows, n_batches * batch)
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)

    speedup = t_reencode / max(t_restore, 1e-9)
    return {
        "metric": "boot_ssb_sf%g_restore_speedup" % scale,
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup, 2),
        "detail": {
            "rows": n_rows,
            "reencode_boot_s": round(t_reencode, 3),
            "restore_boot_s": round(t_restore, 3),
            "restore_replay_boot_s": round(t_restore_replay, 3),
            "restore_speedup": round(speedup, 2),
            "snapshot_disk_bytes": disk_bytes,
            "restored_disk_backed": disk_backed,
            "wal_replayed_records": int(
                recovery.get("replayed_records", 0)
            ),
            "wal_replayed_rows": replay_rows,
            "wal_replay_rows_per_sec": round(replay_rows / replay_s),
            "queries_identical_across_restart": identical,
            "queries_checked": queries,
            "oracle": "byte-identical DataFrames across kill-and-restart "
                      "asserted; replayed row count asserted exact",
            "device": _device(),
        },
    }


def bench_arena(scale: float):
    """One-dispatch arena artifact (ISSUE 14): the loop-vs-arena
    counterfactual on SSB-13.

    Identical data, programs warm, residency dropped before every
    measured rep (the arena re-pays its stack build each time — the
    honest comparison).  Per query both modes report the receipt's
    `dispatch_count` (the collapse the arena exists for: O(covered
    batches) -> O(1)), the arena_build bucket, device time, and wall;
    arena-on frames must be BYTE-identical to the loop path (the
    scan-carry fold replays the loop's select/fold tree op-for-op).

    Headline: total dispatch collapse ratio off/on across SSB-13;
    vs_baseline is the loop-path p50 wall over the arena p50 wall
    (how much one traced program beats the per-batch dispatch loop)."""
    import spark_druid_olap_tpu as sd  # noqa: F401  (bench convention)
    from spark_druid_olap_tpu.workloads import ssb

    ctx = _calibrated_ctx()
    # every measured rep must EXECUTE (a result-cache hit moves nothing)
    ctx.config.result_cache_entries = 0
    tables = ssb.gen_tables(scale=scale)
    ssb.register(ctx, tables=tables, rows_per_segment=1 << 17)
    n_rows = ctx.catalog.get("lineorder").num_rows

    queries = {}
    disp_total = {"on": 0, "off": 0}
    walls = {"on": [], "off": []}
    build_ms = []
    identical_all = True
    frames = {}
    for name, sql_q in ssb.QUERIES.items():
        per = {}
        for arena_mode in ("off", "on"):
            ctx.engine.arena_execution = arena_mode == "on"
            ctx.sql(sql_q)  # program/lowering warm
            ctx.engine.drop_residency()  # stack build re-paid every rep
            rc, wall_ms = _receipt_rep(
                ctx,
                lambda n=name, m=arena_mode, q=sql_q: frames.__setitem__(
                    (n, m), ctx.sql(q)
                ),
            )
            rc = rc or {}
            per[arena_mode] = {
                "wall_ms": wall_ms,
                "dispatch_count": rc.get("dispatch_count"),
                "arena_build_ms": rc.get("arena_build_ms"),
                "device_ms": rc.get("device_ms"),
                "transfer_ms": rc.get("transfer_ms"),
            }
            disp_total[arena_mode] += int(rc.get("dispatch_count") or 0)
            walls[arena_mode].append(wall_ms)
            if arena_mode == "on" and rc.get("arena_build_ms"):
                build_ms.append(float(rc["arena_build_ms"]))
        got_on, got_off = frames.pop((name, "on")), frames.pop((name, "off"))
        per["identical"] = bool(
            got_on.reset_index(drop=True).equals(
                got_off.reset_index(drop=True)
            )
        )
        identical_all = identical_all and per["identical"]
        queries[name] = per
        _note_partial(name, per)
    ctx.engine.arena_execution = True
    p50_on = statistics.median(walls["on"])
    p50_off = statistics.median(walls["off"])
    collapse = disp_total["off"] / max(disp_total["on"], 1)
    return {
        "metric": "arena_ssb_sf%g_dispatch_collapse" % scale,
        "value": round(collapse, 2),
        "unit": "ratio",
        # loop-path p50 wall over arena p50 wall: identical data,
        # programs warm, residency cold both ways
        "vs_baseline": round(p50_off / max(p50_on, 1e-9), 2),
        "identical": identical_all,
        "detail": {
            "rows": n_rows,
            "p50_wall_ms_arena": round(p50_on, 2),
            "p50_wall_ms_loop": round(p50_off, 2),
            "dispatches_arena": disp_total["on"],
            "dispatches_loop": disp_total["off"],
            "arena_build_ms_mean": round(
                sum(build_ms) / max(1, len(build_ms)), 3
            ),
            "results_identical_on_vs_off": identical_all,
            "queries": queries,
            "device": _device(),
        },
    }


def _mesh_receipt_rep(ctx, dist, q, ds, name):
    """Force-sampled rep of a DistributedEngine query under the context's
    tracer.  The mesh engine emits its spans (collective_merge, shard_h2d,
    segment_dispatch) through obs/span like every other executor, so
    opening the query trace HERE — outermost wins — collects them and the
    tracer folds the cost receipt at close, without routing through
    ctx.sql (whose cost model owns backend choice).  Returns
    (result_df, receipt_or_None, wall_ms, span_tree)."""
    import time as _t

    try:
        ctx.tracer.force_sample_next()
    except Exception:  # fault-ok: profiling must never fail a bench
        pass
    t0 = _t.perf_counter()
    with ctx.tracer.query_trace(query_id=name, query_type="bench_mesh"):
        df = dist.execute(q, ds)
    wall_ms = (_t.perf_counter() - t0) * 1e3
    doc = _span_tree(ctx) or {}
    return df, doc.get("receipt"), round(wall_ms, 2), doc


def _find_span_event(node, name):
    """First event dict called `name` in a span tree (depth-first)."""
    if not isinstance(node, dict):
        return None
    for ev in node.get("events") or ():
        if ev.get("name") == name:
            return ev
    for c in node.get("children") or ():
        hit = _find_span_event(c, name)
        if hit is not None:
            return hit
    return None


def bench_mesh_unified(scale: float):
    """Unified-executor counterfactual (ISSUE 15): the same SSB GroupBy
    set through THREE arms in one run — the single-device engine, the
    mesh with the SPMD arena off (legacy per-shard loop), and the mesh
    with the arena on (ONE shard_mapped program, scope as data input,
    collective merge at the boundary) — plus a virtual multi-slice point
    whose merge tree the calibrated cost model chooses (recorded as the
    `merge_tree` span event inside collective_merge).

    Steady-state serving comparison: programs warm and residency KEPT
    across reps in every arm (the arena's whole point is that the
    resident stack amortizes; `bench arena` owns the cold-build
    counterfactual).  The arena arm's receipts must show O(1)
    dispatches per query — that is the acceptance criterion's
    receipt-verified half; p50(mesh arena) vs p50(single) is the other.

    On the virtual CPU mesh the devices share host cores, so mesh-vs-
    single wall time measures SPMD overhead, not scaling; on a real
    multi-chip backend the same mode measures true scaling."""
    import jax

    from spark_druid_olap_tpu.models import query as Q
    from spark_druid_olap_tpu.parallel.distributed import DistributedEngine
    from spark_druid_olap_tpu.parallel.mesh import make_mesh, make_slice_mesh
    from spark_druid_olap_tpu.sql.parser import parse_sql
    from spark_druid_olap_tpu.workloads import ssb

    n_dev = len(jax.devices())
    if n_dev < 2:
        raise RuntimeError(
            f"mesh_unified needs >=2 devices, found {n_dev} (the "
            "orchestrator sets xla_force_host_platform_device_count "
            "for the CPU child)"
        )
    ctx = _calibrated_ctx()
    # every measured rep must EXECUTE (a result-cache hit moves nothing)
    ctx.config.result_cache_entries = 0
    if scale >= 4:
        ssb.register_streamed(ctx, scale=scale, seed=7)
    else:
        # multi-segment registration (bench_arena's convention): one big
        # segment would make the arena decline and both mesh arms
        # silently run the legacy path — no counterfactual left
        ssb.register(
            ctx, tables=ssb.gen_tables(scale=scale),
            rows_per_segment=1 << 17,
        )
    n_rows = ctx.catalog.get("lineorder").num_rows
    dist = DistributedEngine(mesh=make_mesh(n_data=n_dev))

    per_q = {}
    walls = {"single": [], "mesh_loop": [], "mesh_arena": []}
    disp = {"mesh_loop": 0, "mesh_arena": 0}
    arena_disp_max = 0
    errs = []
    plans = []
    for name in ssb.QUERIES:
        lp, _, _ = parse_sql(ssb.QUERIES[name])
        rw = ctx._planner().plan(lp)
        ds = ctx.catalog.get(rw.datasource)
        if not isinstance(rw.query, Q.GroupByQuery):
            continue
        plans.append((name, rw.query, ds))
    assert plans, "no SSB GroupBy rewrites to run"

    for name, q, ds in plans:
        rec = {}
        # arm 1: single-device engine, its best mode (arena on)
        single_df = ctx.engine.execute(q, ds)  # warm: program + residency
        t_single = _timed(lambda: ctx.engine.execute(q, ds), reps=2, warmup=0)
        rec["single_ms"] = round(t_single * 1e3, 2)
        walls["single"].append(t_single * 1e3)
        # arms 2+3: the SAME DistributedEngine, arena off then on — the
        # counterfactual is the execution strategy, not the placement
        for mode, key in (("off", "mesh_loop"), ("on", "mesh_arena")):
            dist.arena_execution = mode == "on"
            dist.execute(q, ds)  # warm: program + shard placement
            df, rc, _w, tree = _mesh_receipt_rep(ctx, dist, q, ds, name)
            t_mesh = _timed(lambda: dist.execute(q, ds), reps=2, warmup=0)
            rc = rc or {}
            rec[key + "_ms"] = round(t_mesh * 1e3, 2)
            rec[key + "_dispatch_count"] = rc.get("dispatch_count")
            rec[key + "_device_ms"] = rc.get("device_ms")
            rec[key + "_transfer_ms"] = rc.get("transfer_ms")
            walls[key].append(t_mesh * 1e3)
            disp[key] += int(rc.get("dispatch_count") or 0)
            if key == "mesh_arena":
                arena_disp_max = max(
                    arena_disp_max, int(rc.get("dispatch_count") or 0)
                )
                err = _ssb_parity(df, single_df)
                rec["max_rel_err_vs_single"] = round(err, 8)
                errs.append(err)
        rec["mesh_over_single"] = round(
            rec["mesh_arena_ms"] / max(rec["single_ms"], 1e-9), 2
        )
        per_q[name] = rec
        _note_partial(name, rec)
    assert max(errs) < 1e-4, f"mesh_unified parity failure: {errs}"
    dist.arena_execution = True

    # multi-slice point: 2 virtual slices over the same devices; the
    # calibrated cost model picks flat-vs-hierarchical per query and
    # records its pricing as the merge_tree span event
    slice_rec = None
    if n_dev >= 4:
        dslice = DistributedEngine(mesh=make_slice_mesh(2, n_dev // 2))
        sw, trees = [], set()
        merge_ev = None
        for name, q, ds in plans:
            dslice.execute(q, ds)  # warm
            df, rc, _w, tree = _mesh_receipt_rep(
                ctx, dslice, q, ds, name + "_slice"
            )
            ev = _find_span_event(tree.get("spans"), "merge_tree")
            if ev is not None:
                trees.add(str((ev.get("attrs") or {}).get("tree")))
                merge_ev = merge_ev or ev
            t_sl = _timed(lambda: dslice.execute(q, ds), reps=2, warmup=0)
            sw.append(t_sl * 1e3)
            errs.append(_ssb_parity(df, ctx.engine.execute(q, ds)))
        assert max(errs) < 1e-4, f"multi-slice parity failure: {errs}"
        p50_slice = statistics.median(sw)
        p50_single = statistics.median(walls["single"])
        slice_rec = {
            "n_slices": 2,
            "n_devices_per_slice": n_dev // 2,
            "p50_ms": round(p50_slice, 2),
            # single-device-engine equivalents of throughput the 2-slice
            # mesh delivers (>1 = beats one device's engine)
            "slice_equivalents": round(p50_single / max(p50_slice, 1e-9), 2),
            "merge_trees_chosen": sorted(trees),
            "merge_tree_event": merge_ev,
        }

    p50_single = statistics.median(walls["single"])
    p50_loop = statistics.median(walls["mesh_loop"])
    p50_arena = statistics.median(walls["mesh_arena"])
    return {
        "metric": "mesh_unified_sf%g_mesh%d_p50_latency" % (scale, n_dev),
        "value": round(p50_arena, 2),
        "unit": "ms",
        # >=1 is the SF10 acceptance bar: mesh arena p50 <= single p50
        # in the SAME run (on virtual CPU meshes this measures overhead)
        "vs_baseline": round(p50_single / max(p50_arena, 1e-9), 2),
        "detail": {
            "rows": n_rows,
            "n_devices": n_dev,
            "p50_ms_single": round(p50_single, 2),
            "p50_ms_mesh_loop": round(p50_loop, 2),
            "p50_ms_mesh_arena": round(p50_arena, 2),
            "dispatches_mesh_loop": disp["mesh_loop"],
            "dispatches_mesh_arena": disp["mesh_arena"],
            # receipt-verified O(1)-dispatches-per-query evidence: the
            # WORST arena query, not just the total
            "arena_dispatches_per_query_max": arena_disp_max,
            "arena_vs_loop_speedup": round(
                p50_loop / max(p50_arena, 1e-9), 2
            ),
            "max_rel_err_vs_single": round(max(errs), 8),
            "multi_slice": slice_rec,
            "queries": per_q,
            "device": _device(),
        },
    }


def bench_cluster(scale: float):
    """Cluster-tier artifact (ISSUE 16): broker + 4 REAL subprocess
    historicals over one shared snapshot store on one host.  Sections:

      1. **QPS scaling** — the same covered SSB groupby workload driven
         through the broker's scatter/gather against 1, 2, and 4
         historicals (replication = min(2, n)); qps + p50/p95/p99 per
         phase.  One historical computes the full scope in one RPC;
         four split it ~4 ways across processes — the near-linear
         scaling the tier exists for.
      2. **kill-and-recover timeline** — a sequential query stream with
         one historical SIGKILLed mid-stream and respawned while
         queries keep flowing: per-query {t_ms, ms, ok, partial}
         records, zero errors asserted (replication=2 keeps every
         answer exact through the loss).
      3. **rolling restart** — every historical killed and rebooted in
         turn with queries across each window; zero failed, zero
         partial.
      4. one sampled broker receipt attributing scatter/gather/merge
         wall time with the per-historical RPC buckets.

    Headline: qps(4 historicals) / qps(1 historical)."""
    import shutil
    import signal
    import statistics as _stats
    import tempfile
    import threading as _threading
    import time as _t

    import spark_druid_olap_tpu as sd
    from spark_druid_olap_tpu.cluster import ClusterClient
    from spark_druid_olap_tpu.config import SessionConfig
    from spark_druid_olap_tpu.workloads import ssb

    n_nodes = 4
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    root = tempfile.mkdtemp(prefix="sdol_cluster_bench_")
    procs = {}  # nid -> subprocess.Popen
    try:
        cfg = SessionConfig.load_calibrated()
        cfg.result_cache_entries = 0  # measure scatter, not cache hits
        cfg.storage_dir = root
        # cold first RPC per historical pays the program compile; the
        # warmup rotation absorbs it, the timeout must survive it
        cfg.cluster_rpc_timeout_ms = 120_000.0
        broker = sd.TPUOlapContext(cfg)
        ssb.register(broker, scale=scale)  # snapshot flush commits here
        n_rows = broker.catalog.get("lineorder").num_rows

        def _spawn(nid):
            ann = os.path.join(root, "%s.announce.json" % nid)
            if os.path.exists(ann):
                os.remove(ann)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"  # N processes share one host
            t0 = _t.perf_counter()
            p = subprocess.Popen(
                [sys.executable, "-m",
                 "spark_druid_olap_tpu.cluster.historical",
                 "--storage-dir", root, "--node-id", nid,
                 "--port", "0", "--announce", ann],
                cwd=repo_dir, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            procs[nid] = p
            return ann, t0

        def _await(nid, ann, t0, timeout_s=300.0):
            # the announce file is written atomically (tmp + rename):
            # existence means the node is serving
            while not os.path.exists(ann):
                if procs[nid].poll() is not None:
                    raise RuntimeError(
                        "historical %s died during boot (rc=%s)"
                        % (nid, procs[nid].returncode)
                    )
                if _t.perf_counter() - t0 > timeout_s:
                    raise RuntimeError("historical %s boot timeout" % nid)
                _t.sleep(0.1)
            with open(ann) as f:
                doc = json.load(f)
            return doc["url"], _t.perf_counter() - t0

        spawned = {nid: _spawn(nid) for nid in
                   ["h%d" % i for i in range(n_nodes)]}
        nodes, boot_s = {}, {}
        for nid, (ann, t0) in spawned.items():
            nodes[nid], boot_s[nid] = _await(nid, ann, t0)

        # covered groupby rotation (int metrics: clustered ⊕ is exact)
        qset = [
            "SELECT %s, %s FROM lineorder GROUP BY %s ORDER BY %s"
            % (d, a, d, d)
            for d, a in [
                ("d_year", "sum(lo_revenue) AS r"),
                ("c_region", "sum(lo_quantity) AS q, count(*) AS n"),
                ("s_nation", "max(lo_extendedprice) AS m, count(*) AS n"),
                ("p_mfgr", "sum(lo_supplycost) AS s"),
                ("d_yearmonth", "sum(lo_revenue) AS r, count(*) AS n"),
                ("c_nation", "sum(lo_discount) AS d2"),
            ]
        ]
        oracles = {q: broker.sql(q) for q in qset}  # local, pre-attach
        max_rel_err = [0.0]

        def _close(a, b, rtol=5e-4):
            # the local fused path and the cluster's per-segment partial
            # states accumulate float32 in different orders; answers
            # agree to float32 tolerance, not byte-for-byte (the chaos
            # sections below DO assert byte-identity, cluster vs
            # cluster, where the chain-ordered fold makes it exact)
            import numpy as np

            if a.shape != b.shape or list(a.columns) != list(b.columns):
                return False
            for c in a.columns:
                av, bv = a[c].to_numpy(), b[c].to_numpy()
                if av.dtype.kind in "iuf" and bv.dtype.kind in "iuf":
                    av = av.astype(float)
                    bv = bv.astype(float)
                    err = float(np.max(
                        np.abs(av - bv) / np.maximum(np.abs(av), 1.0)
                    ))
                    max_rel_err[0] = max(max_rel_err[0], err)
                    if err > rtol:
                        return False
                elif not (av == bv).all():
                    return False
            return True

        def pcts(vals):
            s = sorted(vals)

            def q(p):
                return s[min(len(s) - 1, int(p * (len(s) - 1) + 0.5))]

            return {
                "p50_ms": round(_stats.median(s), 2),
                "p95_ms": round(q(0.95), 2),
                "p99_ms": round(q(0.99), 2),
            }

        def _run_phase(client, n, total=32, threads=4):
            # one rotation of warmup absorbs first-touch mmap page-in
            # and per-historical program compile
            for q in qset:
                broker.sql(q)
            # sequential probe: the scatter path really engaged
            before = client.last_metrics
            df = broker.sql(qset[0])
            m = client.last_metrics
            assert m is not None and m is not before, (
                "phase %d: query did not scatter" % n
            )
            assert m.executor == "cluster" and m.distributed
            assert _close(oracles[qset[0]], df), "clustered answer drifted"
            lat, errors, partials = [], [0], [0]
            lock = _threading.Lock()
            idx = iter(range(total))

            def worker():
                while True:
                    with lock:
                        i = next(idx, None)
                    if i is None:
                        return
                    t0 = _t.perf_counter()
                    try:
                        out = broker.sql(qset[i % len(qset)])
                        ms = (_t.perf_counter() - t0) * 1e3
                        with lock:
                            lat.append(ms)
                            if out.attrs.get("partial"):
                                partials[0] += 1
                    except Exception:
                        with lock:
                            errors[0] += 1

            t0 = _t.perf_counter()
            ts = [_threading.Thread(target=worker) for _ in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            wall = _t.perf_counter() - t0
            return {
                "nodes": n,
                "replication": min(2, n),
                "queries": total,
                "qps": round(total / wall, 2),
                "errors": errors[0],
                "partials": partials[0],
                "segments_scattered": int(m.segments),
                **pcts(lat),
            }

        phases = []
        for n in (1, 2, 4):
            sub = {nid: nodes[nid] for nid in sorted(nodes)[:n]}
            client = ClusterClient(
                broker, nodes=sub, replication=min(2, n)
            ).attach()
            try:
                phases.append(_run_phase(client, n))
            finally:
                client.close()
        assert all(p["errors"] == 0 for p in phases), phases
        assert all(p["partials"] == 0 for p in phases), phases

        # -- full-cluster client for the chaos sections ------------------
        client = ClusterClient(broker, nodes=dict(nodes),
                               replication=2).attach()

        # the chaos oracle is the CLUSTER's own answer under this
        # assignment: the chain-ordered gather fold makes it
        # byte-identical no matter which replica serves each group, so
        # the kill/restart sections assert .equals(), not a tolerance
        cluster_oracles = {}
        for q in qset:
            df = broker.sql(q)
            assert _close(oracles[q], df), "clustered answer drifted"
            cluster_oracles[q] = df

        # one sampled GRAFTED receipt (ISSUE 19): the broker's trace now
        # carries each historical's rendered span subtree under its
        # cluster_rpc span, and the receipt folds the remote
        # device/transfer/host buckets into per-historical attribution
        # (rendered by tools/obs_dump.py)
        broker.tracer.force_sample_next()
        broker.sql(qset[0])
        tdoc = broker.tracer.last_trace_dict()
        rc = tdoc["receipt"]

        def _count_grafts(node):
            a = node.get("attrs") or {}
            n = 1 if (a.get("remote") and not a.get("untraced")) else 0
            return n + sum(
                _count_grafts(c) for c in node.get("children", ())
            )

        receipt = {
            "scatter_ms": rc.get("scatter_ms"),
            "gather_ms": rc.get("gather_ms"),
            "cluster_merge_ms": rc.get("cluster_merge_ms"),
            "wall_ms": rc.get("wall_ms"),
            "unattributed_ms": rc.get("unattributed_ms"),
            "grafted_subtrees": _count_grafts(tdoc["spans"]),
            "nodes": (rc.get("cluster") or {}).get("nodes"),
        }
        assert receipt["nodes"], "broker receipt lost its node buckets"
        # cross-process grafting: real subprocess historicals shipped
        # their subtrees back and the fold attributed their buckets
        assert receipt["grafted_subtrees"] >= 1, receipt
        assert any(
            "device_ms" in b and "transfer_ms" in b
            for b in receipt["nodes"].values()
        ), receipt
        # the ISSUE 19 accounting bar: >= 90% of wall attributed
        assert (
            receipt["unattributed_ms"] <= 0.10 * receipt["wall_ms"]
        ), receipt

        # -- kill-and-recover timeline -----------------------------------
        victim = sorted(nodes)[-1]
        timeline, events = [], []
        rejoined = False
        stream0 = _t.perf_counter()
        ann = t0v = None
        for i in range(30):
            if i == 10:
                procs[victim].send_signal(signal.SIGKILL)
                procs[victim].wait()
                events.append({
                    "t_ms": round((_t.perf_counter() - stream0) * 1e3, 1),
                    "event": "SIGKILL %s" % victim,
                })
            if i == 18:
                ann, t0v = _spawn(victim)
                events.append({
                    "t_ms": round((_t.perf_counter() - stream0) * 1e3, 1),
                    "event": "respawn %s" % victim,
                })
            if ann and not rejoined and os.path.exists(ann):
                url, _boot = _await(victim, ann, t0v)
                client.set_node_url(victim, url)
                rejoined = True
                events.append({
                    "t_ms": round((_t.perf_counter() - stream0) * 1e3, 1),
                    "event": "rejoin %s" % victim,
                })
            q = qset[i % len(qset)]
            t0 = _t.perf_counter()
            try:
                df = broker.sql(q)
                ok = cluster_oracles[q].equals(df)
                partial = bool(df.attrs.get("partial"))
            except Exception:
                ok, partial = False, False
            timeline.append({
                "t_ms": round((t0 - stream0) * 1e3, 1),
                "ms": round((_t.perf_counter() - t0) * 1e3, 2),
                "ok": ok,
                "partial": partial,
            })
        if not rejoined:  # boot outlasted the stream: finish the join
            url, _boot = _await(victim, ann, t0v)
            client.set_node_url(victim, url)
            rejoined = True
        kill_recover = {
            "events": events,
            "timeline": timeline,
            "errors": sum(1 for r in timeline if not r["ok"]),
            "partials": sum(1 for r in timeline if r["partial"]),
        }
        # replication=2: every answer through the loss is EXACT
        assert kill_recover["errors"] == 0, kill_recover
        assert kill_recover["partials"] == 0, kill_recover

        # -- rolling restart: every historical, zero failed queries ------
        _t.sleep(cfg.cluster_breaker_cooldown_ms / 1e3)
        rolled, failed = 0, 0
        for nid in sorted(nodes):
            procs[nid].send_signal(signal.SIGKILL)
            procs[nid].wait()
            for j in range(2):  # through the downtime window
                q = qset[(rolled + j) % len(qset)]
                df = broker.sql(q)
                if not (cluster_oracles[q].equals(df)
                        and not df.attrs.get("partial")):
                    failed += 1
            ann, t0v = _spawn(nid)
            url, _boot = _await(nid, ann, t0v)
            client.set_node_url(nid, url)
            _t.sleep(cfg.cluster_breaker_cooldown_ms / 1e3 + 0.05)
            for j in range(2):  # after rejoin
                q = qset[(rolled + 2 + j) % len(qset)]
                df = broker.sql(q)
                if not (cluster_oracles[q].equals(df)
                        and not df.attrs.get("partial")):
                    failed += 1
            rolled += 4
        assert failed == 0, "rolling restart failed %d queries" % failed
        client.close()
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        shutil.rmtree(root, ignore_errors=True)

    qps1, qps4 = phases[0]["qps"], phases[-1]["qps"]
    scaling = qps4 / max(qps1, 1e-9)
    host_cpus = os.cpu_count() or 1
    metric = "cluster_ssb_sf%g_qps_scaling_1to4" % scale
    if host_cpus < n_nodes:
        # N processes on fewer than N cores serialize on the CPU: the
        # scaling number is a property of the host, not the broker —
        # the metric name must not read like a healthy scaling run
        metric += "_corebound"
    return {
        "metric": metric,
        "value": round(scaling, 2),
        "unit": "x",
        "vs_baseline": round(scaling, 2),
        "detail": {
            "rows": n_rows,
            "n_historicals": n_nodes,
            "host_cpus": host_cpus,
            "scaling_note": (
                "host has %d cpu core(s) for %d historicals: QPS is "
                "core-bound and near-linear scaling cannot show; "
                "re-run on a >=%d-core host for the scaling headline"
                % (host_cpus, n_nodes, n_nodes)
                if host_cpus < n_nodes else "ok"
            ),
            "boot_s": {k: round(v, 2) for k, v in sorted(boot_s.items())},
            "phases": phases,
            "receipt": receipt,
            "kill_recover": kill_recover,
            "rolling_restart": {"queries": rolled, "failed": failed},
            "max_rel_err_vs_local": max_rel_err[0],
            "oracle": "every checked answer .equals() the broker's "
                      "pre-attach local answer; kill/restart sections "
                      "assert zero errors and zero partials",
            "device": _device(),
        },
    }


def bench_sanitize(scale: float):
    """graftsan overhead proof (ISSUE 18): the SSB query set runs over
    two identical contexts — fully armed (SDOL_SANITIZE=1, lock witness
    + fold recorder + schedule explorer against the committed contract
    table) vs uninstalled — and the headline is the armed/bare wall
    ratio.  The armed arm must finish with zero violations and zero
    static<->runtime divergences; the bare arm must count EXACTLY zero
    probes (disabled-means-free, measured rather than asserted)."""
    import time as _t

    from spark_druid_olap_tpu.workloads import ssb
    from tools import graftsan

    root = os.path.dirname(os.path.abspath(__file__))

    def _arm_ctx():
        ctx = _calibrated_ctx()
        ssb.register(ctx, tables=ssb.gen_tables(scale=scale))
        return ctx

    def _sweep(ctx, reps=3):
        # warm once (compiles), then best-of over the full query set
        for name in ssb.QUERIES:
            ctx.sql(ssb.QUERIES[name])
        walls = []
        for _ in range(reps):
            t0 = _t.perf_counter()
            for name in ssb.QUERIES:
                ctx.sql(ssb.QUERIES[name])
            walls.append(_t.perf_counter() - t0)
        return min(walls)

    prev_arm = os.environ.get(graftsan.ENV_ARM)
    os.environ[graftsan.ENV_ARM] = "1"
    san = graftsan.install(
        contracts_path=os.path.join(root, "graftsan_contracts.json"),
        root=root, seed=0,
    )
    try:
        ctx = _arm_ctx()  # built INSIDE the window: witnessed locks
        n_rows = ctx.catalog.get("lineorder").num_rows
        armed_s = _sweep(ctx)
        stats = graftsan.stats_doc(san)
        divergences = graftsan.divergence_report(san)
    finally:
        graftsan.uninstall()
        if prev_arm is None:
            os.environ.pop(graftsan.ENV_ARM, None)
        else:
            os.environ[graftsan.ENV_ARM] = prev_arm

    bare_s = _sweep(_arm_ctx())
    ratio = armed_s / max(bare_s, 1e-9)
    return {
        "metric": "sanitize_sf%g_overhead_ratio" % scale,
        "value": round(ratio, 3),
        "unit": "x",
        # vs_baseline reads as "armed costs this many bare runs"
        "vs_baseline": round(ratio, 3),
        "detail": {
            "rows": n_rows,
            "queries": len(ssb.QUERIES),
            "armed_wall_s": round(armed_s, 4),
            "bare_wall_s": round(bare_s, 4),
            "violations": stats["violations"],
            "divergences": stats["divergences"],
            "divergence_rows": divergences,
            "unarmed_probes": graftsan.probe_count(),
            "sanitizer_stats": stats,
            "seed": san.seed,
            "device": _device(),
        },
    }


def bench_calibrate(rows_log2: int):
    import os

    from spark_druid_olap_tpu.plan.calibrate import calibrate

    budget = os.environ.get("SD_CALIBRATE_BUDGET_S")
    out = calibrate(
        rows=1 << rows_log2,
        budget_s=float(budget) if budget else None,
    )
    return {
        "metric": "calibration_cost_per_row_dense",
        "value": out["cost_per_row_dense"],
        "unit": "us/row/tile",
        "vs_baseline": 1.0,
        "detail": out,
    }


MODES = {
    "ssb": (bench_ssb, 1.0),
    "ssb_mesh": (bench_ssb_mesh, 10.0),
    "sketch_mesh": (bench_sketch_mesh, 1.0),
    "assist": (bench_assist, 2_000_000),
    "tpch_q1": (bench_tpch_q1, 1.0),
    "topn_hll": (bench_topn_hll, 1.0),
    "timeseries": (bench_timeseries, 12),
    "cube_theta": (bench_cube_theta, 0.25),
    "ingest": (bench_ingest, 2.0),
    "deadline": (bench_deadline, 1.0),
    "hammer": (bench_hammer, 0.1),
    "overlap": (bench_overlap, 1.0),
    "boot": (bench_boot, 1.0),
    "arena": (bench_arena, 1.0),
    "mesh_unified": (bench_mesh_unified, 10.0),
    "cluster": (bench_cluster, 1.0),
    "sanitize": (bench_sanitize, 0.1),
    "calibrate": (bench_calibrate, 23),
}


def _parse_args(argv):
    mode = "ssb"
    if argv and argv[0] in MODES:
        mode = argv[0]
        argv = argv[1:]
    fn, default_arg = MODES[mode]
    arg = type(default_arg)(argv[0]) if argv else default_arg
    return mode, fn, arg


def _run_child():
    mode, fn, arg = _parse_args(sys.argv[1:])
    # incremental partial flush: each completed query lands in
    # BENCH_<tag>_partial.json so a watchdog kill mid-window keeps them
    _PARTIAL["path"] = _partial_path("%s_%g" % (mode, arg))
    _PARTIAL["mode"] = mode
    if mode != "calibrate":
        _ensure_calibration()
    result = fn(arg)
    if isinstance(result, dict):
        # cost-constant provenance in every artifact (VERDICT r4 weak #5):
        # which file routed the kernels, measured on which device, partial
        # or full sweep, applied or refused (platform mismatch)
        from spark_druid_olap_tpu.config import SessionConfig

        result.setdefault("detail", {})["calibration"] = (
            SessionConfig.load_calibrated().calibration_meta
        )
    print(json.dumps(result))


# ---------------------------------------------------------------------------
# Orchestrator: the benchmark must ALWAYS emit one parseable JSON line, even
# when the TPU backend hook is wedged (round-1 failure mode: the axon
# sitecustomize shim hangs `import jax` at interpreter startup when its
# tunnel is down, so no in-process guard can help).  The parent process never
# imports jax; it probes the backend in a child under a watchdog, runs the
# real bench in a child, and falls back to a sanitized-CPU child on any
# failure or timeout.
# ---------------------------------------------------------------------------

import os
import subprocess


def _cpu_env():
    from __graft_entry__ import sanitized_cpu_env

    return sanitized_cpu_env()


def _child(env, timeout):
    cmd = [sys.executable, os.path.abspath(__file__), "--child"] + sys.argv[1:]
    try:
        proc = subprocess.run(
            cmd,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, "timeout after %ss" % timeout
    if proc.returncode != 0:
        return None, "rc=%d stderr: %s" % (proc.returncode, proc.stderr[-1500:])
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line), None
        except json.JSONDecodeError:
            continue
    return None, "no JSON in stdout: %s" % proc.stdout[-500:]


def _probe_backend(timeout):
    """Ask a child interpreter (inheriting this env, TPU hook and all) what
    backend JAX lands on.  Returns (platform_or_None, error_string)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
            env=dict(os.environ),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, "probe timeout after %ss" % timeout
    if proc.returncode != 0:
        return None, "probe rc=%d stderr: %s" % (
            proc.returncode,
            proc.stderr[-300:],
        )
    out = proc.stdout.strip().splitlines()
    if not out:
        return None, "probe produced no output"
    return out[-1], ""


def _probe_with_retry(probe_s, window_s, interval_s):
    """Retry the backend probe across a window: the accelerator tunnel
    flakes, and a single end-of-round probe lost rounds 1 AND 2 to it.
    Every attempt is logged (timestamp + error) so the bench JSON shows
    exactly what was tried.  Returns (platform_or_None, attempts)."""
    import datetime

    attempts = []
    deadline = time.monotonic() + window_s
    while True:
        ts = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        )
        platform, err = _probe_backend(probe_s)
        attempts.append(
            {"t": ts, "platform": platform, "error": err[:300]}
        )
        if platform is not None and platform != "cpu":
            return platform, attempts
        if time.monotonic() + interval_s >= deadline:
            return platform, attempts
        time.sleep(interval_s)


def _emit(result, tag):
    """Print the driver-facing headline as ONE COMPACT JSON line and write
    everything else to a sidecar detail file.  Round 3 broke the driver's
    parse by letting per-query metrics + probe logs grow the stdout line past
    what it reads; the contract is now: stdout stays small (asserted < 2000
    chars by tests), the full record lives in BENCH_<tag>_detail.json where
    tag is "<mode>_<arg>" (e.g. ssb_100) so runs at different scales of the
    same mode keep separate evidence."""
    root = os.environ.get("SD_BENCH_DETAIL_DIR") or os.path.dirname(
        os.path.abspath(__file__)
    )
    payload = json.dumps(result, indent=1, default=str)
    write_err = None
    detail_path = os.path.join(root, "BENCH_%s_detail.json" % tag)
    try:
        # atomic (tmp + os.replace): a watchdog kill mid-write leaves the
        # previous artifact whole, never a truncated/zero-length file
        _atomic_write(detail_path, payload)
    except OSError as e:
        detail_path, write_err = None, e
    # a non-degraded accelerator run is rare evidence: keep it under a name
    # a later CPU rerun of the same mode cannot overwrite, and point the
    # headline at THAT copy (independent of the primary write — its failure
    # must not null a valid detail_path)
    dev = str(result.get("device", "cpu")).lower()
    if not result.get("degraded") and "cpu" not in dev:
        tpu_path = os.path.join(root, "BENCH_tpu_%s_detail.json" % tag)
        try:
            _atomic_write(tpu_path, payload)
            detail_path = tpu_path
        except OSError as e:
            write_err = write_err or e
    if detail_path is not None and result.get("unit") != "error":
        # a completed run's final artifact supersedes the incremental
        # sidecar; a FAILED run keeps it (the completed queries' numbers
        # are exactly what the sidecar exists to preserve)
        try:
            os.remove(_partial_path(tag))
        except OSError:
            pass
    compact = {
        "metric": result.get("metric", tag),
        "value": result.get("value", 0.0),
        "unit": result.get("unit", "error"),
        "vs_baseline": result.get("vs_baseline", 0.0),
        "degraded": result.get("degraded", False),
        "device": str(result.get("device", "unknown")),
        "detail_artifact": detail_path,
    }
    detail = result.get("detail") or {}
    # a few small load-bearing summary fields, never the nested per-query
    # maps (strings only when short: the whole point is a bounded line)
    for k in (
        "rows", "max_rel_err", "rows_per_sec_per_chip", "ingest_s",
        "ingest_rows_per_sec",
    ):
        v = detail.get(k)
        if isinstance(v, (int, float)) or (
            isinstance(v, str) and len(v) < 100
        ):
            compact[k] = v
    if compact["unit"] == "error" or write_err is not None:
        # never lose the diagnosis to a failed sidecar write
        msg = str(detail.get("error", "")) or ""
        if write_err is not None:
            msg = ("sidecar write failed: %s; " % write_err) + msg
        compact["error"] = msg[:400]
    if detail_path is None:
        # last-ditch: the fat record goes to stderr so a redirect (the watch
        # loop captures 2>) can still recover a rare hardware run's evidence
        print(payload, file=sys.stderr)
    print(json.dumps(compact))


def main():
    if sys.argv[1:2] == ["--child"]:
        sys.argv = [sys.argv[0]] + sys.argv[2:]
        _run_child()
        return

    mode, _, arg = _parse_args(sys.argv[1:])
    if mode in ("ssb_mesh", "sketch_mesh", "mesh_unified"):
        # the mesh mode measures SPMD execution: give children 8 virtual
        # devices when the backend is single-device CPU (no-op on real
        # multi-chip backends — the flag only affects the host platform)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    # the sidecar is keyed on mode AND its argument so e.g. an ssb-sf1 run
    # inside a hardware window cannot clobber the sf100 per-query evidence
    tag = "%s_%g" % (mode, arg)
    probe_s = int(os.environ.get("SD_BENCH_PROBE_TIMEOUT_S", "120"))
    run_s = int(os.environ.get("SD_BENCH_TIMEOUT_S", "1500"))
    # total window spent retrying a down tunnel before settling for CPU
    # (bounded so a driver-side timeout still sees the one JSON line)
    window_s = int(os.environ.get("SD_BENCH_PROBE_WINDOW_S", "600"))
    interval_s = int(os.environ.get("SD_BENCH_PROBE_INTERVAL_S", "60"))

    platform, probe_attempts = _probe_with_retry(
        probe_s, window_s, interval_s
    )
    result, err = None, None
    degraded = False
    if platform is not None and platform != "cpu":
        result, err = _child(dict(os.environ), run_s)
        if result is None:
            degraded = True
            # the failed accelerated child's partial sidecar holds rare
            # hardware evidence; the CPU rerun writes under the SAME tag,
            # so move it to a name the rerun cannot clobber first
            try:
                pp = _partial_path(tag)
                if os.path.exists(pp):
                    os.replace(
                        pp,
                        os.path.join(
                            os.path.dirname(pp),
                            "BENCH_tpu_%s_partial.json" % tag,
                        ),
                    )
            except OSError:
                pass
    if result is None and os.environ.get("SD_BENCH_NO_CPU_FALLBACK") != "1":
        # Backend unavailable/wedged or the accelerated run failed: rerun on
        # a sanitized CPU interpreter so the round still gets a number.
        # (SD_BENCH_NO_CPU_FALLBACK=1 — set by the TPU watch loop — skips
        # this rerun: inside a short hardware window a degraded CPU number
        # is worthless and the rerun burns the window.)
        if platform is None:
            degraded = True
        cpu_result, cpu_err = _child(_cpu_env(), run_s)
        result, err = cpu_result, err or cpu_err
    if result is not None:
        result["degraded"] = degraded
        result["device"] = result.get("detail", {}).get("device", platform or "cpu")
        if degraded:
            # a degraded number must not read like a healthy one: the
            # metric name itself carries the backend it was measured on
            dev = str(result["device"]).lower()
            dev = "cpu" if "cpu" in dev else dev.replace(" ", "_")
            result["metric"] = "%s_%s_degraded" % (result["metric"], dev)
        result.setdefault("detail", {})["probe_attempts"] = probe_attempts
        _emit(result, tag)
    else:
        # Last resort: still one parseable JSON line, never a bare traceback.
        _emit(
            {
                "metric": mode,
                "value": 0.0,
                "unit": "error",
                "vs_baseline": 0.0,
                "degraded": True,
                "device": platform or "unavailable",
                "detail": {
                    "error": (err or "unknown")[:2000],
                    "probe_attempts": probe_attempts,
                },
            },
            tag,
        )


if __name__ == "__main__":
    main()
