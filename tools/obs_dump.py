"""Pretty-print a query span tree as an indented phase/latency table.

Input: a span-tree JSON produced by the obs/ tracer — either a single
trace document ({"query_id", "total_ms", "spans": {...}}), a bench
detail artifact (BENCH_*_detail.json; every per-query "span_tree" found
is printed), or a raw span node.  Span EVENTS (`@ breaker_state ...`)
render under their span with their trace-relative timestamp.  A
`/status` document (or a bare registry to_dict) additionally yields a
"histogram exemplars" table: the bucket -> trace_id links that jump
from a hot latency bucket to its span tree in one hop.  Sources:

    python -m tools.obs_dump BENCH_tpu_ssb_1_detail.json
    python -m tools.obs_dump trace.json
    curl -s localhost:8082/druid/v2/trace/<qid> | python -m tools.obs_dump -
    python -m tools.obs_dump --url http://localhost:8082/druid/v2/trace/<qid>

Output per trace:

    query 3f2a... (sql)                       total 12.41ms
    phase                     start      dur    %tot
    query                      0.00    12.41  100.0%
      admission                0.01     0.02    0.2%
      plan                     0.04     0.31    2.5%
      execute                  0.37    11.98   96.5%
        segment_dispatch       0.51     9.80   79.0%
        ...
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Iterator, List, Optional, Tuple


def _is_span(node: Any) -> bool:
    return (
        isinstance(node, dict)
        and "name" in node
        and "duration_ms" in node
    )


def _find_traces(doc: Any, label: str = "") -> Iterator[Tuple[str, dict]]:
    """Yield (label, trace-or-span dict) for every span tree in a JSON
    document: trace documents, bare span nodes, and any nested
    "span_tree"/"trace"/"spans" members of a bench detail artifact."""
    if isinstance(doc, dict):
        if "spans" in doc and _is_span(doc.get("spans")):
            yield label, doc
            return
        if _is_span(doc):
            yield label, {"spans": doc, "total_ms": doc.get("duration_ms")}
            return
        for k, v in doc.items():
            sub = f"{label}.{k}" if label else str(k)
            if k in ("span_tree", "trace") or isinstance(v, (dict, list)):
                yield from _find_traces(v, sub)
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            yield from _find_traces(v, f"{label}[{i}]")


def render_trace(trace: dict, label: str = "") -> str:
    root = trace.get("spans", trace)
    total = float(trace.get("total_ms") or root.get("duration_ms") or 0.0)
    head = trace.get("query_id", "")
    qt = trace.get("query_type", "")
    lines: List[str] = []
    title = " ".join(
        x for x in (label, head, f"({qt})" if qt else "") if x
    )
    lines.append(f"{title or 'trace'}    total {total:.2f}ms")
    lines.append(f"{'phase':<28} {'start':>8} {'dur':>9} {'%tot':>7}")

    def walk(node: dict, depth: int) -> None:
        dur = float(node.get("duration_ms", 0.0))
        start = float(node.get("start_ms", 0.0))
        pct = (dur / total * 100.0) if total > 0 else 0.0
        attrs = node.get("attrs") or {}
        suffix = (
            "  " + " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
            if attrs
            else ""
        )
        name = "  " * depth + str(node.get("name", "?"))
        lines.append(
            f"{name:<28} {start:>8.2f} {dur:>8.2f}ms {pct:>6.1f}%{suffix}"
        )
        for e in node.get("events", ()):
            eattrs = " ".join(
                f"{k}={v}"
                for k, v in sorted((e.get("attrs") or {}).items())
            )
            ename = "  " * (depth + 1) + f"@ {e.get('name', '?')}"
            lines.append(
                f"{ename:<28} {float(e.get('at_ms', 0.0)):>8.2f}"
                f"{'':>10} {'':>7}  {eattrs}".rstrip()
            )
        for c in node.get("children", ()):
            walk(c, depth + 1)

    walk(root, 0)
    return "\n".join(lines)


def _find_exemplars(doc: Any) -> List[Tuple[str, str, str, dict]]:
    """(metric, labels, le, exemplar) rows from a `/status` metrics
    document (the registry's to_dict shape): the one-hop link from a
    latency bucket to the trace id that last landed in it."""
    rows: List[Tuple[str, str, str, dict]] = []
    if not isinstance(doc, dict):
        return rows
    families = doc.get("metrics", doc)  # /status doc or bare to_dict()
    if not isinstance(families, dict):
        return rows
    for metric, fam in families.items():
        if not isinstance(fam, dict) or fam.get("type") != "histogram":
            continue
        for labels, entry in (fam.get("values") or {}).items():
            if not isinstance(entry, dict):
                continue
            for le, ex in (entry.get("exemplars") or {}).items():
                rows.append((str(metric), str(labels), str(le), ex))
    return rows


def render_exemplars(rows: List[Tuple[str, str, str, dict]]) -> str:
    lines = ["histogram exemplars (bucket -> trace)"]
    lines.append(
        f"{'metric':<26} {'labels':<12} {'le':>8}  trace_id / value"
    )
    for metric, labels, le, ex in rows:
        lines.append(
            f"{metric:<26} {labels:<12} {le:>8}  "
            f"{ex.get('trace_id', '?')}  ({ex.get('value', '?')}ms)"
        )
    return "\n".join(lines)


def _is_receipt(node: Any) -> bool:
    return (
        isinstance(node, dict)
        and "device_ms" in node
        and "host_ms" in node
        and "wall_ms" in node
    )


def _find_receipts(doc: Any, label: str = "") -> Iterator[Tuple[str, dict]]:
    """Yield (label, receipt) for every cost receipt (obs/prof.py) in a
    document: trace docs carry one under "receipt"; bench details carry
    one per query."""
    if isinstance(doc, dict):
        if _is_receipt(doc):
            yield label, doc
            return
        for k, v in doc.items():
            sub = f"{label}.{k}" if label else str(k)
            if isinstance(v, (dict, list)):
                yield from _find_receipts(v, sub)
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            yield from _find_receipts(v, f"{label}[{i}]")


def render_receipts(rows: List[Tuple[str, dict]]) -> str:
    """Cost receipts as a device/host/transfer attribution table with
    the cache-tier outcomes alongside."""
    lines = ["cost receipts (device/host/transfer attribution)"]
    lines.append(
        f"{'receipt':<34} {'wall':>9} {'device':>9} {'host':>9} "
        f"{'xfer':>8} {'unattr':>8} {'disp':>4} {'cmp':>4}  cache"
    )
    for label, rc in rows:
        cache = rc.get("cache") or {}
        res = cache.get("residency") or {}
        bits = []
        if rc.get("arena_build_ms"):
            bits.append(f"arena={rc['arena_build_ms']:.2f}ms")
        if cache.get("result_cache"):
            bits.append(f"rc={cache['result_cache']}")
        if cache.get("fused_batch"):
            bits.append(f"fused={cache['fused_batch']}")
        if res:
            bits.append(f"resid={res.get('hits', 0)}h/{res.get('misses', 0)}m")
        pch = cache.get("program_cache") or {}
        if pch:
            bits.append(
                "prog="
                + ",".join(
                    f"{fam}:{d.get('hits', 0)}h/{d.get('misses', 0)}m"
                    for fam, d in sorted(pch.items())
                )
            )
        if rc.get("sampled"):
            bits.append("sampled")
        name = label or rc.get("query_id", "receipt")
        lines.append(
            f"{name[:34]:<34} {rc.get('wall_ms', 0):>8.2f} "
            f"{rc.get('device_ms', 0):>8.2f} {rc.get('host_ms', 0):>8.2f} "
            f"{rc.get('transfer_ms', 0):>7.2f} "
            f"{rc.get('unattributed_ms', 0):>7.2f} "
            f"{rc.get('dispatch_count', 0):>4} "
            f"{rc.get('compiles', 0):>4}  {' '.join(bits)}".rstrip()
        )
        cluster = (rc.get("cluster") or {}).get("nodes") or {}
        if cluster:
            # broker receipts (ISSUE 16/19): scatter/gather/merge wall
            # attribution plus the per-historical table folded from the
            # grafted remote subtrees — device/transfer/host buckets on
            # each historical's own clock next to the broker-side rpc
            # wall, so a slow node splits into "slow device work" vs
            # "slow network path" at a glance
            lines.append(
                f"  cluster: scatter={rc.get('scatter_ms', 0):.2f}ms "
                f"gather={rc.get('gather_ms', 0):.2f}ms "
                f"merge={rc.get('cluster_merge_ms', 0):.2f}ms"
            )
            lines.append(
                f"    {'node':<18} {'rpc':>9} {'device':>9} "
                f"{'xfer':>8} {'host':>8} {'remote':>9} "
                f"{'rpcs':>4} {'ok':>3} {'fail':>4} {'seg':>4}  flags"
            )
            for node, b in sorted(cluster.items()):
                flags = []
                if b.get("hedged"):
                    flags.append(f"hedged={b['hedged']}")
                if b.get("untraced"):
                    flags.append(f"untraced={b['untraced']}")
                lines.append(
                    f"    {node[:18]:<18} {b.get('ms', 0):>8.2f}m "
                    f"{b.get('device_ms', 0):>8.2f}m "
                    f"{b.get('transfer_ms', 0):>7.2f}m "
                    f"{b.get('host_ms', 0):>7.2f}m "
                    f"{b.get('remote_wall_ms', 0):>8.2f}m "
                    f"{b.get('rpcs', 0):>4} {b.get('ok', 0):>3} "
                    f"{b.get('failed', 0):>4} "
                    f"{b.get('segments', 0):>4}  {' '.join(flags)}".rstrip()
                )
    return "\n".join(lines)


def _find_sampler_status(doc: Any) -> Optional[dict]:
    """The __sys telemetry sampler's status dict (obs/telemetry.py):
    under "sys_sampler" in a /status document, or the bare dict."""
    if not isinstance(doc, dict):
        return None
    st = doc.get("sys_sampler", doc)
    if (
        isinstance(st, dict)
        and st.get("table") == "__sys"
        and "ticks" in st
    ):
        return st
    return None


def render_sampler_status(st: dict) -> str:
    lines = ["__sys telemetry sampler (obs/telemetry.py)"]
    run = "running" if st.get("running") else "stopped"
    lines.append(
        f"  {run}, every {st.get('interval_s', 0)}s, "
        f"cap {st.get('max_series', 0)} series/tick"
    )
    lines.append(
        f"  ticks={st.get('ticks', 0)} rows={st.get('rows_appended', 0)} "
        f"dropped={st.get('rows_dropped', 0)} errors={st.get('errors', 0)} "
        f"tracked_series={st.get('tracked_series', 0)} "
        f"last_tick={st.get('last_tick_ms', 0)}ms"
    )
    if st.get("last_error"):
        lines.append(f"  last_error: {st['last_error']}")
    return "\n".join(lines)


def dump(doc: Any) -> str:
    out = []
    for label, trace in _find_traces(doc):
        out.append(render_trace(trace, label))
    # dedupe: bench details carry the same receipt at the query level
    # AND inside its span_tree — one row each, not two identical ones
    receipts, seen = [], set()
    for label, rc in _find_receipts(doc):
        ident = json.dumps(rc, sort_keys=True, default=str)
        if ident in seen:
            continue
        seen.add(ident)
        receipts.append((label, rc))
    if receipts:
        out.append(render_receipts(receipts))
    exemplars = _find_exemplars(doc)
    if exemplars:
        out.append(render_exemplars(exemplars))
    sampler = _find_sampler_status(doc)
    if sampler:
        out.append(render_sampler_status(sampler))
    if not out:
        return "no span trees found in input"
    return "\n\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs_dump",
        description="render obs/ span-tree JSON as a phase/latency table",
    )
    ap.add_argument(
        "path", nargs="?", default="-",
        help="JSON file (trace doc or bench detail artifact); '-' = stdin",
    )
    ap.add_argument(
        "--url", help="fetch the trace JSON from a URL "
        "(e.g. a server's /druid/v2/trace/<query_id>)",
    )
    args = ap.parse_args(argv)
    if args.url:
        import urllib.request

        with urllib.request.urlopen(args.url, timeout=30) as r:
            doc = json.loads(r.read())
    elif args.path == "-":
        doc = json.load(sys.stdin)
    else:
        with open(args.path) as f:
            doc = json.load(f)
    print(dump(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
