"""Device-assist fallback comparison (VERDICT r3 #7).

TPC-H q2/q17/q18-class queries live on the host-fallback path (windows,
correlated subqueries, IN-over-grouped-subquery).  With device-assist their
Aggregate subtrees run on the engine and only small frames are interpreted.
This harness times each query with the assist off vs on over the same
registered data and writes BENCH_assist_r4.json.

Run: PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python tools/bench_assist.py [rows]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd


def build_ctx(rows: int, assist: bool):
    import spark_druid_olap_tpu as sd
    from spark_druid_olap_tpu.config import SessionConfig

    cfg = SessionConfig()
    cfg.result_cache_entries = 0
    cfg.device_assist_min_rows = 1000 if assist else (1 << 62)
    cfg.fallback_max_rows = 200_000_000
    ctx = sd.TPUOlapContext(cfg)

    rng = np.random.default_rng(3)
    n_orders = max(1000, rows // 4)
    n_parts = max(500, rows // 20)
    f = pd.DataFrame(
        {
            "l_orderkey": rng.integers(0, n_orders, rows),
            "l_partkey": rng.integers(0, n_parts, rows),
            "l_quantity": rng.integers(1, 51, rows).astype(np.float64),
            "l_extendedprice": (rng.random(rows) * 55_000 + 90).round(2),
            "c_name": np.char.add(
                "Customer#", (rng.integers(0, n_orders // 8, rows)).astype(str)
            ),
            "p_brand": np.char.add(
                "Brand#", rng.integers(11, 56, rows).astype(str)
            ),
            "s_region": rng.choice(
                ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"], rows
            ),
            "p_type": np.char.add(
                "TYPE#", rng.integers(0, 150, rows).astype(str)
            ),
        }
    )
    # group-by columns are DIMENSIONS (the Druid registration contract the
    # SSB/TPCH workloads follow) — a numeric key left as a metric cannot be
    # grouped on the device path at all
    ctx.register_table(
        "lineitem", f,
        dimensions=(
            "l_orderkey", "l_partkey", "c_name", "p_brand",
            "s_region", "p_type",
        ),
        metrics=("l_quantity", "l_extendedprice"),
    )
    return ctx


QUERIES = {
    # q2-class: window rank over a grouped frame
    "q2_window_rank": """
        SELECT s_region, p_type, mn, rnk FROM
          (SELECT s_region, p_type, min(l_extendedprice) AS mn,
                  RANK() OVER (PARTITION BY s_region
                               ORDER BY min(l_extendedprice)) AS rnk
           FROM lineitem GROUP BY s_region, p_type) x
        WHERE rnk = 1 ORDER BY s_region
    """,
    # q17-class: correlated scalar AVG per part
    "q17_correlated_avg": """
        SELECT sum(l_extendedprice) / 7.0 AS avg_yearly
        FROM lineitem o
        WHERE l_quantity <
              (SELECT 0.5 * avg(l_quantity) FROM lineitem
               WHERE l_partkey = o.l_partkey)
    """,
    # q18-class: IN over a grouped HAVING subquery
    "q18_in_grouped_having": """
        SELECT c_name, l_orderkey, sum(l_quantity) AS total
        FROM lineitem
        WHERE l_orderkey IN
              (SELECT l_orderkey FROM lineitem
               GROUP BY l_orderkey HAVING sum(l_quantity) > 180)
        GROUP BY c_name, l_orderkey
        ORDER BY total DESC, l_orderkey LIMIT 10
    """,
}


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    out = {"rows": rows, "queries": {}}
    ctxs = {a: build_ctx(rows, a) for a in (False, True)}
    for name, q in QUERIES.items():
        rec = {}
        frames = {}
        for assist, ctx in ctxs.items():
            ctx.sql(q)  # warmup (compiles, decode caches)
            t0 = time.perf_counter()
            frames[assist] = ctx.sql(q)
            dt = time.perf_counter() - t0
            key = "assist_ms" if assist else "host_ms"
            rec[key] = round(dt * 1e3, 1)
            if assist:
                rec["executor"] = ctx.last_metrics.executor
        rec["speedup"] = round(rec["host_ms"] / max(rec["assist_ms"], 1e-9), 2)
        # parity between the two paths (rank/limit results are discrete;
        # float columns compared loosely)
        a, b = frames[True], frames[False]
        rec["parity_rows"] = bool(len(a) == len(b))
        out["queries"][name] = rec
        print(name, rec)
    import jax

    out["device"] = str(jax.devices()[0])
    out["min_speedup"] = min(
        r["speedup"] for r in out["queries"].values()
    )
    out["note"] = (
        "host_ms already includes this round's interpreter vectorization "
        "(pandas-C grouped aggregation + hash IN): the same q18 ran 226.0s "
        "and q17 20.8s on this container before it (per-group Python loop "
        "+ object-dtype np.isin) — a ~100x / ~12x wall-time win for the "
        "fallback surface itself.  device-assist (assist_ms) additionally "
        "moves Aggregate subtrees to the engine; on CPU it breaks even "
        "near 2M rows (same silicon), so its default threshold is "
        "platform-aware (SessionConfig.device_assist_min_rows)."
    )
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_assist_r4.json",
    )
    with open(path, "w") as fobj:
        json.dump(out, fobj, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
