"""Minimal real-TPU evidence, designed for a FLAPPING tunnel.

The accelerator tunnel has come up for only minutes at a time (round 3:
94 probe attempts, one ~5-minute window).  This script produces the
cheapest meaningful hardware evidence in one short run:

  1. backend identity (platform, device_kind) straight from the live jax,
  2. the Pallas one-hot kernel compiled by Mosaic (interpret=False) and
     parity-checked against numpy,
  3. one end-to-end GroupByQuery through the public Engine on the real
     chip, parity-checked against a float64 pandas oracle,
  4. wall times for each (first-compile and warm).

Writes one JSON line to the path given as argv[1] (default
TPU_SMOKE_r3.json).  Exits non-zero if the backend is not a TPU or any
parity check fails — the watch loop treats that as "window lost, keep
probing".  Run with the DEFAULT environment (the axon PJRT hook on
PYTHONPATH); the caller owns the timeout."""

import json
import os
import sys
import time

# `python tools/tpu_smoke.py` puts tools/ (not the repo root) on sys.path;
# the package is not installed, so make the repo root importable explicitly.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    # --interpret-dryrun: validate this script's own code paths on CPU
    # (Pallas in interpret mode) so a bug here can never burn a real window
    dryrun = "--interpret-dryrun" in sys.argv
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    # a dryrun must never produce the artifact the watch loop trusts
    default_out = "/tmp/tpu_smoke_dryrun.json" if dryrun else "TPU_SMOKE_r3.json"
    out_path = args[0] if args else default_out
    t0 = time.time()
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    info = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "?"),
        "device": str(dev),
        "n_devices": len(jax.devices()),
        "jax_import_s": round(time.time() - t0, 2),
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if dev.platform == "cpu" and not dryrun:
        print(json.dumps({"ok": False, "why": "backend is cpu", **info}))
        return 1
    interpret = bool(dryrun and dev.platform == "cpu")
    info["interpret_dryrun"] = interpret

    rng = np.random.default_rng(0)
    R, G = 1 << 17, 512
    gid_h = rng.integers(0, G, R).astype(np.int32)
    mask_h = (rng.random(R) < 0.7)
    sv_h = rng.random((R, 2)).astype(np.float32)

    # --- Pallas kernel under Mosaic (the r2 verdict's open question) ---
    from spark_druid_olap_tpu.ops.pallas_groupby import pallas_partial_aggregate

    gid = jnp.asarray(gid_h)
    mask = jnp.asarray(mask_h)
    # kernel ABI: sum columns arrive pre-masked (ops/groupby.py contract)
    sv = jnp.asarray(sv_h * mask_h[:, None].astype(np.float32))
    mmv = jnp.zeros((R, 0), jnp.float32)
    mmm = jnp.zeros((R, 0), bool)
    t1 = time.time()
    sums, _, _ = pallas_partial_aggregate(
        gid, mask, sv, mmv, mmm, num_groups=G, num_min=0, num_max=0,
        interpret=interpret,
    )
    sums = np.asarray(jax.block_until_ready(sums))
    info["pallas_compile_plus_run_s"] = round(time.time() - t1, 2)
    t1 = time.time()
    s2, _, _ = pallas_partial_aggregate(
        gid, mask, sv, mmv, mmm, num_groups=G, num_min=0, num_max=0,
        interpret=interpret,
    )
    jax.block_until_ready(s2)
    info["pallas_warm_s"] = round(time.time() - t1, 4)
    want = np.zeros((G, 2), np.float64)
    np.add.at(want, gid_h[mask_h], sv_h[mask_h].astype(np.float64))
    rel = np.abs(sums - want) / np.maximum(np.abs(want), 1e-9)
    info["pallas_max_rel_err"] = float(rel.max())
    if rel.max() > 2e-5:
        print(json.dumps({"ok": False, "why": "pallas parity", **info}))
        return 1

    # --- end-to-end engine query on the real chip ---
    from spark_druid_olap_tpu.catalog.segment import build_datasource
    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.models.aggregations import Count, DoubleSum
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.query import GroupByQuery

    n = 1 << 19
    g_raw = rng.integers(0, 123, n)
    v = rng.random(n).astype(np.float32)
    ds = build_datasource(
        "smoke", {"g": g_raw.astype(np.int64), "v": v},
        dimension_cols=["g"], metric_cols=["v"],
        rows_per_segment=1 << 17,
    )
    q = GroupByQuery(
        datasource="smoke",
        dimensions=(DimensionSpec("g"),),
        aggregations=(DoubleSum("s", "v"), Count("n")),
    )
    eng = Engine()
    t1 = time.time()
    df = eng.execute(q, ds)
    info["engine_first_s"] = round(time.time() - t1, 2)
    t1 = time.time()
    df = eng.execute(q, ds)
    info["engine_warm_s"] = round(time.time() - t1, 4)
    m = eng.last_metrics
    info["engine_strategy"] = m.strategy
    info["engine_rows_per_sec"] = m.rows_per_sec

    import pandas as pd

    oracle = (
        pd.DataFrame({"g": g_raw, "v": v.astype(np.float64)})
        .groupby("g")
        .agg(s=("v", "sum"), n=("v", "size"))
        .reset_index()
    )
    got = df.sort_values("g").reset_index(drop=True)
    want_df = oracle.sort_values("g").reset_index(drop=True)
    assert len(got) == len(want_df), (len(got), len(want_df))
    assert (got["n"].astype(int).values == want_df["n"].values).all()
    srel = (
        np.abs(got["s"].astype(float).values - want_df["s"].values)
        / np.maximum(np.abs(want_df["s"].values), 1e-9)
    ).max()
    info["engine_sum_max_rel_err"] = float(srel)
    if srel > 2e-5:
        print(json.dumps({"ok": False, "why": "engine parity", **info}))
        return 1

    info["ok"] = True
    with open(out_path, "w") as f:
        f.write(json.dumps(info) + "\n")
    print(json.dumps(info))
    return 0


if __name__ == "__main__":
    sys.exit(main())
