#!/bin/bash
# Persistent TPU-tunnel watch (VERDICT r2 #1: "run bench.py yourself
# repeatedly during the round, committing any successful TPU JSON").
#
# Probes the accelerator backend every INTERVAL seconds, appending one line
# per attempt to TPU_PROBE_LOG_r3.txt.  The moment a probe lands on a
# non-CPU platform it runs the full TPU evidence pipeline:
#   1. bench.py calibrate           -> TPU calibration.json
#   2. pytest tests/test_pallas_kernel.py on the real backend (Mosaic)
#   3. bench.py {ssb 1, tpch_q1, topn_hll, timeseries, cube_theta}
#      each saved as BENCH_tpu_<mode>_r3.json
# and drops a TPU_SUCCESS sentinel so the interactive session can commit.
#
# Run under tmux:  tmux new-session -d -s tpuwatch 'bash tools/tpu_watch.sh'
set -u
cd "$(dirname "$0")/.."
LOG=TPU_PROBE_LOG_r3.txt
INTERVAL=${TPU_WATCH_INTERVAL:-240}
N=$(grep -c 'attempt=' "$LOG" 2>/dev/null || echo 0)

probe() {
    timeout 90 python -c 'import jax; print(jax.devices()[0].platform)' \
        2>/tmp/tpu_probe_err.txt
}

run_pipeline() {
    local plat="$1"
    echo "=== TPU pipeline start platform=$plat $(date -u +%FT%TZ)" >> "$LOG"
    export SD_BENCH_PROBE_WINDOW_S=60 SD_BENCH_PROBE_INTERVAL_S=20
    timeout 1800 python bench.py calibrate \
        > BENCH_tpu_calibrate_r3.json 2>/tmp/tpu_cal_err.txt
    echo "calibrate rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    timeout 900 python -m pytest tests/test_pallas_kernel.py -q \
        > /tmp/tpu_pallas_tests.txt 2>&1
    echo "pallas tests rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    local mode
    for mode in "ssb 1" tpch_q1 topn_hll timeseries cube_theta; do
        local name=${mode// /}
        timeout 2400 python bench.py $mode \
            > "BENCH_tpu_${name}_r3.json" 2>"/tmp/tpu_${name}_err.txt"
        echo "bench $mode rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    done
    date -u +%FT%TZ > TPU_SUCCESS
    echo "=== TPU pipeline done $(date -u +%FT%TZ)" >> "$LOG"
}

while true; do
    N=$((N + 1))
    TS=$(date -u +%FT%TZ)
    P=$(probe)
    if [ -n "$P" ] && [ "$P" != "cpu" ]; then
        echo "$TS attempt=$N SUCCESS platform=$P" >> "$LOG"
        run_pipeline "$P"
        exit 0
    fi
    ERR=$(tail -c 200 /tmp/tpu_probe_err.txt 2>/dev/null | tr '\n' ' ')
    echo "$TS attempt=$N fail: ${P:-}${ERR}" >> "$LOG"
    sleep "$INTERVAL"
done
