#!/bin/bash
# Persistent TPU-tunnel watch (VERDICT r2 #1), v2 — designed for a FLAPPING
# tunnel.  Round-3 reality: the tunnel was down for 9.5 h, came up for
# ~5 minutes (probe attempt 94), and the old pipeline burned the window on
# one 25-minute calibrate child.  v2 runs the cheapest evidence first with
# short per-step timeouts, re-probes between steps, and keeps looping after
# a lost window; each step is skipped once its non-degraded artifact exists.
#
#   step 1  tools/tpu_smoke.py       -> TPU_SMOKE_r5.json       (~2 min)
#   step 2  pallas tests, real chip  -> TPU_PALLAS_TESTS_r5.txt (~5 min)
#   step 3  bench.py calibrate       -> BENCH_tpu_calibrate_r5.json
#   step 4  bench.py ssb 1           -> BENCH_tpu_ssb1_r5.json
#   step 5  tpch_q1 topn_hll timeseries cube_theta -> BENCH_tpu_<mode>_r5.json
#
# Run detached:  setsid nohup bash tools/tpu_watch.sh >/tmp/tpu_watch_out.txt 2>&1 &
set -u
cd "$(dirname "$0")/.."
LOG=TPU_PROBE_LOG_r5.txt
INTERVAL=${TPU_WATCH_INTERVAL:-180}
# grep -c prints "0" AND exits 1 on zero matches — an `|| echo 0` fallback
# would yield the two-line string "0\n0" and break the arithmetic below
N=$(grep -c 'attempt=' "$LOG" 2>/dev/null)
N=${N:-0}

ts() { date -u +%FT%TZ; }

probe() {
    timeout 90 python -c 'import jax; print(jax.devices()[0].platform)' \
        2>/tmp/tpu_probe_err.txt
}

# a bench artifact counts only when it really ran on the accelerator
bench_ok() {  # $1 = json path
    [ -s "$1" ] && python - "$1" <<'EOF'
import json, sys
try:
    d = json.load(open(sys.argv[1]))
except Exception:
    sys.exit(1)
sys.exit(0 if (not d.get("degraded") and "cpu" not in str(d.get("device", "cpu")).lower()) else 1)
EOF
}

smoke_ok() { [ -s TPU_SMOKE_r5.json ] && grep -q '"ok": true' TPU_SMOKE_r5.json \
             && ! grep -q '"interpret_dryrun": true' TPU_SMOKE_r5.json; }

pallas_ok() { [ -s TPU_PALLAS_TESTS_r5.txt ] && grep -q 'passed' TPU_PALLAS_TESTS_r5.txt \
              && ! grep -qi 'failed\|error' TPU_PALLAS_TESTS_r5.txt; }

reprobe_alive() {
    P=$(probe)
    [ -n "$P" ] && [ "$P" != "cpu" ]
}

run_window() {
    echo "=== window open $(ts)" >> "$LOG"
    export SD_BENCH_PROBE_WINDOW_S=30 SD_BENCH_PROBE_INTERVAL_S=15 SD_BENCH_PROBE_TIMEOUT_S=60
    # Inside a window: never measure cost constants implicitly (compile-
    # dominated, ~26 min observed on the tunneled chip — it burned window
    # 94) and never rerun a failed bench on CPU (degraded numbers are
    # rejected by bench_ok anyway and the rerun burns the window).
    export SD_BENCH_SKIP_CALIBRATE=1 SD_BENCH_NO_CPU_FALLBACK=1

    # Steps are ordered cheapest-evidence-first and NONE of them gates the
    # later ones: a single flaky/broken step must not make the rest of the
    # evidence permanently unreachable.  Only a dead tunnel ends the window.
    if ! smoke_ok; then
        timeout 300 python tools/tpu_smoke.py TPU_SMOKE_r5.json \
            >> /tmp/tpu_smoke_out.txt 2>&1
        echo "smoke rc=$? $(ts)" >> "$LOG"
    fi

    if ! pallas_ok; then
        reprobe_alive || return
        SDOL_TEST_TPU=1 timeout 420 python -m pytest tests/test_pallas_kernel.py -q \
            > TPU_PALLAS_TESTS_r5.txt.tmp 2>&1 \
            && mv TPU_PALLAS_TESTS_r5.txt.tmp TPU_PALLAS_TESTS_r5.txt
        echo "pallas tests rc=$? $(ts)" >> "$LOG"
    fi

    if ! bench_ok BENCH_tpu_ssb1_r5.json; then
        reprobe_alive || return
        SD_BENCH_TIMEOUT_S=1200 timeout 1300 python bench.py ssb 1 \
            > BENCH_tpu_ssb1_r5.json.tmp 2>/tmp/tpu_ssb1_err.txt \
            && mv BENCH_tpu_ssb1_r5.json.tmp BENCH_tpu_ssb1_r5.json
        echo "bench ssb 1 rc=$? $(ts)" >> "$LOG"
    fi

    local mode
    for mode in tpch_q1 topn_hll timeseries cube_theta; do
        if ! bench_ok "BENCH_tpu_${mode}_r5.json"; then
            reprobe_alive || return
            SD_BENCH_TIMEOUT_S=600 timeout 700 python bench.py "$mode" \
                > "BENCH_tpu_${mode}_r5.json.tmp" 2>"/tmp/tpu_${mode}_err.txt" \
                && mv "BENCH_tpu_${mode}_r5.json.tmp" "BENCH_tpu_${mode}_r5.json"
            echo "bench $mode rc=$? $(ts)" >> "$LOG"
        fi
    done

    # Cost-constant calibration LAST: it is the most expensive step (~26 min
    # observed over the tunnel) and the least essential evidence.  Needs a
    # long stable window; until one appears every shorter window still
    # captures smoke/pallas/bench evidence above.
    if ! bench_ok BENCH_tpu_calibrate_r5.json; then
        reprobe_alive || return
        SD_CALIBRATE_BUDGET_S=1500 SD_BENCH_TIMEOUT_S=1800 timeout 1900 python bench.py calibrate \
            > BENCH_tpu_calibrate_r5.json.tmp 2>/tmp/tpu_cal_err.txt \
            && mv BENCH_tpu_calibrate_r5.json.tmp BENCH_tpu_calibrate_r5.json
        echo "calibrate rc=$? $(ts)" >> "$LOG"
        # calibration.json is gitignored; preserve TPU constants under a
        # tracked name the session can commit
        if bench_ok BENCH_tpu_calibrate_r5.json && [ -s calibration.json ]; then
            cp calibration.json CALIBRATION_tpu_r5.json
        fi
    fi

    if all_done; then
        date -u +%FT%TZ > TPU_SUCCESS
        echo "=== ALL TPU EVIDENCE CAPTURED $(ts)" >> "$LOG"
    fi
}

all_done() {
    smoke_ok && pallas_ok || return 1
    local m
    for m in calibrate ssb1 tpch_q1 topn_hll timeseries cube_theta; do
        bench_ok "BENCH_tpu_${m}_r5.json" || return 1
    done
    return 0
}

while true; do
    if all_done; then
        [ -s TPU_SUCCESS ] || date -u +%FT%TZ > TPU_SUCCESS
        echo "=== watch exiting: all evidence captured $(ts)" >> "$LOG"
        exit 0
    fi
    N=$((N + 1))
    P=$(probe)
    if [ -n "$P" ] && [ "$P" != "cpu" ]; then
        echo "$(ts) attempt=$N SUCCESS platform=$P" >> "$LOG"
        run_window
    else
        ERR=$(tail -c 200 /tmp/tpu_probe_err.txt 2>/dev/null | tr '\n' ' ')
        echo "$(ts) attempt=$N fail: ${P:-}${ERR}" >> "$LOG"
    fi
    sleep "$INTERVAL"
done
