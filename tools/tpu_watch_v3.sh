#!/bin/bash
# Round-5 second-window watch.  The first window (08:31-~13:50 UTC, 5.5 h)
# captured the full v2 pipeline (TPU_SUCCESS) and the slope-recalibrated
# reruns of ssb1/ssb10 — but closed before the SF100 run and the
# post-adaptive-fix reruns landed.  This watch converts the NEXT window
# into exactly those, most-valuable-first.  SF100's float64 oracle is now
# disk-cached (.ssb_oracle_sf100_seed7.pkl) and the TPU residency budget
# fits the working set, so SF100 needs ~40 min of window, not ~90.
#
# Run detached:  setsid nohup bash tools/tpu_watch_v3.sh >/tmp/tpu_watch3_out.txt 2>&1 &
set -u
cd "$(dirname "$0")/.."
LOG=TPU_PROBE_LOG_r5.txt
INTERVAL=${TPU_WATCH_INTERVAL:-180}

ts() { date -u +%FT%TZ; }

probe() {
    timeout 90 python -c 'import jax; print(jax.devices()[0].platform)' \
        2>/tmp/tpu_probe_err.txt
}

bench_ok() {  # $1 = json path
    [ -s "$1" ] && python - "$1" <<'EOF'
import json, sys
try:
    d = json.load(open(sys.argv[1]))
except Exception:
    sys.exit(1)
sys.exit(0 if (not d.get("degraded") and "cpu" not in str(d.get("device", "cpu")).lower()) else 1)
EOF
}

reprobe_alive() {
    P=$(probe)
    [ -n "$P" ] && [ "$P" != "cpu" ]
}

# step markers: a bench artifact produced AFTER the adaptive-remap fix
# (commit 32cf9ca).  The v2-window artifacts predate it, so re-run each
# mode once; completed steps are tracked via stamp files.
run_step() {  # $1 = stamp, $2 = out json, $3 = timeout, rest = bench args
    local stamp="$1" out="$2" to="$3"; shift 3
    [ -s ".probe/$stamp" ] && return 0
    reprobe_alive || return 1
    local rc=1
    SD_BENCH_TIMEOUT_S=$((to - 100)) timeout "$to" python bench.py "$@" \
        > "$out.tmp" 2>"/tmp/tpu_w3_${stamp}.txt" && rc=0
    echo "w3 $stamp rc=$rc $(ts)" >> "$LOG"
    # validate BEFORE replacing: a degraded rerun (rc=0, degraded:true)
    # must neither clobber first-window hardware evidence in $out nor
    # stamp the step; only a non-degraded THIS-RUN artifact does both.
    # A failed/degraded run's output is preserved under .degraded (not
    # stranded as .tmp, not thrown away) for triage.
    if [ "$rc" = 0 ] && bench_ok "$out.tmp"; then
        mv "$out.tmp" "$out"
        mkdir -p .probe && date -u +%FT%TZ > ".probe/$stamp"
    elif [ -s "$out.tmp" ]; then
        mv "$out.tmp" "$out.degraded"
    else
        rm -f "$out.tmp"
    fi
    return 0
}

run_window() {
    echo "=== w3 window open $(ts)" >> "$LOG"
    export SD_BENCH_PROBE_WINDOW_S=30 SD_BENCH_PROBE_INTERVAL_S=15 SD_BENCH_PROBE_TIMEOUT_S=60
    export SD_BENCH_SKIP_CALIBRATE=1 SD_BENCH_NO_CPU_FALLBACK=1

    run_step ssb10_v3 BENCH_tpu_ssb10_r5.json 2500 ssb 10 || return
    run_step ssb100_v3 BENCH_tpu_ssb100_r5.json 5500 ssb 100 || return
    run_step timeseries_v3 BENCH_tpu_timeseries_r5.json 900 timeseries || return
    run_step assist_v3 BENCH_tpu_assist_r5.json 1300 assist || return
    run_step tpch_v3 BENCH_tpu_tpch_q1_r5.json 700 tpch_q1 || return
    run_step topn_v3 BENCH_tpu_topn_hll_r5.json 700 topn_hll || return
    run_step cube_v3 BENCH_tpu_cube_theta_r5.json 700 cube_theta || return

    if all_done; then
        echo "=== w3 ALL STEPS CAPTURED $(ts)" >> "$LOG"
    fi
}

all_done() {
    local s
    for s in ssb10_v3 ssb100_v3 timeseries_v3 assist_v3 tpch_v3 topn_v3 cube_v3; do
        [ -s ".probe/$s" ] || return 1
    done
    return 0
}

N=0
while true; do
    if all_done; then
        echo "=== w3 watch exiting: all evidence captured $(ts)" >> "$LOG"
        exit 0
    fi
    N=$((N + 1))
    P=$(probe)
    if [ -n "$P" ] && [ "$P" != "cpu" ]; then
        echo "$(ts) w3 probe=$N SUCCESS platform=$P" >> "$LOG"
        run_window
    else
        echo "$(ts) w3 probe=$N down" >> "$LOG"
    fi
    sleep "$INTERVAL"
done
