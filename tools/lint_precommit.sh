#!/bin/sh
# Fast pre-commit lint: only files changed since merge-base(HEAD, main)
# plus their reverse-dependency closure (modules that import them),
# computed from graftlint's module dependency graph.
#
# Usage:
#   tools/lint_precommit.sh [BASE] [--sanitize-smoke] [--protocol] \
#       [extra graftlint args...]
#
# BASE defaults to main.  Install as a git hook with:
#   ln -s ../../tools/lint_precommit.sh .git/hooks/pre-commit
# (the hook invocation passes no arguments, so BASE stays main).
#
# --sanitize-smoke additionally runs the graftsan in-process hammer
# (SDOL_SANITIZE=1, every layer armed, on-CPU) after the lint pass and
# fails on any contract violation or static<->runtime divergence.
#
# --protocol verifies the static<->runtime protocol bridge: re-exports
# the contract table to a temp file and fails (exit 2) if the committed
# graftsan_contracts.json is stale, then runs the armed smoke so the
# protocol witness replays the GL28xx automata and the GL2901 slot
# balance against live traffic.
#
# Exit codes follow graftlint: 0 clean, 1 new findings / sanitizer
# violations, 2 stale baseline entries or configuration errors.
set -eu

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
BASE="main"
if [ "$#" -gt 0 ]; then
    case "$1" in
        -*) ;;  # first arg is a flag, keep default BASE
        *) BASE="$1"; shift ;;
    esac
fi

SMOKE=0
PROTO=0
ARGS=""
for a in "$@"; do
    if [ "$a" = "--sanitize-smoke" ]; then
        SMOKE=1
    elif [ "$a" = "--protocol" ]; then
        PROTO=1
    else
        ARGS="$ARGS $a"
    fi
done

cd "$ROOT"
rc=0
# shellcheck disable=SC2086  # ARGS is intentionally word-split
python -m tools.graftlint --changed "$BASE" --stats $ARGS || rc=$?

if [ "$PROTO" -eq 1 ]; then
    # stale-contract check: the committed table must match a fresh
    # export byte for byte, or graftsan is enforcing yesterday's rules
    tmp="$(mktemp)"
    trap 'rm -f "$tmp"' EXIT
    JAX_PLATFORMS=cpu \
        python -m tools.graftlint --export-contracts "$tmp" >/dev/null
    if ! cmp -s graftsan_contracts.json "$tmp"; then
        echo "lint_precommit: graftsan_contracts.json is STALE" >&2
        echo "  regenerate: python -m tools.graftlint --export-contracts graftsan_contracts.json" >&2
        if [ "$rc" -lt 2 ]; then rc=2; fi
    fi
fi

if [ "$SMOKE" -eq 1 ] || [ "$PROTO" -eq 1 ]; then
    src=0
    JAX_PLATFORMS=cpu SDOL_SANITIZE=1 \
        python -m tools.graftsan --smoke --stats || src=$?
    if [ "$src" -gt "$rc" ]; then
        rc=$src
    fi
fi

exit $rc
