#!/bin/sh
# Fast pre-commit lint: only files changed since merge-base(HEAD, main)
# plus their reverse-dependency closure (modules that import them),
# computed from graftlint's module dependency graph.
#
# Usage:
#   tools/lint_precommit.sh [BASE] [--sanitize-smoke] [extra graftlint args...]
#
# BASE defaults to main.  Install as a git hook with:
#   ln -s ../../tools/lint_precommit.sh .git/hooks/pre-commit
# (the hook invocation passes no arguments, so BASE stays main).
#
# --sanitize-smoke additionally runs the graftsan in-process hammer
# (SDOL_SANITIZE=1, every layer armed, on-CPU) after the lint pass and
# fails on any contract violation or static<->runtime divergence.
#
# Exit codes follow graftlint: 0 clean, 1 new findings / sanitizer
# violations, 2 stale baseline entries or configuration errors.
set -eu

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
BASE="main"
if [ "$#" -gt 0 ]; then
    case "$1" in
        -*) ;;  # first arg is a flag, keep default BASE
        *) BASE="$1"; shift ;;
    esac
fi

SMOKE=0
ARGS=""
for a in "$@"; do
    if [ "$a" = "--sanitize-smoke" ]; then
        SMOKE=1
    else
        ARGS="$ARGS $a"
    fi
done

cd "$ROOT"
rc=0
# shellcheck disable=SC2086  # ARGS is intentionally word-split
python -m tools.graftlint --changed "$BASE" --stats $ARGS || rc=$?

if [ "$SMOKE" -eq 1 ]; then
    src=0
    JAX_PLATFORMS=cpu SDOL_SANITIZE=1 \
        python -m tools.graftsan --smoke --stats || src=$?
    if [ "$src" -gt "$rc" ]; then
        rc=$src
    fi
fi

exit $rc
