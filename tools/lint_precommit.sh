#!/bin/sh
# Fast pre-commit lint: only files changed since merge-base(HEAD, main)
# plus their reverse-dependency closure (modules that import them),
# computed from graftlint's module dependency graph.
#
# Usage:
#   tools/lint_precommit.sh [BASE] [extra graftlint args...]
#
# BASE defaults to main.  Install as a git hook with:
#   ln -s ../../tools/lint_precommit.sh .git/hooks/pre-commit
# (the hook invocation passes no arguments, so BASE stays main).
#
# Exit codes follow graftlint: 0 clean, 1 new findings, 2 stale
# baseline entries or configuration errors.
set -eu

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
BASE="main"
if [ "$#" -gt 0 ]; then
    case "$1" in
        -*) ;;  # first arg is a flag, keep default BASE
        *) BASE="$1"; shift ;;
    esac
fi

cd "$ROOT"
exec python -m tools.graftlint --changed "$BASE" --stats "$@"
