"""On-chip stage profile of the q2-class adaptive shape (SF10).

Round-5 open question: with kept-sets cached, q2_1's warm device time is
~585 ms over 60M rows while q4_1 (same bytes, plain pallas/dense) runs
255 ms.  Phase B at G'~280 should be dense-tier fast; this script times
the candidate inner kernels at exactly that shape so the next hardware
window can attribute the gap in one ~2-minute run.

Stages: 40-entry compare-chain remap, dense one-hot at G'=280 (3 tiles),
scatter at G'=280, scatter at the raw G=8008, and the fused
chain+filter+dense program.  Methodology = plan/calibrate.py
(_timeit_synced: salted, device_get-proven).
"""

import functools
import sys

import numpy as np

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))
))


def main():
    import jax
    import jax.numpy as jnp

    from spark_druid_olap_tpu.plan.calibrate import _timeit_synced

    print("device:", jax.devices()[0])
    R = 60_000_000 - (60_000_000 % 32768)
    rng = np.random.default_rng(0)
    brand = jax.device_put(jnp.asarray(rng.integers(0, 1000, R).astype(np.int16)))
    year = jax.device_put(jnp.asarray(rng.integers(0, 8, R).astype(np.int8)))
    cat = jax.device_put(jnp.asarray(rng.integers(0, 25, R).astype(np.int8)))
    sreg = jax.device_put(jnp.asarray(rng.integers(0, 5, R).astype(np.int8)))
    rev = jax.device_put(jnp.asarray(rng.random(R).astype(np.float32)))
    kept_brands = sorted(rng.choice(1000, 40, replace=False).tolist())

    @jax.jit
    def chain_only(b, salt):
        acc = jnp.zeros(b.shape, jnp.int32)
        for i, k in enumerate(kept_brands):
            acc = acc + jnp.where(b == k, jnp.int32(i + 1), 0)
        return jnp.sum((acc - 1).astype(jnp.float32)) + salt

    def fused_factory(inner):
        @jax.jit
        def fused(b, y, c, s, v, salt):
            mask = (c == 7) & (s == 2)
            acc = jnp.zeros(b.shape, jnp.int32)
            for i, k in enumerate(kept_brands):
                acc = acc + jnp.where(b == k, jnp.int32(i + 1), 0)
            gid = (acc - 1) * 8 + y.astype(jnp.int32)
            gp = 40 * 8
            gid = jnp.where(mask & (acc > 0), gid, gp)
            vv = jnp.where(mask, v + salt, jnp.asarray(0.0, v.dtype))
            return inner(gid, vv, gp)

        return fused

    def inner_scatter(gid, vv, gp):
        return jnp.sum(jax.ops.segment_sum(vv, gid, num_segments=gp + 1))

    def inner_onehot(gid, vv, gp):
        oh = jax.nn.one_hot(
            gid.reshape(-1, 4096), gp + 1, dtype=jnp.bfloat16
        )
        return jnp.sum(
            jnp.einsum("brg,br->g", oh, vv.reshape(-1, 4096).astype(jnp.bfloat16))
        )

    @jax.jit
    def scatter_raw(b, y, v, salt):
        gid = b.astype(jnp.int32) * 8 + y.astype(jnp.int32)
        return jnp.sum(
            jax.ops.segment_sum(v + salt, gid, num_segments=8008)
        )

    t = lambda fn: _timeit_synced(fn, reps=3)
    print("chain(40) only      %.4f s" % t(lambda s: chain_only(brand, jnp.float32(s))))
    f_sc = fused_factory(inner_scatter)
    print("fused chain+scatter %.4f s" % t(lambda s: f_sc(brand, year, cat, sreg, rev, jnp.float32(s))))
    f_oh = fused_factory(inner_onehot)
    print("fused chain+one-hot %.4f s" % t(lambda s: f_oh(brand, year, cat, sreg, rev, jnp.float32(s))))
    print("raw scatter G=8008  %.4f s" % t(lambda s: scatter_raw(brand, year, rev, jnp.float32(s))))


if __name__ == "__main__":
    main()
