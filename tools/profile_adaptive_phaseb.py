"""On-chip stage profile of the adaptive tier's phase-B shape (q3_2 SF10).

Round-5 observation (BENCH_tpu_ssb_10_detail.json): q3_1 and q3_2 both run
`adaptive` over the same 52M scanned rows, yet q3_2's warm device time is
1117 ms vs q3_1's 238 ms — a 4.7x gap the compact-domain model says should
not exist (both compact to tiny G').  This script times each stage of the
phase-B program shape in isolation on the live backend to localize the gap:

  filter     2-value IN over a 250-domain int16 code column
  gathers    LUT remap (original code -> compact code) per grouping dim
  combine    mixed-radix combine_group_ids to one gid
  kernel     dense one-hot partial aggregate at the compacted G'
  fused      all of the above in ONE jit (what the engine dispatches)

Timing methodology matches plan/calibrate.py: salted inputs, completion
proven by a 4-byte device_get, median of 3.
"""

import functools
import sys
import time

import numpy as np

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))
))


def t_sync(fn, reps=3):
    # the calibration module owns the timing methodology; reuse it so the
    # tool's numbers stay comparable to the constants it cross-checks
    from spark_druid_olap_tpu.plan.calibrate import _timeit_synced

    return _timeit_synced(fn, reps=reps)


def main():
    import jax
    import jax.numpy as jnp

    print("device:", jax.devices()[0])
    R = 51_970_048  # 1586 x 32768: dense block_rows must divide
    rng = np.random.default_rng(0)
    # q3_2 shape: c_city/s_city 250-wide int16 codes, d_year 8-wide int8
    c_city = jax.device_put(jnp.asarray(rng.integers(0, 250, R).astype(np.int16)))
    s_city = jax.device_put(jnp.asarray(rng.integers(0, 250, R).astype(np.int16)))
    d_year = jax.device_put(jnp.asarray(rng.integers(0, 8, R).astype(np.int8)))
    rev = jax.device_put(jnp.asarray(rng.random(R).astype(np.float32)))

    lut_c = np.full(250, -1, np.int32); lut_c[[11, 17]] = [0, 1]
    lut_s = np.full(250, -1, np.int32); lut_s[[42, 99]] = [0, 1]
    lut_y = np.arange(8, dtype=np.int32)
    lut_c_d, lut_s_d, lut_y_d = map(jnp.asarray, (lut_c, lut_s, lut_y))

    @jax.jit
    def filt(c, s, salt):
        return jnp.sum(
            (((c == 11) | (c == 17)) & ((s == 42) | (s == 99))).astype(
                jnp.float32
            )
        ) + salt

    @jax.jit
    def gathers(c, s, y, salt):
        a = lut_c_d[c.astype(jnp.int32)]
        b = lut_s_d[s.astype(jnp.int32)]
        cc = lut_y_d[y.astype(jnp.int32)]
        return (
            jnp.sum(a.astype(jnp.float32))
            + jnp.sum(b.astype(jnp.float32))
            + jnp.sum(cc.astype(jnp.float32))
            + salt
        )

    @jax.jit
    def fused(c, s, y, v, salt):
        mask = ((c == 11) | (c == 17)) & ((s == 42) | (s == 99))
        a = lut_c_d[c.astype(jnp.int32)]
        b = lut_s_d[s.astype(jnp.int32)]
        cc = lut_y_d[y.astype(jnp.int32)]
        gid = (a * 2 + b) * 8 + cc          # mixed radix, G' = 32
        gid = jnp.where(mask, gid, 32)      # trash slot
        st = jax.ops.segment_sum(
            jnp.where(mask, v + salt, jnp.asarray(0.0, v.dtype)), gid, num_segments=33
        )
        return jnp.sum(st)

    @jax.jit
    def fused_onehot(c, s, y, v, salt):
        mask = ((c == 11) | (c == 17)) & ((s == 42) | (s == 99))
        a = lut_c_d[c.astype(jnp.int32)]
        b = lut_s_d[s.astype(jnp.int32)]
        cc = lut_y_d[y.astype(jnp.int32)]
        gid = (a * 2 + b) * 8 + cc
        gid = jnp.where(mask, gid, 32)
        oh = jax.nn.one_hot(gid.reshape(-1, 4096), 33, dtype=jnp.bfloat16)
        vv = jnp.where(mask, v + salt, jnp.asarray(0.0, v.dtype)).reshape(-1, 4096)
        return jnp.sum(jnp.einsum("brg,br->g", oh, vv.astype(jnp.bfloat16)))

    print("filter        %.4f s" % t_sync(lambda s: filt(c_city, s_city, jnp.float32(s))))
    print("lut gathers   %.4f s" % t_sync(lambda s: gathers(c_city, s_city, d_year, jnp.float32(s))))
    print("fused scatter %.4f s" % t_sync(lambda s: fused(c_city, s_city, d_year, rev, jnp.float32(s))))
    print("fused one-hot %.4f s" % t_sync(lambda s: fused_onehot(c_city, s_city, d_year, rev, jnp.float32(s))))

    # the real engine path for comparison: dense_partial_aggregate at G'=32
    from spark_druid_olap_tpu.ops.groupby import dense_partial_aggregate

    dense_fn = functools.partial(
        dense_partial_aggregate, num_groups=32, block_rows=1 << 15,
        num_min=0, num_max=0,
    )

    @jax.jit
    def engine_dense(gid, mask, v, salt):
        out = dense_fn(gid, mask, (v + salt)[:, None], jnp.zeros((R, 0), jnp.float32), jnp.zeros((R, 0), jnp.bool_))
        return sum(x.astype(jnp.float32).sum() for x in jax.tree_util.tree_leaves(out) if hasattr(x, "dtype"))

    gid32 = jax.device_put(jnp.asarray(rng.integers(0, 32, R).astype(np.int32)))
    mask_d = jax.device_put(jnp.asarray(rng.random(R) < 0.01))
    print("engine dense G'=32 (block 32K) %.4f s" % t_sync(lambda s: engine_dense(gid32, mask_d, rev, jnp.float32(s))))


if __name__ == "__main__":
    main()
