#!/usr/bin/env python
"""Static error-handling discipline check (AST walk, run under tier-1 via
tests/test_error_discipline.py).

Every `except Exception` (or broader) handler in the serving/execution
layers — `server.py`, `exec/`, `parallel/` — must do at least one of:

  * re-raise (a bare or explicit `raise` anywhere in the handler body),
  * route through the resilience layer (`classify_error`, `checkpoint`,
    breaker `record_*`, `note_*`),
  * record the failure observably (touch `self._m` / `*metrics*` /
    `QueryMetrics`, or call a logger: `log.warning(...)`, `logger.error`,
    `self.log_...`),
  * or carry an explicit `# fault-ok: <reason>` pragma on the `except`
    line, documenting WHY swallowing is correct there.

Anything else is a silent swallow — the round-5 class of wound where a
wedged device path turns into a mystery hang or a wrong answer with no
trace.  Exit code 1 + a listing when violations exist; importable
(`check_paths`) for the tier-1 test.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

# names whose call inside a handler counts as routing through resilience
_RESILIENCE_CALLS = {
    "classify_error", "checkpoint", "record_failure", "record_success",
    "note_degraded", "note_server_error", "note_deadline_exceeded", "fire",
}
# attribute/name fragments that count as recording to metrics
_METRIC_MARKS = ("metrics", "_m", "QueryMetrics")
# logger receivers: log.warning(...), logger.error(...), _log().warning(...)
_LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical"}

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    names = []
    if isinstance(t, ast.Tuple):
        names = [e for e in t.elts]
    else:
        names = [t]
    for e in names:
        if isinstance(e, ast.Name) and e.id in _BROAD:
            return True
        if isinstance(e, ast.Attribute) and e.attr in _BROAD:
            return True
    return False


def _has_pragma(src_lines: List[str], handler: ast.ExceptHandler) -> bool:
    # the pragma must sit on the `except` line itself (or the line above),
    # with a reason after the colon
    for ln in (handler.lineno - 1, handler.lineno - 2):
        if 0 <= ln < len(src_lines):
            line = src_lines[ln]
            if "fault-ok:" in line and line.split("fault-ok:", 1)[1].strip():
                return True
    return False


class _HandlerScan(ast.NodeVisitor):
    """Collects evidence of discipline inside one handler body."""

    def __init__(self):
        self.raises = False
        self.resilience = False
        self.metrics = False
        self.logs = False

    def visit_Raise(self, node):
        self.raises = True

    def visit_Call(self, node):
        fn = node.func
        name = None
        if isinstance(fn, ast.Name):
            name = fn.id
        elif isinstance(fn, ast.Attribute):
            name = fn.attr
            recv = fn.value
            recv_name = None
            if isinstance(recv, ast.Name):
                recv_name = recv.id
            elif isinstance(recv, ast.Attribute):
                recv_name = recv.attr
            elif isinstance(recv, ast.Call) and isinstance(
                recv.func, ast.Name
            ):
                recv_name = recv.func.id  # _log().warning(...)
            if (
                name in _LOG_METHODS
                and recv_name is not None
                and "log" in recv_name.lower()
            ):
                self.logs = True
        if name in _RESILIENCE_CALLS:
            self.resilience = True
        if name is not None and name.startswith("note_"):
            self.resilience = True
        self.generic_visit(node)

    def visit_Name(self, node):
        if any(m in node.id for m in _METRIC_MARKS):
            self.metrics = True

    def visit_Attribute(self, node):
        if any(m in node.attr for m in _METRIC_MARKS):
            self.metrics = True
        self.generic_visit(node)


def _check_file(path: str) -> List[Tuple[str, int, str]]:
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"unparseable: {e.msg}")]
    lines = src.splitlines()
    out: List[Tuple[str, int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if _has_pragma(lines, node):
            continue
        scan = _HandlerScan()
        for stmt in node.body:
            scan.visit(stmt)
        if scan.raises or scan.resilience or scan.metrics or scan.logs:
            continue
        out.append(
            (
                path,
                node.lineno,
                "broad `except` swallows silently: add a re-raise, route "
                "through resilience.classify_error, record to metrics/log, "
                "or annotate `# fault-ok: <reason>`",
            )
        )
    return out


def target_files(root: str) -> List[str]:
    pkg = os.path.join(root, "spark_druid_olap_tpu")
    files = [os.path.join(pkg, "server.py")]
    for sub in ("exec", "parallel"):
        d = os.path.join(pkg, sub)
        for name in sorted(os.listdir(d)):
            if name.endswith(".py"):
                files.append(os.path.join(d, name))
    return [f for f in files if os.path.exists(f)]


def check_paths(root: str) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    for f in target_files(root):
        out.extend(_check_file(f))
    return out


def main() -> int:
    root = (
        sys.argv[1]
        if len(sys.argv) > 1
        else os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    violations = check_paths(root)
    for path, line, msg in violations:
        print(f"{path}:{line}: {msg}")
    if violations:
        print(f"{len(violations)} error-discipline violation(s)")
        return 1
    print("error discipline OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
