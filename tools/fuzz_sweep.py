"""Wide fuzz sweep — run the committed differential generators over many
more seeds than the suite pins (a bug-shaking pass for long idle compute;
failures print the reproducing seed).

Usage:  PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python tools/fuzz_sweep.py [start] [end]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    lo = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    hi = int(sys.argv[2]) if len(sys.argv) > 2 else 160

    import tests.test_fuzz_differential as T

    world = T.world.__wrapped__()
    ctx, df = world
    failures = []
    for seed in range(lo, hi):
        try:
            T._run_case(ctx, df, seed)
        except Exception as e:  # noqa: BLE001 — report and continue
            failures.append(("device", seed, repr(e)[:200]))
            print(f"FAIL device seed={seed}: {e!r}", flush=True)
        if seed % 10 == 0:
            print(f"... seed {seed}", flush=True)

    fb = T.fallback_world.__wrapped__(world)
    ctx2, df2 = fb
    for seed in range(lo, hi, 3):
        try:
            T._run_case(ctx2, df2, seed)
        except Exception as e:  # noqa: BLE001
            failures.append(("fallback", seed, repr(e)[:200]))
            print(f"FAIL fallback seed={seed}: {e!r}", flush=True)

    # cross-executor (local / 8-device mesh / streaming) — needs the
    # virtual CPU mesh, so only when the interpreter was launched with
    # xla_force_host_platform_device_count=8
    import jax

    if len(jax.devices()) >= 8:
        from spark_druid_olap_tpu.exec.engine import Engine
        from spark_druid_olap_tpu.exec.streaming import StreamExecutor
        from spark_druid_olap_tpu.parallel.distributed import (
            DistributedEngine,
        )
        from spark_druid_olap_tpu.parallel.mesh import make_mesh

        execs = (
            Engine(),
            DistributedEngine(mesh=make_mesh(n_data=8)),
            StreamExecutor(),
        )
        import _pytest.outcomes

        for seed in range(lo, lo + 25):
            try:
                T.test_fuzz_cross_executor_parity(world, execs, seed)
            except _pytest.outcomes.Skipped:
                continue  # seed drew a fallback-only predicate
            except Exception as e:  # noqa: BLE001
                failures.append(("cross-exec", seed, repr(e)[:200]))
                print(f"FAIL cross-exec seed={seed}: {e!r}", flush=True)

    import tests.test_setops as S

    for seed in range(lo, lo + 20):
        try:
            S.test_setop_fuzz_differential(seed)
        except Exception as e:  # noqa: BLE001
            failures.append(("setop", seed, repr(e)[:200]))
            print(f"FAIL setop seed={seed}: {e!r}", flush=True)

    print(f"swept seeds [{lo},{hi}); failures: {len(failures)}")
    for f in failures:
        print("  ", f)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
