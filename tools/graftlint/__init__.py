"""graftlint: pluggable AST static analysis for JAX/serving discipline.

Entry points:

    python -m tools.graftlint spark_druid_olap_tpu tests bench.py
    python -m tools.graftlint --json --pass jit-cache spark_druid_olap_tpu
    python -m tools.graftlint --update-baseline spark_druid_olap_tpu tests bench.py

Library API (what tests/test_lint.py drives):

    from tools.graftlint import run_lint
    result = run_lint(root, ["spark_druid_olap_tpu", "tests", "bench.py"])
    assert result.ok

See `core.py` for the framework (shared walker, findings, baseline) and
`passes/` for the pass catalog.
"""

from .core import (  # noqa: F401
    BASELINE_NAME,
    BaselineEntry,
    Finding,
    LintConfigError,
    LintPass,
    LintResult,
    load_baseline,
    run_lint,
    save_baseline,
)
from .passes import ALL_PASSES, PASS_BY_NAME, build_passes  # noqa: F401
