"""graftlint project layer: whole-package symbol resolution + flow queries.

PR 2's core parses one file and walks it once; the passes it enables are
syntactic.  The bugs that actually burn the serving stack are cross-file
*contract* violations — a kernel whose BlockSpecs live two modules away
from its `pl.pallas_call`, a collective over an axis name the mesh never
declares, a segment loop whose cooperative checkpoint lives in a helper.
This module gives passes the project-level facts those checks need:

  * **Module table** — every scanned file parsed once (the same
    `ModuleContext` the walker uses) and indexed by root-relative path
    AND dotted module name.
  * **Symbol table per module** — import aliases (including relative
    imports resolved against the module's package), module-level
    constants, functions/methods by qualname, classes and their
    attribute sets.
  * **Canonical names** — `pl.BlockSpec` resolves through
    `from jax.experimental import pallas as pl` to
    `jax.experimental.pallas.BlockSpec`; decorators resolve the same
    way.  Passes match canonical names, not spelling-of-the-day aliases.
  * **Call graph** — intra-project call edges per function qualname
    (best-effort: bare names, import aliases, `self.method`).
  * **Flow layer** — `reaches_call(...)`: does this statement body reach
    a call matching a predicate, lexically or through ONE level of
    intra-project calls?  That is the depth the checkpoint-coverage and
    kernel passes need without whole-program dataflow.

Everything here is best-effort static resolution: when a name cannot be
resolved the answer is "unknown" and passes are expected to stay silent
(no finding) rather than guess — a semantic lint that cries wolf on
dynamic code gets pragma'd into uselessness.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Set, Tuple

from .core import ModuleContext, call_name, dotted_name

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def module_name_for(relpath: str) -> str:
    """Dotted module name for a root-relative posix path:
    `pkg/exec/engine.py` -> "pkg.exec.engine"; `pkg/__init__.py` ->
    "pkg"; `bench.py` -> "bench"."""
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else (
        relpath.split("/")
    )
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class FunctionInfo:
    """One function or method: its AST, owning module, and call edges."""

    __slots__ = ("module", "qualname", "node", "cls", "calls")

    def __init__(self, module: "ModuleInfo", qualname: str, node: ast.AST,
                 cls: Optional[ast.ClassDef]):
        self.module = module
        self.qualname = qualname  # "f" or "Cls.f"
        self.node = node
        self.cls = cls
        # (call node, canonical dotted callee) — resolved lazily by
        # Project._build_call_graph; intra-project targets only
        self.calls: List[Tuple[ast.Call, str]] = []


class ModuleInfo:
    """Per-module symbol table."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.relpath = ctx.relpath
        self.modname = module_name_for(ctx.relpath)
        # package the module's RELATIVE imports resolve against
        self.package = (
            self.modname
            if ctx.relpath.endswith("/__init__.py")
            else self.modname.rpartition(".")[0]
        )
        # local alias -> canonical dotted target ("jnp" -> "jax.numpy",
        # "checkpoint" -> "<pkg>.resilience.checkpoint")
        self.import_aliases: Dict[str, str] = {}
        # module-level `NAME = <expr>` (last assignment wins)
        self.constants: Dict[str, ast.expr] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.class_attrs: Dict[str, Set[str]] = {}
        # every Name id and Attribute attr in the module — the cheap
        # "does this module reference symbol X at all" query wire-parity
        # style passes need
        self.identifiers: Set[str] = set()
        self._index()

    # -- construction ---------------------------------------------------------

    def _resolve_relative(self, level: int, mod: Optional[str]) -> str:
        base = self.package.split(".") if self.package else []
        if level > 1:
            base = base[: len(base) - (level - 1)]
        tail = mod.split(".") if mod else []
        return ".".join(base + tail)

    def _index(self) -> None:
        tree = self.ctx.tree
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.constants[t.id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if isinstance(stmt.target, ast.Name):
                    self.constants[stmt.target.id] = stmt.value
        # imports anywhere in the module (this codebase leans on
        # function-local imports); collisions are rare enough to accept
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                mod = (
                    self._resolve_relative(node.level, node.module)
                    if node.level
                    else (node.module or "")
                )
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.import_aliases[a.asname or a.name] = (
                        f"{mod}.{a.name}" if mod else a.name
                    )
            elif isinstance(node, ast.Name):
                self.identifiers.add(node.id)
            elif isinstance(node, ast.Attribute):
                self.identifiers.add(node.attr)
        self._index_scope(tree.body, prefix="", cls=None)

    def _index_scope(self, body, prefix: str, cls) -> None:
        for stmt in body:
            if isinstance(stmt, _FUNC_NODES):
                qual = prefix + stmt.name
                self.functions[qual] = FunctionInfo(self, qual, stmt, cls)
                # one nesting level of defs inside defs is not indexed:
                # closures are resolved lexically by reaches_call instead
            elif isinstance(stmt, ast.ClassDef):
                self.classes[stmt.name] = c = stmt
                attrs: Set[str] = set()
                for sub in ast.walk(c):
                    if (
                        isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                        and isinstance(sub.ctx, ast.Store)
                    ):
                        attrs.add(sub.attr)
                self.class_attrs[stmt.name] = attrs
                self._index_scope(c.body, prefix=f"{stmt.name}.", cls=c)


class Project:
    """All scanned modules + resolution/flow queries for semantic passes."""

    def __init__(self, root: str):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}  # by relpath
        self.by_name: Dict[str, ModuleInfo] = {}  # by dotted module name
        # (relpath, qualname) -> canonical callee dotted names; built by
        # finalize() for intra-project edges only
        self.call_graph: Dict[Tuple[str, str], List[str]] = {}

    def add_module(self, ctx: ModuleContext) -> ModuleInfo:
        info = ModuleInfo(ctx)
        self.modules[info.relpath] = info
        self.by_name[info.modname] = info
        return info

    def finalize(self) -> None:
        for info in self.modules.values():
            for fi in info.functions.values():
                edges: List[str] = []
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    name = call_name(node)
                    if not name:
                        continue
                    target = self.resolve_function(info, name, cls=fi.cls)
                    if target is not None:
                        canon = (
                            f"{target.module.modname}.{target.qualname}"
                        )
                        fi.calls.append((node, canon))
                        edges.append(canon)
                if edges:
                    self.call_graph[(info.relpath, fi.qualname)] = edges

    # -- name resolution ------------------------------------------------------

    def canonical(self, module: ModuleInfo, dotted: str) -> str:
        """Resolve the leading segment of a dotted name through the
        module's import aliases: `pl.BlockSpec` ->
        "jax.experimental.pallas.BlockSpec".  Unknown roots pass through
        unchanged."""
        if not dotted:
            return dotted
        head, _, rest = dotted.partition(".")
        target = module.import_aliases.get(head)
        if target is None:
            return dotted
        return f"{target}.{rest}" if rest else target

    def resolve_constant_entry(
        self, module: ModuleInfo, dotted: str, _depth: int = 0
    ) -> Optional[Tuple[ModuleInfo, ast.expr]]:
        """Follow a (possibly imported) name to (owning module,
        module-level expression), across project modules.  The owner
        matters: sub-expressions of the result (e.g. the Names inside an
        axis tuple) must be resolved against the module that WROTE them,
        not the importer."""
        if _depth > 5 or not dotted:
            return None
        if "." not in dotted:
            if dotted in module.constants:
                return module, module.constants[dotted]
            alias = module.import_aliases.get(dotted)
            if alias and alias != dotted:
                return self._entry_by_canonical(alias, _depth + 1)
            return None
        return self._entry_by_canonical(
            self.canonical(module, dotted), _depth + 1
        )

    def resolve_constant(
        self, module: ModuleInfo, dotted: str, _depth: int = 0
    ) -> Optional[ast.expr]:
        """`resolve_constant_entry` without the owner; None for anything
        unresolvable (parameters, locals, externals)."""
        entry = self.resolve_constant_entry(module, dotted, _depth)
        return entry[1] if entry is not None else None

    def _entry_by_canonical(
        self, canon: str, depth: int
    ) -> Optional[Tuple[ModuleInfo, ast.expr]]:
        modpath, _, sym = canon.rpartition(".")
        target = self.by_name.get(modpath)
        if target is None or not sym:
            return None
        return self.resolve_constant_entry(target, sym, depth)

    def resolve_string(
        self, module: ModuleInfo, node: ast.AST
    ) -> Optional[str]:
        """Static string value of an expression: literal, or a
        (possibly imported) module-level string constant."""
        if isinstance(node, ast.Constant):
            return node.value if isinstance(node.value, str) else None
        dotted = dotted_name(node)
        if dotted:
            expr = self.resolve_constant(module, dotted)
            if isinstance(expr, ast.Constant) and isinstance(
                expr.value, str
            ):
                return expr.value
        return None

    def resolve_function(
        self,
        module: ModuleInfo,
        dotted: str,
        cls: Optional[ast.ClassDef] = None,
    ) -> Optional[FunctionInfo]:
        """Best-effort intra-project function resolution: bare names
        and `Cls.name` in this module, `self.method` against the
        enclosing class, `from x import f` / `import x` aliases across
        project modules."""
        if not dotted:
            return None
        if dotted.startswith("self.") and cls is not None:
            meth = dotted[len("self."):]
            if "." in meth:
                return None
            return module.functions.get(f"{cls.name}.{meth}")
        if dotted in module.functions:
            return module.functions[dotted]
        # `dotted_name` strips a leading underscore on the first segment
        # (so `import x as _x` aliases match); undo that for bare local
        # helpers like `_helper()`
        if "." not in dotted and f"_{dotted}" in module.functions:
            return module.functions[f"_{dotted}"]
        canon = self.canonical(module, dotted)
        modpath, _, sym = canon.rpartition(".")
        target = self.by_name.get(modpath)
        if target is not None and sym:
            fi = target.functions.get(sym)
            if fi is not None:
                return fi
        # `from .x import f`: canon is "<pkg>.x.f" and "<pkg>.x" is the
        # module — handled above; "import pkg.x" usage "pkg.x.f" too.
        # As a last resort treat the whole canon as module-level symbol
        # of a scanned module two segments up (Cls.method references).
        if target is None and "." in modpath:
            outer, _, clsname = modpath.rpartition(".")
            mod2 = self.by_name.get(outer)
            if mod2 is not None:
                return mod2.functions.get(f"{clsname}.{sym}")
        return None

    # -- flow layer -----------------------------------------------------------

    def reaches_call(
        self,
        module: ModuleInfo,
        body: ast.AST,
        pred: Callable[[str, str], bool],
        depth: int = 1,
        cls: Optional[ast.ClassDef] = None,
    ) -> bool:
        """True when `body` contains a call matching `pred(raw_name,
        canonical_name)` — lexically, or (depth permitting) inside the
        body of an intra-project callee.  One level of call-through is
        the contract the checkpoint-coverage pass is specified against:
        helpers may carry the checkpoint, helpers-of-helpers may not."""
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            if pred(name, self.canonical(module, name)):
                return True
        if depth <= 0:
            return False
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            target = self.resolve_function(module, name, cls=cls)
            if target is not None and self.reaches_call(
                target.module, target.node, pred,
                depth=depth - 1, cls=target.cls,
            ):
                return True
        return False
