"""graftlint project layer: whole-package symbol resolution + flow queries.

PR 2's core parses one file and walks it once; the passes it enables are
syntactic.  The bugs that actually burn the serving stack are cross-file
*contract* violations — a kernel whose BlockSpecs live two modules away
from its `pl.pallas_call`, a collective over an axis name the mesh never
declares, a segment loop whose cooperative checkpoint lives in a helper.
This module gives passes the project-level facts those checks need:

  * **Module table** — every scanned file parsed once (the same
    `ModuleContext` the walker uses) and indexed by root-relative path
    AND dotted module name.
  * **Symbol table per module** — import aliases (including relative
    imports resolved against the module's package), module-level
    constants, functions/methods by qualname, classes and their
    attribute sets.
  * **Canonical names** — `pl.BlockSpec` resolves through
    `from jax.experimental import pallas as pl` to
    `jax.experimental.pallas.BlockSpec`; decorators resolve the same
    way.  Passes match canonical names, not spelling-of-the-day aliases.
  * **Call graph** — intra-project call edges per function qualname
    (best-effort: bare names, import aliases, `self.method`).
  * **Flow layer** — `reaches_call(...)`: does this statement body reach
    a call matching a predicate, lexically or through intra-project
    calls up to a CONFIGURABLE depth?  PR 3 hardcoded one level; the v3
    passes (lock-order builds a whole acquisition graph, deep helpers
    carry checkpoints) take the depth from pass config, and the
    traversal memoizes explored functions so depth >= 2 stays linear in
    the call graph instead of exponential in paths.
  * **Constant propagation** — `const_eval(...)`: a mini-evaluator that
    resolves tile/block-size-shaped expressions to concrete values:
    literals, module constants (cross-module through imports), class
    attribute defaults (`SessionConfig.vmem_budget_mb`), arithmetic on
    resolved values, `min`/`max`/`len`, conditional expressions, and
    tuple/subscript structure.  The resource-budget pass (GL12xx) feeds
    it BlockSpec shapes and grid expressions; anything it cannot prove
    stays unresolved (None), never guessed.

Everything here is best-effort static resolution: when a name cannot be
resolved the answer is "unknown" and passes are expected to stay silent
(no finding) rather than guess — a semantic lint that cries wolf on
dynamic code gets pragma'd into uselessness.
"""

from __future__ import annotations

import ast
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from .core import ModuleContext, call_name, dotted_name

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# sentinel distinguishing "statically unresolvable" from a literal None.
# Passes may place UNRESOLVED in a const_eval env to POISON a name they
# know is not statically trackable (AugAssign-ed locals, loop-mutated
# tuning knobs) — resolution stops there instead of falling through to a
# same-named module constant.
_UNRESOLVED = object()
UNRESOLVED = _UNRESOLVED


def module_name_for(relpath: str) -> str:
    """Dotted module name for a root-relative posix path:
    `pkg/exec/engine.py` -> "pkg.exec.engine"; `pkg/__init__.py` ->
    "pkg"; `bench.py` -> "bench"."""
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else (
        relpath.split("/")
    )
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class FunctionInfo:
    """One function or method: its AST, owning module, and call edges."""

    __slots__ = ("module", "qualname", "node", "cls", "calls")

    def __init__(self, module: "ModuleInfo", qualname: str, node: ast.AST,
                 cls: Optional[ast.ClassDef]):
        self.module = module
        self.qualname = qualname  # "f" or "Cls.f"
        self.node = node
        self.cls = cls
        # (call node, canonical dotted callee) — resolved lazily by
        # Project._build_call_graph; intra-project targets only
        self.calls: List[Tuple[ast.Call, str]] = []


class ModuleInfo:
    """Per-module symbol table."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.relpath = ctx.relpath
        self.modname = module_name_for(ctx.relpath)
        # package the module's RELATIVE imports resolve against
        self.package = (
            self.modname
            if ctx.relpath.endswith("/__init__.py")
            else self.modname.rpartition(".")[0]
        )
        # local alias -> canonical dotted target ("jnp" -> "jax.numpy",
        # "checkpoint" -> "<pkg>.resilience.checkpoint")
        self.import_aliases: Dict[str, str] = {}
        # module-level `NAME = <expr>` (last assignment wins)
        self.constants: Dict[str, ast.expr] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.class_attrs: Dict[str, Set[str]] = {}
        # class-BODY `name = <expr>` / `name: T = <expr>` defaults (the
        # dataclass-field shape `SessionConfig.vmem_budget_mb` resolves
        # through), keyed by class name then attribute
        self.class_defaults: Dict[str, Dict[str, ast.expr]] = {}
        # every Name id and Attribute attr in the module — the cheap
        # "does this module reference symbol X at all" query wire-parity
        # style passes need
        self.identifiers: Set[str] = set()
        self._index()

    # -- construction ---------------------------------------------------------

    def _resolve_relative(self, level: int, mod: Optional[str]) -> str:
        base = self.package.split(".") if self.package else []
        if level > 1:
            base = base[: len(base) - (level - 1)]
        tail = mod.split(".") if mod else []
        return ".".join(base + tail)

    def _index(self) -> None:
        tree = self.ctx.tree
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.constants[t.id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if isinstance(stmt.target, ast.Name):
                    self.constants[stmt.target.id] = stmt.value
        # imports anywhere in the module (this codebase leans on
        # function-local imports); collisions are rare enough to accept
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                mod = (
                    self._resolve_relative(node.level, node.module)
                    if node.level
                    else (node.module or "")
                )
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.import_aliases[a.asname or a.name] = (
                        f"{mod}.{a.name}" if mod else a.name
                    )
            elif isinstance(node, ast.Name):
                self.identifiers.add(node.id)
            elif isinstance(node, ast.Attribute):
                self.identifiers.add(node.attr)
        self._index_scope(tree.body, prefix="", cls=None)

    def _index_scope(self, body, prefix: str, cls) -> None:
        for stmt in body:
            if isinstance(stmt, _FUNC_NODES):
                qual = prefix + stmt.name
                self.functions[qual] = FunctionInfo(self, qual, stmt, cls)
                # one nesting level of defs inside defs is not indexed:
                # closures are resolved lexically by reaches_call instead
            elif isinstance(stmt, ast.ClassDef):
                self.classes[stmt.name] = c = stmt
                defaults: Dict[str, ast.expr] = {}
                for sub in c.body:
                    if isinstance(sub, ast.Assign):
                        for t in sub.targets:
                            if isinstance(t, ast.Name):
                                defaults[t.id] = sub.value
                    elif isinstance(sub, ast.AnnAssign) and (
                        sub.value is not None
                        and isinstance(sub.target, ast.Name)
                    ):
                        defaults[sub.target.id] = sub.value
                self.class_defaults[stmt.name] = defaults
                attrs: Set[str] = set()
                for sub in ast.walk(c):
                    if (
                        isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                        and isinstance(sub.ctx, ast.Store)
                    ):
                        attrs.add(sub.attr)
                self.class_attrs[stmt.name] = attrs
                self._index_scope(c.body, prefix=f"{stmt.name}.", cls=c)


class Project:
    """All scanned modules + resolution/flow queries for semantic passes."""

    def __init__(self, root: str):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}  # by relpath
        self.by_name: Dict[str, ModuleInfo] = {}  # by dotted module name
        # (relpath, qualname) -> canonical callee dotted names; built by
        # finalize() for intra-project edges only
        self.call_graph: Dict[Tuple[str, str], List[str]] = {}

    def add_module(self, ctx: ModuleContext) -> ModuleInfo:
        info = ModuleInfo(ctx)
        self.modules[info.relpath] = info
        self.by_name[info.modname] = info
        return info

    def finalize(self) -> None:
        for info in self.modules.values():
            for fi in info.functions.values():
                edges: List[str] = []
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    name = call_name(node)
                    if not name:
                        continue
                    target = self.resolve_function(info, name, cls=fi.cls)
                    if target is not None:
                        canon = (
                            f"{target.module.modname}.{target.qualname}"
                        )
                        fi.calls.append((node, canon))
                        edges.append(canon)
                if edges:
                    self.call_graph[(info.relpath, fi.qualname)] = edges

    # -- name resolution ------------------------------------------------------

    def canonical(self, module: ModuleInfo, dotted: str) -> str:
        """Resolve the leading segment of a dotted name through the
        module's import aliases: `pl.BlockSpec` ->
        "jax.experimental.pallas.BlockSpec".  Unknown roots pass through
        unchanged."""
        if not dotted:
            return dotted
        head, _, rest = dotted.partition(".")
        target = module.import_aliases.get(head)
        if target is None:
            return dotted
        return f"{target}.{rest}" if rest else target

    def resolve_constant_entry(
        self, module: ModuleInfo, dotted: str, _depth: int = 0
    ) -> Optional[Tuple[ModuleInfo, ast.expr]]:
        """Follow a (possibly imported) name to (owning module,
        module-level expression), across project modules.  The owner
        matters: sub-expressions of the result (e.g. the Names inside an
        axis tuple) must be resolved against the module that WROTE them,
        not the importer."""
        if _depth > 5 or not dotted:
            return None
        if "." not in dotted:
            if dotted in module.constants:
                return module, module.constants[dotted]
            alias = module.import_aliases.get(dotted)
            if alias and alias != dotted:
                return self._entry_by_canonical(alias, _depth + 1)
            return None
        # `Cls.attr` against a class defined in (or imported into) scope
        head, _, attr = dotted.partition(".")
        if "." not in attr and head in module.class_defaults:
            expr = module.class_defaults[head].get(attr)
            if expr is not None:
                return module, expr
        return self._entry_by_canonical(
            self.canonical(module, dotted), _depth + 1
        )

    def resolve_constant(
        self, module: ModuleInfo, dotted: str, _depth: int = 0
    ) -> Optional[ast.expr]:
        """`resolve_constant_entry` without the owner; None for anything
        unresolvable (parameters, locals, externals)."""
        entry = self.resolve_constant_entry(module, dotted, _depth)
        return entry[1] if entry is not None else None

    def _entry_by_canonical(
        self, canon: str, depth: int
    ) -> Optional[Tuple[ModuleInfo, ast.expr]]:
        modpath, _, sym = canon.rpartition(".")
        target = self.by_name.get(modpath)
        if target is not None and sym:
            return self.resolve_constant_entry(target, sym, depth)
        # `pkg.mod.Cls.attr`: a class-body default (the dataclass-field
        # shape budget/config constants live in)
        if "." in modpath and sym:
            outer, _, clsname = modpath.rpartition(".")
            mod2 = self.by_name.get(outer)
            if mod2 is not None:
                expr = mod2.class_defaults.get(clsname, {}).get(sym)
                if expr is not None:
                    return mod2, expr
        return None

    def resolve_string(
        self, module: ModuleInfo, node: ast.AST
    ) -> Optional[str]:
        """Static string value of an expression: literal, or a
        (possibly imported) module-level string constant."""
        if isinstance(node, ast.Constant):
            return node.value if isinstance(node.value, str) else None
        dotted = dotted_name(node)
        if dotted:
            expr = self.resolve_constant(module, dotted)
            if isinstance(expr, ast.Constant) and isinstance(
                expr.value, str
            ):
                return expr.value
        return None

    def resolve_function(
        self,
        module: ModuleInfo,
        dotted: str,
        cls: Optional[ast.ClassDef] = None,
    ) -> Optional[FunctionInfo]:
        """Best-effort intra-project function resolution: bare names
        and `Cls.name` in this module, `self.method` against the
        enclosing class, `from x import f` / `import x` aliases across
        project modules."""
        if not dotted:
            return None
        if dotted.startswith("self.") and cls is not None:
            meth = dotted[len("self."):]
            if "." in meth:
                return None
            return module.functions.get(f"{cls.name}.{meth}")
        if dotted in module.functions:
            return module.functions[dotted]
        # `dotted_name` strips a leading underscore on the first segment
        # (so `import x as _x` aliases match); undo that for bare local
        # helpers like `_helper()`
        if "." not in dotted and f"_{dotted}" in module.functions:
            return module.functions[f"_{dotted}"]
        canon = self.canonical(module, dotted)
        modpath, _, sym = canon.rpartition(".")
        target = self.by_name.get(modpath)
        if target is not None and sym:
            fi = target.functions.get(sym)
            if fi is not None:
                return fi
        # `from .x import f`: canon is "<pkg>.x.f" and "<pkg>.x" is the
        # module — handled above; "import pkg.x" usage "pkg.x.f" too.
        # As a last resort treat the whole canon as module-level symbol
        # of a scanned module two segments up (Cls.method references).
        if target is None and "." in modpath:
            outer, _, clsname = modpath.rpartition(".")
            mod2 = self.by_name.get(outer)
            if mod2 is not None:
                return mod2.functions.get(f"{clsname}.{sym}")
        return None

    # -- flow layer -----------------------------------------------------------

    def reaches_call(
        self,
        module: ModuleInfo,
        body: ast.AST,
        pred: Callable[[str, str], bool],
        depth: int = 1,
        cls: Optional[ast.ClassDef] = None,
        _explored: Optional[Dict[int, int]] = None,
    ) -> bool:
        """True when `body` contains a call matching `pred(raw_name,
        canonical_name)` — lexically, or inside the body of an
        intra-project callee reached through at most `depth` levels of
        call-through.  The depth is the PASS's contract (checkpoint
        coverage defaults to 1: helpers may carry the checkpoint,
        helpers-of-helpers may not; lock-order walks deeper), and the
        traversal memoizes (function, remaining depth) so depth >= 2
        explores each function once, not once per path."""
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            if pred(name, self.canonical(module, name)):
                return True
        if depth <= 0:
            return False
        if _explored is None:
            _explored = {}
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            target = self.resolve_function(module, name, cls=cls)
            if target is None:
                continue
            # re-explore only with MORE remaining depth than last time
            # (a failed shallow visit proves nothing about a deeper one;
            # a failed deep visit covers every shallower one)
            if _explored.get(id(target), -1) >= depth:
                continue
            _explored[id(target)] = depth
            if self.reaches_call(
                target.module, target.node, pred,
                depth=depth - 1, cls=target.cls, _explored=_explored,
            ):
                return True
        return False

    # -- constant propagation -------------------------------------------------

    _BINOPS = {
        ast.Add: lambda a, b: a + b,
        ast.Sub: lambda a, b: a - b,
        ast.Mult: lambda a, b: a * b,
        ast.Div: lambda a, b: a / b,
        ast.FloorDiv: lambda a, b: a // b,
        ast.Mod: lambda a, b: a % b,
        ast.Pow: lambda a, b: a ** b,
        ast.LShift: lambda a, b: a << b,
        ast.RShift: lambda a, b: a >> b,
    }
    _CMPOPS = {
        ast.Eq: lambda a, b: a == b,
        ast.NotEq: lambda a, b: a != b,
        ast.Lt: lambda a, b: a < b,
        ast.LtE: lambda a, b: a <= b,
        ast.Gt: lambda a, b: a > b,
        ast.GtE: lambda a, b: a >= b,
    }
    _CALLS = {
        "min": min, "max": max, "abs": abs, "len": len, "sum": sum,
        "int": int, "float": float, "bool": bool, "round": round,
    }

    def const_eval(
        self,
        module: ModuleInfo,
        expr: Optional[ast.AST],
        env: Optional[Dict[str, Any]] = None,
        _depth: int = 0,
    ) -> Any:
        """Best-effort static value of an expression: int/float/str/bool
        literals, tuples/lists (as tuples), module constants resolved
        cross-module, class-body defaults, arithmetic / comparisons /
        `min`/`max`/`len`/`abs` over resolved values, conditional
        expressions, and constant subscripts of resolved tuples.

        `env` maps local names to already-known values OR to ast
        expressions still to evaluate (how passes feed parameter
        defaults and local assignments in).  Returns None when the value
        cannot be proven (a literal `None` also returns None — shapes
        and budgets, the intended domain, are never legitimately None)."""
        v = self._eval(module, expr, env or {}, _depth)
        return None if v is _UNRESOLVED else v

    def _eval(self, module, expr, env, depth) -> Any:
        if expr is None or depth > 40:
            return _UNRESOLVED
        if isinstance(expr, ast.Constant):
            return expr.value
        if isinstance(expr, ast.Name):
            if expr.id in env:
                bound = env[expr.id]
                if isinstance(bound, ast.AST):
                    # evaluate once, cache the result (also breaks
                    # self-referential `x = x + 1` chains)
                    env[expr.id] = _UNRESOLVED
                    env[expr.id] = self._eval(module, bound, env, depth + 1)
                return env[expr.id]
            entry = self.resolve_constant_entry(module, expr.id)
            if entry is None:
                return _UNRESOLVED
            owner, bound = entry
            return self._eval(owner, bound, {}, depth + 1)
        if isinstance(expr, ast.Attribute):
            dn = dotted_name(expr)
            if not dn:
                return _UNRESOLVED
            entry = self.resolve_constant_entry(module, dn)
            if entry is None:
                return _UNRESOLVED
            owner, bound = entry
            return self._eval(owner, bound, {}, depth + 1)
        if isinstance(expr, ast.UnaryOp):
            v = self._eval(module, expr.operand, env, depth + 1)
            if v is _UNRESOLVED:
                return _UNRESOLVED
            try:
                if isinstance(expr.op, ast.USub):
                    return -v
                if isinstance(expr.op, ast.UAdd):
                    return +v
                if isinstance(expr.op, ast.Not):
                    return not v
            except TypeError:
                return _UNRESOLVED
            return _UNRESOLVED
        if isinstance(expr, ast.BinOp):
            fn = self._BINOPS.get(type(expr.op))
            if fn is None:
                return _UNRESOLVED
            a = self._eval(module, expr.left, env, depth + 1)
            b = self._eval(module, expr.right, env, depth + 1)
            if a is _UNRESOLVED or b is _UNRESOLVED:
                return _UNRESOLVED
            try:
                return fn(a, b)
            except (TypeError, ValueError, ZeroDivisionError,
                    OverflowError):
                return _UNRESOLVED
        if isinstance(expr, ast.Compare):
            left = self._eval(module, expr.left, env, depth + 1)
            if left is _UNRESOLVED:
                return _UNRESOLVED
            for op, comparator in zip(expr.ops, expr.comparators):
                fn = self._CMPOPS.get(type(op))
                right = self._eval(module, comparator, env, depth + 1)
                if fn is None or right is _UNRESOLVED:
                    return _UNRESOLVED
                try:
                    if not fn(left, right):
                        return False
                except TypeError:
                    return _UNRESOLVED
                left = right
            return True
        if isinstance(expr, ast.BoolOp):
            values = [
                self._eval(module, v, env, depth + 1) for v in expr.values
            ]
            if any(v is _UNRESOLVED for v in values):
                return _UNRESOLVED
            if isinstance(expr.op, ast.And):
                for v in values:
                    if not v:
                        return v
                return values[-1]
            for v in values:
                if v:
                    return v
            return values[-1]
        if isinstance(expr, ast.IfExp):
            test = self._eval(module, expr.test, env, depth + 1)
            if test is _UNRESOLVED:
                return _UNRESOLVED
            branch = expr.body if test else expr.orelse
            return self._eval(module, branch, env, depth + 1)
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = []
            for e in expr.elts:
                v = self._eval(module, e, env, depth + 1)
                if v is _UNRESOLVED:
                    return _UNRESOLVED
                out.append(v)
            return tuple(out)
        if isinstance(expr, ast.Subscript):
            base = self._eval(module, expr.value, env, depth + 1)
            idx = self._eval(module, expr.slice, env, depth + 1)
            if base is _UNRESOLVED or idx is _UNRESOLVED:
                return _UNRESOLVED
            try:
                return base[idx]
            except (TypeError, KeyError, IndexError):
                return _UNRESOLVED
        if isinstance(expr, ast.Call):
            fn = self._CALLS.get(self.canonical(module, call_name(expr)))
            if fn is None or expr.keywords:
                return _UNRESOLVED
            args = [
                self._eval(module, a, env, depth + 1) for a in expr.args
            ]
            if any(a is _UNRESOLVED for a in args):
                return _UNRESOLVED
            try:
                return fn(*args)
            except (TypeError, ValueError):
                return _UNRESOLVED
        return _UNRESOLVED

    def param_defaults(self, fi: FunctionInfo) -> Dict[str, Any]:
        """Statically-evaluable parameter defaults of a function, as a
        const_eval env: the values a call site that omits the argument
        gets (how block_rows/block_groups-style tuning knobs resolve)."""
        env: Dict[str, Any] = {}
        a = fi.node.args
        pos = a.posonlyargs + a.args
        for arg, default in zip(pos[len(pos) - len(a.defaults):],
                                a.defaults):
            v = self.const_eval(fi.module, default)
            if v is not None:
                env[arg.arg] = v
        for arg, default in zip(a.kwonlyargs, a.kw_defaults):
            if default is not None:
                v = self.const_eval(fi.module, default)
                if v is not None:
                    env[arg.arg] = v
        return env
