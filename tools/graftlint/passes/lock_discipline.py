"""Lock-discipline pass: guarded-field writes must hold the owner's lock.

The serving layer runs queries on ThreadingHTTPServer handler threads;
the breaker, admission pool, resilience counters, metadata cache, and
the engine's LRU caches are all cross-thread state guarded by an
instance lock.  A write that skips the `with self._lock:` block is a
data race that shows up as a wedged breaker or a corrupted LRU under
concurrency — precisely when nobody is watching.

The pass carries a REGISTRY (per-pass config) of class name -> (lock
attribute, guarded fields).  Inside methods of a registered class it
flags:

* **GL501** — assignment / augmented assignment to a guarded
  `self.<field>` outside a lexical `with self.<lock>:` block.
* **GL502** — mutating operation on a guarded container field outside
  the lock: `self.<field>[k] = v`, `del self.<field>[k]`, or a mutator
  method call (`append`/`pop`/`clear`/`update`/`popitem`/
  `move_to_end`/`setdefault`/`add`/`discard`/`remove`/`extend`).

`__init__` is exempt (no concurrent access before construction
completes).  The analysis is lexical: a helper that the class only ever
calls under the lock should take the (reentrant) lock itself or carry a
pragma — implicit caller-holds-the-lock contracts are exactly what rots.
"""

from __future__ import annotations

import ast

from ..core import LintPass, ModuleContext, dotted_name

_MUTATORS = {
    "append", "pop", "clear", "update", "popitem", "move_to_end",
    "setdefault", "add", "discard", "remove", "extend", "insert",
}

# Default registry: the resilience/serving/caching state machines.
_DEFAULT_REGISTRY = {
    "CircuitBreaker": {
        "lock": "_lock",
        "fields": [
            "_state", "_consecutive_failures", "_opened_at",
            "_failures_total", "_successes_total", "_trips",
            "_probe_started_at",
        ],
    },
    "AdmissionController": {
        "lock": "_lock",
        "fields": [
            "_in_use", "admitted_total", "rejected_total", "_waiting",
            "_hold_ewma_ms", "_held_since",
        ],
    },
    "ResilienceState": {
        "lock": "_lock",
        "fields": [
            "degraded_total", "deadline_exceeded_total",
            "server_errors_total", "last_error",
        ],
    },
    "FaultInjector": {
        "lock": "_lock",
        "fields": ["_sites", "_fired"],
    },
    "MetadataCache": {
        "lock": "_lock",
        "fields": ["_tables", "_stars", "_lookups", "version"],
    },
    "ByteBudgetCache": {
        "lock": "_lock",
        "fields": ["_od", "_bytes"],
    },
    "CountBudgetCache": {
        "lock": "_lock",
        "fields": ["_od", "budget_entries"],
    },
}


class LockDisciplinePass(LintPass):
    name = "lock-discipline"
    default_config = {"registry": _DEFAULT_REGISTRY}

    def _spec(self, ctx: ModuleContext):
        cls = ctx.scope.current_class
        if cls is None:
            return None
        return self.config["registry"].get(cls.name)

    def _exempt(self, ctx: ModuleContext) -> bool:
        func = ctx.scope.current_func
        return func is None or getattr(func, "name", "") == "__init__"

    @staticmethod
    def _self_field(node: ast.AST):
        """`self.<attr>` -> attr, else None."""
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _flag(self, ctx, node, code, field, spec):
        self.report(
            ctx, node, code,
            f"write to guarded field self.{field} outside "
            f"`with self.{spec['lock']}:` — cross-thread state must "
            "mutate under its lock (take the lock reentrantly in helpers "
            "or justify via pragma/baseline)",
        )

    def on_Assign(self, node: ast.Assign, ctx: ModuleContext):
        spec = self._spec(ctx)
        if spec is None or self._exempt(ctx):
            return
        if ctx.scope.holds_lock(spec["lock"]):
            return
        for t in node.targets:
            field = self._self_field(t)
            if field in spec["fields"]:
                self._flag(ctx, node, "GL501", field, spec)
                return
            if isinstance(t, ast.Subscript):
                field = self._self_field(t.value)
                if field in spec["fields"]:
                    self._flag(ctx, node, "GL502", field, spec)
                    return

    def on_AugAssign(self, node: ast.AugAssign, ctx: ModuleContext):
        spec = self._spec(ctx)
        if spec is None or self._exempt(ctx):
            return
        if ctx.scope.holds_lock(spec["lock"]):
            return
        field = self._self_field(node.target)
        if field in spec["fields"]:
            self._flag(ctx, node, "GL501", field, spec)
            return
        if isinstance(node.target, ast.Subscript):
            field = self._self_field(node.target.value)
            if field in spec["fields"]:
                self._flag(ctx, node, "GL502", field, spec)

    def on_Delete(self, node: ast.Delete, ctx: ModuleContext):
        spec = self._spec(ctx)
        if spec is None or self._exempt(ctx):
            return
        if ctx.scope.holds_lock(spec["lock"]):
            return
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                field = self._self_field(t.value)
                if field in spec["fields"]:
                    self._flag(ctx, node, "GL502", field, spec)
                    return

    def on_Call(self, node: ast.Call, ctx: ModuleContext):
        spec = self._spec(ctx)
        if spec is None or self._exempt(ctx):
            return
        if ctx.scope.holds_lock(spec["lock"]):
            return
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
            field = self._self_field(fn.value)
            if field in spec["fields"]:
                self._flag(ctx, node, "GL502", field, spec)
