"""Error-discipline pass (PR 1's standalone checker, now a framework pass).

Every broad `except Exception` / `except BaseException` / bare `except:`
handler in the serving/execution layers must do at least one of:

  * re-raise (a `raise` anywhere in the handler body),
  * route through the resilience layer (`classify_error`, `checkpoint`,
    breaker `record_*`, `note_*`, `fire`),
  * record the failure observably (touch `self._m` / `*metrics*` /
    `QueryMetrics`, or call a logger method),
  * or carry an explicit `# fault-ok: <reason>` pragma on (or above)
    the `except` line, documenting WHY swallowing is correct there.

Anything else is a silent swallow — the round-5 class of wound where a
wedged device path turns into a mystery hang or a wrong answer with no
trace.  **GL601** flags each violation.  The framework-level
`# graftlint: disable=error-discipline` pragma works too, but the
fault-ok spelling is preferred: it names the reason in place.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import LintPass, ModuleContext

# names whose call inside a handler counts as routing through resilience
_RESILIENCE_CALLS = {
    "classify_error", "checkpoint", "record_failure", "record_success",
    "note_degraded", "note_server_error", "note_deadline_exceeded", "fire",
}
# attribute/name fragments that count as recording to metrics
_METRIC_MARKS = ("metrics", "_m", "QueryMetrics")
# logger receivers: log.warning(...), logger.error(...), _log().warning(...)
_LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical"}

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        if isinstance(e, ast.Name) and e.id in _BROAD:
            return True
        if isinstance(e, ast.Attribute) and e.attr in _BROAD:
            return True
    return False


def _has_fault_ok(lines: List[str], handler: ast.ExceptHandler) -> bool:
    # the pragma must sit on the `except` line itself (or the line above),
    # with a reason after the colon
    for ln in (handler.lineno - 1, handler.lineno - 2):
        if 0 <= ln < len(lines):
            line = lines[ln]
            if "fault-ok:" in line and line.split("fault-ok:", 1)[1].strip():
                return True
    return False


class _HandlerScan(ast.NodeVisitor):
    """Collects evidence of discipline inside one handler body."""

    def __init__(self):
        self.raises = False
        self.resilience = False
        self.metrics = False
        self.logs = False

    def visit_Raise(self, node):
        self.raises = True

    def visit_Call(self, node):
        fn = node.func
        name = None
        if isinstance(fn, ast.Name):
            name = fn.id
        elif isinstance(fn, ast.Attribute):
            name = fn.attr
            recv = fn.value
            recv_name = None
            if isinstance(recv, ast.Name):
                recv_name = recv.id
            elif isinstance(recv, ast.Attribute):
                recv_name = recv.attr
            elif isinstance(recv, ast.Call) and isinstance(
                recv.func, ast.Name
            ):
                recv_name = recv.func.id  # _log().warning(...)
            if (
                name in _LOG_METHODS
                and recv_name is not None
                and "log" in recv_name.lower()
            ):
                self.logs = True
        if name in _RESILIENCE_CALLS:
            self.resilience = True
        if name is not None and name.startswith("note_"):
            self.resilience = True
        self.generic_visit(node)

    def visit_Name(self, node):
        if any(m in node.id for m in _METRIC_MARKS):
            self.metrics = True

    def visit_Attribute(self, node):
        if any(m in node.attr for m in _METRIC_MARKS):
            self.metrics = True
        self.generic_visit(node)


class ErrorDisciplinePass(LintPass):
    name = "error-discipline"
    default_config = {
        # the serving/execution layers PR 1 gated; other layers (planner,
        # catalog, host fallback) surface errors through normal raises
        "include": (
            "spark_druid_olap_tpu/server.py",
            "spark_druid_olap_tpu/exec/",
            "spark_druid_olap_tpu/parallel/",
        ),
    }

    def on_ExceptHandler(self, node: ast.ExceptHandler, ctx: ModuleContext):
        if not _is_broad(node):
            return
        if _has_fault_ok(ctx.lines, node):
            return
        scan = _HandlerScan()
        for stmt in node.body:
            scan.visit(stmt)
        if scan.raises or scan.resilience or scan.metrics or scan.logs:
            return
        self.report(
            ctx, node, "GL601",
            "broad `except` swallows silently: add a re-raise, route "
            "through resilience.classify_error, record to metrics/log, "
            "or annotate `# fault-ok: <reason>`",
        )
