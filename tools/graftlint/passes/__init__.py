"""graftlint pass registry.

Adding a pass: subclass `core.LintPass` in a new module here, set
`name`/`default_config`, implement `on_<NodeType>` handlers that call
`self.report(ctx, node, code, message)` — and, for semantic passes,
read `self.project` (the whole-tree symbol table, `project.Project`) or
override `finish(project)` for cross-module checks — then append the
class to `ALL_PASSES`.  Codes are namespaced per pass (GL1xx jit-cache,
GL2xx trace-purity, GL3xx dtype-x64, GL4xx compat-import, GL5xx
lock-discipline, GL6xx error-discipline, GL7xx pallas-shape, GL8xx
collective-axis, GL9xx checkpoint-coverage, GL10xx wire-parity, GL11xx
span-discipline, GL12xx resource-budget, GL13xx jit-collision, GL14xx
lock-order, GL15xx ingest-discipline, GL16xx partial-discipline, GL17xx
serving-discipline, GL18xx obs-discipline, GL19xx transfer-discipline,
GL20xx storage-discipline, GL21xx dispatch-discipline, GL22xx
mesh-discipline, GL23xx broker-discipline, GL24xx fold-determinism,
GL25xx shared-state-races, GL26xx sanitizer-discipline, GL27xx
trace-propagation, GL28xx durability-protocol, GL29xx cleanup-safety;
GL00x are the core's own: GL001 unparseable file, GL002 malformed
pragma).

The GL24xx/GL25xx families are interprocedural: they run on
`engine.DataflowEngine` (bound to every pass as `self.engine`), which
layers a module dependency graph, thread-entry reachability, inferred
lock ownership, and a forward order-taint lattice on top of the
project symbol tables.  The GL28xx/GL29xx families add the engine's
per-function effect-summary layer (ordered journal/fsync/publish/
rename/truncate/acquire/release sequences per exception-split path)
and run declared protocol automata over it; the automata export into
`graftsan_contracts.json` for the runtime protocol witness.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..core import LintConfigError, LintPass
from .broker_discipline import BrokerDisciplinePass
from .checkpoint_coverage import CheckpointCoveragePass
from .cleanup_safety import CleanupSafetyPass
from .collective_axis import CollectiveAxisPass
from .compat_import import CompatImportPass
from .dispatch_discipline import DispatchDisciplinePass
from .dtype_x64 import DtypeX64Pass
from .durability_protocol import DurabilityProtocolPass
from .error_discipline import ErrorDisciplinePass
from .fold_determinism import FoldDeterminismPass
from .ingest_discipline import IngestDisciplinePass
from .jit_cache import JitCachePass
from .jit_collision import JitCollisionPass
from .lock_discipline import LockDisciplinePass
from .lock_order import LockOrderPass
from .mesh_discipline import MeshDisciplinePass
from .obs_discipline import ObsDisciplinePass
from .pallas_shape import PallasShapePass
from .partial_discipline import PartialDisciplinePass
from .resource_budget import ResourceBudgetPass
from .sanitizer_discipline import SanitizerDisciplinePass
from .serving_discipline import ServingDisciplinePass
from .shared_state_races import SharedStateRacesPass
from .span_discipline import SpanDisciplinePass
from .storage_discipline import StorageDisciplinePass
from .trace_propagation import TracePropagationPass
from .trace_purity import TracePurityPass
from .transfer_discipline import TransferDisciplinePass
from .wire_parity import WireParityPass

ALL_PASSES = (
    JitCachePass,
    TracePurityPass,
    DtypeX64Pass,
    CompatImportPass,
    LockDisciplinePass,
    ErrorDisciplinePass,
    PallasShapePass,
    CollectiveAxisPass,
    CheckpointCoveragePass,
    WireParityPass,
    SpanDisciplinePass,
    ResourceBudgetPass,
    JitCollisionPass,
    LockOrderPass,
    IngestDisciplinePass,
    PartialDisciplinePass,
    ServingDisciplinePass,
    ObsDisciplinePass,
    TransferDisciplinePass,
    StorageDisciplinePass,
    DispatchDisciplinePass,
    MeshDisciplinePass,
    BrokerDisciplinePass,
    FoldDeterminismPass,
    SharedStateRacesPass,
    SanitizerDisciplinePass,
    TracePropagationPass,
    DurabilityProtocolPass,
    CleanupSafetyPass,
)

PASS_BY_NAME = {cls.name: cls for cls in ALL_PASSES}


def build_passes(
    pass_names: Optional[Sequence[str]] = None,
    config_overrides: Optional[Dict[str, dict]] = None,
) -> List[LintPass]:
    names = list(pass_names) if pass_names else [c.name for c in ALL_PASSES]
    overrides = config_overrides or {}
    out: List[LintPass] = []
    for name in names:
        cls = PASS_BY_NAME.get(name)
        if cls is None:
            raise LintConfigError(
                f"unknown pass {name!r}; available: "
                f"{sorted(PASS_BY_NAME)}"
            )
        out.append(cls(overrides.get(name)))
    return out
