"""Durability-protocol pass (GL28xx): ordering automata over effect paths.

The durable tier's correctness claim is an ORDERING protocol (ISSUE 13):
journal -> fsync -> publish on the append path, snapshot-rename ->
GC/WAL-truncate on the flush path.  Straight-line reachability cannot
see an exception edge between fsync and publish, or a GC hoisted above
the rename commit point — this pass runs declared protocol automata
(`engine.ProtocolAutomaton`) over every enumerated effect path
(`engine.EffectAnalysis`) of every function the automaton's `scope`
patterns match:

* **GL2801** — a publish is reachable before the journal+fsync pair on
  some path.  In the automaton start state this fires only with true
  reordering evidence (`later:journal`): a path that legitimately never
  journals (ephemeral datasource, `storage is None` gate) stays clean,
  but `catalog.put(...)` hoisted above `journal_append(...)` flags.
* **GL2802** — a GC/truncate effect is reachable before the
  snapshot-rename commit point (`later:rename`): retired segment files
  must only disappear AFTER the new snapshot rename commits, or a crash
  between the two loses rows the WAL was already truncated through.
* **GL2803** — an exception edge escapes in the post-fsync pre-publish
  window (automaton state `durable`) in a function that does NOT carry
  the whole-or-absent guarantee.  `whole_or_absent` lists the canonical
  names whose all-or-nothing contract is discharged elsewhere (WAL
  torn-tail scan on recovery + the raise-injection kill matrix);
  everything else must not let an acked-but-unpublished row escape.

The automata are plain JSON documents so `--export-contracts` ships
them verbatim into `graftsan_contracts.json`, where the graftsan
protocol witness replays the SAME machines over runtime effect stamps
(static<->runtime agreement, PR 18).  Runtime arming uses `arm_on`;
static evaluation starts armed and uses `later:` look-ahead instead.
The clean exemplar is `storage.DurableStorage.flush_locked`.
"""

from __future__ import annotations

from ..core import LintPass
from ..engine import ProtocolAutomaton

# the two shipped machines; JSON-serializable by construction
DURABLE_PUBLISH = {
    "name": "durable-publish",
    "scope": (
        "*.WriteAheadLog.append",
        "*.IngestManager.append_rows",
        "*.DurableStorage.journal_append",
    ),
    "alphabet": ("journal", "fsync", "publish"),
    "arm_on": ("journal",),
    "start": "S0",
    "accept": ("published",),
    "states": {
        "S0": {
            "journal": "journaled",
            "publish": (
                "error", "GL2801",
                "publish reachable before journal+fsync on this path",
                "later:journal",
            ),
        },
        "journaled": {
            "fsync": "durable",
            "journal": "journaled",
            "publish": (
                "error", "GL2801",
                "publish after journal but before the fsync commit "
                "point",
            ),
        },
        "durable": {
            "publish": "published",
            "journal": "journaled",
        },
        "published": {
            "journal": "journaled",
            "publish": "published",
        },
    },
    "unsafe_raise": {"durable": "GL2803"},
}

SNAPSHOT_COMMIT = {
    "name": "snapshot-commit",
    "scope": (
        "*.DurableStorage.flush_locked",
        "*.DurableStorage.flush",
        "*.save_snapshot",
        "*.Compactor.compact",
    ),
    "alphabet": ("rename", "truncate"),
    "arm_on": ("rename", "truncate"),
    "start": "P0",
    "accept": ("committed",),
    "states": {
        "P0": {
            "rename": "committed",
            "truncate": (
                "error", "GL2802",
                "GC/truncate reachable before the snapshot-rename "
                "commit point",
                "later:rename",
            ),
        },
        "committed": {
            "rename": "committed",
            "truncate": "committed",
        },
    },
    "unsafe_raise": {},
}


class DurabilityProtocolPass(LintPass):
    name = "durability-protocol"
    default_config = {
        # the durable tier lives in the package; tools/tests build
        # fixtures that would self-flag
        "include": ("spark_druid_olap_tpu/",),
        # protocol automata documents (exported to graftsan contracts)
        "automata": (DURABLE_PUBLISH, SNAPSHOT_COMMIT),
        # canonical names whose whole-or-absent guarantee is discharged
        # by recovery-scan + raise-injection tests, not by GL2803
        "whole_or_absent": (
            "spark_druid_olap_tpu.ingest.wal.WriteAheadLog.append",
            "spark_druid_olap_tpu.ingest.delta.IngestManager.append_rows",
            "spark_druid_olap_tpu.storage.DurableStorage.journal_append",
        ),
        # extra {dotted-suffix: (effect, ...)} / {site: effect} tables
        "call_effects": {},
        "site_effects": {},
        "summary_depth": 3,
    }

    def finish(self, project) -> None:
        if self.engine is None:
            return
        eff = self.engine.effects(self.config)
        automata = [
            ProtocolAutomaton(dict(doc))
            for doc in self.config.get("automata", ())
        ]
        whole = frozenset(self.config.get("whole_or_absent", ()))
        for info in sorted(
            project.modules.values(), key=lambda m: m.relpath
        ):
            if not self.applies_to(info.relpath):
                continue
            for qual in sorted(info.functions):
                fi = info.functions[qual]
                canon = f"{info.modname}.{fi.qualname}"
                machines = [a for a in automata if a.matches(canon)]
                if not machines:
                    continue
                seen = set()
                for path in eff.paths(fi):
                    for a in machines:
                        for node, code, msg in a.run_static(
                            path, canon, whole
                        ):
                            key = (code, node.lineno,
                                   getattr(node, "col_offset", 0))
                            if key in seen:
                                continue
                            seen.add(key)
                            self.report(info.ctx, node, code, msg)
