"""mesh-discipline pass: the unified SPMD core's placement and
collective contracts (GL22xx, ISSUE 15 satellite).

The unified executor core (parallel/spmd_arena.py + DistributedEngine)
rests on three disciplines that an innocent-looking edit can silently
break long before any multi-device CI runs:

* **GL2201 — collective axis named by a string literal.**  Mesh axis
  names are declared ONCE (`parallel/mesh.py`: `DATA_AXIS`,
  `GROUPS_AXIS`, `SLICE_AXIS`) and consumed by reference; a literal
  `lax.psum(x, "data")` type-checks, runs, and merges over the right
  axis — until the axis layout changes (exactly what the multi-slice
  topology did) and the literal keeps naming the OLD world.  The
  collective-axis pass (GL801) catches names no mesh declares; this
  check catches the sneakier case where the literal IS a declared name
  and therefore never fails, it just stops meaning what the author
  thought.  Scope: the runtime package (fixtures/tests exercise
  literals deliberately).
* **GL2202 — shard placement outside the sanctioned owners.**  In
  parallel/, every host->device placement with an explicit sharding or
  device rides a named owner (`multihost.put_sharded`, the engine's
  `_place_shards` / `_global_columns` / `_place_arena`,
  `spmd_arena.init_carry_stacked`): those own the residency keys, h2d
  fault site, link accounting, and the multi-process placement shim.  A
  bare `jax.device_put(x, sharding)` elsewhere bypasses all four — the
  mesh-side analog of transfer-discipline's GL1901, which deliberately
  excludes parallel/ in deference to this contract.
* **GL2203 — per-shard dispatch loop on the SPMD path.**  The whole
  point of the sharded arena is ONE dispatch per query: a
  dispatch-family span inside a host `for`/`while` in parallel/ is the
  O(shards) round-trip pattern the unified core exists to collapse.
  The chunked anytime mode (`_arena_spmd_deadline`: one iteration per
  deadline checkpoint, not per shard) is the sanctioned owner.

All checks are frame-local (same contract as dispatch-discipline);
allow lists are checked against the whole enclosing-function stack so
helper closures inside an owner stay covered.
"""

from __future__ import annotations

import ast

from ..core import LintPass, ModuleContext, dotted_name

# collective -> index of the positional axis-name argument (the same
# family collective-axis checks; axis_index takes the axis first)
_COLLECTIVES = {
    "psum": 1, "pmin": 1, "pmax": 1, "pmean": 1,
    "all_gather": 1, "psum_scatter": 1, "all_to_all": 1,
    "axis_index": 0,
}

_DISPATCH_SPANS = frozenset({
    "SPAN_SEGMENT_DISPATCH", "SPAN_SPARSE_DISPATCH", "SPAN_ADAPTIVE_PROBE",
    "SPAN_STREAM_CHUNK", "SPAN_COLLECTIVE_MERGE",
    "segment_dispatch", "sparse_dispatch", "adaptive_probe",
    "stream_chunk", "collective_merge",
})


def _collective_short(canon: str) -> str:
    """Short collective name when `canon` is a lax collective, else ''."""
    short = canon.rsplit(".", 1)[-1]
    if short not in _COLLECTIVES:
        return ""
    if canon in (short, f"lax.{short}", f"jax.lax.{short}") or (
        canon.endswith(f".lax.{short}")
    ):
        return short
    return ""


def _is_device_put(canon: str) -> bool:
    return canon == "device_put" or canon.endswith(".device_put")


class MeshDisciplinePass(LintPass):
    name = "mesh-discipline"
    default_config = {
        # GL2201: the whole runtime package (axis constants are a
        # package-wide contract); tests/tools stay out of scope
        "axis_include": ("spark_druid_olap_tpu/",),
        # GL2202 + GL2203: the mesh tree, where the unified core's
        # placement/dispatch ownership lives
        "include": ("spark_druid_olap_tpu/parallel/",),
        "allow_files": (),
        # sanctioned placement owners (GL2202): these hold the residency
        # keys, fire the h2d fault site, and record link accounting
        "place_funcs": (
            "put_sharded",
            "_place_shards",
            "_place_arena",
            "_global_columns",
            "init_carry_stacked",
        ),
        # sanctioned dispatch-loop owners (GL2203): the chunked anytime
        # mode iterates per deadline checkpoint, and the sparse ladder
        # per capacity-escalation rung — neither is per-shard
        "loop_funcs": ("_arena_spmd_deadline", "_execute_sparse"),
    }

    def _in_tree(self, ctx: ModuleContext, include_key: str) -> bool:
        if any(
            ctx.relpath.startswith(p) for p in self.config["allow_files"]
        ):
            return False
        return any(
            ctx.relpath.startswith(p) for p in self.config[include_key]
        )

    def _under(self, ctx: ModuleContext, funcs_key: str) -> bool:
        allow = tuple(self.config[funcs_key])
        return any(
            getattr(f, "name", "") in allow for f in ctx.scope.func_stack
        )

    # applies_to: no global include — each rule scopes itself (GL2201
    # covers the whole package, GL2202/03 only parallel/)
    def applies_to(self, relpath: str) -> bool:
        return True

    # -- GL2201 ---------------------------------------------------------------

    def _check_axis_literal(self, node: ast.Call, ctx, short: str) -> None:
        arg = None
        for k in node.keywords:
            if k.arg == "axis_name":
                arg = k.value
        if arg is None:
            idx = _COLLECTIVES[short]
            if len(node.args) > idx:
                arg = node.args[idx]
        if arg is None:
            return
        elts = (
            list(arg.elts)
            if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
        )
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                self.report(
                    ctx, node, "GL2201",
                    f"lax.{short} over the string literal {e.value!r}: "
                    "axis names are declared once in parallel/mesh.py "
                    "(*_AXIS constants) and consumed by reference — a "
                    "literal keeps 'working' after an axis-layout change "
                    "while silently merging over the wrong scope; use "
                    "the declared constant",
                )

    # -- handlers -------------------------------------------------------------

    @staticmethod
    def _is_dispatch_span(node: ast.Call) -> bool:
        if dotted_name(node.func).split(".")[-1] != "span" or not node.args:
            return False
        arg = node.args[0]
        if isinstance(arg, ast.Name):
            return arg.id in _DISPATCH_SPANS
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value in _DISPATCH_SPANS
        return False

    def on_Call(self, node: ast.Call, ctx: ModuleContext):
        canon = dotted_name(node.func)
        short = _collective_short(canon)
        if short and self._in_tree(ctx, "axis_include"):
            self._check_axis_literal(node, ctx, short)
        if not self._in_tree(ctx, "include"):
            return
        # GL2202: device_put with an explicit placement target (second
        # positional arg or device=/sharding= kwarg) outside an owner.
        # A bare device_put(x) takes the default device and is another
        # pass's business (transfer-discipline, outside parallel/).
        if _is_device_put(canon):
            placed = len(node.args) > 1 or any(
                k.arg in ("device", "sharding") for k in node.keywords
            )
            if placed and not self._under(ctx, "place_funcs"):
                self.report(
                    ctx, node, "GL2202",
                    "sharded device_put outside the sanctioned placement "
                    "owners (put_sharded / _place_shards / _place_arena / "
                    "_global_columns / init_carry_stacked) bypasses the "
                    "residency keys, the h2d fault site, link accounting, "
                    "and the multi-process placement shim — route the "
                    "move through an owner or add one with a "
                    "justification",
                )
            return
        # GL2203: dispatch span under a host loop on the SPMD path
        if (
            ctx.scope.in_loop
            and self._is_dispatch_span(node)
            and not self._under(ctx, "loop_funcs")
        ):
            self.report(
                ctx, node, "GL2203",
                "dispatch span inside a host loop on the SPMD path is a "
                "per-shard round trip — the pattern the sharded arena "
                "collapsed to one dispatch per query; route the scope "
                "through parallel/spmd_arena (one shard_mapped scan) or "
                "add the loop owner to mesh-discipline loop_funcs with a "
                "justification",
            )
