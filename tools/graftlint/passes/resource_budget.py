"""resource-budget pass: kernel tile footprints vs the calibrated VMEM
budget (GL12xx).

An oversized Pallas tile does not fail in CPU interpret mode — it fails
on real hardware, at lowering time, after a full CI cycle passed (the
round-1/2 lesson: the kernel's block tuning notes literally say
"ESTIMATED ... not yet validated on hardware").  The budget the tiles
must fit is a *measured device property*, so this pass validates against
the calibrated config, not a guess (the same pushdown-to-where-cost-is-
known argument the cost model follows):

* **GL1201 — kernel resident bytes exceed the platform VMEM budget.**
  For every `pl.pallas_call` whose BlockSpec block shapes resolve
  statically (through the project layer's constant propagation: module
  constants, arithmetic, parameter defaults, cross-module imports), the
  per-kernel resident estimate is

      pipeline_factor x sum(prod(block_shape) x dtype_width per ref)
        + sum(prod(shape) x dtype_width per pltpu.VMEM scratch)

  — every ref is double-buffered by the Pallas pipeline
  (pipeline_factor=2), dtype widths come from `out_shape` for outputs
  and floor at 1 byte for inputs (no static dtype source; the narrowest
  real element keeps the estimate a true lower bound).  `scratch_shapes`
  entries that resolve to `pltpu.VMEM(shape, dtype)` count at 1x (scratch
  is a single allocation, not pipelined) — previously uncounted, so
  kernels with big scratch accumulators under-reported their residency
  (ISSUE 6 satellite).  The budget is resolved in
  order: pass config override -> `calibration.<platform>.json`'s
  `vmem_budget_bytes` (the calibrated device constant) -> the scanned
  `config.py`'s `SessionConfig.vmem_budget_mb` default -> a built-in
  16 MiB/core fallback (the v5e-class figure from the Pallas guide).
  The estimate is a LOWER bound (kernel-internal intermediates like
  match tiles are invisible), so exceeding it is always a real finding.
* **GL1202 — degenerate grid.**  A grid axis that const-resolves to
  <= 0 (`G // BG` with `G < BG` is the classic): the kernel dispatches
  zero tiles over that axis and silently aggregates nothing.
* **GL1203 — degenerate block shape.**  A BlockSpec dimension that
  resolves to <= 0 — an empty tile is never what a kernel author meant.

* **GL1204 — upper-bound mode for dynamically-tuned kernels.**  A tile
  set tuned as `block = min(G, BOUND)` never resolves exactly (G is
  runtime data), so GL1201 stays silent — which left dynamically-tuned
  kernels entirely unchecked (the carried-over ROADMAP gap).  In
  upper-bound mode, an unresolved dimension spelled `min(...)` resolves
  to the smallest of its PROVABLE arguments (`min(a, b) <= a` always),
  and the resident estimate recomputed with those bounds is the
  worst-case footprint the tuning ALLOWS.  When that upper bound
  exceeds the budget, the finding says "clamp the tuning bound": the
  kernel may fit on today's data and still Mosaic-fail the first time G
  crosses the bound.  Dimensions with no provable bound keep the set
  silent — never a guess.

GL1201 stays exact-only: only EXACTLY resolved spec sets are checked
against the budget directly (an upper-bound guess there would cry wolf);
GL1204 exists precisely for the dynamically-tuned remainder, and says
so in its message.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..core import LintPass, ModuleContext, call_name, dotted_name
from .pallas_shape import _is_blockspec, _is_pallas_call

# dtype name (last dotted segment) -> byte width
_DTYPE_WIDTH = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
}
# unknown dtypes (inputs have no static dtype source) count at 1 byte:
# the narrowest real element keeps the resident estimate a TRUE lower
# bound, so a GL1201 exceedance can never be an artifact of the guess
_DEFAULT_WIDTH = 1


class ResourceBudgetPass(LintPass):
    name = "resource-budget"
    default_config = {
        # the deploy platform whose calibrated budget gates the tree
        "platform": "tpu",
        # explicit byte override (tests); None = resolve the chain below
        "budget_bytes": None,
        "calibration_file": "calibration.{platform}.json",
        # fallback: the scanned session config's declared budget field
        "config_module": "spark_druid_olap_tpu/config.py",
        "config_class": "SessionConfig",
        "config_field": "vmem_budget_mb",
        # last resort: ~16 MiB/core, the v5e-class VMEM figure
        "default_budget_bytes": 16 * 1024 * 1024,
        # every ref is double-buffered by the Pallas pipeline
        "pipeline_factor": 2,
        # GL1204: when exact resolution fails, bound `min(...)`-tuned
        # dimensions by their provable arguments and budget-check the
        # worst case the tuning allows
        "upper_bound": True,
    }

    def __init__(self, config: Optional[dict] = None):
        super().__init__(config)
        self._budget: Optional[Tuple[int, str]] = None  # (bytes, source)

    # -- budget resolution ----------------------------------------------------

    def _resolve_budget(self) -> Tuple[int, str]:
        """(budget_bytes, human-readable source), memoized per run."""
        if self._budget is not None:
            return self._budget
        cfg = self.config
        if cfg.get("budget_bytes"):
            self._budget = (int(cfg["budget_bytes"]), "pass config")
            return self._budget
        platform = cfg["platform"]
        fname = cfg["calibration_file"].format(platform=platform)
        root = self.project.root if self.project is not None else "."
        path = os.path.join(root, fname)
        try:
            with open(path) as f:
                doc = json.load(f)
            v = doc.get("vmem_budget_bytes")
            if isinstance(v, (int, float)) and v > 0:
                self._budget = (int(v), fname)
                return self._budget
        except (OSError, ValueError):
            pass
        # scanned config.py class default (MiB), via constant propagation
        if self.project is not None:
            mod = self.project.modules.get(cfg["config_module"])
            if mod is not None:
                expr = mod.class_defaults.get(
                    cfg["config_class"], {}
                ).get(cfg["config_field"])
                v = self.project.const_eval(mod, expr)
                if isinstance(v, (int, float)) and v > 0:
                    self._budget = (
                        int(v * 1024 * 1024),
                        f"{cfg['config_module']}:"
                        f"{cfg['config_class']}.{cfg['config_field']}",
                    )
                    return self._budget
        self._budget = (
            int(cfg["default_budget_bytes"]), "built-in v5e-class default"
        )
        return self._budget

    # -- static environment ---------------------------------------------------

    def begin_module(self, ctx: ModuleContext) -> None:
        self._fi_by_node: Dict[int, Any] = {}
        if self.project is None:
            return
        module = self.project.modules.get(ctx.relpath)
        if module is not None:
            for fi in module.functions.values():
                self._fi_by_node[id(fi.node)] = fi

    @staticmethod
    def _own_binding_nodes(func: ast.AST):
        """The function's OWN binding statements in source order —
        nested function/lambda subtrees are a different scope and must
        not leak bindings into this one."""
        def walk(node, is_root):
            if not is_root and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return
            if isinstance(
                node, (ast.Assign, ast.AugAssign, ast.For, ast.AsyncFor)
            ):
                yield node
            for child in ast.iter_child_nodes(node):
                yield from walk(child, False)

        yield from walk(func, True)

    def _env_for_site(
        self, ctx: ModuleContext, site: ast.AST
    ) -> Dict[str, Any]:
        """const_eval env at the pallas_call site: parameter defaults of
        every enclosing function (outer first) overlaid with that
        function's own single-assignment locals ABOVE the site — a
        rebinding after the call, or in a sibling nested function, was
        never in effect here and must not flip a verdict.  Names the
        walk cannot track honestly (AugAssign-ed, loop targets,
        tuple-unpacked, or assigned more than once above the site —
        branch-dependent values) are POISONED as UNRESOLVED so they
        neither guess a value nor fall through to a same-named module
        constant (the never-guess contract)."""
        from ..project import UNRESOLVED

        site_line = getattr(site, "lineno", 0)
        env: Dict[str, Any] = {}
        for func in ctx.scope.func_stack:
            fi = self._fi_by_node.get(id(func))
            if fi is not None:
                env.update(self.project.param_defaults(fi))
            assigned: set = set()
            for sub in self._own_binding_nodes(func):
                if sub.lineno >= site_line:
                    continue
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            env[t.id] = (
                                UNRESOLVED if t.id in assigned
                                else sub.value
                            )
                            assigned.add(t.id)
                        else:  # tuple/list unpacking, subscripts, attrs
                            for n in ast.walk(t):
                                if isinstance(n, ast.Name):
                                    env[n.id] = UNRESOLVED
                else:  # AugAssign / For targets mutate in place
                    target = getattr(sub, "target", None)
                    if target is not None:
                        for n in ast.walk(target):
                            if isinstance(n, ast.Name):
                                env[n.id] = UNRESOLVED
        return env

    # -- entry ----------------------------------------------------------------

    def on_Call(self, node: ast.Call, ctx: ModuleContext):
        if self.project is None:
            return
        module = self.project.modules.get(ctx.relpath)
        if module is None:
            return
        canon = self.project.canonical(module, call_name(node))
        if not _is_pallas_call(canon):
            return
        env = self._env_for_site(ctx, node)
        kw = {k.arg: k.value for k in node.keywords if k.arg}

        # GL1202: degenerate grid axes
        grid = kw.get("grid")
        if grid is not None:
            axes = self._grid_axes(module, grid, env)
            for i, v in enumerate(axes):
                if isinstance(v, int) and v <= 0:
                    self.report(
                        ctx, node, "GL1202",
                        f"grid axis {i} resolves to {v}: the kernel "
                        "dispatches zero tiles over it and silently "
                        "aggregates nothing (a floor-divided extent "
                        "smaller than its block size is the classic "
                        "shape)",
                    )

        # block shapes + dtypes per ref
        out_dtypes = self._out_dtypes(module, kw.get("out_shape"), env)
        in_blocks = self._spec_shapes(module, kw.get("in_specs"), env)
        out_blocks = self._spec_shapes(module, kw.get("out_specs"), env)
        if in_blocks is None and out_blocks is None:
            return
        refs: List[Tuple[Optional[Tuple[int, ...]], int]] = []
        exact = in_blocks is not None and out_blocks is not None
        for shape in in_blocks or []:
            refs.append((shape, _DEFAULT_WIDTH))
            exact = exact and shape is not None
        for i, shape in enumerate(out_blocks or []):
            width = _DTYPE_WIDTH.get(
                out_dtypes[i].rsplit(".", 1)[-1]
                if i < len(out_dtypes) else "", _DEFAULT_WIDTH,
            )
            refs.append((shape, width))
            exact = exact and shape is not None
        # pltpu.VMEM scratch allocations: single-instance (1x, no pipeline
        # double-buffering) but fully VMEM-resident for the kernel's
        # lifetime — an unresolved scratch entry degrades the set to
        # inexact (silence) rather than under-reporting
        scratch, scratch_exact = self._scratch_refs(
            module, kw.get("scratch_shapes"), env
        )
        exact = exact and scratch_exact

        # GL1203: degenerate block dims (checked per resolved spec even
        # when the full set stays unresolved); scratch shapes included
        for shape, _ in refs + scratch:
            if shape is not None and any(d <= 0 for d in shape):
                self.report(
                    ctx, node, "GL1203",
                    f"BlockSpec block shape {shape} has a dimension "
                    "<= 0 — an empty tile reads/writes nothing",
                )

        # GL1201: resident-bytes estimate vs the calibrated budget —
        # only when EVERY spec resolved (a partial estimate would be a
        # guess, and guesses get pragma'd into uselessness)
        if not exact or not refs:
            if self.config.get("upper_bound"):
                self._check_upper_bound(ctx, node, module, kw, env)
            return
        factor = int(self.config["pipeline_factor"])
        total = sum(
            self._prod(shape) * width for shape, width in refs
        )
        scratch_bytes = sum(
            self._prod(shape) * width for shape, width in scratch
        )
        resident = factor * total + scratch_bytes
        budget, source = self._resolve_budget()
        if resident > budget:
            breakdown = " + ".join(
                f"{'x'.join(str(d) for d in shape)}*{width}B"
                for shape, width in refs
            )
            if scratch:
                breakdown += " + scratch " + " + ".join(
                    f"{'x'.join(str(d) for d in shape)}*{width}B"
                    for shape, width in scratch
                )
            self.report(
                ctx, node, "GL1201",
                f"kernel resident estimate {resident} bytes "
                f"({factor}x double-buffered: {breakdown}) exceeds the "
                f"{budget}-byte VMEM budget from {source} — this tile "
                "set fails Mosaic on real hardware even though CPU "
                "interpret mode passes; shrink the block shapes or "
                "split the refs",
            )

    # -- GL1204: upper-bound mode (dynamically-tuned kernels) -----------------

    def _upper_dim(self, module, e, env) -> Tuple[Optional[int], bool]:
        """(dimension upper bound, was_bounded): an exactly-resolved int
        is its own bound; a `min(...)` call bounds to the smallest of
        its PROVABLE arguments (min(a, b) <= a always); anything else is
        unprovable (None)."""
        v = self.project.const_eval(module, e, dict(env))
        if isinstance(v, int):
            return v, False
        if isinstance(e, ast.Name) and isinstance(env.get(e.id), ast.AST):
            return self._upper_dim(module, env[e.id], env)
        if isinstance(e, ast.Call):
            fname = (call_name(e) or "").rsplit(".", 1)[-1]
            if fname == "min" and e.args:
                cands = [
                    self.project.const_eval(module, a, dict(env))
                    for a in e.args
                ]
                ints = [c for c in cands if isinstance(c, int)]
                if ints:
                    return min(ints), True
        return None, False

    def _upper_shape(
        self, module, e, env
    ) -> Tuple[Optional[Tuple[int, ...]], bool]:
        """(block-shape upper bound, any dimension used a min() bound)."""
        if isinstance(e, ast.Name) and isinstance(env.get(e.id), ast.AST):
            return self._upper_shape(module, env[e.id], env)
        if not isinstance(e, (ast.Tuple, ast.List)):
            v = self.project.const_eval(module, e, dict(env))
            if isinstance(v, tuple) and all(
                isinstance(d, int) for d in v
            ):
                return v, False
            return None, False
        dims: List[int] = []
        bounded = False
        for el in e.elts:
            d, b = self._upper_dim(module, el, env)
            if d is None:
                return None, False
            dims.append(d)
            bounded = bounded or b
        return tuple(dims), bounded

    def _upper_spec_shapes(
        self, module, specs, env
    ) -> Tuple[Optional[List[Tuple[int, ...]]], bool]:
        if specs is None:
            return [], False
        elts = self._resolve_seq(module, specs, env)
        if elts is None:
            return None, False
        shapes: List[Tuple[int, ...]] = []
        bounded_any = False
        for e in elts:
            if not (
                isinstance(e, ast.Call)
                and _is_blockspec(
                    self.project.canonical(module, call_name(e))
                )
            ):
                return None, False
            shape_expr = e.args[0] if e.args else None
            for k in e.keywords:
                if k.arg == "block_shape":
                    shape_expr = k.value
            shape, bounded = self._upper_shape(module, shape_expr, env)
            if shape is None:
                return None, False
            shapes.append(shape)
            bounded_any = bounded_any or bounded
        return shapes, bounded_any

    def _check_upper_bound(self, ctx, node, module, kw, env):
        """Exact resolution failed: budget-check the WORST CASE the
        dynamic tuning allows, when every dimension is at least
        min()-boundable.  Anything unprovable keeps the set silent."""
        out_dtypes = self._out_dtypes(module, kw.get("out_shape"), env)
        in_shapes, b_in = self._upper_spec_shapes(
            module, kw.get("in_specs"), env
        )
        out_shapes, b_out = self._upper_spec_shapes(
            module, kw.get("out_specs"), env
        )
        if in_shapes is None or out_shapes is None:
            return
        if not (b_in or b_out):
            return  # nothing was dynamically tuned: GL1201's territory
        scratch, scratch_exact = self._scratch_refs(
            module, kw.get("scratch_shapes"), env
        )
        if not scratch_exact:
            return  # an unprovable scratch would make the bound a guess
        refs: List[Tuple[Tuple[int, ...], int]] = [
            (shape, _DEFAULT_WIDTH) for shape in in_shapes
        ]
        for i, shape in enumerate(out_shapes):
            width = _DTYPE_WIDTH.get(
                out_dtypes[i].rsplit(".", 1)[-1]
                if i < len(out_dtypes) else "", _DEFAULT_WIDTH,
            )
            refs.append((shape, width))
        if not refs:
            return
        factor = int(self.config["pipeline_factor"])
        resident = factor * sum(
            self._prod(shape) * width for shape, width in refs
        ) + sum(self._prod(shape) * width for shape, width in scratch)
        budget, source = self._resolve_budget()
        if resident > budget:
            breakdown = " + ".join(
                f"{'x'.join(str(d) for d in shape)}*{width}B"
                for shape, width in refs
            )
            self.report(
                ctx, node, "GL1204",
                f"dynamically-tuned tile set's UPPER BOUND "
                f"({resident} bytes, {factor}x double-buffered: "
                f"{breakdown}) exceeds the {budget}-byte VMEM budget "
                f"from {source} — the kernel may fit today's data and "
                "still Mosaic-fail the first time the tuned extent "
                "reaches its min() bound; clamp the tuning bound below "
                "the budget",
            )

    # -- shape resolution -----------------------------------------------------

    @staticmethod
    def _prod(shape: Tuple[int, ...]) -> int:
        out = 1
        for d in shape:
            out *= d
        return out

    def _grid_axes(self, module, grid, env) -> List[Any]:
        if isinstance(grid, ast.Name) and isinstance(
            env.get(grid.id), ast.AST
        ):
            grid = env[grid.id]
        if isinstance(grid, (ast.Tuple, ast.List)):
            return [
                self.project.const_eval(module, e, dict(env))
                for e in grid.elts
            ]
        v = self.project.const_eval(module, grid, dict(env))
        if isinstance(v, tuple):
            return list(v)
        return [v]

    def _resolve_seq(self, module, node, env) -> Optional[List[ast.AST]]:
        """Spec-list elements: a literal tuple/list, a local name bound
        to one, or a single BlockSpec call (the out_specs shorthand)."""
        if isinstance(node, ast.Name) and isinstance(
            env.get(node.id), ast.AST
        ):
            node = env[node.id]
        if isinstance(node, (ast.Tuple, ast.List)):
            return list(node.elts)
        if isinstance(node, ast.Call):
            return [node]
        return None

    def _spec_shapes(
        self, module, specs, env
    ) -> Optional[List[Optional[Tuple[int, ...]]]]:
        """Per-spec resolved block shapes; None entries = that spec is
        unresolved; None overall = the spec LIST itself is unresolved."""
        if specs is None:
            return None
        elts = self._resolve_seq(module, specs, env)
        if elts is None:
            return None
        shapes: List[Optional[Tuple[int, ...]]] = []
        for e in elts:
            shape = None
            if isinstance(e, ast.Call) and _is_blockspec(
                self.project.canonical(module, call_name(e))
            ):
                shape_expr = e.args[0] if e.args else None
                for k in e.keywords:
                    if k.arg == "block_shape":
                        shape_expr = k.value
                v = self.project.const_eval(
                    module, shape_expr, dict(env)
                )
                if isinstance(v, tuple) and all(
                    isinstance(d, int) for d in v
                ):
                    shape = v
            shapes.append(shape)
        return shapes

    def _scratch_refs(
        self, module, scratch, env
    ) -> Tuple[List[Tuple[Tuple[int, ...], int]], bool]:
        """Resolved `scratch_shapes` entries as (shape, dtype_width)
        pairs, plus whether the WHOLE scratch set resolved.  Only
        `pltpu.VMEM(shape, dtype)` entries occupy the VMEM budget; SMEM/
        semaphore scratch lives elsewhere and resolves as zero-byte.
        No scratch_shapes kwarg at all is exact by definition."""
        if scratch is None:
            return [], True
        elts = self._resolve_seq(module, scratch, env)
        if elts is None:
            return [], False
        out: List[Tuple[Tuple[int, ...], int]] = []
        exact = True
        for e in elts:
            if not isinstance(e, ast.Call):
                exact = False
                continue
            canon = self.project.canonical(module, call_name(e)) or ""
            last = canon.rsplit(".", 1)[-1]
            if last != "VMEM":
                # SMEM / SemaphoreType etc.: not VMEM-resident bytes
                continue
            shape_expr = e.args[0] if e.args else None
            dtype_expr = e.args[1] if len(e.args) > 1 else None
            for k in e.keywords:
                if k.arg == "shape":
                    shape_expr = k.value
                elif k.arg == "dtype":
                    dtype_expr = k.value
            v = self.project.const_eval(module, shape_expr, dict(env))
            if not (
                isinstance(v, tuple)
                and all(isinstance(d, int) for d in v)
            ):
                exact = False
                continue
            dt = (
                self.project.canonical(module, dotted_name(dtype_expr))
                or ""
                if dtype_expr is not None
                else ""
            )
            width = _DTYPE_WIDTH.get(
                dt.rsplit(".", 1)[-1], _DEFAULT_WIDTH
            )
            out.append((v, width))
        return out, exact

    def _out_dtypes(self, module, out_shape, env) -> List[str]:
        if out_shape is None:
            return []
        elts = self._resolve_seq(module, out_shape, env)
        if elts is None:
            return []
        dtypes = []
        for e in elts:
            dt = ""
            if isinstance(e, ast.Call) and len(e.args) > 1:
                dt = self.project.canonical(
                    module, dotted_name(e.args[1])
                ) or ""
            dtypes.append(dt)
        return dtypes
